"""Cluster launcher: bring a whole cluster up from a YAML, tear it down.

Reference parity: python/ray/autoscaler/_private/commands.py
(create_or_update_cluster :186, teardown_cluster :394, attach via
scripts.py:1235 `ray up/down/attach`) and the NodeUpdater bootstrap
lifecycle (_private/updater.py — provision, install, start the node
service). TPU-first redesign: the head runs on the launching machine (on
TPU pods the "head" is a CPU-only coordinator; slices join as agents) and
worker bootstrap IS the agent start command — there is no multi-stage
rsync/setup ladder because the framework ships as one package and TPU VM
images bake the runtime (`runtime_version`), so "updater" collapses to
"run `ray_tpu start --address` on the node" (startup script for cloud
nodes, direct spawn for process nodes, ssh for bare metal).

Cluster YAML shape (the subset of the reference's schema that survives the
redesign; unknown keys are rejected to catch typos):

    cluster_name: demo
    provider:
      type: process | gcp_tpu | ssh
      # gcp_tpu: project, zone, runtime_version
      # ssh: nodes: [host1, host2], ssh_user, ssh_args
    head:
      port: 0             # 0 = pick a free port
      num_cpus: 4
      num_tpus: 0
    available_node_types:
      worker:
        resources: {CPU: 2}
        min_workers: 2
        max_workers: 4
    max_workers: 8        # cluster-wide cap for the autoscaler
"""

from __future__ import annotations

import json
import os
import signal
import subprocess
import sys
import time
from typing import Any, Dict, List, Optional

_ALLOWED_TOP = {
    "cluster_name", "provider", "head", "available_node_types", "max_workers",
}
_ALLOWED_TYPE = {"resources", "min_workers", "max_workers"}


def state_dir() -> str:
    d = os.environ.get(
        "RAY_TPU_CLUSTER_STATE_DIR",
        os.path.join(os.path.expanduser("~"), ".ray_tpu", "clusters"),
    )
    os.makedirs(d, exist_ok=True)
    return d


def _state_path(name: str) -> str:
    return os.path.join(state_dir(), f"{name}.json")


def load_cluster_config(path: str) -> Dict[str, Any]:
    import yaml

    with open(path) as f:
        cfg = yaml.safe_load(f) or {}
    unknown = set(cfg) - _ALLOWED_TOP
    if unknown:
        raise ValueError(f"unknown cluster config keys: {sorted(unknown)}")
    if "cluster_name" not in cfg:
        raise ValueError("cluster config needs cluster_name")
    cfg.setdefault("provider", {"type": "process"})
    cfg.setdefault("head", {})
    cfg.setdefault("available_node_types", {})
    for tname, t in cfg["available_node_types"].items():
        bad = set(t) - _ALLOWED_TYPE
        if bad:
            raise ValueError(f"node type {tname!r}: unknown keys {sorted(bad)}")
        t.setdefault("resources", {"CPU": 1})
        t.setdefault("min_workers", 0)
        t.setdefault("max_workers", max(1, t["min_workers"]))
    return cfg


def _read_state(name: str) -> Optional[Dict[str, Any]]:
    try:
        with open(_state_path(name)) as f:
            return json.load(f)
    except (OSError, json.JSONDecodeError):
        return None


def _write_state(name: str, state: Dict[str, Any]) -> None:
    tmp = _state_path(name) + ".tmp"
    with open(tmp, "w") as f:
        json.dump(state, f, indent=2)
    os.replace(tmp, _state_path(name))


def _alive(pid: int) -> bool:
    _reap(pid)
    try:
        os.kill(pid, 0)
    except OSError:
        return False
    # a zombie still answers kill(pid, 0): when `up` ran in-process (tests,
    # library use) the dead child lingers unreaped and would read as alive
    try:
        with open(f"/proc/{pid}/stat") as f:
            return f.read().split(")")[-1].split()[0] != "Z"
    except OSError:
        return False


def _reap(pid: int) -> None:
    """Collect the exit status if `pid` is OUR dead child (no-op for
    processes we didn't spawn in this process)."""
    try:
        os.waitpid(pid, os.WNOHANG)
    except (ChildProcessError, OSError):
        pass


# --------------------------------------------------------------------------
# node launchers (the NodeUpdater collapse — see module docstring)
# --------------------------------------------------------------------------


def _child_env() -> Dict[str, str]:
    """Head/agent children must import ray_tpu regardless of the caller's
    cwd (dev checkouts aren't on the default path): hand down this
    process's sys.path."""
    from .._private.spawn import child_pythonpath, framework_root

    env = dict(os.environ)
    env["PYTHONPATH"] = child_pythonpath(
        [framework_root()], inherited=env.get("PYTHONPATH")
    )
    return env


class ProcessNodeLauncher:
    """Workers are local detached `ray_tpu start --address` subprocesses —
    real agent processes over real TCP, the fake_multi_node analogue with
    actual process isolation. This is the e2e-testable path."""

    def __init__(self, head_address: str):
        self.head_address = head_address

    def launch(self, node_id: str, resources: Dict[str, float]) -> Dict[str, Any]:
        argv = [
            sys.executable, "-m", "ray_tpu.scripts", "start",
            "--address", self.head_address, "--node-id", node_id,
        ]
        resources = {k: v for k, v in resources.items() if k != "_node_type"}
        if "CPU" in resources:
            argv += ["--num-cpus", str(int(resources.pop("CPU")))]
        if resources.get("TPU"):
            argv += ["--num-tpus", str(int(resources.pop("TPU")))]
        resources.pop("TPU", None)
        if resources:  # custom resources (e.g. a node-type marker)
            argv += ["--resources", json.dumps(resources)]
        proc = subprocess.Popen(
            argv,
            env=_child_env(),
            stdout=subprocess.DEVNULL,
            stderr=subprocess.DEVNULL,
            start_new_session=True,  # survives `up` exiting
        )
        return {"kind": "process", "pid": proc.pid}

    def terminate(self, handle: Dict[str, Any]) -> None:
        pid = handle.get("pid")
        if pid and _alive(pid):
            try:
                os.killpg(pid, signal.SIGTERM)
            except OSError:
                try:
                    os.kill(pid, signal.SIGTERM)
                except OSError:
                    pass


class SSHNodeLauncher:
    """Bare-metal bootstrap: run the agent start command over ssh
    (reference: updater.py NodeUpdaterThread's command runner, collapsed
    to the one start command). Hosts come from provider.nodes and are
    consumed round-robin."""

    def __init__(self, head_address: str, hosts: List[str], user: str = "",
                 ssh_args: Optional[List[str]] = None,
                 ssh_cmd: Optional[str] = None,
                 python: str = "python3",
                 env: Optional[Dict[str, str]] = None):
        self.head_address = head_address
        self.hosts = list(hosts)
        self.user = user
        self.ssh_args = list(ssh_args or [])
        # pluggable transport binary: tests drive a local sh-exec shim
        # through the SAME code path (RAY_TPU_SSH / provider ssh_cmd)
        self.ssh_cmd = ssh_cmd or os.environ.get("RAY_TPU_SSH", "ssh")
        self.python = python
        # provider.env: exported before the start command (PYTHONPATH to a
        # checkout, JAX flags, ... — the slot the reference fills with
        # setup_commands)
        self.env = dict(env or {})
        self._next = 0

    def _run(self, target: str, command: str, check: bool):
        return subprocess.run(
            [self.ssh_cmd, *self.ssh_args, target, command],
            check=check, timeout=60,
        )

    def launch(self, node_id: str, resources: Dict[str, float]) -> Dict[str, Any]:
        if not self.hosts:
            raise RuntimeError("ssh provider has no hosts left")
        host = self.hosts[self._next % len(self.hosts)]
        self._next += 1
        target = f"{self.user}@{host}" if self.user else host
        numeric = {k: v for k, v in resources.items() if k != "_node_type"}
        res_arg = ""
        if numeric:
            res_arg = f" --resources '{json.dumps(numeric)}'"
        # record the agent's pid remotely so terminate kills EXACTLY this
        # process (pattern-matching pkill could hit unrelated commands)
        pidfile = f"/tmp/ray_tpu_agent_{node_id}.pid"
        import shlex

        exports = "".join(
            f"export {k}={shlex.quote(str(v))}; " for k, v in self.env.items()
        )
        remote = (
            f"{exports}nohup {self.python} -m ray_tpu.scripts start --address "
            f"{self.head_address} --node-id {node_id}{res_arg} "
            f">/tmp/ray_tpu_agent_{node_id}.log 2>&1 & echo $! > {pidfile}"
        )
        self._run(target, remote, check=True)
        return {"kind": "ssh", "host": host, "node_id": node_id,
                "pidfile": pidfile}

    def terminate(self, handle: Dict[str, Any]) -> None:
        target = (
            f"{self.user}@{handle['host']}" if self.user else handle["host"]
        )
        pidfile = handle.get("pidfile")
        if pidfile:
            cmd = (
                f"kill $(cat {pidfile}) 2>/dev/null; rm -f {pidfile}"
            )
        else:  # pre-pidfile handles: best-effort pattern match
            cmd = f"pkill -f 'node-id {handle['node_id']}'"
        self._run(target, cmd, check=False)


def _make_launcher(cfg: Dict[str, Any], head_address: str):
    ptype = cfg["provider"].get("type", "process")
    if ptype == "process":
        return ProcessNodeLauncher(head_address)
    if ptype == "ssh":
        return SSHNodeLauncher(
            head_address,
            hosts=cfg["provider"].get("nodes", []),
            user=cfg["provider"].get("ssh_user", ""),
            ssh_args=cfg["provider"].get("ssh_args"),
            ssh_cmd=cfg["provider"].get("ssh_cmd"),
            python=cfg["provider"].get("python", "python3"),
            env=cfg["provider"].get("env"),
        )
    if ptype == "gcp_tpu":
        from .node_provider import GCPTPUNodeProvider

        provider = GCPTPUNodeProvider(
            head_address,
            project=cfg["provider"].get("project", ""),
            zone=cfg["provider"].get("zone", ""),
            runtime_version=cfg["provider"].get(
                "runtime_version", "tpu-ubuntu2204-base"
            ),
            name_prefix=cfg["cluster_name"],
        )

        class _GCPAdapter:
            def launch(self, node_id, resources):
                # GCP names nodes itself via the provider counter; node_id
                # is advisory. Copy before pop: the caller's resource dict
                # is shared cluster config, not ours to mutate
                resources = dict(resources)
                accel = resources.pop("_node_type", "v5e-4")
                real = provider.create_node(accel, resources)
                return {"kind": "gcp", "node_id": real}

            def terminate(self, handle):
                provider.terminate_node(handle["node_id"])

        return _GCPAdapter()
    raise ValueError(f"unknown provider type {ptype!r}")


# --------------------------------------------------------------------------
# up / down / attach
# --------------------------------------------------------------------------


def create_or_update_cluster(
    config_path: str, *, wait_timeout: float = 60.0
) -> Dict[str, Any]:
    """`ray_tpu up`: start (or reuse) the head, then launch min_workers of
    every node type and wait for them to register. Idempotent — re-running
    against a live cluster only tops up missing workers (reference:
    commands.py:186 create_or_update semantics)."""
    cfg = load_cluster_config(config_path)
    name = cfg["cluster_name"]
    state = _read_state(name)

    if state and _alive(state.get("head_pid", -1)):
        head_address = state["head_address"]
    else:
        state = {"config_path": os.path.abspath(config_path), "nodes": {}}
        head_cfg = cfg.get("head", {})
        argv = [sys.executable, "-m", "ray_tpu.scripts", "start", "--head"]
        if head_cfg.get("port"):
            argv += ["--port", str(head_cfg["port"])]
        if head_cfg.get("num_cpus") is not None:
            argv += ["--num-cpus", str(head_cfg["num_cpus"])]
        if head_cfg.get("num_tpus") is not None:
            argv += ["--num-tpus", str(head_cfg["num_tpus"])]
        proc = subprocess.Popen(
            argv, env=_child_env(), stdout=subprocess.PIPE,
            stderr=subprocess.STDOUT, text=True, start_new_session=True,
        )
        head_address = None
        session_dir = None
        deadline = time.time() + wait_timeout
        assert proc.stdout is not None
        while time.time() < deadline:
            line = proc.stdout.readline()
            if not line:
                if proc.poll() is not None:
                    raise RuntimeError(
                        f"head process exited rc={proc.returncode} during start"
                    )
                continue
            if "--address=" in line:
                head_address = line.split("--address=", 1)[1].strip()
            if "session dir:" in line:
                session_dir = line.split("session dir:", 1)[1].strip()
                break
        if not head_address:
            proc.kill()
            raise RuntimeError("head did not report an address in time")
        state.update(
            head_pid=proc.pid, head_address=head_address,
            session_dir=session_dir,
        )
        _write_state(name, state)

    launcher = _make_launcher(cfg, head_address)
    # drop dead process-nodes from state so top-up relaunches them
    for nid, h in list(state["nodes"].items()):
        if h.get("kind") == "process" and not _alive(h.get("pid", -1)):
            del state["nodes"][nid]
    counts: Dict[str, int] = {}
    for h in state["nodes"].values():
        counts[h["node_type"]] = counts.get(h["node_type"], 0) + 1
    # monotonic: a replacement must NOT reuse a live node's id (dead
    # workers leave gaps in the numbering)
    seq = int(state.get("next_seq", 0))
    for nid in state["nodes"]:
        tail = nid.rsplit("-", 1)[-1]
        if tail.isdigit():
            seq = max(seq, int(tail))
    for tname, t in cfg["available_node_types"].items():
        for _ in range(max(0, t["min_workers"] - counts.get(tname, 0))):
            seq += 1
            node_id = f"{name}-{tname}-{seq}"
            res = dict(t["resources"])
            res["_node_type"] = tname  # consumed by the gcp adapter only
            handle = launcher.launch(node_id, res)
            handle["node_type"] = tname
            state["nodes"][node_id] = handle
    state["next_seq"] = seq
    _write_state(name, state)

    _wait_for_nodes(
        head_address,
        expected=len(state["nodes"]),
        timeout=wait_timeout,
    )
    return state


def _wait_for_nodes(head_address: str, expected: int, timeout: float) -> None:
    """Poll the head until `expected` agent nodes registered (reference:
    commands.py waiting on node provider + monitor convergence)."""
    import asyncio

    from .._private import protocol

    async def count_nodes() -> int:
        reader, writer = await protocol.open_stream(head_address)

        async def _handler(msg):
            return None

        conn = protocol.Connection(reader, writer, _handler).start()
        try:
            nodes = await conn.request({"t": "nodes"}, timeout=10)
            # the head machine itself is not a launched worker
            return sum(
                1 for n in nodes
                if n.get("alive") and n.get("node_id") != "node-head"
            )
        finally:
            await conn.close()

    deadline = time.time() + timeout
    while time.time() < deadline:
        try:
            if asyncio.run(count_nodes()) >= expected:
                return
        except Exception:
            pass
        time.sleep(0.5)
    raise TimeoutError(
        f"cluster did not reach {expected} registered workers in {timeout}s"
    )


def teardown_cluster(config_path_or_name: str) -> None:
    """`ray_tpu down`: terminate every launched worker, then the head
    (reference: commands.py:394 teardown_cluster)."""
    name = config_path_or_name
    if os.path.exists(config_path_or_name):
        name = load_cluster_config(config_path_or_name)["cluster_name"]
    state = _read_state(name)
    if state is None:
        raise RuntimeError(f"no state for cluster {name!r} (never launched?)")
    cfg = (
        load_cluster_config(state["config_path"])
        if state.get("config_path") and os.path.exists(state["config_path"])
        else {"provider": {"type": "process"}, "cluster_name": name}
    )
    launcher = _make_launcher(cfg, state.get("head_address", ""))
    for nid, handle in state.get("nodes", {}).items():
        try:
            launcher.terminate(handle)
        except Exception as e:  # noqa: BLE001 — best-effort teardown
            print(f"[down] terminating {nid}: {e!r}", file=sys.stderr)
    head_pid = state.get("head_pid")
    if head_pid and _alive(head_pid):
        try:
            os.killpg(head_pid, signal.SIGTERM)
        except OSError:
            try:
                os.kill(head_pid, signal.SIGTERM)
            except OSError:
                pass
        deadline = time.time() + 10
        while time.time() < deadline and _alive(head_pid):
            time.sleep(0.2)
        if _alive(head_pid):
            try:
                os.killpg(head_pid, signal.SIGKILL)
            except OSError:
                pass
    try:
        os.unlink(_state_path(name))
    except OSError:
        pass


def attach_address(config_path_or_name: str) -> str:
    """`ray_tpu attach`: the address a driver should init() against
    (reference: attach_cluster — ours prints the env instead of opening a
    remote shell, because the head is local)."""
    name = config_path_or_name
    if os.path.exists(config_path_or_name):
        name = load_cluster_config(config_path_or_name)["cluster_name"]
    state = _read_state(name)
    if state is None or not _alive(state.get("head_pid", -1)):
        raise RuntimeError(f"cluster {name!r} is not running")
    return state["head_address"]
