"""Node providers: how the autoscaler actually adds/removes capacity.

Reference parity: python/ray/autoscaler/node_provider.py (the interface
every cloud implements) + _private/fake_multi_node/node_provider.py:237
(FakeMultiNodeProvider — in-process nodes for tests) + the TPU wiring in
autoscaler/_private/gcp/node_provider.py (GCPTPU, SURVEY §5.5).
"""

from __future__ import annotations

import itertools
from typing import Callable, Dict, List, Optional

_fake_counter = itertools.count(1)


class NodeProvider:
    """Minimal provider surface (reference: node_provider.py)."""

    def create_node(self, node_type: str, resources: Dict[str, float]) -> str:
        raise NotImplementedError

    def terminate_node(self, node_id: str) -> None:
        raise NotImplementedError

    def non_terminated_nodes(self) -> List[str]:
        raise NotImplementedError

    def node_type_of(self, node_id: str) -> Optional[str]:
        raise NotImplementedError


class FakeMultiNodeProvider(NodeProvider):
    """Adds real in-process nodes to the running head (the moral equivalent
    of the reference's fake_multi_node provider: full scheduling fidelity,
    zero cloud)."""

    def __init__(self):
        self._nodes: Dict[str, str] = {}  # node_id -> node_type

    def create_node(self, node_type: str, resources: Dict[str, float]) -> str:
        from .._private.worker import global_worker

        node_id = f"autoscaled-{node_type}-{next(_fake_counter)}"
        global_worker.request(
            {
                "t": "add_node",
                "node_id": node_id,
                "resources": dict(resources),
                "labels": {"autoscaled": "1", "node_type": node_type},
            }
        )
        self._nodes[node_id] = node_type
        return node_id

    def terminate_node(self, node_id: str) -> None:
        from .._private.worker import global_worker

        if node_id in self._nodes:
            global_worker.request({"t": "remove_node", "node_id": node_id})
            del self._nodes[node_id]

    def non_terminated_nodes(self) -> List[str]:
        return list(self._nodes)

    def node_type_of(self, node_id: str) -> Optional[str]:
        return self._nodes.get(node_id)


# chips per host for the standard pod-slice accelerator types
TPU_SLICE_TOPOLOGIES: Dict[str, Dict[str, float]] = {
    "v4-8": {"TPU": 4.0, "CPU": 120.0},
    "v5e-4": {"TPU": 4.0, "CPU": 112.0},
    "v5e-8": {"TPU": 8.0, "CPU": 224.0},
    "v5p-8": {"TPU": 4.0, "CPU": 208.0},
}

# ray_tpu node-type name -> GCP acceleratorType string
GCP_ACCELERATOR_TYPES: Dict[str, str] = {
    "v4-8": "v4-8",
    "v5e-4": "v5litepod-4",
    "v5e-8": "v5litepod-8",
    "v5p-8": "v5p-8",
}


class GCPTPUApi:
    """Thin client for the Cloud TPU VM REST API (tpu.googleapis.com/v2),
    authenticated via the GCE metadata server. Injected into
    GCPTPUNodeProvider so tests substitute a fake (reference:
    gcp/node_provider.py:86-90 builds the discovery client the same way)."""

    def __init__(self, project: str, zone: str):
        self.base = (
            f"https://tpu.googleapis.com/v2/projects/{project}"
            f"/locations/{zone}/nodes"
        )
        self._token_value = ""
        self._token_expiry = 0.0

    def _token(self) -> str:
        import json
        import time
        import urllib.request

        if self._token_value and time.time() < self._token_expiry - 60:
            return self._token_value
        req = urllib.request.Request(
            "http://metadata.google.internal/computeMetadata/v1/instance/"
            "service-accounts/default/token",
            headers={"Metadata-Flavor": "Google"},
        )
        with urllib.request.urlopen(req, timeout=10) as resp:
            payload = json.loads(resp.read())
        self._token_value = payload["access_token"]
        self._token_expiry = time.time() + float(payload.get("expires_in", 300))
        return self._token_value

    def _call(self, method: str, url: str, body: Optional[dict] = None) -> dict:
        import json
        import urllib.request

        data = None if body is None else json.dumps(body).encode()
        req = urllib.request.Request(
            url,
            data=data,
            method=method,
            headers={
                "Authorization": f"Bearer {self._token()}",
                "Content-Type": "application/json",
            },
        )
        with urllib.request.urlopen(req, timeout=60) as resp:
            return json.loads(resp.read() or b"{}")

    def create(self, node_id: str, body: dict) -> dict:
        return self._call("POST", f"{self.base}?nodeId={node_id}", body)

    def delete(self, node_id: str) -> dict:
        return self._call("DELETE", f"{self.base}/{node_id}")

    def list(self) -> List[dict]:
        nodes: List[dict] = []
        token = ""
        while True:
            url = self.base + (f"?pageToken={token}" if token else "")
            page = self._call("GET", url)
            nodes.extend(page.get("nodes", []))
            token = page.get("nextPageToken", "")
            if not token:
                return nodes


class GCPTPUNodeProvider(NodeProvider):
    """Provisions TPU VM slices through the Cloud TPU API; each VM's startup
    script joins the running head as a node agent (`ray_tpu start
    --address`). Reference parity: autoscaler/_private/gcp/node_provider.py
    (GCPTPU :19, client wiring :86-90) — rebuilt on the v2 TPU VM API with
    the agent join baked into the startup script."""

    def __init__(
        self,
        head_address: str,
        project: str = "",
        zone: str = "",
        runtime_version: str = "tpu-ubuntu2204-base",
        name_prefix: str = "raytpu",
        api: Optional[GCPTPUApi] = None,
    ):
        if api is None:
            api = GCPTPUApi(project, zone)
        self.api = api
        self.head_address = head_address
        self.runtime_version = runtime_version
        self.name_prefix = name_prefix
        self._nodes: Dict[str, str] = {}
        self._absent_polls: Dict[str, int] = {}
        self._next_index = 1

    def _startup_script(self, node_id: str, num_tpus: float) -> str:
        return (
            "#!/bin/bash\n"
            "python3 -m ray_tpu.scripts start "
            f"--address {self.head_address} --node-id {node_id} "
            f"--num-tpus {int(num_tpus)} >/var/log/ray_tpu_agent.log 2>&1 &\n"
        )

    def create_node(self, node_type: str, resources: Dict[str, float]) -> str:
        accel = GCP_ACCELERATOR_TYPES.get(node_type, node_type)
        merged = dict(TPU_SLICE_TOPOLOGIES.get(node_type, {}))
        merged.update(resources)
        node_id = f"{self.name_prefix}-{node_type}-{self._next_index}"
        self._next_index += 1
        self.api.create(
            node_id,
            {
                "acceleratorType": accel,
                "runtimeVersion": self.runtime_version,
                "metadata": {
                    "startup-script": self._startup_script(
                        node_id, merged.get("TPU", 0)
                    ),
                },
                # the cluster label scopes adoption/termination to THIS
                # cluster's nodes — two clusters in one project/zone must
                # never adopt (and idle-terminate) each other's slices
                "labels": {
                    "ray-tpu-node-type": node_type,
                    "ray-tpu-cluster": self.name_prefix,
                },
            },
        )
        self._nodes[node_id] = node_type
        return node_id

    def terminate_node(self, node_id: str) -> None:
        if node_id in self._nodes:
            self.api.delete(node_id)
            del self._nodes[node_id]

    # TPU node states that mean "this capacity is gone" (the API keeps
    # reporting preempted/terminated nodes in list() until deleted)
    _TERMINAL_STATES = {"PREEMPTED", "TERMINATED", "STOPPED", "DELETING"}
    # a freshly created node may take a while to appear in list() (create
    # returns a long-running op) — only give up after this many consecutive
    # absent polls so we never double-launch against a provisioning slice
    _MAX_ABSENT_POLLS = 24  # ~2 min at the 5s autoscaler tick

    def non_terminated_nodes(self) -> List[str]:
        nodes = self.api.list()
        listed = {n["name"].rsplit("/", 1)[-1]: n.get("state", "") for n in nodes}
        # adopt cloud nodes carrying our label that we don't track (provider
        # restart, or a slow-provisioning node we'd given up on): orphans
        # would otherwise bill forever with no way to terminate them
        for n in nodes:
            nid = n["name"].rsplit("/", 1)[-1]
            labels = n.get("labels") or {}
            ntype = labels.get("ray-tpu-node-type")
            if (
                ntype
                and labels.get("ray-tpu-cluster") == self.name_prefix
                and nid not in self._nodes
                and n.get("state", "") not in self._TERMINAL_STATES
            ):
                self._nodes[nid] = ntype
                # keep fresh names ahead of adopted ones (a restarted
                # provider re-creating 'prefix-type-1' would hit 409)
                tail = nid.rsplit("-", 1)[-1]
                if tail.isdigit():
                    self._next_index = max(self._next_index, int(tail) + 1)
        for nid in list(self._nodes):
            state = listed.get(nid)
            if state is None:
                # not visible yet (or create failed): tolerate a bounded
                # provisioning window before declaring it lost
                self._absent_polls[nid] = self._absent_polls.get(nid, 0) + 1
                if self._absent_polls[nid] > self._MAX_ABSENT_POLLS:
                    del self._nodes[nid]
                    self._absent_polls.pop(nid, None)
            elif state in self._TERMINAL_STATES:
                # preempted/terminated: drop so the autoscaler launches a
                # replacement; best-effort delete of the husk
                try:
                    self.api.delete(nid)
                except Exception:
                    pass
                del self._nodes[nid]
                self._absent_polls.pop(nid, None)
            else:
                self._absent_polls.pop(nid, None)
        return list(self._nodes)

    def node_type_of(self, node_id: str) -> Optional[str]:
        return self._nodes.get(node_id)


class TPUPodProvider(NodeProvider):
    """TPU-VM provider shell: knows slice topologies (scale quanta) but
    delegates actual provisioning to an injected launcher — cloud APIs are
    deployment-specific (reference: gcp/node_provider.py GCPTPU wiring).

    launch_fn(node_type, resources) -> node_id;
    terminate_fn(node_id) -> None.
    """

    def __init__(
        self,
        launch_fn: Optional[Callable[[str, Dict[str, float]], str]] = None,
        terminate_fn: Optional[Callable[[str], None]] = None,
    ):
        self._launch_fn = launch_fn
        self._terminate_fn = terminate_fn
        self._nodes: Dict[str, str] = {}

    def create_node(self, node_type: str, resources: Dict[str, float]) -> str:
        if self._launch_fn is None:
            raise RuntimeError(
                "TPUPodProvider needs a launch_fn wired to your TPU VM "
                "provisioning API (gcloud/queued resources)"
            )
        merged = dict(TPU_SLICE_TOPOLOGIES.get(node_type, {}))
        merged.update(resources)
        node_id = self._launch_fn(node_type, merged)
        self._nodes[node_id] = node_type
        return node_id

    def terminate_node(self, node_id: str) -> None:
        if self._terminate_fn is not None and node_id in self._nodes:
            self._terminate_fn(node_id)
            del self._nodes[node_id]

    def non_terminated_nodes(self) -> List[str]:
        return list(self._nodes)

    def node_type_of(self, node_id: str) -> Optional[str]:
        return self._nodes.get(node_id)
