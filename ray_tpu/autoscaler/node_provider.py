"""Node providers: how the autoscaler actually adds/removes capacity.

Reference parity: python/ray/autoscaler/node_provider.py (the interface
every cloud implements) + _private/fake_multi_node/node_provider.py:237
(FakeMultiNodeProvider — in-process nodes for tests) + the TPU wiring in
autoscaler/_private/gcp/node_provider.py (GCPTPU, SURVEY §5.5).
"""

from __future__ import annotations

import itertools
from typing import Callable, Dict, List, Optional

_fake_counter = itertools.count(1)


class NodeProvider:
    """Minimal provider surface (reference: node_provider.py)."""

    def create_node(self, node_type: str, resources: Dict[str, float]) -> str:
        raise NotImplementedError

    def terminate_node(self, node_id: str) -> None:
        raise NotImplementedError

    def non_terminated_nodes(self) -> List[str]:
        raise NotImplementedError

    def node_type_of(self, node_id: str) -> Optional[str]:
        raise NotImplementedError


class FakeMultiNodeProvider(NodeProvider):
    """Adds real in-process nodes to the running head (the moral equivalent
    of the reference's fake_multi_node provider: full scheduling fidelity,
    zero cloud)."""

    def __init__(self):
        self._nodes: Dict[str, str] = {}  # node_id -> node_type

    def create_node(self, node_type: str, resources: Dict[str, float]) -> str:
        from .._private.worker import global_worker

        node_id = f"autoscaled-{node_type}-{next(_fake_counter)}"
        global_worker.request(
            {
                "t": "add_node",
                "node_id": node_id,
                "resources": dict(resources),
                "labels": {"autoscaled": "1", "node_type": node_type},
            }
        )
        self._nodes[node_id] = node_type
        return node_id

    def terminate_node(self, node_id: str) -> None:
        from .._private.worker import global_worker

        if node_id in self._nodes:
            global_worker.request({"t": "remove_node", "node_id": node_id})
            del self._nodes[node_id]

    def non_terminated_nodes(self) -> List[str]:
        return list(self._nodes)

    def node_type_of(self, node_id: str) -> Optional[str]:
        return self._nodes.get(node_id)


# chips per host for the standard pod-slice accelerator types
TPU_SLICE_TOPOLOGIES: Dict[str, Dict[str, float]] = {
    "v4-8": {"TPU": 4.0, "CPU": 120.0},
    "v5e-4": {"TPU": 4.0, "CPU": 112.0},
    "v5e-8": {"TPU": 8.0, "CPU": 224.0},
    "v5p-8": {"TPU": 4.0, "CPU": 208.0},
}


class TPUPodProvider(NodeProvider):
    """TPU-VM provider shell: knows slice topologies (scale quanta) but
    delegates actual provisioning to an injected launcher — cloud APIs are
    deployment-specific (reference: gcp/node_provider.py GCPTPU wiring).

    launch_fn(node_type, resources) -> node_id;
    terminate_fn(node_id) -> None.
    """

    def __init__(
        self,
        launch_fn: Optional[Callable[[str, Dict[str, float]], str]] = None,
        terminate_fn: Optional[Callable[[str], None]] = None,
    ):
        self._launch_fn = launch_fn
        self._terminate_fn = terminate_fn
        self._nodes: Dict[str, str] = {}

    def create_node(self, node_type: str, resources: Dict[str, float]) -> str:
        if self._launch_fn is None:
            raise RuntimeError(
                "TPUPodProvider needs a launch_fn wired to your TPU VM "
                "provisioning API (gcloud/queued resources)"
            )
        merged = dict(TPU_SLICE_TOPOLOGIES.get(node_type, {}))
        merged.update(resources)
        node_id = self._launch_fn(node_type, merged)
        self._nodes[node_id] = node_type
        return node_id

    def terminate_node(self, node_id: str) -> None:
        if self._terminate_fn is not None and node_id in self._nodes:
            self._terminate_fn(node_id)
            del self._nodes[node_id]

    def non_terminated_nodes(self) -> List[str]:
        return list(self._nodes)

    def node_type_of(self, node_id: str) -> Optional[str]:
        return self._nodes.get(node_id)
