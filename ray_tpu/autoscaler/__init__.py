"""Autoscaler: demand-driven node scale-up, idle-timeout scale-down.

Reference parity: python/ray/autoscaler/_private/autoscaler.py
(StandardAutoscaler.update :172,374), resource_demand_scheduler.py
(get_nodes_to_launch :101 bin-packing), node_provider plugins, and the
fake_multi_node provider used for tests (node_provider.py:237).

TPU-first: node types are pod-slice shaped — a "node" is a TPU VM host
carrying a fixed chip count, and slices scale in topology-legal quanta
(you can't add half a v5e-16), which the TPUPodProvider encodes.
"""

from .autoscaler import (  # noqa: F401
    Monitor,
    NodeTypeConfig,
    ResourceDemandScheduler,
    StandardAutoscaler,
)
from .node_provider import (  # noqa: F401
    FakeMultiNodeProvider,
    NodeProvider,
    TPUPodProvider,
)
