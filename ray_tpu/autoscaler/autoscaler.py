"""StandardAutoscaler: the update loop + bin-packing demand scheduler.

Reference parity: autoscaler/_private/autoscaler.py (StandardAutoscaler.
update :172,374 — read load, launch for unmet demand, terminate idle) and
resource_demand_scheduler.py (get_nodes_to_launch :101,169 — first-fit
bin-packing of pending demands onto hypothetical nodes).
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional

from .node_provider import NodeProvider


@dataclass
class NodeTypeConfig:
    """One scalable node shape (reference: available_node_types YAML)."""

    resources: Dict[str, float]
    min_workers: int = 0
    max_workers: int = 10


def _fits(avail: Dict[str, float], demand: Dict[str, float]) -> bool:
    return all(avail.get(k, 0.0) + 1e-9 >= v for k, v in demand.items())


def _take(avail: Dict[str, float], demand: Dict[str, float]) -> None:
    for k, v in demand.items():
        avail[k] = avail.get(k, 0.0) - v


class ResourceDemandScheduler:
    """Bin-pack unmet demands onto hypothetical new nodes
    (reference: resource_demand_scheduler.py:101 get_nodes_to_launch)."""

    def __init__(self, node_types: Dict[str, NodeTypeConfig]):
        self.node_types = node_types

    def get_nodes_to_launch(
        self,
        demands: List[Dict[str, float]],
        existing_available: List[Dict[str, float]],
        current_counts: Dict[str, int],
    ) -> Dict[str, int]:
        """demands: pending resource requests. existing_available: per-live-
        node available resources. current_counts: live nodes per type."""
        virtual = [dict(a) for a in existing_available]
        to_launch: Dict[str, int] = {}
        counts = dict(current_counts)
        # biggest demands first: classic first-fit-decreasing
        for demand in sorted(demands, key=lambda d: -sum(d.values())):
            placed = False
            for slot in virtual:
                if _fits(slot, demand):
                    _take(slot, demand)
                    placed = True
                    break
            if placed:
                continue
            # need a new node: smallest type that fits the demand
            candidates = [
                (sum(cfg.resources.values()), name, cfg)
                for name, cfg in self.node_types.items()
                if _fits(cfg.resources, demand)
                and counts.get(name, 0) < cfg.max_workers
            ]
            if not candidates:
                continue  # infeasible demand: nothing this cluster can do
            _, name, cfg = min(candidates)
            counts[name] = counts.get(name, 0) + 1
            to_launch[name] = to_launch.get(name, 0) + 1
            slot = dict(cfg.resources)
            _take(slot, demand)
            virtual.append(slot)
        return to_launch


class StandardAutoscaler:
    """Reads pending demand from the head, launches nodes through the
    provider, terminates nodes idle past the timeout."""

    def __init__(
        self,
        provider: NodeProvider,
        node_types: Dict[str, NodeTypeConfig],
        idle_timeout_s: float = 60.0,
        upscaling_speed: float = 1.0,
    ):
        self.provider = provider
        self.node_types = node_types
        self.scheduler = ResourceDemandScheduler(node_types)
        self.idle_timeout_s = idle_timeout_s
        self.upscaling_speed = max(upscaling_speed, 1e-3)
        self._idle_since: Dict[str, float] = {}

    def _request(self, msg):
        from .._private.worker import global_worker

        return global_worker.request(msg)

    def update(self) -> Dict[str, int]:
        """One reconciliation pass; returns {launched: n, terminated: n}."""
        load = self._request({"t": "pending_demands"})
        demands: List[Dict[str, float]] = list(load["demands"])
        for bundle_set in load["pg_bundles"]:
            demands.extend(bundle_set)
        nodes = self._request({"t": "nodes"})
        by_id = {n["node_id"]: n for n in nodes}

        managed = self.provider.non_terminated_nodes()
        counts: Dict[str, int] = {}
        for nid in managed:
            t = self.provider.node_type_of(nid)
            counts[t] = counts.get(t, 0) + 1

        launched = 0
        # min_workers floor
        for name, cfg in self.node_types.items():
            while counts.get(name, 0) < cfg.min_workers:
                self.provider.create_node(name, dict(cfg.resources))
                counts[name] = counts.get(name, 0) + 1
                launched += 1

        if demands:
            existing_avail = [
                dict(n.get("available", {})) for n in nodes if n.get("alive", True)
            ]
            plan = self.scheduler.get_nodes_to_launch(demands, existing_avail, counts)
            # one launch budget for the whole tick, shared across node types
            budget = max(1, int(self.upscaling_speed * max(1, len(managed))))
            for name, n in plan.items():
                for _ in range(n):
                    if budget <= 0:
                        break
                    self.provider.create_node(name, dict(self.node_types[name].resources))
                    counts[name] = counts.get(name, 0) + 1
                    launched += 1
                    budget -= 1

        # idle scale-down: a managed node is idle when its available ==
        # total resources AND it hosts no live actor/busy worker (a
        # zero-resource actor consumes nothing but must not be killed)
        workers = self._request({"t": "list_workers"})
        occupied = {
            w["node_id"]
            for w in workers
            if w["state"] in ("actor", "busy", "starting")
        }
        terminated = 0
        now = time.monotonic()
        for nid in list(managed):
            info = by_id.get(nid)
            if info is None or not info.get("alive", True):
                self._idle_since.pop(nid, None)
                continue
            total, avail = info.get("resources", {}), info.get("available", {})
            idle = nid not in occupied and all(
                abs(avail.get(k, 0.0) - v) < 1e-9 for k, v in total.items()
            )
            # a pending demand only protects nodes that could actually serve
            # it — an infeasible demand must not pin idle nodes forever
            wanted = any(_fits(total, d) for d in demands)
            if not idle or wanted:
                self._idle_since.pop(nid, None)
                continue
            first = self._idle_since.setdefault(nid, now)
            name = self.provider.node_type_of(nid)
            floor = self.node_types.get(name, NodeTypeConfig({})).min_workers
            if now - first >= self.idle_timeout_s and counts.get(name, 0) > floor:
                self.provider.terminate_node(nid)
                self._idle_since.pop(nid, None)
                counts[name] = counts.get(name, 0) - 1
                terminated += 1
        return {"launched": launched, "terminated": terminated}


class Monitor:
    """Background thread driving StandardAutoscaler.update (reference:
    monitor.py:126 — the head-side process hosting the autoscaler)."""

    def __init__(self, autoscaler: StandardAutoscaler, interval_s: float = 5.0):
        self.autoscaler = autoscaler
        self.interval_s = interval_s
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def start(self):
        self._thread = threading.Thread(target=self._run, daemon=True, name="autoscaler")
        self._thread.start()
        return self

    def _run(self):
        while not self._stop.is_set():
            try:
                self.autoscaler.update()
            except Exception:
                pass  # autoscaling must not kill the driver; retry next tick
            self._stop.wait(self.interval_s)

    def stop(self):
        self._stop.set()
        if self._thread:
            self._thread.join(timeout=5)
