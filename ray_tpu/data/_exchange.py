"""Two-stage exchanges: distributed sort and hash-partitioned groupby.

Reference parity: python/ray/data/_internal/planner/exchange/ (range/hash
partition task schedulers), sort.py (sample-based boundaries), and the
push-based shuffle idea (_internal/push_based_shuffle.py): map tasks emit
K partitions via num_returns=K, reduce tasks consume one partition from
every map task — the driver never touches block data, only refs.

Blocks are normalized to dict-of-numpy columns for the exchange; plain
row-list blocks (rows = dicts or scalars) convert on entry.
"""

from __future__ import annotations

import builtins
from typing import Any, Callable, Dict, List, Optional, Union

import numpy as np

Key = Union[str, None]


def to_columns(block) -> Dict[str, np.ndarray]:
    """Normalize any supported block to dict-of-numpy. Scalar rows become a
    single 'value' column (marker key so we can convert back)."""
    if isinstance(block, dict):
        return {k: np.asarray(v) for k, v in block.items()}
    try:
        import pyarrow as pa

        if isinstance(block, pa.Table):
            return {k: np.asarray(v) for k, v in block.to_pydict().items()}
    except ImportError:
        pass
    try:
        import pandas as pd

        if isinstance(block, pd.DataFrame):
            return {k: block[k].to_numpy() for k in block.columns}
    except ImportError:
        pass
    rows = list(block)
    if rows and isinstance(rows[0], dict):
        keys = list(rows[0])
        return {k: np.asarray([r[k] for r in rows]) for k in keys}
    return {"__value__": np.asarray(rows)}


def from_columns(cols: Dict[str, np.ndarray]):
    if set(cols) == {"__value__"}:
        return list(cols["__value__"])
    return cols


def _key_values(cols: Dict[str, np.ndarray], key: Key) -> np.ndarray:
    if key is None:
        if "__value__" in cols:
            return cols["__value__"]
        raise ValueError("sort/groupby on record blocks requires a key column")
    if key not in cols:
        raise KeyError(f"no column {key!r}; have {sorted(cols)}")
    return cols[key]


def _take(cols: Dict[str, np.ndarray], idx) -> Dict[str, np.ndarray]:
    return {k: v[idx] for k, v in cols.items()}


def cols_to_rows(cols: Dict[str, np.ndarray]) -> list:
    """Column dict -> row list ('__value__' marker unwraps to raw values;
    used when an exchange must fall back to row-list form)."""
    if not cols:
        return []
    if set(cols) == {"__value__"}:
        return list(cols["__value__"])
    keys = list(cols)
    n = len(cols[keys[0]])
    return [{k: cols[k][i] for k in keys} for i in builtins.range(n)]


def _concat(parts: List[Dict[str, np.ndarray]]) -> Dict[str, np.ndarray]:
    parts = [p for p in parts if p and len(next(iter(p.values())))]
    if not parts:
        return {}
    keys = list(parts[0])
    return {k: np.concatenate([p[k] for p in parts]) for k in keys}


# ---- task bodies (run remotely via ray_tpu.remote(...)) ----


def sample_keys(block, key: Key, n_samples: int = 64) -> np.ndarray:
    cols = to_columns(block)
    vals = _key_values(cols, key)
    if len(vals) == 0:
        return vals
    idx = np.linspace(0, len(vals) - 1, num=min(n_samples, len(vals))).astype(int)
    return np.sort(vals)[idx]


def range_partition(block, key: Key, boundaries: np.ndarray, num_parts: int):
    """Sort the block, split at boundaries into EXACTLY num_parts parts
    (the task is submitted with num_returns=num_parts; empty boundaries —
    e.g. an all-empty dataset — still must honor that contract)."""
    cols = to_columns(block)
    vals = _key_values(cols, key)
    order = np.argsort(vals, kind="stable")
    cols = _take(cols, order)
    vals = vals[order]
    cuts = np.searchsorted(vals, boundaries, side="right")
    parts = []
    prev = 0
    for c in list(cuts) + [len(vals)]:
        parts.append(_take(cols, slice(prev, c)))
        prev = c
    empty = {k: v[:0] for k, v in cols.items()}
    while len(parts) < num_parts:
        parts.append(dict(empty))
    parts = parts[:num_parts]
    return tuple(parts) if num_parts > 1 else parts[0]


def merge_sorted(key: Key, descending: bool, *parts):
    cols = _concat([p for p in parts])
    if not cols:
        return {}
    vals = _key_values(cols, key)
    order = np.argsort(vals, kind="stable")
    if descending:
        order = order[::-1]
    return from_columns(_take(cols, order))


def random_partition(block, k: int, seed):
    """Map stage of the distributed random_shuffle: scatter this block's
    rows into k partitions uniformly at random (one return per partition —
    push-based shuffle shape, _internal/push_based_shuffle.py).

    Row-list blocks (heterogeneous dicts, ragged values) scatter as LISTS —
    forcing them through to_columns would crash or mangle them; columnar
    blocks scatter as schema-preserving column dicts."""
    rng = np.random.default_rng(seed)
    if isinstance(block, (list, tuple)):
        rows = list(block)
        assignment = rng.integers(0, k, size=len(rows))
        parts: list = [
            [r for r, a in zip(rows, assignment) if a == i]
            for i in builtins.range(k)
        ]
    else:
        table = _as_arrow(block)
        if table is not None:
            # filter() copies compactly AND keeps arrow types (nullable
            # ints, timestamps) that a numpy round-trip would destroy
            import pyarrow as pa

            assignment = rng.integers(0, k, size=table.num_rows)
            parts = [
                table.filter(pa.array(assignment == i)) for i in builtins.range(k)
            ]
        else:
            cols = to_columns(block)
            n = len(next(iter(cols.values()))) if cols else 0
            assignment = rng.integers(0, k, size=n)
            parts = [_take(cols, assignment == i) for i in builtins.range(k)]
    return parts if k > 1 else parts[0]


def shuffle_merge(seed, *parts):
    """Reduce stage: concat this partition's pieces from every map task and
    permute locally — global uniformity comes from the random scatter.
    Empty partitions keep their SCHEMA (zero-row columns) so downstream
    block concat never sees a key-less block."""
    rng = np.random.default_rng(seed)
    tables = [_as_arrow(p) for p in parts]
    if parts and all(t is not None for t in tables):
        import pyarrow as pa

        merged_t = pa.concat_tables(tables)
        order = rng.permutation(merged_t.num_rows)
        return merged_t.take(pa.array(order))
    if any(t is not None for t in tables):
        parts = tuple(
            to_columns(p) if t is not None else p for p, t in zip(parts, tables)
        )
    if any(isinstance(p, list) for p in parts):
        # mixed-format partitions (e.g. a union of columnar and row-list
        # datasets): fall back to row form — dropping the columnar parts
        # would silently lose data
        rows = []
        for p in parts:
            if isinstance(p, list):
                rows.extend(p)
            elif p:
                rows.extend(cols_to_rows(p))
        order = rng.permutation(len(rows))
        return [rows[i] for i in order]
    merged = _concat(list(parts))
    if not merged:
        for p in parts:  # schema-preserving empty block
            if p:
                return from_columns({key: v[:0] for key, v in p.items()})
        return {}
    n = len(next(iter(merged.values())))
    order = rng.permutation(n)
    return from_columns(_take(merged, order))


def _as_arrow(block):
    try:
        import pyarrow as pa

        if isinstance(block, pa.Table):
            return block
    except ImportError:
        pass
    return None


def block_rows(block) -> int:
    """Row count (map stage of the exact repartition exchange)."""
    if isinstance(block, (list, tuple)):
        return len(block)
    table = _as_arrow(block)
    if table is not None:
        return table.num_rows
    cols = to_columns(block)
    return len(next(iter(cols.values()))) if cols else 0


def slice_partition(block, start: int, boundaries):
    """Map stage of repartition: this block covers global rows
    [start, start+n); emit its intersection with each output range
    [boundaries[j], boundaries[j+1]) — exact even splits without the
    driver ever touching rows. Row-list blocks (heterogeneous/ragged
    rows) slice as lists; arrow Tables stay arrow (types preserved)."""
    is_rows = isinstance(block, (list, tuple))
    table = None if is_rows else _as_arrow(block)
    if is_rows:
        data: Any = list(block)
        n = len(data)
    elif table is not None:
        n = table.num_rows
    else:
        data = to_columns(block)
        n = len(next(iter(data.values()))) if data else 0
    ranges = []
    for j in builtins.range(len(boundaries) - 1):
        lo = max(0, int(boundaries[j]) - start)
        hi = min(n, int(boundaries[j + 1]) - start)
        ranges.append((lo, max(lo, hi)))
    if is_rows:
        out: list = [data[lo:hi] for lo, hi in ranges]
    elif table is not None:
        # take() (not slice()): a zero-copy slice still PICKLES with the
        # full parent buffers, so shipping it through the object store
        # would copy the whole block per partition
        import pyarrow as pa

        out = [table.take(pa.array(np.arange(lo, hi))) for lo, hi in ranges]
    else:
        out = [{k: v[lo:hi] for k, v in data.items()} for lo, hi in ranges]
    return out if len(out) > 1 else out[0]


def concat_parts(*parts):
    """Reduce stage of repartition: order-preserving concat (row-list
    parts — possibly mixed with columnar ones — merge in row form;
    all-arrow parts stay arrow)."""
    tables = [_as_arrow(p) for p in parts]
    if parts and all(t is not None for t in tables):
        import pyarrow as pa

        return pa.concat_tables(tables)
    if any(t is not None for t in tables):
        # mixed arrow + other formats: normalize arrow down to columns
        parts = tuple(
            to_columns(p) if t is not None else p for p, t in zip(parts, tables)
        )
    if any(isinstance(p, list) for p in parts):
        rows: list = []
        for p in parts:
            if isinstance(p, list):
                rows.extend(p)
            elif p:
                rows.extend(cols_to_rows(p))
        return rows
    merged = _concat(list(parts))
    if not merged:
        for p in parts:
            if p:
                return from_columns({k: v[:0] for k, v in p.items()})
        return {}
    return from_columns(merged)


def hash_partition(block, key: Key, k: int):
    cols = to_columns(block)
    vals = _key_values(cols, key)
    if len(vals) == 0:
        empty = {c: v[:0] for c, v in cols.items()}
        return tuple(empty for _ in builtins.range(k)) if k > 1 else empty
    # stable hash per key value (python hash is salted per-process: NOT usable)
    if vals.dtype.kind in "iub":
        h = vals.astype(np.int64) % k
    elif vals.dtype.kind == "f":
        h = vals.astype(np.float64).view(np.int64) % k
    else:
        import hashlib

        h = np.asarray(
            [int(hashlib.md5(str(v).encode()).hexdigest()[:8], 16) % k for v in vals]
        )
    parts = tuple(_take(cols, h == i) for i in builtins.range(k))
    return parts if k > 1 else parts[0]


_AGGS: Dict[str, Callable] = {
    "count": lambda v, inv, n: np.bincount(inv, minlength=n),
    "sum": lambda v, inv, n: np.bincount(inv, weights=v.astype(np.float64), minlength=n),
    "min": lambda v, inv, n: _reduce_at(np.minimum, v, inv, n),
    "max": lambda v, inv, n: _reduce_at(np.maximum, v, inv, n),
}


def _reduce_at(ufunc, v, inv, n):
    init = np.full(n, np.inf if ufunc is np.minimum else -np.inf, dtype=np.float64)
    getattr(ufunc, "at")(init, inv, v.astype(np.float64))
    return init


def group_aggregate(key: Key, specs: List[tuple], *parts):
    """specs: [(col, agg_name, out_name)]. Returns dict block with the key
    column + one column per spec. Runs on ONE hash partition."""
    cols = _concat([p for p in parts])
    if not cols:
        return {}
    vals = _key_values(cols, key)
    uniq, inv = np.unique(vals, return_inverse=True)
    n = len(uniq)
    out: Dict[str, np.ndarray] = {key if key is not None else "__value__": uniq}
    for col, agg, out_name in specs:
        v = cols[col] if col is not None else np.ones(len(vals))
        if agg in ("count", "sum", "min", "max"):
            out[out_name] = _AGGS[agg](v, inv, n)
            if agg == "count":
                out[out_name] = out[out_name].astype(np.int64)
        elif agg == "mean":
            s = np.bincount(inv, weights=v.astype(np.float64), minlength=n)
            c = np.bincount(inv, minlength=n)
            out[out_name] = s / np.maximum(c, 1)
        elif agg == "std":
            s = np.bincount(inv, weights=v.astype(np.float64), minlength=n)
            s2 = np.bincount(inv, weights=v.astype(np.float64) ** 2, minlength=n)
            c = np.maximum(np.bincount(inv, minlength=n), 1)
            var = np.maximum(s2 / c - (s / c) ** 2, 0.0)
            # sample std (ddof=1), the reference default
            out[out_name] = np.sqrt(var * c / np.maximum(c - 1, 1))
        else:
            raise ValueError(f"unknown aggregation {agg!r}")
    return out


def group_map(key: Key, fn: Callable, *parts):
    """map_groups: apply fn to each group's sub-block (dict-of-numpy),
    concatenate the outputs (reference: GroupedData.map_groups)."""
    cols = _concat([p for p in parts])
    if not cols:
        return {}
    vals = _key_values(cols, key)
    order = np.argsort(vals, kind="stable")
    cols = _take(cols, order)
    vals = vals[order]
    uniq, starts = np.unique(vals, return_index=True)
    outs = []
    bounds = list(starts) + [len(vals)]
    for i in builtins.range(len(uniq)):
        sub = _take(cols, slice(bounds[i], bounds[i + 1]))
        outs.append(to_columns(fn(from_columns(sub))))
    return from_columns(_concat(outs))
