"""Aggregation descriptors (reference: python/ray/data/aggregate.py —
AggregateFn + the Count/Sum/Min/Max/Mean/Std/AbsMax convenience classes,
consumed by Dataset.aggregate and GroupedData.aggregate).

Two tiers:
  - Named classes (Count/Sum/Min/Max/Mean/Std/AbsMax) compile to the
    exchange kernel's native spec tuples — the two-stage distributed group aggregate stays fully
    vectorized.
  - AggregateFn (init/accumulate_row/merge/finalize) is the escape hatch
    for arbitrary reductions; it rides the group_map path (the fold runs
    per group on the reduce side).
"""

from __future__ import annotations

from typing import Any, Callable, Optional


def _numpy_aggregate(kind: str, values) -> Any:
    """One group's native aggregation over a value sequence (the mixed
    AggregateFn+native fold path; the pure-native path stays on the
    vectorized exchange kernel)."""
    import numpy as np

    if kind == "count":
        return len(values)
    v = np.asarray(values, dtype=np.float64)
    if kind == "std":
        # match the exchange kernel's singleton clamp (std of one value is
        # 0.0, not NaN) so the answer doesn't depend on which path ran
        return float(np.std(v, ddof=1)) if v.size > 1 else 0.0
    return {"sum": np.sum, "min": np.min, "max": np.max, "mean": np.mean}[kind](v)


class AggregateFn:
    """User-defined aggregation (reference: aggregate.py AggregateFn).

    init(key) -> accumulator; accumulate_row(acc, row) -> acc;
    merge(acc1, acc2) -> acc; finalize(acc) -> result.
    """

    def __init__(
        self,
        init: Callable[[Any], Any],
        accumulate_row: Callable[[Any, Any], Any],
        merge: Callable[[Any, Any], Any],
        finalize: Optional[Callable[[Any], Any]] = None,
        name: str = "aggregate",
    ):
        self.init = init
        self.accumulate_row = accumulate_row
        self.merge = merge
        self.finalize = finalize or (lambda a: a)
        self.name = name

    def _fold_rows(self, key_value, rows):
        acc = self.init(key_value)
        for row in rows:
            acc = self.accumulate_row(acc, row)
        return self.finalize(acc)


class _NativeAgg:
    """Base for aggregations the exchange kernel computes vectorized."""

    kind: str = ""

    def __init__(self, on: Optional[str] = None, alias_name: Optional[str] = None):
        self.on = on
        self.name = alias_name or (f"{self.kind}({on})" if on else f"{self.kind}()")

    def _spec(self):
        return (self.on, self.kind, self.name)


class Count(_NativeAgg):
    kind = "count"


class Sum(_NativeAgg):
    kind = "sum"

    def __init__(self, on: str, alias_name: Optional[str] = None):
        super().__init__(on, alias_name)


class Min(_NativeAgg):
    kind = "min"

    def __init__(self, on: str, alias_name: Optional[str] = None):
        super().__init__(on, alias_name)


class Max(_NativeAgg):
    kind = "max"

    def __init__(self, on: str, alias_name: Optional[str] = None):
        super().__init__(on, alias_name)


class Mean(_NativeAgg):
    kind = "mean"

    def __init__(self, on: str, alias_name: Optional[str] = None):
        super().__init__(on, alias_name)


class Std(_NativeAgg):
    kind = "std"

    def __init__(self, on: str, alias_name: Optional[str] = None):
        super().__init__(on, alias_name)


class AbsMax(AggregateFn):
    """max(|x|) — no native kernel kind; rides the generic fold."""

    def __init__(self, on: str, alias_name: Optional[str] = None):
        super().__init__(
            init=lambda k: 0.0,
            accumulate_row=lambda a, row: max(a, abs(row[on])),
            merge=lambda a, b: max(a, b),
            name=alias_name or f"abs_max({on})",
        )
