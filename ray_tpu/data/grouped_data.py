"""GroupedData: the result of Dataset.groupby (reference:
python/ray/data/grouped_data.py — count/sum/mean/min/max/std/map_groups
over hash-partitioned groups)."""

from __future__ import annotations

from typing import Callable, List, Optional

from . import _exchange


class GroupedData:
    def __init__(self, dataset, key: Optional[str]):
        self._ds = dataset
        self._key = key

    def _run(self, specs: List[tuple]):
        return self._ds._group_exchange(
            self._key, _exchange.group_aggregate, (self._key, list(specs))
        )

    def count(self):
        return self._run([(None, "count", "count()")])

    def sum(self, on: str):
        return self._run([(on, "sum", f"sum({on})")])

    def mean(self, on: str):
        return self._run([(on, "mean", f"mean({on})")])

    def min(self, on: str):
        return self._run([(on, "min", f"min({on})")])

    def max(self, on: str):
        return self._run([(on, "max", f"max({on})")])

    def std(self, on: str):
        return self._run([(on, "std", f"std({on})")])

    def aggregate(self, *aggs, **named_specs: tuple):
        """Two call shapes (reference: grouped_data.py aggregate):
          aggregate(Sum("x"), Count())          — aggregate descriptors
          aggregate(total=("x", "sum"))          — named spec tuples
        Native descriptors compile to vectorized exchange specs; an
        AggregateFn folds per group on the reduce side via map_groups."""
        from .aggregate import AggregateFn, _NativeAgg

        fn_aggs = [a for a in aggs if isinstance(a, AggregateFn)]
        native = [a for a in aggs if isinstance(a, _NativeAgg)]
        bad = [a for a in aggs if not isinstance(a, (AggregateFn, _NativeAgg))]
        if bad:
            raise TypeError(f"not aggregation descriptors: {bad}")
        out_names = [a.name for a in (*fn_aggs, *native)] + list(named_specs)
        if self._key is not None and self._key in out_names:
            raise ValueError(
                f"aggregation name {self._key!r} collides with the groupby key"
            )
        if len(set(out_names)) != len(out_names):
            raise ValueError(f"duplicate aggregation names: {sorted(out_names)}")
        if fn_aggs:
            # AggregateFns fold per group via map_groups; native descriptors
            # in the SAME call compute inside that fold too (numpy over the
            # group's columns) so mixing works — the fully-native call below
            # keeps the vectorized two-stage exchange path
            key = self._key
            native_np = {a.name: (a.on, a.kind) for a in native}
            # named spec tuples compute in the fold too when mixed with
            # AggregateFns (they must not silently vanish)
            native_np.update({out: (col, agg) for out, (col, agg) in named_specs.items()})

            def _fold(group_block):
                from .aggregate import _numpy_aggregate
                from .dataset import _block_to_rows

                rows = list(_block_to_rows(group_block))
                kv = rows[0][key] if (key is not None and rows) else None
                out = {key: kv} if key is not None else {}
                for name, (on, kind) in native_np.items():
                    out[name] = _numpy_aggregate(kind, [r[on] for r in rows] if on else rows)
                for a in fn_aggs:
                    out[a.name] = a._fold_rows(kv, rows)
                return [out]

            return self.map_groups(_fold)
        specs = [a._spec() for a in native]
        specs += [(col, agg, out) for out, (col, agg) in named_specs.items()]
        return self._run(specs)

    def map_groups(self, fn: Callable):
        return self._ds._group_exchange(self._key, _exchange.group_map, (self._key, fn))
