"""GroupedData: the result of Dataset.groupby (reference:
python/ray/data/grouped_data.py — count/sum/mean/min/max/std/map_groups
over hash-partitioned groups)."""

from __future__ import annotations

from typing import Callable, List, Optional

from . import _exchange


class GroupedData:
    def __init__(self, dataset, key: Optional[str]):
        self._ds = dataset
        self._key = key

    def _run(self, specs: List[tuple]):
        return self._ds._group_exchange(
            self._key, _exchange.group_aggregate, (self._key, list(specs))
        )

    def count(self):
        return self._run([(None, "count", "count()")])

    def sum(self, on: str):
        return self._run([(on, "sum", f"sum({on})")])

    def mean(self, on: str):
        return self._run([(on, "mean", f"mean({on})")])

    def min(self, on: str):
        return self._run([(on, "min", f"min({on})")])

    def max(self, on: str):
        return self._run([(on, "max", f"max({on})")])

    def std(self, on: str):
        return self._run([(on, "std", f"std({on})")])

    def aggregate(self, **named_specs: tuple):
        """aggregate(total=("x", "sum"), n=(None, "count"))"""
        specs = [(col, agg, out) for out, (col, agg) in named_specs.items()]
        return self._run(specs)

    def map_groups(self, fn: Callable):
        return self._ds._group_exchange(self._key, _exchange.group_map, (self._key, fn))
