"""Additional datasources: TFRecord, SQL, WebDataset, binary files, images.

Reference parity: python/ray/data/datasource/ (tfrecords_datasource.py,
sql_datasource.py, webdataset_datasource.py, binary_datasource.py,
image_datasource.py). The reference routes these through a Datasource
plugin interface; ray_tpu keeps the same user-facing read_*/write_* surface
over its block model (one lazily-read file/shard/query per block, so reads
parallelize across the task pool exactly like read_parquet).
"""

from __future__ import annotations

from typing import Any, Callable, Dict, List, Optional

import numpy as np

from .dataset import Dataset, _block_to_rows, _file_blocks

# --------------------------------------------------------------------------
# TFRecord (tfrecords_datasource.py)
# --------------------------------------------------------------------------


def read_tfrecords(paths, *, verify_crc: bool = False) -> Dataset:
    """Rows are dicts decoded from tf.train.Example (bytes/float/int64
    features; singleton lists unwrapped, like the reference)."""
    from . import _tfrecord

    def read_one(p):
        return [
            _tfrecord.parse_example(rec)
            for rec in _tfrecord.read_records(p, verify_crc=verify_crc)
        ]

    return _file_blocks(paths, read_one)


def _write_tfrecords(ds: Dataset, path: str) -> List[str]:
    from . import _tfrecord

    def write_one(block, fp):
        _tfrecord.write_records(
            fp, (_tfrecord.build_example(row) for row in _block_to_rows(block))
        )

    return ds._write_files(path, "tfrecords", write_one)


# --------------------------------------------------------------------------
# SQL (sql_datasource.py) — any DB-API 2.0 connection factory
# --------------------------------------------------------------------------


_PARAM_PLACEHOLDERS = {"qmark": "?", "format": "%s", "pyformat": "%s", "numeric": ":1"}


def _placeholder(paramstyle: str) -> str:
    try:
        return _PARAM_PLACEHOLDERS[paramstyle]
    except KeyError:
        raise ValueError(
            f"unsupported DB-API paramstyle {paramstyle!r} "
            f"(supported: {sorted(_PARAM_PLACEHOLDERS)})"
        ) from None


def read_sql(
    sql: str,
    connection_factory: Callable[[], Any],
    *,
    shard_keys: Optional[List[Any]] = None,
    shard_column: Optional[str] = None,
    paramstyle: str = "qmark",
) -> Dataset:
    """Execute `sql` against a DB-API connection; rows become dict blocks.

    With shard_column + shard_keys, one block is read per key by wrapping
    the query in a subselect (`SELECT * FROM (<sql>) sub WHERE col = ?`),
    so queries that already contain WHERE clauses shard correctly (parallel
    reads, like the reference's sharded read_sql); otherwise the whole
    result is one block. `paramstyle` matches the driver's DB-API
    paramstyle ("qmark" for sqlite3, "format"/"pyformat" for
    postgres/mysql drivers).
    """
    ph = _placeholder(paramstyle)

    def read_shard(key=None):
        conn = connection_factory()
        try:
            cur = conn.cursor()
            if key is None:
                cur.execute(sql)
            else:
                sharded = (
                    f"SELECT * FROM ({sql}) __ray_tpu_shard "
                    f"WHERE {shard_column} = {ph}"
                )
                cur.execute(sharded, (key,))
            names = [d[0] for d in cur.description]
            rows = cur.fetchall()
            return [dict(zip(names, r)) for r in rows]
        finally:
            conn.close()

    if shard_keys is not None:
        if not shard_column:
            raise ValueError("shard_keys requires shard_column")
        return Dataset([lambda k=k: read_shard(k) for k in shard_keys])
    return Dataset([read_shard])


def _write_sql(
    ds: Dataset,
    table: str,
    connection_factory: Callable[[], Any],
    *,
    paramstyle: str = "qmark",
    create_table: bool = True,
) -> int:
    """Insert every row into `table`. With create_table (default), a
    typeless `CREATE TABLE IF NOT EXISTS` is issued from the first row's
    keys — that shorthand is SQLite-only, so pre-create the table (and pass
    create_table=False) on other backends. Returns the row count."""
    ph = _placeholder(paramstyle)
    total = 0
    conn = connection_factory()
    try:
        cur = conn.cursor()
        created = not create_table
        for block in ds._iter_computed_blocks():
            for row in _block_to_rows(block):
                if not isinstance(row, dict):
                    row = {"value": row}
                row = {
                    k: (v.item() if hasattr(v, "item") else v) for k, v in row.items()
                }
                if not created:
                    cols = ", ".join(row.keys())
                    cur.execute(f"CREATE TABLE IF NOT EXISTS {table} ({cols})")
                    created = True
                phs = ", ".join(ph for _ in row)
                cur.execute(
                    f"INSERT INTO {table} ({', '.join(row.keys())}) VALUES ({phs})",
                    tuple(row.values()),
                )
                total += 1
        conn.commit()
    finally:
        conn.close()
    return total


# --------------------------------------------------------------------------
# WebDataset (webdataset_datasource.py) — tar shards of per-sample files
# --------------------------------------------------------------------------


def _decode_wds_member(ext: str, data: bytes) -> Any:
    # type decisions use the LAST extension component ("img.npy" -> "npy",
    # the webdataset convention for dotted member names)
    kind = ext.rsplit(".", 1)[-1]
    if kind in ("txt", "text"):
        return data.decode()
    if kind == "json":
        import json

        return json.loads(data)
    if kind == "cls":
        return int(data.decode())
    if kind == "npy":
        import io

        return np.load(io.BytesIO(data))
    return data  # images etc. stay bytes; decode in map_batches


def read_webdataset(paths) -> Dataset:
    """Each tar shard is one block; members sharing a basename stem form one
    sample row {"__key__": stem, "<ext>": decoded}."""
    import tarfile

    def read_one(p):
        samples: Dict[str, Dict[str, Any]] = {}
        order: List[str] = []
        with tarfile.open(p) as tf:
            for member in tf:
                if not member.isfile():
                    continue
                name = member.name
                stem, _, ext = name.partition(".")
                if stem not in samples:
                    samples[stem] = {"__key__": stem}
                    order.append(stem)
                data = tf.extractfile(member).read()
                samples[stem][ext] = _decode_wds_member(ext, data)
        return [samples[k] for k in order]

    return _file_blocks(paths, read_one)


def _encode_wds_member(value: Any) -> bytes:
    if isinstance(value, bytes):
        return value
    if isinstance(value, str):
        return value.encode()
    if isinstance(value, np.ndarray):
        import io

        buf = io.BytesIO()
        np.save(buf, value)
        return buf.getvalue()
    import json

    return json.dumps(value).encode()


def _write_webdataset(ds: Dataset, path: str) -> List[str]:
    import io
    import tarfile

    def write_one(block, fp):
        with tarfile.open(fp, "w") as tf:
            for i, row in enumerate(_block_to_rows(block)):
                if not isinstance(row, dict):
                    raise TypeError("write_webdataset needs dict rows")
                key = str(row.get("__key__", f"{i:06d}"))
                for col, value in row.items():
                    if col == "__key__":
                        continue
                    suffix = col
                    if isinstance(value, np.ndarray) and not suffix.endswith("npy"):
                        suffix = f"{suffix}.npy"  # read side np.load()s .npy
                    data = _encode_wds_member(value)
                    info = tarfile.TarInfo(name=f"{key}.{suffix}")
                    info.size = len(data)
                    tf.addfile(info, io.BytesIO(data))

    return ds._write_files(path, "tar", write_one)


# --------------------------------------------------------------------------
# binary + image (binary_datasource.py, image_datasource.py)
# --------------------------------------------------------------------------


def read_binary_files(paths, *, include_paths: bool = False) -> Dataset:
    def read_one(p):
        with open(p, "rb") as f:
            data = f.read()
        row: Dict[str, Any] = {"bytes": data}
        if include_paths:
            row["path"] = p
        return [row]

    return _file_blocks(paths, read_one)


def read_images(paths, *, size: Optional[tuple] = None, mode: Optional[str] = None) -> Dataset:
    """Decoded images as {"image": HxWxC uint8 array}; requires pillow
    (gated import, like the reference's ImageDatasource)."""

    def read_one(p):
        try:
            from PIL import Image
        except ImportError as e:  # pragma: no cover
            raise ImportError("read_images requires pillow") from e
        img = Image.open(p)
        if mode is not None:
            img = img.convert(mode)
        if size is not None:
            img = img.resize(size)
        return [{"image": np.asarray(img)}]

    return _file_blocks(paths, read_one)
