"""Lazy distributed Dataset.

Design (reference: python/ray/data/dataset.py:168 + _internal/execution):
  - a Dataset is an immutable logical plan: a block source + a chain of ops
    (map_batches / filter / repartition / ...). Nothing runs until iteration
    or materialize().
  - blocks are plain Python payloads (dict-of-numpy "batch" format, lists of
    rows, or pyarrow Tables) stored in the object store; transforms run as
    ray_tpu tasks over blocks with windowed streaming (submit-ahead window =
    backpressure, the moral equivalent of StreamingExecutor's resource-aware
    pull loop).
  - per-worker shards come from split_at(rank, n) — contiguous block ranges,
    matching DataConfig's streaming split (train/_internal/dataset_spec.py).
"""

from __future__ import annotations

import builtins
import itertools
import time
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, Iterator, List, Optional, Sequence, Union

import numpy as np

Batch = Union[Dict[str, np.ndarray], "pd.DataFrame", List[Any]]  # noqa: F821


# --------------------------------------------------------------------------
# block helpers
# --------------------------------------------------------------------------


def _block_num_rows(block) -> int:
    if isinstance(block, dict):
        for v in block.values():
            return len(v)
        return 0
    try:
        import pyarrow as pa

        if isinstance(block, pa.Table):
            return block.num_rows
    except ImportError:
        pass
    return len(block)


def _block_slice(block, start: int, end: int):
    if isinstance(block, dict):
        return {k: v[start:end] for k, v in block.items()}
    try:
        import pyarrow as pa

        if isinstance(block, pa.Table):
            return block.slice(start, end - start)
    except ImportError:
        pass
    return block[start:end]


def _block_concat(blocks: List[Any]):
    first = blocks[0]
    if isinstance(first, dict):
        return {k: np.concatenate([b[k] for b in blocks]) for k in first}
    try:
        import pyarrow as pa

        if isinstance(first, pa.Table):
            return pa.concat_tables(blocks)
    except ImportError:
        pass
    out = []
    for b in blocks:
        out.extend(b)
    return out


def _block_take(block, indices):
    """Row gather preserving block format."""
    if isinstance(block, dict):
        return {k: np.asarray(v)[indices] for k, v in block.items()}
    try:
        import pyarrow as pa

        if isinstance(block, pa.Table):
            return block.take(indices)
    except ImportError:
        pass
    return [block[i] for i in indices]


def _block_to_rows(block) -> Iterator[Any]:
    if isinstance(block, dict):
        keys = list(block)
        n = _block_num_rows(block)
        for i in builtins.range(n):
            yield {k: block[k][i] for k in keys}
        return
    try:
        import pyarrow as pa

        if isinstance(block, pa.Table):
            yield from block.to_pylist()
            return
    except ImportError:
        pass
    yield from block


# --------------------------------------------------------------------------
# logical ops
# --------------------------------------------------------------------------


@dataclass
class _Op:
    kind: str  # map_batches | map | filter | flat_map
    fn: Callable
    batch_size: Optional[int] = None
    fn_kwargs: Dict[str, Any] = field(default_factory=dict)
    # "tasks" (stateless, one task per block) or "actors" (a pool of
    # stateful workers; callable classes are constructed once per worker —
    # reference: _internal/execution/operators/actor_pool_map_operator.py)
    compute: str = "tasks"
    num_actors: int = 2
    fn_constructor_args: tuple = ()
    # planner marker: ("select", cols) | ("filter_expr", Expr) — structured
    # ops the pushdown rule may fold into the datasource read (_plan.py)
    meta: Any = None


def _op_callable(op: _Op, cache: Optional[Dict[int, Callable]]) -> Callable:
    """Resolve the op's fn: callable classes are instantiated ONCE per
    cache (the actor-pool contract: expensive state like models or
    tokenizers loads once per worker — _MapWorker passes its own long-lived
    cache; the stateless task path rebuilds per task)."""
    fn = op.fn
    if isinstance(fn, type):
        if cache is None:
            return fn(*op.fn_constructor_args)
        key = id(op)
        inst = cache.get(key)
        if inst is None:
            inst = cache[key] = fn(*op.fn_constructor_args)
        return inst
    return fn


def _apply_ops(block, ops: List[_Op], cache: Optional[Dict[int, Callable]] = None):
    """Runs inside a task/actor: fold the op chain over one block."""
    for op in ops:
        if op.kind == "map_batches":
            fn = _op_callable(op, cache)
            if op.batch_size is None:
                block = fn(block, **op.fn_kwargs)
            else:
                n = _block_num_rows(block)
                outs = [
                    fn(_block_slice(block, s, min(s + op.batch_size, n)), **op.fn_kwargs)
                    for s in builtins.range(0, n, op.batch_size)
                ]
                block = _block_concat(outs) if outs else block
        elif op.kind == "map":
            block = [op.fn(row) for row in _block_to_rows(block)]
        elif op.kind == "filter":
            block = [row for row in _block_to_rows(block) if op.fn(row)]
        elif op.kind == "filter_batch":
            # vectorized expression filter (expressions.Expr.mask)
            from . import _exchange

            mask = np.asarray(op.fn.mask(_exchange.to_columns(block)), bool)
            block = _block_take(block, np.nonzero(mask)[0])
        elif op.kind == "flat_map":
            out: List[Any] = []
            for row in _block_to_rows(block):
                out.extend(op.fn(row))
            block = out
        elif op.kind == "row_chain":
            # fused map/filter/flat_map pipeline (_plan.fuse_row_ops):
            # one pass per block instead of one intermediate list per op
            block = op.fn(_block_to_rows(block))
        elif op.kind == "limit":
            # per-block cap pushed down by _plan.push_limit; the global
            # cross-block limit is enforced by the consumer
            n = op.batch_size or 0
            if _block_num_rows(block) > n:
                block = _block_slice(block, 0, n)
        else:
            raise ValueError(f"unknown op {op.kind}")
    return block


def _execute_block(block_fn, ops: List[_Op]):
    """Runs inside a task: the source read (block_fn) AND the op chain both
    execute off-driver so I/O parallelizes and the driver stays off the data
    path (reference: plan_read_op.py fuses read+transform into one task)."""
    return _apply_ops(block_fn(), ops)


def _execute_block_stats(block_fn, ops: List[_Op], cache=None):
    """_execute_block variant for the streaming iterator: returns
    (block, per-op stat rows) — the executing side times its own read + ops
    and ships the measurements back with the block (_stats.py). Used by
    task execution, pool actors, and the local (driver-process) path."""
    from . import _stats

    t0 = time.perf_counter()
    block = block_fn()
    read = _stats.read_stat(time.perf_counter() - t0, block)
    block, rows = _stats.timed_apply(_apply_ops, block, ops, cache)
    return block, [read] + rows


class _MapWorker:
    """Stateful pool worker for compute="actors" map operators: the op
    chain (and any callable-class state) lives for the actor's lifetime
    (reference: actor_pool_map_operator.py's _MapWorker)."""

    def __init__(self, ops: List[_Op]):
        self._ops = ops
        self._cache: Dict[int, Callable] = {}

    def run(self, block_fn):
        return _execute_block_stats(block_fn, self._ops, self._cache)


def _block_size_bytes(block) -> int:
    """Approximate in-memory size of a block (backpressure accounting)."""
    if isinstance(block, np.ndarray):
        return int(block.nbytes)
    if isinstance(block, dict):
        return sum(_block_size_bytes(v) for v in block.values())
    try:
        import pyarrow as pa

        if isinstance(block, pa.Table):
            return int(block.nbytes)
    except ImportError:
        pass
    if isinstance(block, (list, tuple)):
        return 64 * len(block)  # rough row-overhead guess
    return 1024


class Dataset:
    def __init__(
        self,
        block_fns: List[Callable[[], Any]],
        ops: Optional[List[_Op]] = None,
        read_meta: Optional[Dict[str, Any]] = None,
    ):
        # block_fns: zero-arg callables producing the source blocks (lazy read)
        self._block_fns = block_fns
        self._ops = ops or []
        # pushdown-capable source descriptor ({"kind", "paths", ...});
        # set by read_parquet so _plan.pushdown_reads can rebuild reads
        # with columns=/filters= (reference: logical-plan read pushdown)
        self._read_meta = read_meta

    # ---- metadata ----

    def num_blocks(self) -> int:
        return len(self._block_fns)

    def __repr__(self):
        return f"Dataset(num_blocks={self.num_blocks()}, ops={[o.kind for o in self._ops]})"

    # ---- transforms (lazy) ----

    def _with_op(self, op: _Op) -> "Dataset":
        return Dataset(self._block_fns, self._ops + [op], read_meta=self._read_meta)

    def map_batches(
        self,
        fn: Callable[[Batch], Batch],
        *,
        batch_size: Optional[int] = None,
        fn_kwargs: Optional[Dict[str, Any]] = None,
        compute: str = "tasks",
        num_actors: int = 2,
        fn_constructor_args: tuple = (),
        **_,
    ) -> "Dataset":
        """compute="actors" runs this op (and the rest of the chain) on a
        pool of stateful worker actors; pass a callable CLASS as `fn` to
        construct per-worker state once (reference: actor_pool_map_operator).
        """
        if isinstance(fn, type) and compute != "actors":
            raise ValueError("callable-class map_batches requires compute='actors'")
        return self._with_op(
            _Op(
                "map_batches", fn, batch_size, fn_kwargs or {},
                compute=compute, num_actors=num_actors,
                fn_constructor_args=tuple(fn_constructor_args),
            )
        )

    def map(self, fn: Callable[[Any], Any]) -> "Dataset":
        return self._with_op(_Op("map", fn))

    def filter(self, fn) -> "Dataset":
        """Row predicate (opaque callable) or column Expression.

        Expressions (`from ray_tpu.data import col; ds.filter(col("x") > 5)`)
        evaluate vectorized in column space AND are visible to the planner:
        over a parquet read they push down into the scan itself
        (_plan.pushdown_reads), so pruned row groups never leave disk."""
        from .expressions import Expr

        if isinstance(fn, Expr):
            return self._with_op(
                _Op("filter_batch", fn, meta=("filter_expr", fn))
            )
        return self._with_op(_Op("filter", fn))

    def flat_map(self, fn: Callable[[Any], Sequence[Any]]) -> "Dataset":
        return self._with_op(_Op("flat_map", fn))

    def _slice_exchange(self, make_boundaries) -> List[Callable[[], Any]]:
        """Shared scaffolding of the exact-slice exchanges (repartition,
        train_test_split): count tasks yield global offsets,
        `make_boundaries(total) -> [b0..bk]` picks the output ranges, map
        tasks emit each block's intersection with every range
        (num_returns=K), concat tasks assemble outputs — order preserved,
        the driver holds only counts and refs. Returns K block thunks."""
        from . import _exchange

        import ray_tpu

        blocks, remote = self._exchange_tasks()
        if not blocks:
            return []
        if not remote:
            counts = [_exchange.block_rows(b) for b in blocks]
        else:
            rows_t = ray_tpu.remote(_exchange.block_rows)
            counts = ray_tpu.get([rows_t.remote(b) for b in blocks])
        boundaries = [int(b) for b in make_boundaries(sum(counts))]
        k = len(boundaries) - 1
        starts = list(np.cumsum([0] + counts[:-1]))
        if not remote:
            part_lists = [
                _exchange.slice_partition(b, int(s), boundaries) if k > 1
                else [_exchange.slice_partition(b, int(s), boundaries)]
                for b, s in zip(blocks, starts)
            ]
            merged = [
                _exchange.concat_parts(*[pl[j] for pl in part_lists])
                for j in builtins.range(k)
            ]
            return [lambda b=b: b for b in merged]
        slice_t = ray_tpu.remote(_exchange.slice_partition).options(num_returns=k)
        concat_t = ray_tpu.remote(_exchange.concat_parts)
        parts = [slice_t.remote(b, int(s), boundaries) for b, s in zip(blocks, starts)]
        if k == 1:
            outs = [concat_t.remote(*parts)]
        else:
            outs = [
                concat_t.remote(*[parts[b][j] for b in builtins.range(len(parts))])
                for j in builtins.range(k)
            ]
        return [lambda r=r: ray_tpu.get(r) for r in outs]

    def repartition(self, num_blocks: int) -> "Dataset":
        """Exact even repartition as a two-stage exchange (reference:
        repartition over the exchange task scheduler)."""
        k = max(1, num_blocks)
        fns = self._slice_exchange(
            lambda total: [round(j * total / k) for j in builtins.range(k + 1)]
        )
        return Dataset(fns)

    def random_shuffle(self, *, seed: Optional[int] = None) -> "Dataset":
        """Global shuffle as a two-stage push-based exchange (reference:
        _internal/push_based_shuffle.py): map tasks scatter each block's
        rows into K random partitions (num_returns=K), one merge task per
        partition concats + locally permutes — the driver only holds refs,
        so shuffle scale is bounded by the cluster, not driver memory.
        Block formats survive: arrow Tables stay arrow (types preserved),
        dict-of-numpy stays columnar, row lists stay rows."""
        from . import _exchange

        import ray_tpu

        blocks, remote = self._exchange_tasks()
        if not blocks:
            return Dataset([])
        k = len(blocks)
        base = np.random.default_rng(seed).integers(0, 2**31) if seed is not None else None

        def map_seed(i):
            return None if base is None else base + i

        def merge_seed(i):
            return None if base is None else base + k + i

        if not remote:
            # local fallback runs the SAME two-stage algorithm with the
            # same derived seeds, so a fixed seed produces identical output
            # whether or not a cluster is attached
            part_lists = [
                _exchange.random_partition(b, k, map_seed(i)) if k > 1
                else [_exchange.random_partition(b, k, map_seed(i))]
                for i, b in enumerate(blocks)
            ]
            merged = [
                _exchange.shuffle_merge(merge_seed(i), *[pl[i] for pl in part_lists])
                for i in builtins.range(k)
            ]
            return Dataset([lambda b=b: b for b in merged])
        if k == 1:
            blocks = ray_tpu.get(blocks)
            part = _exchange.random_partition(blocks[0], 1, map_seed(0))
            merged0 = _exchange.shuffle_merge(merge_seed(0), part)
            return Dataset([lambda b=merged0: b])
        part_t = ray_tpu.remote(_exchange.random_partition).options(num_returns=k)
        merge_t = ray_tpu.remote(_exchange.shuffle_merge)
        parts = [part_t.remote(b, k, map_seed(i)) for i, b in enumerate(blocks)]
        outs = [
            merge_t.remote(
                merge_seed(i), *[parts[b][i] for b in builtins.range(len(parts))]
            )
            for i in builtins.range(k)
        ]
        return Dataset([lambda r=r: ray_tpu.get(r) for r in outs])

    # ---- exchanges: sort / groupby (two-stage shuffles) ----

    def _exchange_tasks(self):
        """Materialize this dataset's blocks as object refs for an exchange
        (map stages run as tasks; see _exchange.py for the protocol)."""
        import ray_tpu

        use_tasks = ray_tpu.is_initialized()
        if use_tasks and any(op.compute == "actors" for op in self._ops):
            # actor-pool ops must run through their pool (callable-class
            # state constructs once per worker, not once per block): compute
            # via the pool and re-publish blocks as refs so the exchange
            # still distributes. STREAMING put: holding the whole dataset
            # in a driver-side list would defeat the windowed backpressure
            refs = []
            for b in self._iter_computed_blocks():
                refs.append(ray_tpu.put(b))
                del b
            return refs, True
        if use_tasks:
            exec_task = ray_tpu.remote(_execute_block)
            refs = [exec_task.remote(fn, self._ops) for fn in self._block_fns]
            return refs, True
        return self._compute_blocks(parallel=False), False

    def sort(self, key: Optional[str] = None, descending: bool = False) -> "Dataset":
        """Distributed sample-sort (reference: dataset.py Dataset.sort via
        _internal/planner/exchange + sort.py sample boundaries): map tasks
        range-partition with num_returns=K, one merge task per partition."""
        from . import _exchange

        import ray_tpu

        blocks, remote = self._exchange_tasks()
        if not blocks:
            return Dataset([])
        k = len(blocks)
        if not remote or k == 1:
            blocks = blocks if not remote else ray_tpu.get(blocks)
            merged = _exchange.merge_sorted(key, descending, *[
                _exchange.to_columns(b) for b in blocks
            ])
            return Dataset([lambda b=merged: b])
        sample_t = ray_tpu.remote(_exchange.sample_keys)
        part_t = ray_tpu.remote(_exchange.range_partition).options(num_returns=k)
        merge_t = ray_tpu.remote(_exchange.merge_sorted)
        samples = np.concatenate(ray_tpu.get([sample_t.remote(b, key) for b in blocks]))
        samples = np.sort(samples)
        # K-1 boundaries at even quantiles of the global sample
        boundaries = samples[
            np.linspace(0, len(samples) - 1, num=k + 1).astype(int)[1:-1]
        ] if len(samples) else np.array([])
        parts = [part_t.remote(b, key, boundaries, k) for b in blocks]
        outs = [
            merge_t.remote(key, descending, *[parts[b][i] for b in builtins.range(len(parts))])
            for i in builtins.range(k)
        ]
        if descending:
            outs = outs[::-1]
        final = ray_tpu.get(outs)
        return Dataset([lambda b=b: b for b in final if _block_num_rows(b)])

    def groupby(self, key: Optional[str]) -> "GroupedData":
        from .grouped_data import GroupedData

        return GroupedData(self, key)

    def _group_exchange(self, key, reducer, reducer_args) -> "Dataset":
        """Hash-partition blocks by key, run `reducer(*args, *partition
        parts)` once per partition."""
        from . import _exchange

        import ray_tpu

        blocks, remote = self._exchange_tasks()
        if not blocks:
            return Dataset([])
        k = len(blocks)
        if not remote or k == 1:
            blocks = blocks if not remote else ray_tpu.get(blocks)
            out = reducer(*reducer_args, *[_exchange.to_columns(b) for b in blocks])
            return Dataset([lambda b=out: b])
        part_t = ray_tpu.remote(_exchange.hash_partition).options(num_returns=k)
        reduce_t = ray_tpu.remote(reducer)
        parts = [part_t.remote(b, key, k) for b in blocks]
        outs = [
            reduce_t.remote(*reducer_args, *[parts[b][i] for b in builtins.range(len(parts))])
            for i in builtins.range(k)
        ]
        final = ray_tpu.get(outs)
        return Dataset([lambda b=b: b for b in final if b and _block_num_rows(b)])

    # ---- schema / column ops ----

    def add_column(self, name: str, fn: Callable[[Batch], Any]) -> "Dataset":
        def add(batch):
            from . import _exchange

            cols = _exchange.to_columns(batch)
            cols[name] = np.asarray(fn(cols))
            return cols

        return self.map_batches(add)

    def drop_columns(self, cols: Sequence[str]) -> "Dataset":
        drop = set(cols)

        def do(batch):
            from . import _exchange

            return {k: v for k, v in _exchange.to_columns(batch).items() if k not in drop}

        return self.map_batches(do)

    def select_columns(self, cols: Sequence[str]) -> "Dataset":
        keep = list(cols)

        def do(batch):
            from . import _exchange

            c = _exchange.to_columns(batch)
            return {k: c[k] for k in keep}

        # markered so the planner can fold the projection into a parquet
        # read (pq.read_table(columns=...)) — _plan.pushdown_reads
        return self._with_op(_Op("map_batches", do, meta=("select", keep)))

    def rename_columns(self, mapping: Dict[str, str]) -> "Dataset":
        def do(batch):
            from . import _exchange

            return {mapping.get(k, k): v for k, v in _exchange.to_columns(batch).items()}

        return self.map_batches(do)

    def unique(self, column: str) -> List[Any]:
        out = set()
        for block in self._iter_computed_blocks():
            from . import _exchange

            cols = _exchange.to_columns(block)
            out.update(np.unique(cols[column]).tolist())
        return sorted(out)

    def limit(self, n: int) -> "Dataset":
        """First n rows (materializes only what it needs; a per-block cap
        is pushed below row-preserving ops so tasks transform only rows
        that can survive — _plan.push_limit)."""
        from ._plan import push_limit

        capped = Dataset(self._block_fns, push_limit(self._ops, n),
                         read_meta=self._read_meta)
        capped._stats_sink = self
        taken = []
        remaining = n
        for block in capped._iter_computed_blocks():
            rows = _block_num_rows(block)
            take = min(rows, remaining)
            if take > 0:
                taken.append(_block_slice(block, 0, take))
                remaining -= take
            if remaining <= 0:
                break
        return Dataset([lambda b=b: b for b in taken])

    def union(self, *others: "Dataset") -> "Dataset":
        """Lazy union: each input's op chain folds into its block fns, so
        nothing materializes until the union is consumed."""
        from ._plan import optimize

        datasets = [self, *others]
        block_fns = []
        for ds in datasets:
            if any(op.compute == "actors" for op in ds._ops):
                # actor-pool ops must run through the pool (callable-class
                # state constructs once per worker) — folding them into
                # plain block fns would rebuild the state per block
                blocks = ds._compute_blocks()
                block_fns.extend(lambda b=b: b for b in blocks)
            elif ds._ops:
                ops = optimize(ds._ops)
                block_fns.extend(
                    (lambda fn=fn, ops=ops: _apply_ops(fn(), list(ops)))
                    for fn in ds._block_fns
                )
            else:
                block_fns.extend(ds._block_fns)
        return Dataset(block_fns)

    def zip(self, other: "Dataset") -> "Dataset":
        """Column-wise zip of equal-length datasets (reference:
        zip_operator.py); overlapping names get a _1 suffix."""
        from . import _exchange

        left = self._compute_blocks()
        right = other._compute_blocks()
        lc = _exchange._concat([_exchange.to_columns(b) for b in left]) if left else {}
        rc = _exchange._concat([_exchange.to_columns(b) for b in right]) if right else {}
        ln = len(next(iter(lc.values()))) if lc else 0
        rn = len(next(iter(rc.values()))) if rc else 0
        if ln != rn:
            raise ValueError(f"zip requires equal row counts, got {ln} vs {rn}")
        out = dict(lc)
        for k, v in rc.items():
            out[k if k not in out else f"{k}_1"] = v
        return Dataset([lambda b=out: b])

    def train_test_split(self, test_size: float, *, shuffle: bool = False, seed=None):
        """Returns (train, test) datasets split at a global row boundary —
        a two-output slice exchange over tasks, so nothing funnels through
        the driver (reference: dataset.py train_test_split)."""
        ds = self.random_shuffle(seed=seed) if shuffle else self

        def boundaries(n):
            cut = n - int(n * test_size) if isinstance(test_size, float) else n - test_size
            return [0, cut, n]

        fns = ds._slice_exchange(boundaries)
        if not fns:
            return Dataset([]), Dataset([])
        return Dataset([fns[0]]), Dataset([fns[1]])

    # ---- writes (reference: data/datasource do_write paths) ----

    def _write_files(self, path: str, ext: str, write_one: Callable[[Any, str], None]):
        import os

        os.makedirs(path, exist_ok=True)
        paths = []
        for i, block in enumerate(self._iter_computed_blocks()):
            fp = os.path.join(path, f"part-{i:05d}.{ext}")
            write_one(block, fp)
            paths.append(fp)
        return paths

    def write_parquet(self, path: str) -> List[str]:
        from . import _exchange

        def write_one(block, fp):
            import pyarrow as pa
            import pyarrow.parquet as pq

            pq.write_table(pa.table(_exchange.to_columns(block)), fp)

        return self._write_files(path, "parquet", write_one)

    def write_csv(self, path: str) -> List[str]:
        from . import _exchange

        def write_one(block, fp):
            import csv

            cols = _exchange.to_columns(block)
            keys = list(cols)
            with open(fp, "w", newline="") as f:
                w = csv.writer(f)
                w.writerow(keys)
                for i in builtins.range(len(cols[keys[0]]) if keys else 0):
                    w.writerow([cols[k][i] for k in keys])

        return self._write_files(path, "csv", write_one)

    def write_json(self, path: str) -> List[str]:
        def write_one(block, fp):
            import json

            with open(fp, "w") as f:
                for row in _block_to_rows(block):
                    if isinstance(row, dict):
                        row = {
                            k: (v.item() if hasattr(v, "item") else v) for k, v in row.items()
                        }
                    f.write(json.dumps(row) + "\n")

        return self._write_files(path, "json", write_one)

    def write_tfrecords(self, path: str) -> List[str]:
        from .datasource import _write_tfrecords

        return _write_tfrecords(self, path)

    def write_sql(self, table: str, connection_factory, **kwargs) -> int:
        from .datasource import _write_sql

        return _write_sql(self, table, connection_factory, **kwargs)

    def write_webdataset(self, path: str) -> List[str]:
        from .datasource import _write_webdataset

        return _write_webdataset(self, path)

    def iter_torch_batches(self, *, batch_size: int = 256, drop_last: bool = False):
        """Batches as dicts of torch CPU tensors (reference:
        iter_torch_batches; the TPU path is iter_device_batches)."""
        import torch

        from . import _exchange

        for batch in self.iter_batches(batch_size=batch_size, drop_last=drop_last):
            cols = _exchange.to_columns(batch)
            yield {k: torch.as_tensor(np.ascontiguousarray(v)) for k, v in cols.items()}

    def split_at(self, rank: int, world_size: int) -> "Dataset":
        """Contiguous block-range shard for one worker (streaming split)."""
        n = self.num_blocks()
        if n % world_size == 0:
            per = n // world_size
            fns = self._block_fns[rank * per : (rank + 1) * per]
        else:
            fns = self._block_fns[rank::world_size]
        return Dataset(fns, list(self._ops))

    # aliases matching the reference API
    def split(self, n: int) -> List["Dataset"]:
        return [self.split_at(i, n) for i in builtins.range(n)]

    # ---- execution ----

    def _compute_blocks(self, parallel: bool = True) -> List[Any]:
        return list(self._iter_computed_blocks(parallel=parallel))

    def _iter_computed_blocks(
        self,
        parallel: bool = True,
        window: int = 4,
        max_in_flight_bytes: Optional[int] = None,
    ):
        """Streaming block computation with bounded memory: submit up to
        `window` block tasks ahead, and additionally shrink the effective
        window so (observed avg block size x in-flight) stays under
        `max_in_flight_bytes` (reference: streaming_executor.py:48
        resource-aware backpressure, collapsed to a byte budget).

        If any op in the chain has compute="actors", the WHOLE chain runs on
        a pool of stateful _MapWorker actors (round-robin, same windowing)."""
        import ray_tpu

        from . import _stats
        from ._plan import optimize, pushdown_reads

        block_fns, ops = pushdown_reads(self._read_meta, self._block_fns, self._ops)
        ops = optimize(ops)
        use_cluster = parallel and ray_tpu.is_initialized() and len(block_fns) > 1

        # per-execution stats live on the dataset the USER executed (take()/
        # count() run internal derived datasets; _stats_sink points back).
        # sink=None (schema()'s probe) collects without attaching/publishing,
        # so a metadata peek never clobbers a real execution's stats.
        sink = getattr(self, "_stats_sink", self)
        stats = _stats.DatasetStats(ops, use_cluster)
        if sink is not None:
            sink._last_stats = stats

        if not use_cluster:
            completed = False
            try:
                cache: Dict[int, Callable] = {}
                for fn in block_fns:
                    block, stat_rows = _execute_block_stats(fn, ops, cache)
                    stats.record(stat_rows)
                    yield block
                completed = True
            finally:
                stats.close(completed)
                if completed and sink is not None:
                    _stats.publish(stats)
            return

        actor_ops = [op for op in ops if op.compute == "actors"]
        actors = []
        if actor_ops:
            # the chain shares one pool: honor the LARGEST request among its
            # actor ops (silently using op[0]'s size would shrink a user's
            # explicit pool for the expensive op)
            n = max(1, min(max(op.num_actors for op in actor_ops), len(block_fns)))
            worker_cls = ray_tpu.remote(_MapWorker)
            actors = [worker_cls.remote(ops) for _ in builtins.range(n)]
            rr = itertools.cycle(actors)

            def submit(fn):
                return next(rr).run.remote(fn)
        else:
            exec_task = ray_tpu.remote(_execute_block_stats)

            def submit(fn):
                return exec_task.remote(fn, ops)

        avg_bytes = 0.0
        fetched = 0

        def effective_window() -> int:
            if max_in_flight_bytes is None:
                return window
            if fetched == 0:
                # no size observation yet: a full-window burst could blow
                # the budget arbitrarily — probe with one block first
                return 1
            return max(1, min(window, int(max_in_flight_bytes // max(1.0, avg_bytes))))

        completed = False
        try:
            pending: List[Any] = []
            fn_iter = iter(block_fns)
            for fn in itertools.islice(fn_iter, effective_window()):
                pending.append(submit(fn))
            while pending:
                ref = pending.pop(0)
                t0 = time.perf_counter()
                block, stat_rows = ray_tpu.get(ref)
                stats.add_wait(time.perf_counter() - t0)
                stats.record(stat_rows)
                size = stat_rows[-1][3]  # always >=1 row: the read stat
                avg_bytes = (avg_bytes * fetched + size) / (fetched + 1)
                fetched += 1
                while len(pending) < effective_window():
                    nxt = next(fn_iter, None)
                    if nxt is None:
                        break
                    pending.append(submit(nxt))
                yield block
            completed = True
        finally:
            stats.close(completed)
            if completed and sink is not None:
                # publish only on normal completion: abandoned iterators
                # finalize from GC, where a head round-trip is unsafe
                _stats.publish(stats)
            for a in actors:
                try:
                    ray_tpu.kill(a)
                except Exception:
                    pass

    def materialize(self) -> "Dataset":
        blocks = self._compute_blocks()
        return Dataset([lambda b=b: b for b in blocks])

    # ---- consumption ----

    def iter_rows(self) -> Iterator[Any]:
        for block in self._iter_computed_blocks():
            yield from _block_to_rows(block)

    def iter_batches(
        self,
        *,
        batch_size: int = 256,
        drop_last: bool = False,
        prefetch_blocks: int = 2,
        max_in_flight_bytes: Optional[int] = None,
    ) -> Iterator[Batch]:
        carry = None
        for block in self._iter_computed_blocks(
            window=max(1, prefetch_blocks), max_in_flight_bytes=max_in_flight_bytes
        ):
            if carry is not None:
                block = _block_concat([carry, block])
                carry = None
            n = _block_num_rows(block)
            s = 0
            while n - s >= batch_size:
                yield _block_slice(block, s, s + batch_size)
                s += batch_size
            if s < n:
                carry = _block_slice(block, s, n)
        if carry is not None and not drop_last:
            yield carry

    def iter_device_batches(
        self,
        *,
        batch_size: int,
        mesh=None,
        rules=None,
        drop_last: bool = True,
        prefetch: int = 2,
    ):
        """TPU feed path: host batches -> sharded device arrays, with a
        `prefetch`-deep pipeline so device_put overlaps the step (the
        iter_torch_batches ergonomics of the reference, device-native)."""
        import collections

        import jax

        batch_axes = None
        if mesh is not None:
            from jax.sharding import NamedSharding, PartitionSpec as P

            spec = rules.spec("batch") if rules is not None else P()
            batch_axes = spec[0] if len(spec) else None

        def to_device(batch):
            def put(v):
                arr = np.asarray(v)
                if mesh is not None:
                    from jax.sharding import NamedSharding, PartitionSpec as P

                    # shard dim 0 (batch); replicate the rest, rank-aware
                    s = NamedSharding(
                        mesh, P(*([batch_axes] + [None] * (arr.ndim - 1)))
                    )
                    return jax.device_put(arr, s)
                return jax.device_put(arr)

            if isinstance(batch, dict):
                return {k: put(v) for k, v in batch.items()}
            return put(batch)

        queue: collections.deque = collections.deque()
        it = self.iter_batches(batch_size=batch_size, drop_last=drop_last)
        for batch in it:
            queue.append(to_device(batch))
            if len(queue) > prefetch:
                yield queue.popleft()
        while queue:
            yield queue.popleft()

    def take(self, limit: int = 20) -> List[Any]:
        from ._plan import push_limit

        capped = Dataset(self._block_fns, push_limit(self._ops, limit),
                         read_meta=self._read_meta)
        capped._stats_sink = self
        out = []
        for row in capped.iter_rows():
            out.append(row)
            if len(out) >= limit:
                break
        return out

    def take_all(self) -> List[Any]:
        return list(self.iter_rows())

    def count(self) -> int:
        # count pushdown: the TRAILING suffix of row-count-preserving ops
        # (map) never changes the answer, so skip running it (_plan rule;
        # earlier preserving ops must still run — downstream filters read
        # their output shapes)
        from ._plan import _preserves_row_count

        ops = list(self._ops)
        while ops and _preserves_row_count(ops[-1]):
            ops.pop()
        pruned = Dataset(self._block_fns, ops, read_meta=self._read_meta)
        pruned._stats_sink = self
        return sum(_block_num_rows(b) for b in pruned._iter_computed_blocks())

    def explain(self) -> str:
        """The logical -> optimized plan (reference: logical plan dumps)."""
        from ._plan import explain

        return explain(self._ops)

    def stats(self) -> str:
        """Per-operator execution stats for this dataset's LAST execution:
        wall time, rows out, bytes out per operator, plus how long the
        consuming iterator sat blocked waiting for blocks (reference:
        Dataset.stats() over _internal/stats.py DatasetStats). Execute the
        dataset first (iterate/take/count/materialize), then read stats."""
        st = getattr(self, "_last_stats", None)
        if st is None:
            return (
                "Dataset has not been executed yet. stats() reports the "
                "last execution (iterate, take, count, or materialize first)."
            )
        return st.summary()

    def stats_dict(self) -> Optional[Dict[str, Any]]:
        """Structured form of stats() (None before first execution)."""
        st = getattr(self, "_last_stats", None)
        return st.to_dict() if st is not None else None

    def schema(self):
        # metadata probe: must not clobber the last REAL execution's stats
        probe = Dataset(self._block_fns, self._ops, read_meta=self._read_meta)
        probe._stats_sink = None
        for block in probe._iter_computed_blocks(parallel=False):
            if isinstance(block, dict):
                return {k: getattr(v, "dtype", type(v)) for k, v in block.items()}
            try:
                import pyarrow as pa

                if isinstance(block, pa.Table):
                    return block.schema
            except ImportError:
                pass
            rows = list(_block_to_rows(block))
            return type(rows[0]) if rows else None
        return None

    def to_pandas(self):
        import pandas as pd

        rows = self.take_all()
        if rows and isinstance(rows[0], dict):
            return pd.DataFrame(rows)
        return pd.DataFrame({"value": rows})

    # ---- global aggregates (reference: dataset.py sum/min/max/mean/std
    # via _aggregate_on -> AggregateFn; per-block partials stream through
    # the windowed executor and combine driver-side) ----

    def _column_values(self, block, on: Optional[str]):
        if isinstance(block, dict):
            if on is None:
                raise ValueError("this dataset has named columns; pass on=<column>")
            return np.asarray(block[on])
        try:
            import pyarrow as pa

            if isinstance(block, pa.Table):
                if on is None:
                    raise ValueError("this dataset has named columns; pass on=<column>")
                return block.column(on).to_numpy(zero_copy_only=False)
        except ImportError:
            pass
        if on is not None:
            return np.asarray([r[on] for r in block])
        return np.asarray(block)

    @staticmethod
    def _block_partial(v: np.ndarray):
        """(n, sum, mean, M2, min, max) for one block's values. mean/M2
        feed the Chan/Welford merge — a naive global sum-of-squares
        catastrophically cancels when |mean| >> spread."""
        m = v.mean()
        return (v.size, v.sum(), m, ((v - m) ** 2).sum(), v.min(), v.max())

    def _agg_partials(self, on: Optional[str]):
        for block in self._iter_computed_blocks():
            if _block_num_rows(block) == 0:
                continue
            yield self._block_partial(self._column_values(block, on).astype(np.float64))

    def sum(self, on: Optional[str] = None):
        total, seen = 0.0, False
        for n, s, _, _, _, _ in self._agg_partials(on):
            total += s
            seen = True
        return total if seen else None

    def min(self, on: Optional[str] = None):
        out = None
        for _, _, _, _, mn, _ in self._agg_partials(on):
            out = mn if out is None else builtins.min(out, mn)
        return out

    def max(self, on: Optional[str] = None):
        out = None
        for _, _, _, _, _, mx in self._agg_partials(on):
            out = mx if out is None else builtins.max(out, mx)
        return out

    def mean(self, on: Optional[str] = None):
        n_total, s_total = 0, 0.0
        for n, s, _, _, _, _ in self._agg_partials(on):
            n_total += n
            s_total += s
        return s_total / n_total if n_total else None

    @staticmethod
    def _chan_merge(partials):
        """Combine per-block (n, sum, mean, M2, min, max) partials into a
        global (n, mean, M2) via Chan's parallel variance algorithm."""
        n_a, mean_a, m2_a = 0, 0.0, 0.0
        for n, _, mean_b, m2_b, _, _ in partials:
            if n_a == 0:
                n_a, mean_a, m2_a = n, mean_b, m2_b
                continue
            delta = mean_b - mean_a
            n_ab = n_a + n
            m2_a += m2_b + delta * delta * n_a * n / n_ab
            mean_a += delta * n / n_ab
            n_a = n_ab
        return n_a, mean_a, m2_a

    def std(self, on: Optional[str] = None, ddof: int = 1):
        n_a, _, m2_a = self._chan_merge(self._agg_partials(on))
        if n_a <= ddof:
            return None
        return float(np.sqrt(m2_a / (n_a - ddof)))

    def aggregate(self, *aggs):
        """Whole-dataset aggregation (reference: dataset.py aggregate):
        one global group; returns a result dict keyed by aggregation
        name. Native descriptors reuse the streaming partial aggregators;
        AggregateFns fold rows driver-side. The pipeline materializes once
        so multiple descriptors don't recompute it."""
        import functools

        from .aggregate import AggregateFn, _NativeAgg

        if not aggs:
            raise ValueError("aggregate() requires at least one descriptor")
        bad = [a for a in aggs if not isinstance(a, (AggregateFn, _NativeAgg))]
        if bad:
            raise TypeError(f"not aggregation descriptors: {bad}")
        names = [a.name for a in aggs]
        if len(set(names)) != len(names):
            raise ValueError(f"duplicate aggregation names: {sorted(names)}")
        native = [a for a in aggs if isinstance(a, _NativeAgg)]
        fn_aggs = [a for a in aggs if isinstance(a, AggregateFn)]
        # ONE streaming pass over the blocks: per-column (n, sum, mean, M2,
        # min, max) partials feed every native descriptor; AggregateFns
        # fold per block and merge across blocks (the one place merge()
        # semantics genuinely run)
        col_partials: Dict[Optional[str], list] = {a.on: [] for a in native}
        fn_accs: Dict[int, list] = {id(a): [] for a in fn_aggs}
        needs_values = {a.on for a in native if a.kind != "count"}
        for block in self._iter_computed_blocks():
            if _block_num_rows(block) == 0:
                continue
            for on in col_partials:
                if on not in needs_values:
                    # count-only column (e.g. Count() with on=None): row
                    # counts suffice, and dict rows have no float cast
                    col_partials[on].append(
                        (_block_num_rows(block), 0.0, 0.0, 0.0, None, None)
                    )
                    continue
                col_partials[on].append(
                    self._block_partial(self._column_values(block, on).astype(np.float64))
                )
            if fn_aggs:
                rows = list(_block_to_rows(block))
                for a in fn_aggs:
                    acc = a.init(None)
                    for row in rows:
                        acc = a.accumulate_row(acc, row)
                    fn_accs[id(a)].append(acc)
        out: Dict[str, Any] = {}
        for a in native:
            parts = col_partials[a.on]
            if a.kind == "count":
                out[a.name] = builtins.sum(p[0] for p in parts)
            elif not parts:
                out[a.name] = None
            elif a.kind == "sum":
                out[a.name] = builtins.sum(p[1] for p in parts)
            elif a.kind == "min":
                out[a.name] = builtins.min(p[4] for p in parts)
            elif a.kind == "max":
                out[a.name] = builtins.max(p[5] for p in parts)
            elif a.kind == "mean":
                out[a.name] = builtins.sum(p[1] for p in parts) / builtins.sum(
                    p[0] for p in parts
                )
            elif a.kind == "std":
                n_a, _, m2_a = self._chan_merge(parts)
                out[a.name] = (
                    float(np.sqrt(m2_a / (n_a - 1))) if n_a > 1 else None
                )
        for a in fn_aggs:
            accs = fn_accs[id(a)]
            acc = functools.reduce(a.merge, accs) if accs else a.init(None)
            out[a.name] = a.finalize(acc)
        return out or None

    # ---- sampling / ordering ----

    def random_sample(self, fraction: float, *, seed: Optional[int] = None) -> "Dataset":
        """Uniform per-row sample without a full shuffle (reference:
        dataset.py random_sample)."""
        if not 0.0 <= fraction <= 1.0:
            raise ValueError("fraction must be in [0, 1]")
        rng_seed = seed

        def _sample(block, _seed=rng_seed, _frac=fraction):
            import zlib

            n = _block_num_rows(block)
            if _seed is None:
                rng = np.random.default_rng()
            else:
                # decorrelate equal-length blocks: fold a cheap content
                # fingerprint into the seed (seeding on (seed, n) alone
                # makes every 125-row block keep identical row positions)
                rows = list(itertools.islice(_block_to_rows(block), 3))
                fp = zlib.crc32(repr(rows).encode()) if rows else 0
                rng = np.random.default_rng((_seed, n, fp))
            keep = np.nonzero(rng.random(n) < _frac)[0]
            return _block_take(block, keep)

        return self._with_op(_Op("map_batches", _sample))

    def randomize_block_order(self, *, seed: Optional[int] = None) -> "Dataset":
        """Shuffle BLOCK order only — cheap decorrelation for training
        input (reference: dataset.py randomize_block_order)."""
        fns = list(self._block_fns)
        rng = np.random.default_rng(seed)
        rng.shuffle(fns)
        # read pushdown must NOT survive the shuffle (pushdown_reads would
        # rebuild block_fns in source order, undoing it) — but keep the
        # path list so input_files() still answers
        meta = {"paths": list(self._read_meta.get("paths", []))} if self._read_meta else None
        return Dataset(fns, list(self._ops), read_meta=meta)

    # ---- inspection / conversion ----

    def show(self, limit: int = 20) -> None:
        for row in self.take(limit):
            print(row)

    def take_batch(self, batch_size: int = 20):
        for batch in self.iter_batches(batch_size=batch_size):
            return batch
        raise ValueError("dataset is empty")

    def size_bytes(self) -> int:
        total = 0
        for block in self._iter_computed_blocks():
            if isinstance(block, (list, tuple)):
                # refine the backpressure helper's flat 64-bytes/row guess:
                # user-facing size estimates should see real array payloads
                for r in block:
                    if isinstance(r, dict):
                        total += builtins.sum(
                            getattr(v, "nbytes", len(str(v))) for v in r.values()
                        )
                    else:
                        total += getattr(r, "nbytes", len(str(r)))
            else:
                total += _block_size_bytes(block)
        return total

    def input_files(self) -> List[str]:
        meta = self._read_meta or {}
        return list(meta.get("paths", []))

    def split_at_indices(self, indices: Sequence[int]) -> List["Dataset"]:
        """Split by global ROW indices (reference: dataset.py
        split_at_indices). Materializes once; each output holds its row
        range."""
        indices = list(indices)
        if indices != sorted(indices) or (indices and indices[0] < 0):
            raise ValueError(f"indices must be sorted and non-negative: {indices}")
        blocks = self._compute_blocks()
        rows: List[Any] = []
        for b in blocks:
            rows.extend(_block_to_rows(b))
        if indices and indices[-1] > len(rows):
            raise ValueError(
                f"index {indices[-1]} out of range for {len(rows)} rows"
            )
        bounds = [0] + indices + [len(rows)]
        out = []
        for lo, hi in zip(bounds[:-1], bounds[1:]):
            chunk = rows[lo:hi]
            out.append(from_items(chunk))
        return out

    def split_proportionately(self, proportions: Sequence[float]) -> List["Dataset"]:
        if not proportions or any(p <= 0 for p in proportions) or builtins.sum(proportions) >= 1.0:
            raise ValueError("proportions must be positive and sum to < 1")
        n = self.count()
        indices, acc = [], 0.0
        for p in proportions:
            acc += p
            indices.append(int(n * acc))
        return self.split_at_indices(indices)

    def to_pandas_refs(self) -> List[Any]:
        """One ObjectRef of a pandas DataFrame per block (reference:
        dataset.py to_pandas_refs)."""
        import pandas as pd

        import ray_tpu

        refs = []
        for block in self._iter_computed_blocks():
            rows = list(_block_to_rows(block))
            df = pd.DataFrame(rows) if rows and isinstance(rows[0], dict) else pd.DataFrame({"value": rows})
            refs.append(ray_tpu.put(df))
        return refs

    def to_numpy_refs(self) -> List[Any]:
        import ray_tpu

        refs = []
        for block in self._iter_computed_blocks():
            if isinstance(block, dict):
                refs.append(ray_tpu.put({k: np.asarray(v) for k, v in block.items()}))
                continue
            # columnarize arrow/row blocks too, so the output shape does
            # not depend on the internal block format
            rows = list(_block_to_rows(block))
            if rows and isinstance(rows[0], dict):
                refs.append(
                    ray_tpu.put({k: np.asarray([r[k] for r in rows]) for k in rows[0]})
                )
            else:
                refs.append(ray_tpu.put(np.asarray(rows)))
        return refs

    def iter_tf_batches(self, *, batch_size: int = 256, drop_last: bool = False):
        """Dict-of-ndarray batches shaped for tf.data consumption; yields
        tf tensors when tensorflow is importable, numpy otherwise
        (hermetic TPU images ship without TF)."""
        try:
            import tensorflow as tf  # type: ignore

            conv = tf.convert_to_tensor
        except Exception:
            conv = None
        for batch in self.iter_batches(batch_size=batch_size, drop_last=drop_last):
            if not isinstance(batch, dict):
                try:
                    import pyarrow as pa

                    if isinstance(batch, pa.Table):
                        batch = {c: batch.column(c).to_numpy(zero_copy_only=False)
                                 for c in batch.column_names}
                except ImportError:
                    pass
            if isinstance(batch, list) and batch and isinstance(batch[0], dict):
                batch = {k: np.asarray([r[k] for r in batch]) for k in batch[0]}
            if not isinstance(batch, dict):
                batch = {"value": np.asarray(batch)}
            yield {k: conv(v) for k, v in batch.items()} if conv is not None else batch


# --------------------------------------------------------------------------
# sources
# --------------------------------------------------------------------------


def from_items(items: List[Any], *, override_num_blocks: int = 8) -> Dataset:
    n = max(1, min(override_num_blocks, len(items) or 1))
    per = (len(items) + n - 1) // n
    chunks = [items[i * per : (i + 1) * per] for i in builtins.range(n)]
    chunks = [c for c in chunks if c]
    return Dataset([lambda c=c: c for c in chunks])


def range(n: int, *, override_num_blocks: int = 8) -> Dataset:  # noqa: A001
    k = max(1, min(override_num_blocks, n or 1))
    per = (n + k - 1) // k
    spans = [(i * per, min((i + 1) * per, n)) for i in builtins.range(k)]
    spans = [s for s in spans if s[0] < s[1]]
    return Dataset(
        [lambda s=s: {"id": np.arange(s[0], s[1], dtype=np.int64)} for s in spans]
    )


def from_numpy(arr: np.ndarray, *, override_num_blocks: int = 8) -> Dataset:
    chunks = np.array_split(arr, override_num_blocks)
    return Dataset([lambda c=c: {"data": c} for c in chunks if len(c)])


def from_pandas(df) -> Dataset:
    return Dataset([lambda: {c: df[c].to_numpy() for c in df.columns}])


def _expand_paths(paths) -> List[str]:
    import glob as globmod
    import os

    expanded: List[str] = []
    for p in paths if isinstance(paths, (list, tuple)) else [paths]:
        if os.path.isdir(p):
            expanded.extend(sorted(globmod.glob(os.path.join(p, "*"))))
        elif any(ch in p for ch in "*?["):
            expanded.extend(sorted(globmod.glob(p)))
        else:
            expanded.append(p)
    if not expanded:
        raise FileNotFoundError(f"no files matched {paths!r}")
    return expanded


def _file_blocks(paths, read_one: Callable[[str], Any]) -> Dataset:
    return Dataset([lambda p=p: read_one(p) for p in _expand_paths(paths)])


def _read_parquet_one(path: str, columns=None, filter_expr=None):
    import pyarrow.parquet as pq

    filters = filter_expr.to_arrow() if filter_expr is not None else None
    return pq.read_table(path, columns=columns, filters=filters)


def read_parquet(paths, *, columns=None, filter=None) -> Dataset:
    """Parquet scan with projection/predicate support: `columns` prunes at
    the reader, `filter` (an expressions.Expr) prunes row groups. Both are
    also REACHED by the planner — a leading select_columns/filter(expr) on
    the Dataset folds into the read (_plan.pushdown_reads; reference: the
    logical planner's read-op pushdown rules)."""
    import functools

    expanded = _expand_paths(paths)
    fns = [
        functools.partial(_read_parquet_one, p, columns, filter)
        for p in expanded
    ]
    return Dataset(
        fns,
        read_meta={
            "kind": "parquet",
            "paths": expanded,
            "columns": columns,
            "filter": filter,
        },
    )


def _apply_scan_prune(block, columns, filter_expr):
    """Shared post-parse pruning for readers without native projection/
    predicate support: mask first (filters may read dropped columns —
    pushdown_reads only pushes filters whose columns survive a pushed
    projection, so this order is safe), then project."""
    from . import _exchange

    if filter_expr is not None:
        mask = np.asarray(filter_expr.mask(_exchange.to_columns(block)), bool)
        block = _block_take(block, np.nonzero(mask)[0])
    if columns is not None:
        try:
            import pyarrow as pa

            if isinstance(block, pa.Table):
                return block.select(list(columns))
        except ImportError:
            pass
        if isinstance(block, dict):
            return {k: block[k] for k in columns}
    return block


def _read_csv_one(path: str, columns=None, filter_expr=None):
    import pyarrow.csv as pacsv

    opts = None
    if columns is not None and filter_expr is None:
        # true parse-level projection; with a filter, parse the filter's
        # columns too, prune after masking
        opts = pacsv.ConvertOptions(include_columns=list(columns))
    elif columns is not None:
        need = sorted(set(columns) | set(filter_expr.columns()))
        opts = pacsv.ConvertOptions(include_columns=need)
    table = pacsv.read_csv(path, convert_options=opts)
    return _apply_scan_prune(table, columns, filter_expr)


def read_csv(paths) -> Dataset:
    expanded = _expand_paths(paths)
    return Dataset(
        [lambda p=p: _read_csv_one(p) for p in expanded],
        read_meta={"kind": "csv", "paths": expanded},
    )


def _read_json_one(path: str, columns=None, filter_expr=None):
    import json

    with open(path) as f:
        rows = [json.loads(line) for line in f if line.strip()]
    # stay in ROW space: JSONL rows may be ragged (optional keys), so a
    # columnar conversion keyed off any one row would drop columns. Only
    # the filter's own columns materialize as arrays for the mask.
    if filter_expr is not None and rows:
        cols = {
            k: np.asarray([r.get(k) for r in rows])
            for k in filter_expr.columns()
        }
        mask = np.asarray(filter_expr.mask(cols), bool)
        rows = [r for r, m in zip(rows, mask) if m]
    if columns is not None:
        # r[k], not r.get: a missing key must raise exactly like the
        # unpushed select_columns op would — the optimizer firing must
        # never change observable semantics
        rows = [{k: r[k] for k in columns} for r in rows]
    return rows


def read_json(paths) -> Dataset:
    expanded = _expand_paths(paths)
    return Dataset(
        [lambda p=p: _read_json_one(p) for p in expanded],
        read_meta={"kind": "json", "paths": expanded},
    )


def read_numpy(paths) -> Dataset:
    return _file_blocks(paths, lambda p: {"data": np.load(p)})
