"""Lazy distributed Dataset.

Design (reference: python/ray/data/dataset.py:168 + _internal/execution):
  - a Dataset is an immutable logical plan: a block source + a chain of ops
    (map_batches / filter / repartition / ...). Nothing runs until iteration
    or materialize().
  - blocks are plain Python payloads (dict-of-numpy "batch" format, lists of
    rows, or pyarrow Tables) stored in the object store; transforms run as
    ray_tpu tasks over blocks with windowed streaming (submit-ahead window =
    backpressure, the moral equivalent of StreamingExecutor's resource-aware
    pull loop).
  - per-worker shards come from split_at(rank, n) — contiguous block ranges,
    matching DataConfig's streaming split (train/_internal/dataset_spec.py).
"""

from __future__ import annotations

import builtins
import itertools
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, Iterator, List, Optional, Sequence, Union

import numpy as np

Batch = Union[Dict[str, np.ndarray], "pd.DataFrame", List[Any]]  # noqa: F821


# --------------------------------------------------------------------------
# block helpers
# --------------------------------------------------------------------------


def _block_num_rows(block) -> int:
    if isinstance(block, dict):
        for v in block.values():
            return len(v)
        return 0
    try:
        import pyarrow as pa

        if isinstance(block, pa.Table):
            return block.num_rows
    except ImportError:
        pass
    return len(block)


def _block_slice(block, start: int, end: int):
    if isinstance(block, dict):
        return {k: v[start:end] for k, v in block.items()}
    try:
        import pyarrow as pa

        if isinstance(block, pa.Table):
            return block.slice(start, end - start)
    except ImportError:
        pass
    return block[start:end]


def _block_concat(blocks: List[Any]):
    first = blocks[0]
    if isinstance(first, dict):
        return {k: np.concatenate([b[k] for b in blocks]) for k in first}
    try:
        import pyarrow as pa

        if isinstance(first, pa.Table):
            return pa.concat_tables(blocks)
    except ImportError:
        pass
    out = []
    for b in blocks:
        out.extend(b)
    return out


def _block_take(block, indices):
    """Row gather preserving block format."""
    if isinstance(block, dict):
        return {k: np.asarray(v)[indices] for k, v in block.items()}
    try:
        import pyarrow as pa

        if isinstance(block, pa.Table):
            return block.take(indices)
    except ImportError:
        pass
    return [block[i] for i in indices]


def _block_to_rows(block) -> Iterator[Any]:
    if isinstance(block, dict):
        keys = list(block)
        n = _block_num_rows(block)
        for i in builtins.range(n):
            yield {k: block[k][i] for k in keys}
        return
    try:
        import pyarrow as pa

        if isinstance(block, pa.Table):
            yield from block.to_pylist()
            return
    except ImportError:
        pass
    yield from block


# --------------------------------------------------------------------------
# logical ops
# --------------------------------------------------------------------------


@dataclass
class _Op:
    kind: str  # map_batches | map | filter | flat_map
    fn: Callable
    batch_size: Optional[int] = None
    fn_kwargs: Dict[str, Any] = field(default_factory=dict)


def _apply_ops(block, ops: List[_Op]):
    """Runs inside a task: fold the op chain over one block."""
    for op in ops:
        if op.kind == "map_batches":
            if op.batch_size is None:
                block = op.fn(block, **op.fn_kwargs)
            else:
                n = _block_num_rows(block)
                outs = [
                    op.fn(_block_slice(block, s, min(s + op.batch_size, n)), **op.fn_kwargs)
                    for s in builtins.range(0, n, op.batch_size)
                ]
                block = _block_concat(outs) if outs else block
        elif op.kind == "map":
            block = [op.fn(row) for row in _block_to_rows(block)]
        elif op.kind == "filter":
            block = [row for row in _block_to_rows(block) if op.fn(row)]
        elif op.kind == "flat_map":
            out: List[Any] = []
            for row in _block_to_rows(block):
                out.extend(op.fn(row))
            block = out
        else:
            raise ValueError(f"unknown op {op.kind}")
    return block


def _execute_block(block_fn, ops: List[_Op]):
    """Runs inside a task: the source read (block_fn) AND the op chain both
    execute off-driver so I/O parallelizes and the driver stays off the data
    path (reference: plan_read_op.py fuses read+transform into one task)."""
    return _apply_ops(block_fn(), ops)


class Dataset:
    def __init__(self, block_fns: List[Callable[[], Any]], ops: Optional[List[_Op]] = None):
        # block_fns: zero-arg callables producing the source blocks (lazy read)
        self._block_fns = block_fns
        self._ops = ops or []

    # ---- metadata ----

    def num_blocks(self) -> int:
        return len(self._block_fns)

    def __repr__(self):
        return f"Dataset(num_blocks={self.num_blocks()}, ops={[o.kind for o in self._ops]})"

    # ---- transforms (lazy) ----

    def _with_op(self, op: _Op) -> "Dataset":
        return Dataset(self._block_fns, self._ops + [op])

    def map_batches(
        self,
        fn: Callable[[Batch], Batch],
        *,
        batch_size: Optional[int] = None,
        fn_kwargs: Optional[Dict[str, Any]] = None,
        **_,
    ) -> "Dataset":
        return self._with_op(_Op("map_batches", fn, batch_size, fn_kwargs or {}))

    def map(self, fn: Callable[[Any], Any]) -> "Dataset":
        return self._with_op(_Op("map", fn))

    def filter(self, fn: Callable[[Any], bool]) -> "Dataset":
        return self._with_op(_Op("filter", fn))

    def flat_map(self, fn: Callable[[Any], Sequence[Any]]) -> "Dataset":
        return self._with_op(_Op("flat_map", fn))

    def repartition(self, num_blocks: int) -> "Dataset":
        """Materializing repartition into equal-ish contiguous blocks."""
        blocks = self._compute_blocks()
        merged = _block_concat(blocks) if len(blocks) > 1 else blocks[0]
        total = _block_num_rows(merged)
        per = max(1, total // num_blocks)
        slices = []
        for i in builtins.range(num_blocks):
            s = i * per
            e = total if i == num_blocks - 1 else min((i + 1) * per, total)
            if s >= total:
                break
            blk = _block_slice(merged, s, e)
            slices.append(lambda b=blk: b)
        return Dataset(slices)

    def random_shuffle(self, *, seed: Optional[int] = None) -> "Dataset":
        """Global shuffle (materializes; push-based shuffle is the planned
        scale path, reference _internal/push_based_shuffle.py). Preserves the
        block format (dict-of-numpy stays dict-of-numpy)."""
        blocks = self._compute_blocks()
        if not blocks:
            return Dataset([])
        merged = _block_concat(blocks) if len(blocks) > 1 else blocks[0]
        n = _block_num_rows(merged)
        if n == 0:
            return Dataset([lambda: merged])
        rng = np.random.default_rng(seed)
        order = rng.permutation(n)
        shuffled = _block_take(merged, order)
        k = max(1, self.num_blocks())
        per = (n + k - 1) // k
        slices = [
            _block_slice(shuffled, s, min(s + per, n))
            for s in builtins.range(0, n, per)
        ]
        return Dataset([lambda b=b: b for b in slices])

    def split_at(self, rank: int, world_size: int) -> "Dataset":
        """Contiguous block-range shard for one worker (streaming split)."""
        n = self.num_blocks()
        if n % world_size == 0:
            per = n // world_size
            fns = self._block_fns[rank * per : (rank + 1) * per]
        else:
            fns = self._block_fns[rank::world_size]
        return Dataset(fns, list(self._ops))

    # aliases matching the reference API
    def split(self, n: int) -> List["Dataset"]:
        return [self.split_at(i, n) for i in builtins.range(n)]

    # ---- execution ----

    def _compute_blocks(self, parallel: bool = True) -> List[Any]:
        return list(self._iter_computed_blocks(parallel=parallel))

    def _iter_computed_blocks(self, parallel: bool = True, window: int = 4):
        """Streaming block computation: submit up to `window` block tasks
        ahead and yield in order (backpressure against unbounded memory)."""
        import ray_tpu

        ops = self._ops
        use_tasks = parallel and ray_tpu.is_initialized() and len(self._block_fns) > 1

        if not use_tasks:
            for fn in self._block_fns:
                yield _apply_ops(fn(), ops)
            return

        exec_task = ray_tpu.remote(_execute_block)
        pending: List[Any] = []
        fn_iter = iter(self._block_fns)
        for fn in itertools.islice(fn_iter, window):
            pending.append(exec_task.remote(fn, ops))
        while pending:
            ref = pending.pop(0)
            nxt = next(fn_iter, None)
            if nxt is not None:
                pending.append(exec_task.remote(nxt, ops))
            yield ray_tpu.get(ref)

    def materialize(self) -> "Dataset":
        blocks = self._compute_blocks()
        return Dataset([lambda b=b: b for b in blocks])

    # ---- consumption ----

    def iter_rows(self) -> Iterator[Any]:
        for block in self._iter_computed_blocks():
            yield from _block_to_rows(block)

    def iter_batches(
        self,
        *,
        batch_size: int = 256,
        drop_last: bool = False,
        prefetch_blocks: int = 2,
    ) -> Iterator[Batch]:
        carry = None
        for block in self._iter_computed_blocks(window=max(1, prefetch_blocks)):
            if carry is not None:
                block = _block_concat([carry, block])
                carry = None
            n = _block_num_rows(block)
            s = 0
            while n - s >= batch_size:
                yield _block_slice(block, s, s + batch_size)
                s += batch_size
            if s < n:
                carry = _block_slice(block, s, n)
        if carry is not None and not drop_last:
            yield carry

    def iter_device_batches(
        self,
        *,
        batch_size: int,
        mesh=None,
        rules=None,
        drop_last: bool = True,
        prefetch: int = 2,
    ):
        """TPU feed path: host batches -> sharded device arrays, with a
        `prefetch`-deep pipeline so device_put overlaps the step (the
        iter_torch_batches ergonomics of the reference, device-native)."""
        import collections

        import jax

        batch_axes = None
        if mesh is not None:
            from jax.sharding import NamedSharding, PartitionSpec as P

            spec = rules.spec("batch") if rules is not None else P()
            batch_axes = spec[0] if len(spec) else None

        def to_device(batch):
            def put(v):
                arr = np.asarray(v)
                if mesh is not None:
                    from jax.sharding import NamedSharding, PartitionSpec as P

                    # shard dim 0 (batch); replicate the rest, rank-aware
                    s = NamedSharding(
                        mesh, P(*([batch_axes] + [None] * (arr.ndim - 1)))
                    )
                    return jax.device_put(arr, s)
                return jax.device_put(arr)

            if isinstance(batch, dict):
                return {k: put(v) for k, v in batch.items()}
            return put(batch)

        queue: collections.deque = collections.deque()
        it = self.iter_batches(batch_size=batch_size, drop_last=drop_last)
        for batch in it:
            queue.append(to_device(batch))
            if len(queue) > prefetch:
                yield queue.popleft()
        while queue:
            yield queue.popleft()

    def take(self, limit: int = 20) -> List[Any]:
        out = []
        for row in self.iter_rows():
            out.append(row)
            if len(out) >= limit:
                break
        return out

    def take_all(self) -> List[Any]:
        return list(self.iter_rows())

    def count(self) -> int:
        return sum(_block_num_rows(b) for b in self._iter_computed_blocks())

    def schema(self):
        for block in self._iter_computed_blocks(parallel=False):
            if isinstance(block, dict):
                return {k: getattr(v, "dtype", type(v)) for k, v in block.items()}
            try:
                import pyarrow as pa

                if isinstance(block, pa.Table):
                    return block.schema
            except ImportError:
                pass
            rows = list(_block_to_rows(block))
            return type(rows[0]) if rows else None
        return None

    def to_pandas(self):
        import pandas as pd

        rows = self.take_all()
        if rows and isinstance(rows[0], dict):
            return pd.DataFrame(rows)
        return pd.DataFrame({"value": rows})


# --------------------------------------------------------------------------
# sources
# --------------------------------------------------------------------------


def from_items(items: List[Any], *, override_num_blocks: int = 8) -> Dataset:
    n = max(1, min(override_num_blocks, len(items) or 1))
    per = (len(items) + n - 1) // n
    chunks = [items[i * per : (i + 1) * per] for i in builtins.range(n)]
    chunks = [c for c in chunks if c]
    return Dataset([lambda c=c: c for c in chunks])


def range(n: int, *, override_num_blocks: int = 8) -> Dataset:  # noqa: A001
    k = max(1, min(override_num_blocks, n or 1))
    per = (n + k - 1) // k
    spans = [(i * per, min((i + 1) * per, n)) for i in builtins.range(k)]
    spans = [s for s in spans if s[0] < s[1]]
    return Dataset(
        [lambda s=s: {"id": np.arange(s[0], s[1], dtype=np.int64)} for s in spans]
    )


def from_numpy(arr: np.ndarray, *, override_num_blocks: int = 8) -> Dataset:
    chunks = np.array_split(arr, override_num_blocks)
    return Dataset([lambda c=c: {"data": c} for c in chunks if len(c)])


def from_pandas(df) -> Dataset:
    return Dataset([lambda: {c: df[c].to_numpy() for c in df.columns}])


def _file_blocks(paths, read_one: Callable[[str], Any]) -> Dataset:
    import glob as globmod
    import os

    expanded: List[str] = []
    for p in paths if isinstance(paths, (list, tuple)) else [paths]:
        if os.path.isdir(p):
            expanded.extend(sorted(globmod.glob(os.path.join(p, "*"))))
        elif any(ch in p for ch in "*?["):
            expanded.extend(sorted(globmod.glob(p)))
        else:
            expanded.append(p)
    if not expanded:
        raise FileNotFoundError(f"no files matched {paths!r}")
    return Dataset([lambda p=p: read_one(p) for p in expanded])


def read_parquet(paths) -> Dataset:
    import pyarrow.parquet as pq

    return _file_blocks(paths, lambda p: pq.read_table(p))


def read_csv(paths) -> Dataset:
    import pyarrow.csv as pacsv

    return _file_blocks(paths, lambda p: pacsv.read_csv(p))


def read_json(paths) -> Dataset:
    import json

    def read_one(p):
        with open(p) as f:
            return [json.loads(line) for line in f if line.strip()]

    return _file_blocks(paths, read_one)


def read_numpy(paths) -> Dataset:
    return _file_blocks(paths, lambda p: {"data": np.load(p)})
