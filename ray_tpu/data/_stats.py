"""Per-operator dataset execution stats.

Reference parity: python/ray/data/_internal/stats.py (DatasetStats /
StatsManager) — per-operator wall time, output rows, output bytes, block
counts, plus driver-side iterator timings (time blocked waiting on the
cluster vs. total). The reference threads a StatsActor through the
streaming executor; ray_tpu's per-block op chain lets each task time its
own ops and ship the rows back WITH the block, so stats cost one tuple
per (block, op) and no extra RPCs.

Stats answer the question that matters on TPU: is the input pipeline
keeping the chip fed, and if not, which operator is the bottleneck.
"""

from __future__ import annotations

import time
from typing import Any, Dict, List, Optional, Tuple

# one measurement: (op_index, wall_s, rows_out, bytes_out); op_index -1 is
# the source read
StatRow = Tuple[int, float, int, int]


def timed_apply(apply_fn, block, ops, cache=None) -> Tuple[Any, List[StatRow]]:
    """Run `apply_fn(block, [op], cache)` per op, timing each: the remote
    side of stats collection (runs inside tasks / pool actors)."""
    from .dataset import _block_num_rows, _block_size_bytes

    rows: List[StatRow] = []
    for i, op in enumerate(ops):
        t0 = time.perf_counter()
        block = apply_fn(block, [op], cache)
        wall = time.perf_counter() - t0
        rows.append((i, wall, _block_num_rows(block), _block_size_bytes(block)))
    return block, rows


def read_stat(wall: float, block) -> StatRow:
    from .dataset import _block_num_rows, _block_size_bytes

    return (-1, wall, _block_num_rows(block), _block_size_bytes(block))


class _OpAcc:
    __slots__ = ("name", "wall_s", "max_wall_s", "rows", "bytes", "blocks")

    def __init__(self, name: str):
        self.name = name
        self.wall_s = 0.0
        self.max_wall_s = 0.0
        self.rows = 0
        self.bytes = 0
        self.blocks = 0

    def add(self, wall: float, rows: int, nbytes: int):
        self.wall_s += wall
        self.max_wall_s = max(self.max_wall_s, wall)
        self.rows += rows
        self.bytes += nbytes
        self.blocks += 1


def _op_name(op) -> str:
    kind = op.kind
    if kind == "row_chain":
        steps = getattr(op.fn, "_steps", ())
        return "row_chain(%s)" % ",".join(k for k, _ in steps)
    return kind


def _fmt_bytes(n: float) -> str:
    for unit in ("B", "KB", "MB", "GB"):
        if abs(n) < 1024 or unit == "GB":
            return f"{n:.1f}{unit}" if unit != "B" else f"{int(n)}B"
        n /= 1024
    return f"{n:.1f}GB"


class DatasetStats:
    """Driver-side aggregate for one dataset execution."""

    def __init__(self, ops: List[Any], executed_remotely: bool):
        self.op_accs: List[_OpAcc] = [_OpAcc("read")] + [
            _OpAcc(_op_name(op)) for op in ops
        ]
        self.executed_remotely = executed_remotely
        self.iter_wait_s = 0.0  # driver blocked on the cluster (get)
        self.total_s = 0.0  # first submit -> iterator exhausted/closed
        self.blocks = 0
        self.finished = False
        self._t0 = time.perf_counter()

    def record(self, stat_rows: List[StatRow]):
        self.blocks += 1
        for idx, wall, rows, nbytes in stat_rows:
            acc = self.op_accs[idx + 1]
            acc.add(wall, rows, nbytes)

    def add_wait(self, dt: float):
        self.iter_wait_s += dt

    def close(self, finished: bool):
        if not self.finished:
            self.total_s = time.perf_counter() - self._t0
            self.finished = finished

    @property
    def output_rows(self) -> int:
        for acc in reversed(self.op_accs):
            if acc.blocks:
                return acc.rows
        return 0

    def to_dict(self) -> Dict[str, Any]:
        return {
            "operators": [
                {
                    "name": a.name,
                    "wall_s": round(a.wall_s, 6),
                    "max_block_wall_s": round(a.max_wall_s, 6),
                    "rows": a.rows,
                    "bytes": a.bytes,
                    "blocks": a.blocks,
                }
                for a in self.op_accs
                if a.blocks
            ],
            "iter_wait_s": round(self.iter_wait_s, 6),
            "total_s": round(self.total_s, 6),
            "blocks": self.blocks,
            "output_rows": self.output_rows,
            "executed_remotely": self.executed_remotely,
            "finished": self.finished,
        }

    def summary(self) -> str:
        """Human-readable per-operator table (reference: DatasetStats
        __repr__ / Dataset.stats() output)."""
        lines = []
        where = "cluster tasks" if self.executed_remotely else "driver process"
        state = "" if self.finished else " (iteration stopped early)"
        lines.append(
            f"Dataset execution over {self.blocks} blocks on {where}{state}:"
        )
        for a in self.op_accs:
            if not a.blocks:
                continue
            avg_ms = 1000.0 * a.wall_s / a.blocks
            lines.append(
                f"  {a.name}: {a.wall_s * 1000:.1f}ms total"
                f" (avg {avg_ms:.2f}ms/block, max {a.max_wall_s * 1000:.1f}ms),"
                f" {a.rows} rows out, {_fmt_bytes(a.bytes)} out,"
                f" {a.blocks} blocks"
            )
        lines.append(
            f"  iterator: {self.total_s * 1000:.1f}ms total,"
            f" {self.iter_wait_s * 1000:.1f}ms blocked waiting on blocks"
            f" ({100.0 * self.iter_wait_s / self.total_s if self.total_s else 0:.0f}%"
            " of wall)"
        )
        lines.append(f"  output rows: {self.output_rows}")
        return "\n".join(lines)


def publish(stats: "DatasetStats", label: Optional[str] = None):
    """Best-effort push of a finished execution's stats to the head so the
    dashboard's Datasets panel can show them (reference: StatsActor feeding
    dashboard/data's DataHead). Never raises; never blocks the iterator."""
    try:
        from ray_tpu._private.worker import global_worker

        if not global_worker.connected:
            return
        payload = stats.to_dict()
        payload["label"] = label
        payload["time"] = time.time()
        global_worker.request({"t": "report_data_stats", "stats": payload})
    except Exception:
        pass
