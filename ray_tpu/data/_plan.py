"""Logical-plan optimizer rules for Dataset op chains.

Reference parity: python/ray/data/_internal/logical/ (optimizers.py and its
rule set — OperatorFusionRule, limit pushdown) + planner/planner.py. The
reference optimizes a DAG of logical operators before lowering to physical
execution; ray_tpu's plan is a linear per-block op chain, so rules operate
on that chain right before execution (`Dataset._iter_computed_blocks`).

Rules:
- fuse_row_ops: consecutive row-level ops (map / filter / flat_map) fold
  into ONE "row_chain" op applied in a single pass per block — without it,
  every op materializes a full intermediate row list per block.
- fuse_map_batches: adjacent stateless map_batches with identical
  batch_size/fn_kwargs-free signatures compose into one op, skipping a
  slice+concat round per fused op.
- push_limit: a per-block row cap hops over the longest suffix of
  row-count-preserving ops (map, row_chain of maps) so remote tasks
  transform only rows that can survive the limit.
"""

from __future__ import annotations

from typing import Callable, List, Optional

from typing import TYPE_CHECKING

if TYPE_CHECKING:  # circular import (dataset imports this module)
    from .dataset import _Op

_ROW_KINDS = ("map", "filter", "flat_map")


def _make_row_chain(steps) -> Callable:
    """Compose row steps into one generator-style pass (fn for row_chain)."""

    def run(rows):
        out = []
        for row in rows:
            emit = [row]
            for kind, fn in steps:
                if kind == "map":
                    emit = [fn(r) for r in emit]
                elif kind == "filter":
                    emit = [r for r in emit if fn(r)]
                else:  # flat_map
                    nxt: List = []
                    for r in emit:
                        nxt.extend(fn(r))
                    emit = nxt
                if not emit:
                    break
            out.extend(emit)
        return out

    run._steps = steps  # introspection for explain()/tests
    return run


def fuse_row_ops(ops: List["_Op"]) -> List["_Op"]:
    from .dataset import _Op

    out: List[_Op] = []
    pending = []  # (kind, fn) steps to fuse
    for op in ops:
        if op.kind in _ROW_KINDS:
            pending.append((op.kind, op.fn))
            continue
        if pending:
            out.append(_make_chain_op(pending))
            pending = []
        out.append(op)
    if pending:
        out.append(_make_chain_op(pending))
    return out


def _make_chain_op(pending) -> "_Op":
    from .dataset import _Op

    if len(pending) == 1:  # nothing to fuse: keep the original kind
        return _Op(pending[0][0], pending[0][1])
    return _Op("row_chain", _make_row_chain(list(pending)))


def fuse_map_batches(ops: List["_Op"]) -> List["_Op"]:
    from .dataset import _Op

    out: List[_Op] = []
    for op in ops:
        prev = out[-1] if out else None
        if (
            prev is not None
            and op.kind == "map_batches" == prev.kind
            and op.compute == "tasks" == prev.compute
            and not isinstance(op.fn, type) and not isinstance(prev.fn, type)
            # fuse only whole-block ops: with a batch_size, the second op
            # re-slices the first's output, so if fn #1 changes row counts
            # fn #2 would stop seeing its declared batch shape when fused
            and op.batch_size is None and prev.batch_size is None
            and not op.fn_kwargs and not prev.fn_kwargs
        ):
            f, g = prev.fn, op.fn
            out[-1] = _Op("map_batches", lambda b, _f=f, _g=g: _g(_f(b)))
            continue
        out.append(op)
    return out


def _preserves_row_count(op: "_Op") -> bool:
    if op.kind == "map":
        return True
    if op.kind == "row_chain":
        return all(kind == "map" for kind, _ in getattr(op.fn, "_steps", [(None, None)]))
    return False


def push_limit(ops: List["_Op"], n: int) -> List["_Op"]:
    """Insert a per-block `limit` cap as early as row-count preservation
    allows. The global (cross-block) limit stays with the consumer."""
    from .dataset import _Op

    cap = _Op("limit", None, batch_size=n)
    i = len(ops)
    while i > 0 and _preserves_row_count(ops[i - 1]):
        i -= 1
    return ops[:i] + [cap] + ops[i:]


_PUSHDOWN_READERS = {}  # kind -> read_one(path, columns, filter_expr)


def _pushdown_reader(kind: str):
    """read_one factory per pushdown-capable source. parquet prunes at the
    file reader (columns + row-group filters); csv projects at parse time
    and masks post-parse; json masks/projects post-parse — each the deepest
    pruning its format supports (reference: per-datasource pushdown in the
    planner's read-op rules)."""
    if not _PUSHDOWN_READERS:
        from .dataset import _read_csv_one, _read_json_one, _read_parquet_one

        _PUSHDOWN_READERS.update(
            parquet=_read_parquet_one, csv=_read_csv_one, json=_read_json_one
        )
    return _PUSHDOWN_READERS.get(kind)


def pushdown_reads(read_meta, block_fns, ops: List["_Op"]):
    """Fold leading structured ops into the datasource scan.

    Scans the op-chain prefix for planner-markered ops (op.meta): every
    leading `filter(Expr)` pushes its predicate, and a `select_columns`
    pushes its projection; filters AFTER the projection still push when
    every column they read survives it. Pushed ops are dropped; the reads
    are rebuilt with columns=/filters= so pruning happens inside the
    reader (reference: the logical planner's read-op pushdown rules +
    datasource-level `columns`/`filter` args). Applies to parquet, csv,
    and json sources.
    """
    read_one = _pushdown_reader(read_meta.get("kind")) if read_meta else None
    if read_one is None:
        return block_fns, ops
    exprs = []
    cols = None
    n_pushed = 0
    for op in ops:
        tag = getattr(op, "meta", None)
        if not tag:
            break
        if tag[0] == "filter_expr":
            if cols is not None and not set(tag[1].columns()) <= set(cols):
                break  # reads a projected-away column: cannot cross
            exprs.append(tag[1])
            n_pushed += 1
            continue
        if tag[0] == "select":
            if cols is not None:
                break  # a second projection: stop at the first
            cols = list(tag[1])
            n_pushed += 1
            continue
        break
    if n_pushed == 0:
        return block_fns, ops
    import functools

    expr = read_meta.get("filter")
    for e in exprs:
        expr = e if expr is None else (expr & e)
    if cols is None:
        cols = read_meta.get("columns")
    fns = [
        functools.partial(read_one, p, cols, expr)
        for p in read_meta["paths"]
    ]
    return fns, ops[n_pushed:]


# ordered, extensible rule registry (reference: logical/optimizers.py —
# LogicalOptimizer runs a list of Rule objects; users add theirs). Each
# rule: List[_Op] -> List[_Op], pure. pushdown_reads stays separate — it
# rewrites the SOURCE, not the chain, and needs read_meta.
_RULES: List[Callable[[List["_Op"]], List["_Op"]]] = [
    fuse_row_ops,
    fuse_map_batches,
]


def register_optimizer_rule(rule: Callable[[List["_Op"]], List["_Op"]],
                            *, before: Optional[Callable] = None) -> None:
    """Add a chain-rewrite rule to the optimizer pipeline (appended, or
    inserted before an existing rule)."""
    if before is not None:
        _RULES.insert(_RULES.index(before), rule)
    else:
        _RULES.append(rule)


def optimize(ops: List["_Op"]) -> List["_Op"]:
    """The rule pipeline applied before execution."""
    for rule in _RULES:
        ops = rule(ops)
    return ops


def explain(ops: List["_Op"]) -> str:
    """Human-readable plan: original -> optimized (reference: the logical
    plan dumps used by Dataset.explain/stats)."""
    def fmt(chain):
        parts = []
        for op in chain:
            if op.kind == "row_chain":
                steps = "+".join(k for k, _ in getattr(op.fn, "_steps", []))
                parts.append(f"row_chain[{steps}]")
            else:
                parts.append(op.kind)
        return " -> ".join(parts) if parts else "(read only)"

    return f"logical: {fmt(ops)}\noptimized: {fmt(optimize(ops))}"
