"""TFRecord container + tf.train.Example codec, dependency-free.

Reference parity: python/ray/data/datasource/tfrecords_datasource.py —
the reference reads/writes TFRecord files of tf.train.Example protos via
tensorflow. TPUs feed from the same format (it is the standard corpus
container on GCS), but pulling tensorflow into a JAX framework for a
16-byte framing and three proto messages is absurd, so both are
implemented directly:

- TFRecord framing: each record is
    uint64 length | uint32 masked-crc32c(length) | data | uint32 masked-crc32c(data)
  (masked_crc = ((crc >> 15 | crc << 17) + 0xa282ead8) & 0xffffffff).
- tf.train.Example wire format (proto3):
    Example.features(1) -> Features.feature(1) = map<string, Feature>
    Feature: bytes_list(1) | float_list(2) | int64_list(3)
  with float_list/int64_list packed-repeated.
"""

from __future__ import annotations

import struct
from typing import Any, Dict, Iterator, List

import numpy as np

# --------------------------------------------------------------------------
# crc32c (Castagnoli). A C extension is used when one is importable; the
# fallback is a slicing-by-8 table implementation (8 bytes per loop
# iteration over plain-list tables — numpy scalar indexing is slower than
# list indexing for this access pattern).
# --------------------------------------------------------------------------


def _build_tables():
    table0 = []
    for i in range(256):
        c = i
        for _ in range(8):
            c = (c >> 1) ^ (0x82F63B78 if c & 1 else 0)
        table0.append(c)
    tables = [table0]
    for t in range(1, 8):
        prev = tables[t - 1]
        tables.append([(prev[i] >> 8) ^ table0[prev[i] & 0xFF] for i in range(256)])
    return tables


_TABLES = _build_tables()
_T0, _T1, _T2, _T3, _T4, _T5, _T6, _T7 = _TABLES


def _crc32c_py(data: bytes) -> int:
    crc = 0xFFFFFFFF
    n = len(data)
    i = 0
    # slicing-by-8: one table lookup per byte but only one loop iteration
    # (and one int rebuild) per 8 bytes
    end8 = n - (n % 8)
    while i < end8:
        crc ^= int.from_bytes(data[i : i + 4], "little")
        b4, b5, b6, b7 = data[i + 4], data[i + 5], data[i + 6], data[i + 7]
        crc = (
            _T7[crc & 0xFF]
            ^ _T6[(crc >> 8) & 0xFF]
            ^ _T5[(crc >> 16) & 0xFF]
            ^ _T4[(crc >> 24) & 0xFF]
            ^ _T3[b4]
            ^ _T2[b5]
            ^ _T1[b6]
            ^ _T0[b7]
        )
        i += 8
    t0 = _T0
    while i < n:
        crc = (crc >> 8) ^ t0[(crc ^ data[i]) & 0xFF]
        i += 1
    return crc ^ 0xFFFFFFFF


try:  # optional C extensions (not baked into this environment, but common)
    import google_crc32c as _gcrc

    def crc32c(data: bytes) -> int:
        return _gcrc.value(data)

except ImportError:
    try:
        from crc32c import crc32c as _ccrc  # type: ignore

        def crc32c(data: bytes) -> int:
            return _ccrc(data)

    except ImportError:
        crc32c = _crc32c_py


def masked_crc(data: bytes) -> int:
    crc = crc32c(data)
    return ((crc >> 15 | crc << 17) + 0xA282EAD8) & 0xFFFFFFFF


# --------------------------------------------------------------------------
# record framing
# --------------------------------------------------------------------------


def read_records(path: str, *, verify_crc: bool = False) -> Iterator[bytes]:
    """Yield raw record payloads. CRC verification is opt-in: the checksums
    date from tape-era durability concerns and double the read cost."""
    with open(path, "rb") as f:
        while True:
            header = f.read(12)
            if len(header) < 12:
                return
            (length,) = struct.unpack("<Q", header[:8])
            if verify_crc:
                (crc,) = struct.unpack("<I", header[8:12])
                if masked_crc(header[:8]) != crc:
                    raise ValueError(f"corrupt TFRecord length crc in {path}")
            data = f.read(length)
            if len(data) < length:
                raise ValueError(f"truncated TFRecord in {path}")
            footer = f.read(4)
            if verify_crc:
                (crc,) = struct.unpack("<I", footer)
                if masked_crc(data) != crc:
                    raise ValueError(f"corrupt TFRecord data crc in {path}")
            yield data


def write_records(path: str, payloads: Iterator[bytes]) -> int:
    n = 0
    with open(path, "wb") as f:
        for data in payloads:
            header = struct.pack("<Q", len(data))
            f.write(header)
            f.write(struct.pack("<I", masked_crc(header)))
            f.write(data)
            f.write(struct.pack("<I", masked_crc(data)))
            n += 1
    return n


# --------------------------------------------------------------------------
# proto wire helpers
# --------------------------------------------------------------------------


def _read_varint(buf: bytes, pos: int):
    result = 0
    shift = 0
    while True:
        b = buf[pos]
        pos += 1
        result |= (b & 0x7F) << shift
        if not b & 0x80:
            return result, pos
        shift += 7


def _write_varint(out: bytearray, value: int) -> None:
    while True:
        b = value & 0x7F
        value >>= 7
        if value:
            out.append(b | 0x80)
        else:
            out.append(b)
            return


def _iter_fields(buf: bytes):
    """Yield (field_number, wire_type, value) over a serialized message.
    Length-delimited values are returned as memoryview slices."""
    pos = 0
    mv = memoryview(buf)
    n = len(buf)
    while pos < n:
        tag, pos = _read_varint(buf, pos)
        field, wire = tag >> 3, tag & 7
        if wire == 0:  # varint
            value, pos = _read_varint(buf, pos)
        elif wire == 2:  # length-delimited
            length, pos = _read_varint(buf, pos)
            value = mv[pos : pos + length]
            pos += length
        elif wire == 5:  # 32-bit
            value = mv[pos : pos + 4]
            pos += 4
        elif wire == 1:  # 64-bit
            value = mv[pos : pos + 8]
            pos += 8
        else:
            raise ValueError(f"unsupported proto wire type {wire}")
        yield field, wire, value


def _decode_feature(buf: bytes) -> Any:
    """Feature -> list of python values (bytes | float | int)."""
    for field, wire, value in _iter_fields(buf):
        payload = bytes(value)
        if field == 1:  # BytesList
            return [bytes(v) for f, w, v in _iter_fields(payload) if f == 1]
        if field == 2:  # FloatList (packed or not)
            out: List[float] = []
            for f, w, v in _iter_fields(payload):
                if f != 1:
                    continue
                if w == 2:  # packed
                    out.extend(np.frombuffer(v, dtype="<f4").tolist())
                else:  # single 32-bit
                    out.append(struct.unpack("<f", v)[0])
            return out
        if field == 3:  # Int64List (packed varints or not)
            out = []
            for f, w, v in _iter_fields(payload):
                if f != 1:
                    continue
                if w == 2:  # packed varints
                    raw = bytes(v)
                    pos = 0
                    while pos < len(raw):
                        n, pos = _read_varint(raw, pos)
                        out.append(n - (1 << 64) if n >= (1 << 63) else n)
                else:
                    out.append(v - (1 << 64) if v >= (1 << 63) else v)
            return out
    return []


def parse_example(buf: bytes) -> Dict[str, Any]:
    """tf.train.Example bytes -> {feature name: scalar or list}."""
    row: Dict[str, Any] = {}
    for field, _, value in _iter_fields(bytes(buf)):
        if field != 1:  # Example.features
            continue
        for f2, _, entry in _iter_fields(bytes(value)):
            if f2 != 1:  # Features.feature map entry
                continue
            key = None
            feat: Any = []
            for f3, _, v3 in _iter_fields(bytes(entry)):
                if f3 == 1:
                    key = bytes(v3).decode()
                elif f3 == 2:
                    feat = _decode_feature(bytes(v3))
            if key is not None:
                row[key] = feat[0] if len(feat) == 1 else feat
    return row


def _encode_field(out: bytearray, field: int, wire: int, payload: bytes = b"",
                  varint: int = 0) -> None:
    _write_varint(out, field << 3 | wire)
    if wire == 0:
        _write_varint(out, varint)
    else:
        _write_varint(out, len(payload))
        out += payload


def _encode_feature(values: List[Any]) -> bytes:
    inner = bytearray()
    if values and isinstance(values[0], (bytes, str)):
        blist = bytearray()
        for v in values:
            _encode_field(blist, 1, 2, v.encode() if isinstance(v, str) else v)
        _encode_field(inner, 1, 2, bytes(blist))
    elif values and isinstance(values[0], (float, np.floating)):
        packed = np.asarray(values, dtype="<f4").tobytes()
        flist = bytearray()
        _encode_field(flist, 1, 2, packed)
        _encode_field(inner, 2, 2, bytes(flist))
    else:  # ints (including empty lists)
        packed = bytearray()
        for v in values:
            _write_varint(packed, int(v) & ((1 << 64) - 1))
        ilist = bytearray()
        _encode_field(ilist, 1, 2, bytes(packed))
        _encode_field(inner, 3, 2, bytes(ilist))
    return bytes(inner)


def build_example(row: Dict[str, Any]) -> bytes:
    """{name: scalar or list} -> serialized tf.train.Example."""
    features = bytearray()
    for key, value in row.items():
        if isinstance(value, np.ndarray):
            value = value.tolist()
        values = value if isinstance(value, (list, tuple)) else [value]
        entry = bytearray()
        _encode_field(entry, 1, 2, key.encode())
        _encode_field(entry, 2, 2, _encode_feature(list(values)))
        _encode_field(features, 1, 2, bytes(entry))
    example = bytearray()
    _encode_field(example, 1, 2, bytes(features))
    return bytes(example)
