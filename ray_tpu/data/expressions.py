"""Column expressions: introspectable predicates the planner can push down.

Reference parity: the reference's logical planner pushes structured
predicates/projections into file reads (data/_internal/logical/ rules +
datasource-level `columns`/`filter` args; pyarrow dataset expressions).
Opaque Python lambdas can't be reordered safely — an expression tree can:

    from ray_tpu.data import col
    ds = read_parquet(path).filter((col("score") > 0.5) & (col("split") == "train"))

`Dataset.filter(expr)` evaluates vectorized in column space, and the
pushdown rule rewrites parquet reads to `pq.read_table(..., filters=expr)`
so pruned row groups never leave disk.
"""

from __future__ import annotations

from typing import Any, Dict, Set

import numpy as np

_OPS = {
    ">": lambda a, b: a > b,
    ">=": lambda a, b: a >= b,
    "<": lambda a, b: a < b,
    "<=": lambda a, b: a <= b,
    "==": lambda a, b: a == b,
    "!=": lambda a, b: a != b,
    "&": lambda a, b: a & b,
    "|": lambda a, b: a | b,
}


class Expr:
    """Base: comparisons/logic build a tree; `mask(cols)` evaluates it."""

    def __gt__(self, other):
        return _Cmp(">", self, _wrap(other))

    def __ge__(self, other):
        return _Cmp(">=", self, _wrap(other))

    def __lt__(self, other):
        return _Cmp("<", self, _wrap(other))

    def __le__(self, other):
        return _Cmp("<=", self, _wrap(other))

    def __eq__(self, other):  # type: ignore[override]
        return _Cmp("==", self, _wrap(other))

    def __ne__(self, other):  # type: ignore[override]
        return _Cmp("!=", self, _wrap(other))

    def __and__(self, other):
        return _Cmp("&", self, _wrap(other))

    def __or__(self, other):
        return _Cmp("|", self, _wrap(other))

    def __invert__(self):
        return _Not(self)

    __hash__ = None  # type: ignore[assignment]

    def isin(self, values):
        return _IsIn(self, list(values))

    # -- interface --
    def mask(self, cols: Dict[str, np.ndarray]) -> np.ndarray:
        raise NotImplementedError

    def columns(self) -> Set[str]:
        raise NotImplementedError

    def to_arrow(self):
        """pyarrow.compute expression for datasource pushdown."""
        raise NotImplementedError


class Col(Expr):
    def __init__(self, name: str):
        self.name = name

    def mask(self, cols):
        return np.asarray(cols[self.name])

    def columns(self):
        return {self.name}

    def to_arrow(self):
        import pyarrow.compute as pc

        return pc.field(self.name)

    def __repr__(self):
        return f"col({self.name!r})"


class _Lit(Expr):
    def __init__(self, value: Any):
        self.value = value

    def mask(self, cols):
        return self.value

    def columns(self):
        return set()

    def to_arrow(self):
        import pyarrow.compute as pc

        return pc.scalar(self.value)

    def __repr__(self):
        return repr(self.value)


def _wrap(v) -> Expr:
    return v if isinstance(v, Expr) else _Lit(v)


class _Cmp(Expr):
    def __init__(self, op: str, left: Expr, right: Expr):
        self.op, self.left, self.right = op, left, right

    def mask(self, cols):
        return _OPS[self.op](self.left.mask(cols), self.right.mask(cols))

    def columns(self):
        return self.left.columns() | self.right.columns()

    def to_arrow(self):
        l, r = self.left.to_arrow(), self.right.to_arrow()
        if self.op == "&":
            return l & r
        if self.op == "|":
            return l | r
        return _OPS[self.op](l, r)

    def __repr__(self):
        return f"({self.left!r} {self.op} {self.right!r})"


class _Not(Expr):
    def __init__(self, inner: Expr):
        self.inner = inner

    def mask(self, cols):
        return ~np.asarray(self.inner.mask(cols))

    def columns(self):
        return self.inner.columns()

    def to_arrow(self):
        return ~self.inner.to_arrow()

    def __repr__(self):
        return f"~{self.inner!r}"


class _IsIn(Expr):
    def __init__(self, inner: Expr, values: list):
        self.inner, self.values = inner, values

    def mask(self, cols):
        return np.isin(np.asarray(self.inner.mask(cols)), self.values)

    def columns(self):
        return self.inner.columns()

    def to_arrow(self):
        import pyarrow.compute as pc

        return self.inner.to_arrow().isin(self.values)

    def __repr__(self):
        return f"{self.inner!r}.isin({self.values!r})"


def col(name: str) -> Col:
    return Col(name)
