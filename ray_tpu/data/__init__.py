"""ray_tpu.data: lazy, streaming, distributed datasets.

Reference parity: python/ray/data (Dataset dataset.py:168, lazy logical plan,
streaming executor streaming_executor.py:48, blocks = Arrow/numpy). TPU-first
additions: per-host shard iterators with double-buffered jax.device_put
prefetch (SURVEY §7.1 M4), feeding sharded global batches directly onto a
mesh.
"""

from .dataset import (  # noqa: F401
    Dataset,
    from_items,
    from_numpy,
    from_pandas,
    range as range_,  # noqa: A001
    read_csv,
    read_json,
    read_numpy,
    read_parquet,
)
from .datasource import (  # noqa: F401
    read_binary_files,
    read_images,
    read_sql,
    read_tfrecords,
    read_webdataset,
)
from .expressions import Expr, col  # noqa: F401
from .grouped_data import GroupedData  # noqa: F401
from . import aggregate  # noqa: F401
from .aggregate import AggregateFn  # noqa: F401

range = range_  # noqa: A001 — mirror ray.data.range

from .._private.usage import record_library_usage as _rlu  # noqa: E402

_rlu("data")
