"""Named-channel pub/sub over the cluster control plane.

Reference parity: src/ray/pubsub (publisher.h:307, subscriber.h:329) — the
long-poll publisher/subscriber the reference uses for object-location,
actor, node, log, and error channels. ray_tpu exposes the same mechanism as
a small utility: named channels on the head, push delivery to subscribed
processes, and a long-poll primitive (the transport under Serve's config
push, serve/_private/long_poll.py:68).

Channels retain only the LATEST published value (snapshot semantics, like
the reference's long-poll "object state" channels) — subscribers that join
late see the current snapshot plus all future publishes.
"""

from __future__ import annotations

from typing import Any, Callable, Optional, Tuple

from .._private.worker import global_worker


def publish(channel: str, data: Any) -> int:
    """Publish `data` to `channel`; returns the new sequence number."""
    return global_worker.publish(channel, data)


def subscribe(channel: str, callback: Callable[[int, Any], None]) -> Tuple[int, Any]:
    """Register `callback(seq, data)` for pushes on `channel`.

    Returns the (seq, data) snapshot at subscribe time — (0, None) if the
    channel has never been published. The callback runs on a background
    thread in this process.
    """
    return global_worker.subscribe(channel, callback)


def unsubscribe(channel: str) -> None:
    global_worker.unsubscribe(channel)


def poll(
    channel: str, last_seq: int = 0, timeout: float = 30.0
) -> Optional[Tuple[int, Any]]:
    """Block until `channel` has a publish newer than `last_seq`; returns
    (seq, data), or None if `timeout` elapses first (re-poll to continue —
    classic long-poll)."""
    return global_worker.poll_channel(channel, last_seq, timeout)
