"""Application metrics: Counter / Gauge / Histogram.

Reference parity: python/ray/util/metrics.py (Counter :150, Histogram :215,
Gauge :290) + the per-node MetricsAgent (python/ray/_private/metrics_agent.py)
that converts to Prometheus. Here every process keeps a local registry and
pushes throttled snapshots to the head over the control socket (the
reference's opencensus export path); `export_prometheus()` renders the
cluster-wide aggregate in Prometheus text format.
"""

from __future__ import annotations

import os
import threading
import time
from typing import Dict, List, Optional, Sequence, Tuple

_FLUSH_INTERVAL_S = 0.5

DEFAULT_HISTOGRAM_BOUNDARIES = [
    0.001, 0.005, 0.01, 0.05, 0.1, 0.5, 1.0, 5.0, 10.0, 50.0, 100.0,
]


class _Registry:
    def __init__(self):
        self.lock = threading.Lock()
        self.metrics: Dict[str, "Metric"] = {}
        self._last_flush = 0.0

    def register(self, m: "Metric"):
        """Same-name re-creation ALIASES the existing metric (shared values/
        lock) instead of replacing it — a task body re-declaring a Counter in
        a reused worker process keeps accumulating, never resets."""
        with self.lock:
            existing = self.metrics.get(m.name)
            if existing is not None:
                if type(existing) is not type(m):
                    raise ValueError(
                        f"metric {m.name!r} already registered as {type(existing).__name__}"
                    )
                if isinstance(m, Histogram) and m.boundaries != existing.boundaries:
                    raise ValueError(
                        f"histogram {m.name!r} already registered with boundaries "
                        f"{existing.boundaries}, got {m.boundaries}"
                    )
                m._values = existing._values
                m._lock = existing._lock
                return
            self.metrics[m.name] = m

    def snapshot(self) -> Dict[str, dict]:
        with self.lock:
            return {name: m._snapshot() for name, m in self.metrics.items()}

    def maybe_flush(self, force: bool = False):
        now = time.monotonic()
        if not force and now - self._last_flush < _FLUSH_INTERVAL_S:
            return
        self._last_flush = now
        try:
            from .._private.worker import global_worker

            if global_worker.connected:
                # node id disambiguates same-pid workers on different hosts
                node = getattr(global_worker, "node_id", None) or "node"
                global_worker.send(
                    {
                        "t": "push_metrics",
                        "proc": f"{node}:pid-{os.getpid()}",
                        "metrics": self.snapshot(),
                    }
                )
        except Exception:
            pass  # metrics must never break the workload


_REGISTRY = _Registry()


def _tags_key(tags: Optional[Dict[str, str]]) -> Tuple[Tuple[str, str], ...]:
    return tuple(sorted((tags or {}).items()))


class Metric:
    def __init__(self, name: str, description: str = "", tag_keys: Sequence[str] = ()):
        if not name or not isinstance(name, str):
            raise ValueError("metric name must be a non-empty string")
        self.name = name
        self.description = description
        self.tag_keys = tuple(tag_keys)
        self._default_tags: Dict[str, str] = {}
        self._values: Dict[Tuple, float] = {}
        self._lock = threading.Lock()
        _REGISTRY.register(self)

    def set_default_tags(self, tags: Dict[str, str]):
        self._default_tags = dict(tags)
        return self

    def _merged(self, tags: Optional[Dict[str, str]]) -> Dict[str, str]:
        out = dict(self._default_tags)
        out.update(tags or {})
        extra = set(out) - set(self.tag_keys)
        if extra:
            raise ValueError(f"unknown tag keys {sorted(extra)} for metric {self.name!r}")
        return out

    def _snapshot(self) -> dict:
        import copy

        with self._lock:
            return {
                "type": type(self).__name__.lower(),
                "description": self.description,
                # deep-copy: histogram value dicts must not be mutated after
                # the lock is released (pickling happens later on the IO thread)
                "values": {k: copy.deepcopy(v) for k, v in self._values.items()},
            }


class Counter(Metric):
    """Monotonic counter (reference: util/metrics.py:150)."""

    def inc(self, value: float = 1.0, tags: Optional[Dict[str, str]] = None):
        if value < 0:
            raise ValueError("Counter.inc value must be >= 0")
        key = _tags_key(self._merged(tags))
        with self._lock:
            self._values[key] = self._values.get(key, 0.0) + value
        _REGISTRY.maybe_flush()


class Gauge(Metric):
    """Last-value gauge (reference: util/metrics.py:290)."""

    def set(self, value: float, tags: Optional[Dict[str, str]] = None):
        key = _tags_key(self._merged(tags))
        with self._lock:
            self._values[key] = float(value)
        _REGISTRY.maybe_flush()


class Histogram(Metric):
    """Bucketed histogram (reference: util/metrics.py:215)."""

    def __init__(
        self,
        name: str,
        description: str = "",
        boundaries: Optional[List[float]] = None,
        tag_keys: Sequence[str] = (),
    ):
        self.boundaries = sorted(boundaries or DEFAULT_HISTOGRAM_BOUNDARIES)
        if any(b <= 0 for b in self.boundaries):
            raise ValueError("histogram boundaries must be positive")
        super().__init__(name, description, tag_keys)

    def observe(self, value: float, tags: Optional[Dict[str, str]] = None):
        key = _tags_key(self._merged(tags))
        with self._lock:
            ent = self._values.get(key)
            if not isinstance(ent, dict):
                ent = self._values[key] = {
                    "buckets": [0] * (len(self.boundaries) + 1),
                    "sum": 0.0,
                    "count": 0,
                }
            idx = len(self.boundaries)
            for i, b in enumerate(self.boundaries):
                if value <= b:
                    idx = i
                    break
            ent["buckets"][idx] += 1
            ent["sum"] += value
            ent["count"] += 1
        _REGISTRY.maybe_flush()

    def _snapshot(self) -> dict:
        snap = super()._snapshot()
        snap["boundaries"] = list(self.boundaries)
        return snap


def flush():
    """Force-push this process's metrics to the head."""
    _REGISTRY.maybe_flush(force=True)


def _fmt_tags(tags: Tuple[Tuple[str, str], ...], extra: str = "") -> str:
    parts = [f'{k}="{v}"' for k, v in tags]
    if extra:
        parts.append(extra)
    return "{" + ",".join(parts) + "}" if parts else ""


def export_prometheus() -> str:
    """Render the cluster-wide metric aggregate (all processes) as
    Prometheus text (reference: metrics_agent.py opencensus->prometheus)."""
    from .._private.worker import global_worker

    flush()
    store = global_worker.request({"t": "get_metrics"})
    # merge: counters/histograms sum across processes; gauges take the most
    # recent process write (push timestamp order)
    merged: Dict[str, dict] = {}
    gauge_ts: Dict[Tuple[str, Tuple], float] = {}
    for proc in sorted(store, key=lambda p: store[p].get("ts", 0.0)):
        ts = store[proc].get("ts", 0.0)
        for name, snap in store[proc].get("metrics", {}).items():
            m = merged.setdefault(
                name,
                {
                    "type": snap["type"],
                    "description": snap["description"],
                    "boundaries": snap.get("boundaries"),
                    "values": {},
                },
            )
            if snap["type"] != m["type"] or snap.get("boundaries") != m["boundaries"]:
                # cross-process schema clash: skip rather than crash the export
                continue
            for tags, v in snap["values"].items():
                if m["type"] == "histogram":
                    ent = m["values"].setdefault(
                        tags, {"buckets": [0] * (len(m["boundaries"]) + 1), "sum": 0.0, "count": 0}
                    )
                    ent["buckets"] = [a + b for a, b in zip(ent["buckets"], v["buckets"])]
                    ent["sum"] += v["sum"]
                    ent["count"] += v["count"]
                elif m["type"] == "counter":
                    m["values"][tags] = m["values"].get(tags, 0.0) + v
                else:  # gauge: most recent push wins
                    if ts >= gauge_ts.get((name, tags), -1.0):
                        gauge_ts[(name, tags)] = ts
                        m["values"][tags] = v
    lines = []
    for name, m in sorted(merged.items()):
        if m["description"]:
            lines.append(f"# HELP {name} {m['description']}")
        ptype = {"counter": "counter", "gauge": "gauge", "histogram": "histogram"}[m["type"]]
        lines.append(f"# TYPE {name} {ptype}")
        for tags, v in sorted(m["values"].items()):
            if m["type"] == "histogram":
                cum = 0
                for b, n in zip(m["boundaries"], v["buckets"]):
                    cum += n
                    le = f'le="{b}"'
                    lines.append(f"{name}_bucket{_fmt_tags(tags, le)} {cum}")
                inf = 'le="+Inf"'
                lines.append(f"{name}_bucket{_fmt_tags(tags, inf)} {v['count']}")
                lines.append(f"{name}_sum{_fmt_tags(tags)} {v['sum']}")
                lines.append(f"{name}_count{_fmt_tags(tags)} {v['count']}")
            else:
                lines.append(f"{name}{_fmt_tags(tags)} {v}")
    return "\n".join(lines) + "\n"
