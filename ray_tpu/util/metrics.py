"""Application metrics: Counter / Gauge / Histogram.

Reference parity: python/ray/util/metrics.py (Counter :150, Histogram :215,
Gauge :290) + the per-node MetricsAgent (python/ray/_private/metrics_agent.py)
that converts to Prometheus. Here every process keeps a local registry and
pushes throttled snapshots to the head over the control socket (the
reference's opencensus export path); `export_prometheus()` renders the
cluster-wide aggregate in Prometheus text format.
"""

from __future__ import annotations

import os
import threading
import time
from typing import Dict, List, Optional, Sequence, Tuple

_FLUSH_INTERVAL_S = 0.5

DEFAULT_HISTOGRAM_BOUNDARIES = [
    0.001, 0.005, 0.01, 0.05, 0.1, 0.5, 1.0, 5.0, 10.0, 50.0, 100.0,
]


class _Registry:
    def __init__(self):
        self.lock = threading.Lock()
        self.metrics: Dict[str, "Metric"] = {}
        self._last_flush = 0.0

    def register(self, m: "Metric"):
        """Same-name re-creation ALIASES the existing metric (shared values/
        lock) instead of replacing it — a task body re-declaring a Counter in
        a reused worker process keeps accumulating, never resets."""
        with self.lock:
            existing = self.metrics.get(m.name)
            if existing is not None:
                if type(existing) is not type(m):
                    raise ValueError(
                        f"metric {m.name!r} already registered as {type(existing).__name__}"
                    )
                if isinstance(m, Histogram) and m.boundaries != existing.boundaries:
                    raise ValueError(
                        f"histogram {m.name!r} already registered with boundaries "
                        f"{existing.boundaries}, got {m.boundaries}"
                    )
                m._values = existing._values
                m._lock = existing._lock
                return
            self.metrics[m.name] = m

    def snapshot(self) -> Dict[str, dict]:
        with self.lock:
            return {name: m._snapshot() for name, m in self.metrics.items()}

    def maybe_flush(self, force: bool = False):
        now = time.monotonic()
        if not force and now - self._last_flush < _FLUSH_INTERVAL_S:
            return
        self._last_flush = now
        try:
            from .._private.worker import global_worker

            if global_worker.connected:
                # node id disambiguates same-pid workers on different hosts
                node = getattr(global_worker, "node_id", None) or "node"
                global_worker.send(
                    {
                        "t": "push_metrics",
                        "proc": f"{node}:pid-{os.getpid()}",
                        "metrics": self.snapshot(),
                    }
                )
        except Exception:
            pass  # metrics must never break the workload


_REGISTRY = _Registry()


def _tags_key(tags: Optional[Dict[str, str]]) -> Tuple[Tuple[str, str], ...]:
    return tuple(sorted((tags or {}).items()))


class Metric:
    def __init__(self, name: str, description: str = "", tag_keys: Sequence[str] = ()):
        if not name or not isinstance(name, str):
            raise ValueError("metric name must be a non-empty string")
        self.name = name
        self.description = description
        self.tag_keys = tuple(tag_keys)
        self._default_tags: Dict[str, str] = {}
        # hot-path cache: an observe/inc/set with NO call-site tags uses
        # the precomputed default key directly — no dict merge, no set
        # difference, no sort per data point (serving observes per token)
        self._default_key: Tuple[Tuple[str, str], ...] = ()
        self._values: Dict[Tuple, float] = {}
        self._lock = threading.Lock()
        _REGISTRY.register(self)

    def set_default_tags(self, tags: Dict[str, str]):
        extra = set(tags) - set(self.tag_keys)
        if extra:
            raise ValueError(
                f"unknown tag keys {sorted(extra)} for metric {self.name!r}")
        self._default_tags = dict(tags)
        self._default_key = _tags_key(self._default_tags)
        return self

    def _merged(self, tags: Optional[Dict[str, str]]) -> Dict[str, str]:
        out = dict(self._default_tags)
        out.update(tags or {})
        extra = set(out) - set(self.tag_keys)
        if extra:
            raise ValueError(f"unknown tag keys {sorted(extra)} for metric {self.name!r}")
        return out

    def _snapshot(self) -> dict:
        import copy

        with self._lock:
            return {
                "type": type(self).__name__.lower(),
                "description": self.description,
                # deep-copy: histogram value dicts must not be mutated after
                # the lock is released (pickling happens later on the IO thread)
                "values": {k: copy.deepcopy(v) for k, v in self._values.items()},
            }


class Counter(Metric):
    """Monotonic counter (reference: util/metrics.py:150)."""

    def inc(self, value: float = 1.0, tags: Optional[Dict[str, str]] = None):
        if value < 0:
            raise ValueError("Counter.inc value must be >= 0")
        key = (self._default_key if tags is None
               else _tags_key(self._merged(tags)))
        with self._lock:
            self._values[key] = self._values.get(key, 0.0) + value
        _REGISTRY.maybe_flush()


class Gauge(Metric):
    """Last-value gauge (reference: util/metrics.py:290)."""

    def set(self, value: float, tags: Optional[Dict[str, str]] = None):
        key = (self._default_key if tags is None
               else _tags_key(self._merged(tags)))
        with self._lock:
            self._values[key] = float(value)
        _REGISTRY.maybe_flush()


class Histogram(Metric):
    """Bucketed histogram (reference: util/metrics.py:215)."""

    def __init__(
        self,
        name: str,
        description: str = "",
        boundaries: Optional[List[float]] = None,
        tag_keys: Sequence[str] = (),
    ):
        self.boundaries = sorted(boundaries or DEFAULT_HISTOGRAM_BOUNDARIES)
        if any(b <= 0 for b in self.boundaries):
            raise ValueError("histogram boundaries must be positive")
        super().__init__(name, description, tag_keys)

    def observe(self, value: float, tags: Optional[Dict[str, str]] = None):
        key = (self._default_key if tags is None
               else _tags_key(self._merged(tags)))
        self.observe_key(value, key)

    def observe_key(self, value: float, key: Tuple[Tuple[str, str], ...]):
        """Fast path for hot loops: observe under a PRECOMPUTED tags key
        (see tags_key) — no per-point dict merge/validation/sort."""
        with self._lock:
            ent = self._values.get(key)
            if not isinstance(ent, dict):
                ent = self._values[key] = {
                    "buckets": [0] * (len(self.boundaries) + 1),
                    "sum": 0.0,
                    "count": 0,
                }
            idx = len(self.boundaries)
            for i, b in enumerate(self.boundaries):
                if value <= b:
                    idx = i
                    break
            ent["buckets"][idx] += 1
            ent["sum"] += value
            ent["count"] += 1
        _REGISTRY.maybe_flush()

    def tags_key(self, tags: Optional[Dict[str, str]] = None):
        """Precompute an observe_key key for default tags + `tags`."""
        return (self._default_key if tags is None
                else _tags_key(self._merged(tags)))

    def _snapshot(self) -> dict:
        snap = super()._snapshot()
        snap["boundaries"] = list(self.boundaries)
        return snap


def data_plane_orphaned_counter() -> Counter:
    """THE definition of data_plane_orphaned_requests_total — shared by
    the protocol watchdog's serve-free fallback and the serve telemetry
    plane, so the two registration sites cannot drift (the registry
    aliases by name and keeps the first description it sees)."""
    return Counter(
        "data_plane_orphaned_requests_total",
        "data-plane requests past the no-reply warn deadline "
        "(request/reply correlation loss suspects)",
        tag_keys=("kind",),
    )


def data_plane_retries_counter() -> Counter:
    """Retransmits sent for deadline-armed plane requests (same shared
    single-definition discipline as data_plane_orphaned_counter)."""
    return Counter(
        "data_plane_request_retries_total",
        "deadline-expired data-plane requests retransmitted with the "
        "same rid and a bumped attempt counter",
        tag_keys=("kind",),
    )


def data_plane_recovered_counter() -> Counter:
    """Requests answered only AFTER at least one retransmit — recovery
    made as visible as loss (the orphaned counter) was."""
    return Counter(
        "data_plane_requests_recovered_total",
        "data-plane requests whose reply arrived only after retransmit "
        "(a lost request/reply pair that self-healed)",
        tag_keys=("kind",),
    )


def data_plane_duplicate_replies_counter() -> Counter:
    return Counter(
        "data_plane_duplicate_replies_total",
        "replies dropped because their rid was already answered or "
        "abandoned (retransmit races and late replies)",
        tag_keys=(),
    )


def bulk_plane_bytes_counter() -> Counter:
    """Bytes moved by the bulk object plane, by transfer path (same shared
    single-definition discipline as data_plane_orphaned_counter)."""
    return Counter(
        "bulk_plane_bytes_total",
        "bytes pulled over the bulk object plane, tagged by path: "
        "direct (single-socket / same-host slab), striped (parallel "
        "READ_RANGE sockets), relay (through the head), spilled "
        "(served from a peer's spill file)",
        tag_keys=("path",),
    )


def bulk_plane_pulls_counter() -> Counter:
    return Counter(
        "bulk_plane_pulls_total",
        "buffers pulled over the bulk object plane, tagged by path "
        "(direct | striped | relay | spilled)",
        tag_keys=("path",),
    )


def bulk_plane_fallbacks_counter() -> Counter:
    return Counter(
        "bulk_plane_fallbacks_total",
        "direct node-to-node pulls that failed (peer death, socket loss, "
        "timeout) and fell back to the head relay",
        tag_keys=(),
    )


def kv_transfer_fallbacks_counter() -> Counter:
    """Cross-replica KV transfers abandoned for local recompute — peer
    unreachable, payload corrupt/truncated mid-flight, verification
    reject, or local pool pressure (serve/kv_transfer.py). Shared
    single-definition discipline: incremented from the transfer manager,
    read from Replica.stats and the chaos suite."""
    return Counter(
        "kv_transfer_fallbacks_total",
        "cross-replica KV prefix transfers that fell back to local "
        "recompute (the output is recomputed, never wrong)",
        tag_keys=(),
    )


def weight_swap_fallbacks_counter() -> Counter:
    """Live weight swaps abandoned with the OLD version left serving —
    a leaf pull failed, arrived truncated/corrupt, or the manifest did
    not verify (serve/weight_swap.py). The invariant the counter guards:
    a replica serves version N or version N+1 in full, never a
    half-swapped tree."""
    return Counter(
        "weight_swap_fallbacks_total",
        "weight pulls that failed verification and left the replica on "
        "its previous (intact) weight version",
        tag_keys=(),
    )


def rl_rollout_tokens_counter() -> Counter:
    """Tokens sampled through the serving engine by generation-based RL
    rollouts (rl/llm), tagged like the serve metrics so a dashboard can
    split rollout traffic from user traffic per deployment/replica."""
    return Counter(
        "rl_rollout_tokens_total",
        "tokens generated by rl/llm rollout workers",
        tag_keys=("deployment", "replica"),
    )


def rl_reward_mean_gauge() -> Gauge:
    """Mean reward of the latest rl/llm rollout batch — the
    one-glance learning signal on the push registry."""
    return Gauge(
        "rl_reward_mean",
        "mean reward over the most recent rl/llm rollout batch",
        tag_keys=("deployment", "replica"),
    )


def local_counter_by_tag(name: str, tag_key: str) -> Dict[str, float]:
    """THIS process's counter totals grouped by one tag's value (stats
    surfaces, no cluster round trip). Empty dict when absent/never inc'd."""
    with _REGISTRY.lock:
        m = _REGISTRY.metrics.get(name)
    if m is None or not isinstance(m, Counter):
        return {}
    out: Dict[str, float] = {}
    with m._lock:
        for tags, v in m._values.items():
            key = dict(tags).get(tag_key, "") or "untagged"
            out[key] = out.get(key, 0.0) + v
    return out


def flush():
    """Force-push this process's metrics to the head."""
    _REGISTRY.maybe_flush(force=True)


def pump():
    """Throttled push (the normal observe-time path, callable from
    periodic pollers): an idle process's LAST observations otherwise sit
    unpushed until its next metric op — which may never come."""
    _REGISTRY.maybe_flush()


def _escape_tag_value(v: str) -> str:
    """Prometheus text-format label-value escaping: backslash, double quote
    and newline must be escaped or a hostile/unlucky tag value (a model id
    with a quote, a route with a newline) corrupts the whole scrape."""
    return (
        str(v).replace("\\", "\\\\").replace('"', '\\"').replace("\n", "\\n")
    )


def _fmt_tags(tags: Tuple[Tuple[str, str], ...], extra: str = "") -> str:
    parts = [f'{k}="{_escape_tag_value(v)}"' for k, v in tags]
    if extra:
        parts.append(extra)
    return "{" + ",".join(parts) + "}" if parts else ""


def merge_snapshots(store: Dict[str, dict]) -> Dict[str, dict]:
    """Merge per-process metric snapshots ({proc: {"ts":, "metrics":}})
    into one cluster-wide view: counters/histograms SUM across processes;
    gauges take the most recent process write (push-timestamp order, ties
    broken by process-name sort so the merge is deterministic)."""
    merged: Dict[str, dict] = {}
    gauge_ts: Dict[Tuple[str, Tuple], float] = {}
    for proc in sorted(store, key=lambda p: (store[p].get("ts", 0.0), p)):
        ts = store[proc].get("ts", 0.0)
        for name, snap in store[proc].get("metrics", {}).items():
            m = merged.setdefault(
                name,
                {
                    "type": snap["type"],
                    "description": snap["description"],
                    "boundaries": snap.get("boundaries"),
                    "values": {},
                },
            )
            if snap["type"] != m["type"] or snap.get("boundaries") != m["boundaries"]:
                # cross-process schema clash: skip rather than crash the export
                continue
            for tags, v in snap["values"].items():
                if m["type"] == "histogram":
                    ent = m["values"].setdefault(
                        tags, {"buckets": [0] * (len(m["boundaries"]) + 1), "sum": 0.0, "count": 0}
                    )
                    ent["buckets"] = [a + b for a, b in zip(ent["buckets"], v["buckets"])]
                    ent["sum"] += v["sum"]
                    ent["count"] += v["count"]
                elif m["type"] == "counter":
                    m["values"][tags] = m["values"].get(tags, 0.0) + v
                else:  # gauge: most recent push wins
                    if ts >= gauge_ts.get((name, tags), -1.0):
                        gauge_ts[(name, tags)] = ts
                        m["values"][tags] = v
    return merged


def render_prometheus(merged: Dict[str, dict]) -> str:
    """Render a merged metric view as Prometheus exposition text."""
    lines = []
    for name, m in sorted(merged.items()):
        if m["description"]:
            lines.append(f"# HELP {name} {m['description']}")
        ptype = {"counter": "counter", "gauge": "gauge", "histogram": "histogram"}[m["type"]]
        lines.append(f"# TYPE {name} {ptype}")
        for tags, v in sorted(m["values"].items()):
            if m["type"] == "histogram":
                cum = 0
                for b, n in zip(m["boundaries"], v["buckets"]):
                    cum += n
                    le = f'le="{b}"'
                    lines.append(f"{name}_bucket{_fmt_tags(tags, le)} {cum}")
                inf = 'le="+Inf"'
                lines.append(f"{name}_bucket{_fmt_tags(tags, inf)} {v['count']}")
                lines.append(f"{name}_sum{_fmt_tags(tags)} {v['sum']}")
                lines.append(f"{name}_count{_fmt_tags(tags)} {v['count']}")
            else:
                lines.append(f"{name}{_fmt_tags(tags)} {v}")
    return "\n".join(lines) + "\n"


def export_prometheus(timeout: Optional[float] = None) -> str:
    """Render the cluster-wide metric aggregate (all processes) as
    Prometheus text (reference: metrics_agent.py opencensus->prometheus).
    `timeout` bounds the head round-trip — callers holding a shared
    resource (the proxy's call pool) must not park on a wedged head."""
    from .._private.worker import global_worker

    flush()
    store = global_worker.request({"t": "get_metrics"}, timeout=timeout)
    return render_prometheus(merge_snapshots(store))


def quantile_from_buckets(
    boundaries: Sequence[float], buckets: Sequence[int], q: float
) -> Optional[float]:
    """Estimate the q-quantile (0..1) from cumulative-free bucket counts by
    linear interpolation within the containing bucket. Values in the +Inf
    overflow bucket clamp to the last finite boundary (the histogram holds
    no better information). Returns None for an empty histogram."""
    count = sum(buckets)
    if count <= 0:
        return None
    rank = q * count
    cum = 0.0
    for i, n in enumerate(buckets):
        prev = cum
        cum += n
        if cum >= rank and n > 0:
            if i >= len(boundaries):  # overflow bucket
                return float(boundaries[-1])
            lo = float(boundaries[i - 1]) if i else 0.0
            hi = float(boundaries[i])
            return lo + (hi - lo) * max(0.0, rank - prev) / n
    return float(boundaries[-1])


def local_histogram_quantiles(
    name: str, qs: Sequence[float], tags: Optional[Dict[str, str]] = None
) -> Optional[List[Optional[float]]]:
    """Quantile estimates from THIS process's registry (bench/test helper —
    no cluster round trip). Aggregates across all tag sets unless `tags`
    pins one exactly. Returns None when the metric doesn't exist here."""
    with _REGISTRY.lock:
        m = _REGISTRY.metrics.get(name)
    if m is None or not isinstance(m, Histogram):
        return None
    # pinning resolves through the metric's own default-tag merge (the
    # same key construction observe uses) — stored keys include the
    # defaults set_default_tags stamped, so a raw caller key never would
    want = m.tags_key(tags) if tags is not None else None
    agg = [0] * (len(m.boundaries) + 1)
    with m._lock:
        for key, ent in m._values.items():
            if want is not None and key != want:
                continue
            if isinstance(ent, dict):
                agg = [a + b for a, b in zip(agg, ent["buckets"])]
    return [quantile_from_buckets(m.boundaries, agg, q) for q in qs]
