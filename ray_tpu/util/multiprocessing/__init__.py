"""Drop-in multiprocessing.Pool over cluster actors.

Reference parity: python/ray/util/multiprocessing/pool.py (Pool whose
workers are actors, so `map` fans out across the cluster instead of local
forks). Supported surface: apply/apply_async/map/map_async/starmap/
imap/imap_unordered/close/terminate/join + context manager.
"""

from __future__ import annotations

import itertools
from typing import Any, Callable, Iterable, List, Optional


class _PoolWorker:
    def run(self, fn, args, kwargs):
        return fn(*args, **(kwargs or {}))

    def run_batch(self, fn, chunk):
        return [fn(*args) for args in chunk]


class AsyncResult:
    def __init__(self, refs, single: bool = False):
        self._refs = refs
        self._single = single

    def get(self, timeout: Optional[float] = None):
        import ray_tpu

        out = ray_tpu.get(self._refs, timeout=timeout)
        return out[0] if self._single else out

    def wait(self, timeout: Optional[float] = None) -> None:
        import ray_tpu

        ray_tpu.wait(self._refs, num_returns=len(self._refs), timeout=timeout)

    def ready(self) -> bool:
        import ray_tpu

        done, _ = ray_tpu.wait(self._refs, num_returns=len(self._refs), timeout=0)
        return len(done) == len(self._refs)

    def successful(self) -> bool:
        try:
            self.get(timeout=0)
            return True
        except Exception:
            return False


class Pool:
    def __init__(self, processes: Optional[int] = None, ray_remote_args: Optional[dict] = None):
        import os

        import ray_tpu

        if not ray_tpu.is_initialized():
            ray_tpu.init()
        self._n = processes or os.cpu_count() or 4
        cls = ray_tpu.remote(_PoolWorker)
        if ray_remote_args:
            cls = cls.options(**ray_remote_args)
        self._workers = [cls.remote() for _ in range(self._n)]
        self._rr = itertools.cycle(range(self._n))
        self._closed = False

    def _check(self):
        if self._closed:
            raise ValueError("Pool not running")

    def _submit(self, fn, args, kwargs=None):
        w = self._workers[next(self._rr)]
        return w.run.remote(fn, tuple(args), kwargs or {})

    # -- apply --

    def apply(self, fn: Callable, args=(), kwds=None):
        return self.apply_async(fn, args, kwds).get()

    def apply_async(
        self, fn: Callable, args=(), kwds=None, callback=None, error_callback=None
    ) -> AsyncResult:
        self._check()
        result = AsyncResult([self._submit(fn, args, kwds)], single=True)
        if callback is not None or error_callback is not None:
            import threading

            def _notify():
                try:
                    value = result.get()
                except Exception as e:  # noqa: BLE001 - forwarded to error_callback
                    if error_callback is not None:
                        error_callback(e)
                    return
                if callback is not None:
                    callback(value)

            threading.Thread(target=_notify, daemon=True).start()
        return result

    # -- map family --

    def _starmap_refs(self, fn, items: List[tuple], chunksize: Optional[int]):
        self._check()
        if chunksize is None:
            chunksize = max(1, len(items) // (self._n * 4) or 1)
        refs = []
        for i in range(0, len(items), chunksize):
            w = self._workers[next(self._rr)]
            refs.append(w.run_batch.remote(fn, items[i : i + chunksize]))
        return refs

    def map(self, fn: Callable, iterable: Iterable, chunksize: Optional[int] = None) -> List[Any]:
        import ray_tpu

        items = [(x,) for x in iterable]
        chunks = ray_tpu.get(self._starmap_refs(fn, items, chunksize))
        return [x for chunk in chunks for x in chunk]

    def map_async(self, fn, iterable, chunksize=None) -> AsyncResult:
        refs = self._starmap_refs(fn, [(x,) for x in iterable], chunksize)
        return _FlatAsyncResult(refs)

    def starmap(self, fn: Callable, iterable: Iterable[tuple], chunksize=None) -> List[Any]:
        import ray_tpu

        chunks = ray_tpu.get(self._starmap_refs(fn, list(iterable), chunksize))
        return [x for chunk in chunks for x in chunk]

    def imap(self, fn: Callable, iterable: Iterable, chunksize: int = 1):
        import ray_tpu

        refs = self._starmap_refs(fn, [(x,) for x in iterable], chunksize)
        for ref in refs:  # ordered
            for x in ray_tpu.get(ref):
                yield x

    def imap_unordered(self, fn: Callable, iterable: Iterable, chunksize: int = 1):
        import ray_tpu

        pending = list(self._starmap_refs(fn, [(x,) for x in iterable], chunksize))
        while pending:
            done, pending = ray_tpu.wait(pending, num_returns=1)
            for ref in done:  # wait may surface several completions at once
                for x in ray_tpu.get(ref):
                    yield x

    # -- lifecycle --

    def close(self) -> None:
        self._closed = True

    def terminate(self) -> None:
        import ray_tpu

        self._closed = True
        for w in self._workers:
            try:
                ray_tpu.kill(w)
            except Exception:
                pass

    def join(self) -> None:
        if not self._closed:
            raise ValueError("Pool is still running")

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.terminate()
        return False


class _FlatAsyncResult(AsyncResult):
    def get(self, timeout: Optional[float] = None):
        import ray_tpu

        chunks = ray_tpu.get(self._refs, timeout=timeout)
        return [x for chunk in chunks for x in chunk]
