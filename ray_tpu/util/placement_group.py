"""Placement groups: atomic gang reservation of resource bundles.

Reference parity: python/ray/util/placement_group.py (PlacementGroup :34,
placement_group() :139) + GcsPlacementGroupManager. On TPU the canonical use
is reserving a pod slice (bundles of {"TPU": chips_per_host, "CPU": ...} per
host) with STRICT_SPREAD/SPREAD so one SPMD gang lands one-worker-per-host.
"""

from __future__ import annotations

from typing import Dict, List, Optional

from .._private.ids import PlacementGroupID


class PlacementGroup:
    def __init__(self, pg_id: str, bundles: List[Dict[str, float]]):
        self.id = pg_id
        self._bundles = bundles

    @property
    def bundle_specs(self) -> List[Dict[str, float]]:
        return list(self._bundles)

    @property
    def bundle_count(self) -> int:
        return len(self._bundles)

    def ready(self):
        """Returns an ObjectRef-like wait: blocks until placed (sync helper)."""
        from .._private.worker import global_worker

        ok = global_worker.request({"t": "pg_ready", "pg_id": self.id, "timeout": None})
        return ok

    def wait(self, timeout_seconds: Optional[float] = None) -> bool:
        from .._private.worker import global_worker

        return global_worker.request(
            {"t": "pg_ready", "pg_id": self.id, "timeout": timeout_seconds}
        )

    def __reduce__(self):
        return (PlacementGroup, (self.id, self._bundles))


def placement_group(
    bundles: List[Dict[str, float]],
    strategy: str = "PACK",
    name: str = "",
    lifetime: Optional[str] = None,
) -> PlacementGroup:
    from .._private.worker import global_worker

    if strategy not in ("PACK", "SPREAD", "STRICT_PACK", "STRICT_SPREAD"):
        raise ValueError(f"Invalid strategy {strategy!r}")
    if not bundles:
        raise ValueError("bundles cannot be empty")
    for b in bundles:
        if not b or any(v < 0 for v in b.values()):
            raise ValueError(f"Invalid bundle {b!r}")
    pg_id = PlacementGroupID.of(global_worker.job_id).hex()
    spec = {"pg_id": pg_id, "bundles": bundles, "strategy": strategy, "name": name or None}
    global_worker.request({"t": "create_placement_group", "spec": spec})
    return PlacementGroup(pg_id, bundles)


def remove_placement_group(pg: PlacementGroup) -> None:
    from .._private.worker import global_worker

    global_worker.request({"t": "remove_placement_group", "pg_id": pg.id})


def placement_group_table(pg: Optional[PlacementGroup] = None) -> dict:
    from .._private.worker import global_worker

    table = global_worker.request({"t": "pg_table"})
    if pg is not None:
        return table.get(pg.id, {})
    return table


def tpu_slice_placement_group(
    num_hosts: int,
    chips_per_host: int = 4,
    cpus_per_host: float = 1.0,
    strategy: str = "STRICT_SPREAD",
) -> PlacementGroup:
    """Reserve a TPU pod slice: one bundle per host, each with the host's chips.

    TPU-native addition (the reference has no TPU resource type — SURVEY §5.5).
    """
    bundles = [
        {"TPU": float(chips_per_host), "CPU": cpus_per_host} for _ in range(num_hosts)
    ]
    return placement_group(bundles, strategy=strategy)
