"""Fixed-pool actor work distribution.

Reference parity: python/ray/util/actor_pool.py (ActorPool: map /
map_unordered / submit / get_next / get_next_unordered / has_next /
has_free / pop_idle / push). Rebuilt on ray_tpu primitives: an idle-actor
free list plus a future->actor table, with `wait` driving the unordered
completion order.
"""

from __future__ import annotations

from typing import Any, Callable, Iterable, List


class ActorPool:
    """Operate on a fixed pool of actors, keeping every actor busy while
    work remains.

    Example:
        pool = ActorPool([Actor.remote(), Actor.remote()])
        list(pool.map(lambda a, v: a.double.remote(v), [1, 2, 3, 4]))
    """

    def __init__(self, actors: Iterable[Any]):
        self._idle_actors: List[Any] = list(actors)
        self._future_to_actor: dict = {}  # ref key -> (index, actor)
        self._index_to_future: dict = {}  # submit index -> ref
        self._next_task_index = 0
        self._next_return_index = 0
        self._pending_submits: List[tuple] = []  # (fn, value) waiting for an actor

    # -- bulk mapping ---------------------------------------------------

    def map(self, fn: Callable[[Any, Any], Any], values: Iterable[Any]):
        """Apply fn(actor, value) over values; yield results IN ORDER as
        they become ready."""
        for v in values:
            self.submit(fn, v)

        def gen():
            while self.has_next():
                yield self.get_next()

        return gen()

    def map_unordered(self, fn: Callable[[Any, Any], Any], values: Iterable[Any]):
        """Like map, but yields in completion order (better utilization
        under uneven task durations)."""
        for v in values:
            self.submit(fn, v)

        def gen():
            while self.has_next():
                yield self.get_next_unordered()

        return gen()

    # -- incremental submission -----------------------------------------

    def submit(self, fn: Callable[[Any, Any], Any], value: Any):
        """Schedule fn(actor, value) on an idle actor; queue it when every
        actor is busy (drained as results are collected)."""
        if self._idle_actors:
            actor = self._idle_actors.pop()
            future = fn(actor, value)
            self._future_to_actor[future] = (self._next_task_index, actor)
            self._index_to_future[self._next_task_index] = future
            self._next_task_index += 1
        else:
            self._pending_submits.append((fn, value))

    def has_next(self) -> bool:
        return bool(self._index_to_future) or bool(self._pending_submits)

    def get_next(self, timeout: float | None = None, ignore_if_timedout: bool = False):
        """Next result in SUBMISSION order."""
        import ray_tpu
        from ..exceptions import GetTimeoutError

        if not self.has_next():
            raise StopIteration("No more results to get")
        if self._next_return_index >= self._next_task_index:
            raise ValueError("It is not allowed to call get_next() after get_next_unordered().")
        future = self._index_to_future[self._next_return_index]
        timed_out = False
        if timeout is not None:
            res, _ = ray_tpu.wait([future], timeout=timeout)
            if not res:
                timed_out = True
        if timed_out:
            if not ignore_if_timedout:
                raise GetTimeoutError(f"get_next() timed out after {timeout}s")
            return None
        del self._index_to_future[self._next_return_index]
        self._next_return_index += 1
        i, actor = self._future_to_actor.pop(future)
        self._return_actor(actor)
        return ray_tpu.get(future)

    def get_next_unordered(self, timeout: float | None = None, ignore_if_timedout: bool = False):
        """Next result in COMPLETION order."""
        import ray_tpu
        from ..exceptions import GetTimeoutError

        if not self.has_next():
            raise StopIteration("No more results to get")
        res, _ = ray_tpu.wait(
            list(self._future_to_actor), num_returns=1, timeout=timeout
        )
        if res:
            [future] = res
        else:
            if not ignore_if_timedout:
                raise GetTimeoutError(f"get_next_unordered() timed out after {timeout}s")
            return None
        i, actor = self._future_to_actor.pop(future)
        self._return_actor(actor)
        del self._index_to_future[i]
        self._next_return_index = max(self._next_return_index, i + 1)
        return ray_tpu.get(future)

    # -- pool membership -------------------------------------------------

    def _return_actor(self, actor):
        self._idle_actors.append(actor)
        if self._pending_submits:
            self.submit(*self._pending_submits.pop(0))

    def has_free(self) -> bool:
        """True when an actor is idle AND no submits are queued."""
        return bool(self._idle_actors) and not self._pending_submits

    def pop_idle(self):
        """Remove and return an idle actor (None if all are busy)."""
        if self.has_free():
            return self._idle_actors.pop()
        return None

    def push(self, actor):
        """Add an actor to the pool (e.g. returning one from pop_idle)."""
        busy = {a for _, a in self._future_to_actor.values()}
        if actor in self._idle_actors or actor in busy:
            raise ValueError("Actor already belongs to current ActorPool")
        self._return_actor(actor)
