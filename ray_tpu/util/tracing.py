"""OpenTelemetry task/actor tracing.

Reference parity: python/ray/util/tracing/tracing_helper.py — the reference
lazily imports opentelemetry (:35-59), wraps task submission and execution
in spans, and propagates the W3C tracecontext inside the TaskSpec so a
driver's trace continues across worker processes. ray_tpu does the same:
enable with `ray_tpu.util.tracing.enable()` (or
init(_tracing_startup_hook=...)); the hook is where an application installs
its opentelemetry SDK TracerProvider/exporter — without an SDK the API's
no-op tracer makes every call here free.
"""

from __future__ import annotations

import contextlib
from typing import Any, Callable, Dict, Optional

_enabled = False
_import_failed = False


def _otel():
    """(trace, propagator) or None when opentelemetry isn't importable."""
    global _import_failed
    if _import_failed:
        return None
    try:
        from opentelemetry import trace
        from opentelemetry.trace.propagation.tracecontext import (
            TraceContextTextMapPropagator,
        )

        return trace, TraceContextTextMapPropagator()
    except ImportError:
        _import_failed = True
        return None


def enable(startup_hook: Optional[Callable[[], None]] = None) -> bool:
    """Turn on trace propagation for this process. `startup_hook` typically
    installs the opentelemetry SDK provider/exporter (the reference's
    _tracing_startup_hook). Returns False when opentelemetry is missing."""
    global _enabled
    if startup_hook is not None:
        startup_hook()
    if _otel() is None:
        return False
    _enabled = True
    return True


def is_enabled() -> bool:
    return _enabled


def inject_current_context() -> Optional[Dict[str, str]]:
    """W3C tracecontext carrier for the caller's current span (None when
    tracing is off or there is no recording span) — attached to task specs
    at submission (reference: _inject_tracing_into_function wrapping at
    remote_function.py:244)."""
    if not _enabled:
        return None
    otel = _otel()
    if otel is None:
        return None
    carrier: Dict[str, str] = {}
    otel[1].inject(carrier)
    return carrier or None


@contextlib.contextmanager
def span_for_execution(name: str, trace_ctx: Optional[Dict[str, str]], **attrs: Any):
    """Worker-side execution span, parented to the submitter's span via the
    propagated carrier (reference: _tracing_task_execution wrapping the
    execute path)."""
    if trace_ctx and not _enabled:
        # a propagated context implies the submitter traces: auto-enable so
        # worker processes join the trace without their own enable() call
        # (an SDK provider, if wanted in workers, comes via a runtime_env
        # worker setup hook — same split as the reference)
        enable()
    if not _enabled:
        yield None
        return
    otel = _otel()
    if otel is None:
        yield None
        return
    trace, propagator = otel
    parent = propagator.extract(trace_ctx) if trace_ctx else None
    tracer = trace.get_tracer("ray_tpu")
    with tracer.start_as_current_span(name, context=parent) as span:
        for k, v in attrs.items():
            try:
                span.set_attribute(k, v)
            except Exception:
                pass
        yield span


@contextlib.contextmanager
def span_for_submission(name: str, **attrs: Any):
    """Driver-side submission span (cheap no-op when disabled)."""
    if not _enabled:
        yield None
        return
    otel = _otel()
    if otel is None:
        yield None
        return
    trace, _ = otel
    with trace.get_tracer("ray_tpu").start_as_current_span(name) as span:
        for k, v in attrs.items():
            try:
                span.set_attribute(k, v)
            except Exception:
                pass
        yield span
