"""Accelerator types and TPU-topology helpers.

Reference parity: python/ray/util/accelerators/accelerators.py — the
reference enumerates NVIDIA types only and has no TPU resource anywhere in
core (SURVEY §5.5); ray_tpu makes TPU generations and pod-slice topologies
first-class, since slice-aware placement is the whole point of this
framework.
"""

from __future__ import annotations

from typing import Dict, Optional, Tuple

# generation constants (mirror the reference's NVIDIA_TESLA_* style)
TPU_V4 = "TPU-V4"
TPU_V5E = "TPU-V5E"  # a.k.a. v5 lite
TPU_V5P = "TPU-V5P"
TPU_V6E = "TPU-V6E"

# chips per host for each generation's standard TPU-VM shape
CHIPS_PER_HOST: Dict[str, int] = {
    TPU_V4: 4,
    TPU_V5E: 8,
    TPU_V5P: 4,
    TPU_V6E: 8,
}


def parse_accelerator_type(name: str) -> Tuple[str, int]:
    """"v4-32" / "v5e-16" / "v5p-128" -> (generation constant, chip count).

    The numeric suffix follows cloud naming: TensorCore count for v4/v5p
    (2 cores per chip), chip count for v5e/v6e.
    """
    gen_map = {"v4": TPU_V4, "v5e": TPU_V5E, "v5litepod": TPU_V5E,
               "v5p": TPU_V5P, "v6e": TPU_V6E}
    base, _, suffix = name.lower().partition("-")
    if base not in gen_map or not suffix.isdigit():
        raise ValueError(f"unknown TPU accelerator type {name!r}")
    n = int(suffix)
    gen = gen_map[base]
    chips = n // 2 if gen in (TPU_V4, TPU_V5P) else n
    return gen, max(1, chips)


def slice_hosts(accelerator_type: str) -> int:
    """Host count in a pod slice (drives placement-group bundle counts)."""
    gen, chips = parse_accelerator_type(accelerator_type)
    per = CHIPS_PER_HOST[gen]
    return max(1, (chips + per - 1) // per)


def slice_bundles(accelerator_type: str, cpus_per_host: float = 1.0) -> list:
    """Placement-group bundles for a full slice: one bundle per host with
    its TPU chips — pass to placement_group(..., strategy="STRICT_SPREAD")
    for gang scheduling over a slice (SURVEY §7.2 gang semantics)."""
    gen, chips = parse_accelerator_type(accelerator_type)
    per = CHIPS_PER_HOST[gen]
    hosts = slice_hosts(accelerator_type)
    bundles = []
    remaining = chips
    for _ in range(hosts):
        take = min(per, remaining)
        bundles.append({"CPU": cpus_per_host, "TPU": float(take)})
        remaining -= take
    return bundles


def detect_local_generation() -> Optional[str]:
    """Best-effort generation of this host's chips (env hints on TPU VMs)."""
    import os

    env = os.environ.get("TPU_ACCELERATOR_TYPE") or os.environ.get(
        "ACCELERATOR_TYPE", ""
    )
    if env:
        try:
            return parse_accelerator_type(env)[0]
        except ValueError:
            return None
    return None
