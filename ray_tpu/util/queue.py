"""Distributed FIFO queue backed by an actor.

Reference parity: python/ray/util/queue.py (Queue on a _QueueActor;
blocking semantics via polling, Empty/Full exceptions re-exported).
"""

from __future__ import annotations

import collections
import time
from queue import Empty, Full  # re-export the stdlib exception types
from typing import Any, List, Optional

_POLL_S = 0.01


class _QueueActor:
    def __init__(self, maxsize: int):
        self.maxsize = maxsize
        self.items: collections.deque = collections.deque()

    def qsize(self) -> int:
        return len(self.items)

    def put(self, item) -> bool:
        if self.maxsize > 0 and len(self.items) >= self.maxsize:
            return False
        self.items.append(item)
        return True

    def get(self):
        if not self.items:
            return (False, None)
        return (True, self.items.popleft())

    def put_batch(self, items: List[Any]) -> bool:
        """All-or-nothing (matching the reference's capacity pre-check) so a
        caller can retry a rejected batch without duplicating items."""
        if self.maxsize > 0 and len(self.items) + len(items) > self.maxsize:
            return False
        self.items.extend(items)
        return True


class Queue:
    """A FIFO queue usable from any driver/task/actor in the cluster."""

    def __init__(self, maxsize: int = 0, actor_options: Optional[dict] = None):
        import ray_tpu

        self.maxsize = maxsize
        cls = ray_tpu.remote(_QueueActor)
        if actor_options:
            cls = cls.options(**actor_options)
        self._actor = cls.remote(maxsize)

    def qsize(self) -> int:
        import ray_tpu

        return ray_tpu.get(self._actor.qsize.remote())

    def empty(self) -> bool:
        return self.qsize() == 0

    def full(self) -> bool:
        return self.maxsize > 0 and self.qsize() >= self.maxsize

    def put(self, item, block: bool = True, timeout: Optional[float] = None) -> None:
        import ray_tpu

        deadline = None if timeout is None else time.monotonic() + timeout
        while True:
            if ray_tpu.get(self._actor.put.remote(item)):
                return
            if not block:
                raise Full
            if deadline is not None and time.monotonic() > deadline:
                raise Full
            time.sleep(_POLL_S)

    def put_nowait(self, item) -> None:
        self.put(item, block=False)

    def get(self, block: bool = True, timeout: Optional[float] = None):
        import ray_tpu

        deadline = None if timeout is None else time.monotonic() + timeout
        while True:
            ok, item = ray_tpu.get(self._actor.get.remote())
            if ok:
                return item
            if not block:
                raise Empty
            if deadline is not None and time.monotonic() > deadline:
                raise Empty
            time.sleep(_POLL_S)

    def get_nowait(self):
        return self.get(block=False)

    def put_nowait_batch(self, items: List[Any]) -> None:
        import ray_tpu

        items = list(items)
        if not ray_tpu.get(self._actor.put_batch.remote(items)):
            raise Full(f"batch of {len(items)} does not fit (maxsize={self.maxsize})")

    def shutdown(self) -> None:
        import ray_tpu

        ray_tpu.kill(self._actor)
