"""Per-axis (ICI vs DCN) collective byte counters.

GSPMD inserts the collectives, so the only honest accounting of what
crosses the slow slice boundary is the COMPILED program: this module walks
the optimized HLO of a jitted step, finds every collective op, maps its
replica groups back to mesh coordinates, and classifies the op by the mesh
axes its groups span.  An op whose groups vary along the `dcn` axis moves
bytes over DCN; everything else stays on ICI.

This is what lets the multi-slice presets (parallel/multislice.py) PROVE
their contract — e.g. "tp/sp/ep traffic never crosses a slice boundary" is
`assert_no_cross_slice(report)`, not a comment.

Byte convention: each op is charged its per-participant payload (the HLO
output shape), recorded once per replica group member-set; collective-
permute is charged per source→target pair.  A SEPARABLE op whose groups
span both a dcn axis and ICI axes (e.g. the gradient all-reduce over
("dcn", "dp")) is charged on BOTH sides: the runtime decomposes it into an
intra-slice leg (ICI) plus one inter-slice exchange (DCN), so its payload
appears in `ici_bytes` AND `dcn_bytes` — which is what makes "compression
left ICI traffic untouched" an equality test rather than a judgement
call.  Non-separable dcn-crossing ops are charged to DCN alone.  The
numbers are therefore a consistent basis for ICI:DCN ratios and zero/
nonzero assertions, not a wire-level byte count (which would fold in
algorithm choice — ring vs tree — that XLA owns).

Each op also records its payload `dtype` (of the largest buffer), so the
quantize-wrapped collectives of util/collective/compress.py are auditable:
the compressed gradient path must show an `s8` all-reduce spanning only
`dcn` next to the small `f32` shared-scale exchange.

Static-count caveat: an op inside a `while` body (scanned layers, pipeline
ticks) is counted ONCE, not per iteration — compare like against like
(e.g. measure compression ratios on scan_layers=False configs, where every
gradient collective is top-level).
"""

from __future__ import annotations

import dataclasses
import re
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

DCN_AXES_DEFAULT: Tuple[str, ...] = ("dcn",)

_DTYPE_BYTES = {
    "pred": 1, "s4": 1, "u4": 1, "s8": 1, "u8": 1,
    "s16": 2, "u16": 2, "f16": 2, "bf16": 2,
    "s32": 4, "u32": 4, "f32": 4,
    "s64": 8, "u64": 8, "f64": 8, "c64": 8, "c128": 16,
    "f8e4m3fn": 1, "f8e5m2": 1, "f8e4m3b11fnuz": 1, "f8e4m3fnuz": 1,
    "f8e5m2fnuz": 1,
}

_OP_RE = re.compile(
    r"=\s*(?P<out>\([^)]*\)|[a-z0-9]+\[[^\]]*\]\S*)\s+"
    r"(?P<kind>all-reduce|all-gather|reduce-scatter|all-to-all|"
    r"collective-permute|collective-broadcast)(?P<start>-start)?\("
)
_SHAPE_RE = re.compile(r"([a-z][a-z0-9]*)\[([\d,]*)\]")
_GROUPS_RE = re.compile(r"replica_groups=\{(\{[\d,]+\}(?:,\{[\d,]+\})*)\}")
_IOTA_RE = re.compile(
    r"replica_groups=\[([\d,]+)\]<=\[([\d,]+)\](?:T\(([\d,]+)\))?"
)
_PAIRS_RE = re.compile(r"source_target_pairs=\{(\{[\d,]+\}(?:,\{[\d,]+\})*)\}")


@dataclasses.dataclass
class CollectiveOp:
    kind: str
    payload_bytes: int                 # one participant's output payload
    axes: Tuple[str, ...]              # mesh axes the op communicates over
    group_size: int
    crosses_dcn: bool
    dcn_bytes: int                     # payload charged to the slow axes
    ici_bytes: int
    # True when every group is a full cartesian product of per-axis member
    # sets: the runtime can decompose the op hierarchically (reduce/gather
    # intra-slice on ICI first, then one inter-slice exchange over DCN) —
    # e.g. a gradient all-reduce over ("dcn", "dp"). False means the op
    # irreducibly MIXES axes in one exchange.
    separable: bool = True
    # element type of the LARGEST payload buffer ("f32", "s8", ...) — lets
    # tests assert a quantize-wrapped exchange really went over the wire
    # narrow (compress.py's s8 dcn all-reduce) instead of trusting the
    # python-side cast.
    dtype: str = ""


def _payload_info(out: str, async_start: bool = False) -> Tuple[int, str]:
    """(payload bytes, dtype of largest buffer) of an HLO output type. For
    async `-start` forms the tuple carries BOTH the operand and result
    buffers (plus u32 context scalars), so summing would double-charge:
    take the largest single shape instead — the actual payload."""
    sizes: List[Tuple[int, str]] = []
    for dtype, dims in _SHAPE_RE.findall(out):
        if dtype == "token":
            continue
        size = _DTYPE_BYTES.get(dtype)
        if size is None:
            m = re.fullmatch(r"[a-z]+?(\d+)", dtype)
            size = max(1, int(m.group(1)) // 8) if m else 4
        n = 1
        for d in dims.split(","):
            if d:
                n *= int(d)
        sizes.append((n * size, dtype))
    if not sizes:
        return 0, ""
    big = max(sizes, key=lambda s: s[0])
    if async_start:
        return big
    return sum(s[0] for s in sizes), big[1]


def _shape_bytes(out: str, async_start: bool = False) -> int:
    return _payload_info(out, async_start)[0]


def _parse_brace_groups(body: str) -> List[Tuple[int, ...]]:
    return [
        tuple(int(x) for x in g.split(",") if x)
        for g in re.findall(r"\{([\d,]+)\}", "{" + body + "}")
    ]


def _parse_iota_groups(dims_s: str, reshape_s: str, perm_s: Optional[str]):
    """XLA iota replica-group list: iota over prod(reshape) dims, reshaped,
    transposed by perm, then reshaped to [n_groups, group_size]."""
    out_dims = [int(x) for x in dims_s.split(",")]
    reshape = [int(x) for x in reshape_s.split(",")]
    arr = np.arange(int(np.prod(reshape))).reshape(reshape)
    if perm_s:
        arr = arr.transpose([int(x) for x in perm_s.split(",")])
    arr = arr.reshape(out_dims)
    return [tuple(int(v) for v in row) for row in arr]


def _extract_groups(line: str, n_devices: int) -> Optional[List[Tuple[int, ...]]]:
    m = _GROUPS_RE.search(line)
    if m:
        return _parse_brace_groups(m.group(1))
    m = _IOTA_RE.search(line)
    if m:
        return _parse_iota_groups(m.group(1), m.group(2), m.group(3))
    if re.search(r"replica_groups=\{\}", line):
        # XLA shorthand: one group spanning every participant
        return [tuple(range(n_devices))]
    return None


def _spanned_axes(
    members: Sequence[int], shape: Sequence[int], names: Sequence[str]
) -> Tuple[str, ...]:
    coords = np.array([np.unravel_index(i, shape) for i in members])
    return tuple(
        names[d] for d in range(len(names)) if len(set(coords[:, d])) > 1
    )


def _is_separable(members: Sequence[int], shape: Sequence[int]) -> bool:
    """True iff the member set is a full cartesian product of its per-axis
    coordinate sets — the condition for hierarchical (per-axis, ICI-then-
    DCN) decomposition of the op."""
    coords = np.array([np.unravel_index(i, shape) for i in members])
    expect = 1
    for d in range(coords.shape[1]):
        expect *= len(set(coords[:, d]))
    return expect == len(set(members))


def collective_byte_report(
    hlo_text: str,
    *,
    axis_names: Sequence[str],
    axis_sizes: Sequence[int],
    dcn_axes: Sequence[str] = DCN_AXES_DEFAULT,
) -> Dict:
    """Classify every collective in optimized HLO text by the mesh axes its
    replica groups span.  Group/pair member ids are positions in the mesh's
    flattened device array (row-major over `axis_sizes`), which is how both
    GSPMD partition ids and `build_multislice_mesh`'s slice-major layout
    are defined."""
    names, shape = list(axis_names), list(axis_sizes)
    n_devices = int(np.prod(shape))
    ops: List[CollectiveOp] = []
    for line in hlo_text.splitlines():
        m = _OP_RE.search(line)
        if m is None:
            continue
        kind = m.group("kind")
        payload, pdtype = _payload_info(
            m.group("out"), async_start=bool(m.group("start"))
        )
        if kind == "collective-permute":
            pm = _PAIRS_RE.search(line)
            pairs = _parse_brace_groups(pm.group(1)) if pm else []
            pairs = [p for p in pairs if len(p) == 2 and p[0] != p[1]]
            if not pairs:
                continue
            spanned: set = set()
            dcn_b = ici_b = 0
            separable = True
            for src, dst in pairs:
                axes = _spanned_axes((src, dst), shape, names)
                spanned.update(axes)
                separable = separable and len(axes) <= 1
                if any(a in dcn_axes for a in axes):
                    dcn_b += payload
                else:
                    ici_b += payload
            ops.append(CollectiveOp(
                kind=kind, payload_bytes=payload, axes=tuple(sorted(spanned)),
                group_size=2, crosses_dcn=dcn_b > 0,
                dcn_bytes=dcn_b, ici_bytes=ici_b, separable=separable,
                dtype=pdtype,
            ))
            continue
        groups = _extract_groups(line, n_devices)
        if not groups:
            continue
        # groups are symmetric partitions of the mesh: one is enough to
        # classify, but span the union in case XLA merged unequal groups
        spanned = set()
        separable = True
        for g in groups:
            if len(g) > 1:
                spanned.update(_spanned_axes(g, shape, names))
                separable = separable and _is_separable(g, shape)
        if not spanned:
            continue
        crosses = any(a in dcn_axes for a in spanned)
        spans_ici = any(a not in dcn_axes for a in spanned)
        if crosses and spans_ici and separable:
            # hierarchical decomposition: intra-slice leg on ICI (reduce-
            # scatter/gather within the slice) plus one DCN exchange —
            # charge the payload to both tiers so "ICI traffic unchanged"
            # stays an equality when a dcn-only op replaces the dcn leg
            dcn_b, ici_b = payload, payload
        elif crosses:
            dcn_b, ici_b = payload, 0
        else:
            dcn_b, ici_b = 0, payload
        ops.append(CollectiveOp(
            kind=kind, payload_bytes=payload, axes=tuple(sorted(spanned)),
            group_size=max(len(g) for g in groups), crosses_dcn=crosses,
            dcn_bytes=dcn_b, ici_bytes=ici_b,
            separable=separable, dtype=pdtype,
        ))

    per_axis: Dict[str, int] = {}
    for op in ops:
        for a in op.axes:
            per_axis[a] = per_axis.get(a, 0) + op.payload_bytes
    return {
        "ops": ops,
        "per_axis_bytes": per_axis,
        "dcn_bytes": sum(op.dcn_bytes for op in ops),
        "ici_bytes": sum(op.ici_bytes for op in ops),
        "total_bytes": sum(op.payload_bytes for op in ops),
    }


def mesh_collective_report(
    compiled_or_text, mesh=None, dcn_axes: Sequence[str] = DCN_AXES_DEFAULT
) -> Dict:
    """Convenience wrapper: accepts a jax Compiled/Lowered object (or HLO
    text) plus the Mesh the program was jitted over."""
    # a jax Lowered must be COMPILED first: its own as_text() is the
    # pre-partitioning StableHLO, which contains no collectives at all
    if hasattr(compiled_or_text, "compile"):
        compiled_or_text = compiled_or_text.compile()
    if hasattr(compiled_or_text, "as_text"):
        text = compiled_or_text.as_text()
    else:
        text = compiled_or_text
    if mesh is None:
        raise ValueError("mesh_collective_report requires the mesh")
    names = list(mesh.shape.keys())
    sizes = [mesh.shape[n] for n in names]
    return collective_byte_report(
        text, axis_names=names, axis_sizes=sizes, dcn_axes=dcn_axes
    )


_DATA_MOVEMENT_KINDS = (
    "all-gather", "all-to-all", "collective-permute", "collective-broadcast"
)


def assert_no_cross_slice(
    report: Dict, ici_axes: Sequence[str] = ("tp", "sp", "ep")
) -> None:
    """Fail if any collective moves ICI-only-axis traffic over DCN.

    Flagged: (a) DATA-MOVEMENT ops (all-gather / all-to-all / collective-
    permute / broadcast) whose groups span both a bandwidth-hungry axis and
    a dcn axis — tp/sp/ep-sharded payload is being shipped across slices;
    (b) reductions whose dcn-crossing groups are NOT separable cartesian
    products — they cannot be decomposed into intra-slice-then-DCN stages.

    NOT flagged: separable reductions spanning dcn x other axes (e.g. the
    gradient all-reduce over ("dcn", "dp"), or a region-boundary cotangent
    psum over ("dcn", "tp")) — the runtime reduces those hierarchically,
    so the DCN leg carries only the once-per-step inter-slice exchange."""
    bad = []
    for op in report["ops"]:
        if not op.crosses_dcn:
            continue
        mixes_ici = any(a in ici_axes for a in op.axes)
        if op.kind in _DATA_MOVEMENT_KINDS and mixes_ici:
            bad.append(op)
        elif mixes_ici and not op.separable:
            bad.append(op)
    if bad:
        lines = ", ".join(
            f"{op.kind}[{'/'.join(op.axes)}]={op.payload_bytes}B" for op in bad
        )
        raise AssertionError(
            f"{len(bad)} collective(s) carry {ici_axes} traffic across the "
            f"DCN slice boundary: {lines}"
        )
