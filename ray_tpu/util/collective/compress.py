"""DCN-only compressed gradient all-reduce: int8 block quantization + EF21.

The dp-outer multislice preset pays for one gradient all-reduce over the
slow `dcn` axis per step — the hop the whole multislice design exists to
protect (PAPERS.md: cross-slice DCN bytes, not ICI flops, bound multi-slice
scaling). This module compresses ONLY that hop:

  1. per-slice gradients are computed with GSPMD auto sharding inside the
     slice (vmap over a leading n_slices dim with spmd_axis_name="dcn"),
     so the intra-slice reduce stays a full-precision ICI all-reduce;
  2. each slice adds its error-feedback residual, quantizes to int8 with a
     per-block fp32 scale AGREED across slices (one tiny f32 max
     all-reduce over dcn), and keeps the fresh quantization error as the
     next residual (EF21: the error re-enters the gradient next step, so
     compression bias does not accumulate);
  3. the quantized blocks cross DCN as ONE s8 all-reduce — values are
     clipped to ±(127 // n_slices) so the integer sum cannot overflow —
     and are dequantized with the shared scales into the fp32 mean.

Per-step DCN bytes drop from 4·numel (fp32) to numel + 4·numel/block
(int8 payload + shared scales) — ~3.94x for block=256. The byte counters
(util/collective/bytes.py) see an s8 all-reduce + a small f32 all-reduce
whose replica groups span only `dcn`, which is what the two_slice bench
gates measure.

Scope: quantization operates on gradients as laid out within the slice;
with within-slice-replicated grads (pure-DP / dp+tp-light rules) the
reshapes below are communication-free. With fsdp-sharded grads GSPMD may
insert intra-slice gathers around the flatten — correct, but not yet
byte-optimal; the supported configuration is pinned by the multislice
tests.
"""

from __future__ import annotations

from typing import Any, NamedTuple, Tuple

import jax
import jax.numpy as jnp

DEFAULT_BLOCK = 256
_EPS = 1e-30


class EFState(NamedTuple):
    """Error-feedback residuals: one fp32 buffer per slice holding the
    quantization error of the last step, flat over every gradient leaf
    (padded to a whole number of blocks). Sharded P("dcn") on dim 0 —
    each slice owns its own residual."""

    residual: jax.Array  # f32 [n_slices, padded_numel]


def _flat_sizes(params) -> Tuple[int, ...]:
    return tuple(int(l.size) for l in jax.tree.leaves(params))


def ef_buffer_numel(params, block: int = DEFAULT_BLOCK) -> int:
    """Padded flat length of the EF residual for a param/grad pytree."""
    total = sum(_flat_sizes(params))
    return ((total + block - 1) // block) * block


def init_ef_state(params, n_slices: int, block: int = DEFAULT_BLOCK) -> EFState:
    return EFState(
        residual=jnp.zeros((n_slices, ef_buffer_numel(params, block)), jnp.float32)
    )


def ef_state_sharding(mesh, dcn_axis: str = "dcn"):
    from jax.sharding import NamedSharding, PartitionSpec as P

    return EFState(residual=NamedSharding(mesh, P(dcn_axis)))


def compressed_slice_mean(
    grads_stacked: Any, ef: EFState, *, block: int = DEFAULT_BLOCK
) -> Tuple[Any, EFState]:
    """Mean per-slice gradients over the `dcn` dimension through the int8
    path. grads_stacked: pytree whose leaves are [n_slices, *shape] with
    dim 0 sharded over `dcn` (from a vmap(spmd_axis_name="dcn") backward).
    Returns (mean_grads, new_ef) where mean_grads leaves are [*shape] in
    the leaf's original dtype."""
    leaves, treedef = jax.tree.flatten(grads_stacked)
    n = int(leaves[0].shape[0])
    sizes = [int(l.size) // n for l in leaves]
    total = sum(sizes)
    padded = ((total + block - 1) // block) * block
    if ef.residual.shape != (n, padded):
        raise ValueError(
            f"EF residual shape {ef.residual.shape} does not match "
            f"{(n, padded)} (n_slices, padded grad numel)"
        )

    flat = jnp.concatenate(
        [l.reshape(n, -1).astype(jnp.float32) for l in leaves], axis=1
    )
    if padded != total:
        flat = jnp.pad(flat, ((0, 0), (0, padded - total)))

    if n == 1:
        mean_flat = flat[0]
        new_ef = ef  # nothing crosses DCN, nothing is quantized
    else:
        x = flat + ef.residual
        nb = padded // block
        blocks = x.reshape(n, nb, block)
        qmax = 127 // n  # integer sum of n terms stays inside int8
        # shared per-block scale: one small f32 max all-reduce over dcn
        s = jnp.max(jnp.abs(blocks), axis=-1) / qmax       # [n, nb]
        s = jnp.maximum(jnp.max(s, axis=0), _EPS)          # [nb], dcn pmax
        q = jnp.clip(jnp.round(blocks / s[None, :, None]), -qmax, qmax)
        q = q.astype(jnp.int8)
        deq = q.astype(jnp.float32) * s[None, :, None]
        new_ef = EFState(residual=(blocks - deq).reshape(n, padded))
        # the DCN hop: ONE s8 all-reduce of the quantized blocks
        qsum = jnp.sum(q, axis=0, dtype=jnp.int8)          # [nb, block]
        mean_flat = (qsum.astype(jnp.float32) * s[:, None]).reshape(padded) / n

    out, off = [], 0
    for l, sz in zip(leaves, sizes):
        out.append(mean_flat[off : off + sz].reshape(l.shape[1:]).astype(l.dtype))
        off += sz
    return jax.tree.unflatten(treedef, out), new_ef


def compression_dcn_byte_ratio(block: int = DEFAULT_BLOCK) -> float:
    """Analytic fp32-vs-int8 DCN byte ratio: 4·numel / (numel + 4·numel/block)."""
    return 4.0 / (1.0 + 4.0 / block)
