"""In-graph collectives: the ICI data plane.

These are the TPU-native replacement for the reference's NCCL calls
(SURVEY.md §5.9): thin, name-stable wrappers over `jax.lax` collectives,
usable inside shard_map/pjit over a mesh axis. XLA lowers them onto ICI
links and overlaps them with compute — nothing to bootstrap, no process
groups (the reference needed dist.init_process_group,
train/torch/config.py:113; here the mesh IS the group).

Every function takes `axis_name` (a mesh axis or tuple of axes) instead of
the out-of-graph API's `group_name`.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

from .types import ReduceOp


def allreduce(x, axis_name, op: ReduceOp = ReduceOp.SUM):
    if op == ReduceOp.SUM:
        return lax.psum(x, axis_name)
    if op == ReduceOp.AVERAGE:
        return lax.pmean(x, axis_name)
    if op == ReduceOp.MAX:
        return lax.pmax(x, axis_name)
    if op == ReduceOp.MIN:
        return lax.pmin(x, axis_name)
    if op == ReduceOp.PRODUCT:
        return jnp.prod(lax.all_gather(x, axis_name, axis=0, tiled=False), axis=0)
    raise ValueError(op)


def allgather(x, axis_name, *, axis: int = 0, tiled: bool = True):
    """Gather shards along `axis` from every device on the mesh axis."""
    return lax.all_gather(x, axis_name, axis=axis, tiled=tiled)


def reducescatter(x, axis_name, *, axis: int = 0, op: ReduceOp = ReduceOp.SUM):
    if op not in (ReduceOp.SUM, ReduceOp.AVERAGE):
        raise ValueError("reducescatter supports SUM/AVERAGE")
    out = lax.psum_scatter(x, axis_name, scatter_dimension=axis, tiled=True)
    if op == ReduceOp.AVERAGE:
        out = out / lax.psum(1, axis_name)
    return out


def broadcast(x, axis_name, *, src_index: int = 0):
    """Every device gets device src_index's value (one all_gather + index;
    XLA folds this into a collective-broadcast)."""
    return lax.all_gather(x, axis_name, axis=0, tiled=False)[src_index]


def alltoall(x, axis_name, *, split_axis: int = 0, concat_axis: int = 0):
    return lax.all_to_all(
        x, axis_name, split_axis=split_axis, concat_axis=concat_axis, tiled=True
    )


def permute(x, axis_name, perm):
    """ppermute: perm is a list of (source_index, destination_index)."""
    return lax.ppermute(x, axis_name, perm=perm)


def shift(x, axis_name, *, offset: int = 1):
    """Ring shift by `offset` along the axis (the ring-attention primitive)."""
    n = lax.psum(1, axis_name)
    perm = [(i, (i + offset) % n) for i in range(n)]
    return lax.ppermute(x, axis_name, perm=perm)


def rank(axis_name):
    return lax.axis_index(axis_name)


def world_size(axis_name):
    return lax.psum(1, axis_name)


def barrier(axis_name):
    """In-graph barrier: a trivial psum forces a synchronizing collective."""
    return lax.psum(jnp.zeros((), jnp.int32), axis_name)


__all__ = [
    "allreduce",
    "allgather",
    "reducescatter",
    "broadcast",
    "alltoall",
    "permute",
    "shift",
    "rank",
    "world_size",
    "barrier",
    "ReduceOp",
]
