"""Collective communication with Ray's group-management API shape.

Reference: python/ray/util/collective/collective.py (init_collective_group
:120, allreduce :258, broadcast :373, allgather :423, reducescatter :472,
send :531, recv :594) over NCCL/Gloo groups.

TPU-native split (SURVEY.md §5.9): in-graph collectives are XLA/GSPMD ops
on mesh axes (`ray_tpu.util.collective.in_graph` — psum/all_gather/
ppermute lowered by pjit over ICI); the out-of-graph "host" backend here
serves the reference's Gloo role — host-buffer rendezvous for control,
weight broadcast, and DCN-side exchange — built on the object store
instead of a separate transport.
"""

from .types import Backend, ReduceOp  # noqa: F401
from .collective import (  # noqa: F401
    allgather,
    allreduce,
    alltoall,
    barrier,
    broadcast,
    create_collective_group,
    destroy_collective_group,
    get_collective_group_size,
    get_rank,
    init_collective_group,
    is_group_initialized,
    recv,
    reducescatter,
    send,
)
from . import in_graph  # noqa: F401
from .bytes import (  # noqa: F401
    CollectiveOp,
    assert_no_cross_slice,
    collective_byte_report,
    mesh_collective_report,
)
from . import compress  # noqa: F401
from .compress import (  # noqa: F401
    EFState,
    compressed_slice_mean,
    compression_dcn_byte_ratio,
)
