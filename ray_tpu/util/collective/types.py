"""Collective op types (reference: python/ray/util/collective/types.py)."""

from __future__ import annotations

import enum


class ReduceOp(enum.Enum):
    SUM = "sum"
    PRODUCT = "product"
    MIN = "min"
    MAX = "max"
    AVERAGE = "average"


class Backend:
    """Backend names. HOST is the object-store rendezvous backend (the
    reference's GLOO role); XLA means "use in_graph on a mesh axis" and is
    rejected by the out-of-graph API with a pointer to in_graph."""

    HOST = "host"
    GLOO = "host"  # alias: accept reference spelling
    NCCL = "host"  # alias: no NVIDIA path on TPU; host rendezvous instead
    XLA = "xla"

    _ALIASES = {"host": "host", "gloo": "host", "nccl": "host"}

    @classmethod
    def resolve(cls, name: str) -> str:
        """Map a backend spelling to its implementation; raise on unknown."""
        if name == cls.XLA:
            raise ValueError(
                "backend='xla' collectives are in-graph: use "
                "ray_tpu.util.collective.in_graph inside shard_map/pjit"
            )
        try:
            return cls._ALIASES[name]
        except KeyError:
            raise ValueError(
                f"unknown collective backend {name!r}; expected one of "
                f"{sorted(cls._ALIASES)}"
            ) from None
