"""Out-of-graph host collectives over the object store.

API parity with python/ray/util/collective/collective.py; the transport is
a named rendezvous actor per group (the moral equivalent of the reference's
NCCLUniqueID store + communicator, nccl_collective_group.py:127) holding a
two-phase mailbox: every rank `contribute()`s its buffer (non-blocking on
the actor), then polls `fetch()` until the op is complete. Actor methods
stay serial, so there is no blocking wait inside the actor and no deadlock.

Collective calls must be issued in the same order by every rank of a group
(standard collective semantics); each local client keeps a per-group op
counter that forms the rendezvous key.
"""

from __future__ import annotations

import time
from typing import Any, Dict, List, Optional

import numpy as np

from .types import Backend, ReduceOp

_POLL_S = 0.002
_POLL_MAX_S = 0.05
DEFAULT_TIMEOUT_S = 300.0
# Rendezvous entries older than this are garbage-collected: any rank still
# interested has long since hit its own timeout. Keep > DEFAULT_TIMEOUT_S.
_GC_TTL_S = 900.0


def _reduce(arrs: List[np.ndarray], op: ReduceOp) -> np.ndarray:
    out = np.array(arrs[0], copy=True)
    for a in arrs[1:]:
        if op in (ReduceOp.SUM, ReduceOp.AVERAGE):
            out = out + a
        elif op == ReduceOp.PRODUCT:
            out = out * a
        elif op == ReduceOp.MIN:
            out = np.minimum(out, a)
        elif op == ReduceOp.MAX:
            out = np.maximum(out, a)
    if op == ReduceOp.AVERAGE:
        out = out / len(arrs)
    return out


class _Rendezvous:
    """Named actor: per-group mailbox. One instance per collective group.

    Completion is PUSHED: every completed op / deposited p2p payload
    publishes to the group's pubsub channel, and waiting ranks park on a
    long-poll instead of sleep-polling the actor (VERDICT r2 weak #4 — the
    2-50ms backoff loop was too slow for IMPALA-rate weight broadcast;
    reference intent: ray.util.collective's NCCL groups complete in-line,
    collective.py:373)."""

    def __init__(self, world_size: int, group_name: str = "default"):
        self.world_size = world_size
        self.channel = f"_collective:{group_name}"
        self.members: set = set(range(world_size))
        self.ops: Dict[Any, dict] = {}  # key -> {parts, meta, result, fetched}
        self.p2p: Dict[Any, Any] = {}  # (src, dst, seq) -> payload

    def _notify(self, key):
        """Wake parked ranks (publish rides this actor's head connection)."""
        try:
            from .. import pubsub

            pubsub.publish(self.channel, key)
        except Exception:
            pass  # ranks still progress via their long-poll safety refetch

    def describe(self) -> dict:
        return {"world_size": self.world_size}

    def leave(self, rank: int) -> int:
        """A rank leaving the group (destroy_collective_group). Returns the
        number of remaining members; the last leaver kills the actor."""
        self.members.discard(rank)
        return len(self.members)

    def _gc(self):
        """Drop op/p2p state no live rank will ever collect: entries older
        than _GC_TTL_S (every interested rank has timed out by then). Keeps
        the detached rendezvous actor's memory bounded across failures."""
        now = time.monotonic()
        for key in [k for k, e in self.ops.items() if now - e["ts"] > _GC_TTL_S]:
            del self.ops[key]
        for key in [k for k, (ts, _) in self.p2p.items() if now - ts > _GC_TTL_S]:
            del self.p2p[key]

    def contribute(self, key, rank: int, payload, meta: dict):
        """Deposit one rank's buffer. If this contribution completes the op,
        returns this rank's result immediately (saves one fetch RPC);
        otherwise the rank polls fetch()."""
        self._gc()
        ent = self.ops.setdefault(
            key,
            {"parts": {}, "meta": meta, "result": None, "error": None, "fetched": set(), "ts": time.monotonic()},
        )
        ent["ts"] = time.monotonic()  # staggered arrivals keep the op live
        ent["parts"][rank] = payload
        if len(ent["parts"]) == self.world_size:
            try:
                ent["result"] = self._complete(ent["parts"], ent["meta"])
            except Exception as e:  # surface to EVERY rank, not just the last
                ent["error"] = e
            ent["parts"] = {}
            self._notify(key)
            return self.fetch(key, rank)
        return ("pending", None)

    def _complete(self, parts: Dict[int, Any], meta: dict):
        kind = meta["kind"]
        ordered = [parts[r] for r in range(self.world_size)]
        if kind == "allreduce":
            return _reduce(ordered, ReduceOp(meta["op"]))
        if kind == "allgather":
            return ordered
        if kind == "reducescatter":
            red = _reduce(ordered, ReduceOp(meta["op"]))
            if red.shape[0] % self.world_size != 0:
                raise ValueError(
                    f"reducescatter axis-0 size {red.shape[0]} is not divisible "
                    f"by world_size {self.world_size} (matching in_graph/"
                    "psum_scatter semantics)"
                )
            return np.array_split(red, self.world_size, axis=0)
        if kind == "broadcast":
            return parts[meta["src_rank"]]
        if kind == "alltoall":
            return [[ordered[j][i] for j in range(self.world_size)] for i in range(self.world_size)]
        if kind == "barrier":
            return True
        raise ValueError(f"unknown collective kind {kind!r}")

    def fetch(self, key, rank: int):
        ent = self.ops.get(key)
        if ent is None or (ent["result"] is None and ent["error"] is None):
            return ("pending", None)
        if ent["error"] is not None:
            ent["fetched"].add(rank)
            if len(ent["fetched"]) == self.world_size:
                err = ent["error"]
                del self.ops[key]
                return ("error", err)
            return ("error", ent["error"])
        kind = ent["meta"]["kind"]
        if kind in ("reducescatter", "alltoall"):
            out = ent["result"][rank]
        elif kind == "allgather":
            out = list(ent["result"])
        else:
            out = ent["result"]
        ent["fetched"].add(rank)
        if len(ent["fetched"]) == self.world_size:
            del self.ops[key]
        return ("ready", out)

    def p2p_send(self, src: int, dst: int, seq: int, payload):
        self._gc()
        self.p2p[(src, dst, seq)] = (time.monotonic(), payload)
        self._notify((src, dst, seq))

    def p2p_recv(self, src: int, dst: int, seq: int):
        if (src, dst, seq) in self.p2p:
            return ("ready", self.p2p.pop((src, dst, seq))[1])
        return ("pending", None)


class _GroupClient:
    def __init__(self, group_name: str, world_size: int, rank: int, actor):
        self.group_name = group_name
        self.world_size = world_size
        self.rank = rank
        self.actor = actor
        self._channel = f"_collective:{group_name}"
        self.seq = 0
        self.send_seq: Dict[int, int] = {}
        self.recv_seq: Dict[int, int] = {}
        # set after a collective timeout: the group's op counters can no
        # longer be assumed aligned across ranks, so further use is an error
        self.broken = False

    def run(self, payload, meta: dict, timeout_s: Optional[float] = None):
        import ray_tpu

        from .. import pubsub

        if self.broken:
            raise RuntimeError(
                f"collective group {self.group_name!r} is broken after a "
                "timeout (op counters may be desynchronized); destroy and "
                "re-init the group on every rank"
            )
        timeout_s = timeout_s if timeout_s is not None else DEFAULT_TIMEOUT_S
        if timeout_s > _GC_TTL_S:
            raise ValueError(
                f"timeout_s {timeout_s} exceeds the rendezvous GC TTL "
                f"({_GC_TTL_S}s); state would be collected before the wait ends"
            )
        key = self.seq
        self.seq += 1
        deadline = time.monotonic() + timeout_s
        state, out = ray_tpu.get(self.actor.contribute.remote(key, self.rank, payload, meta))
        last_seq = 0
        while state == "pending":
            remaining = deadline - time.monotonic()
            if remaining <= 0:
                self.broken = True
                raise TimeoutError(
                    f"collective {meta['kind']!r} op {key} on group "
                    f"{self.group_name!r} timed out waiting for peers "
                    f"(rank {self.rank}/{self.world_size}); a peer likely "
                    "died or diverged in collective-call order. The group is "
                    "now marked broken; destroy and re-init to continue"
                )
            # park on the group channel until the actor publishes a
            # completion (push, not poll); the bounded wait is only a
            # safety net against a lost publish
            res = pubsub.poll(self._channel, last_seq, min(remaining, 5.0))
            if res is not None:
                last_seq = res[0]
            state, out = ray_tpu.get(self.actor.fetch.remote(key, self.rank))
        if state == "error":
            raise RuntimeError(
                f"collective {meta['kind']!r} op {key} on group "
                f"{self.group_name!r} failed on the rendezvous: {out!r}"
            ) from (out if isinstance(out, Exception) else None)
        return out


_GROUPS: Dict[str, _GroupClient] = {}


def _rendezvous_actor(group_name: str, world_size: int):
    import ray_tpu

    name = f"_ray_tpu_collective:{group_name}"
    try:
        return (
            ray_tpu.remote(_Rendezvous)
            .options(name=name, lifetime="detached")
            .remote(world_size, group_name)
        )
    except ValueError:
        return ray_tpu.get_actor(name)


def init_collective_group(
    world_size: int,
    rank: int,
    backend: str = "host",
    group_name: str = "default",
) -> None:
    """Initialize this process's membership in a named collective group
    (reference: collective.py:120)."""
    import ray_tpu

    Backend.resolve(backend)
    if group_name in _GROUPS:
        raise RuntimeError(f"collective group {group_name!r} already initialized")
    if not 0 <= rank < world_size:
        raise ValueError(f"rank {rank} out of range for world_size {world_size}")
    actor = _rendezvous_actor(group_name, world_size)
    desc = ray_tpu.get(actor.describe.remote())
    if desc["world_size"] != world_size:
        raise ValueError(
            f"group {group_name!r} already exists with world_size "
            f"{desc['world_size']}, not {world_size}; destroy it on every "
            "rank first"
        )
    _GROUPS[group_name] = _GroupClient(group_name, world_size, rank, actor)


def create_collective_group(
    actors: List[Any],
    world_size: int,
    ranks: List[int],
    backend: str = "host",
    group_name: str = "default",
) -> None:
    """Driver-side declarative setup (reference: collective.py:170): tells
    each actor to join the group. Requires each actor to expose an
    `init_collective_group(world_size, rank, backend, group_name)` method
    (typically by calling this module's init_collective_group)."""
    import ray_tpu

    ray_tpu.get(
        [
            a.init_collective_group.remote(world_size, r, backend, group_name)
            for a, r in zip(actors, ranks)
        ]
    )


def is_group_initialized(group_name: str = "default") -> bool:
    return group_name in _GROUPS


def destroy_collective_group(group_name: str = "default") -> None:
    """Leave the group; the last rank to leave kills the rendezvous actor so
    the group name can be re-created with a fresh world_size."""
    import ray_tpu

    g = _GROUPS.pop(group_name, None)
    if g is None:
        return
    remaining = ray_tpu.get(g.actor.leave.remote(g.rank))
    if remaining == 0:
        ray_tpu.kill(g.actor)


def get_rank(group_name: str = "default") -> int:
    g = _GROUPS.get(group_name)
    return g.rank if g else -1


def get_collective_group_size(group_name: str = "default") -> int:
    g = _GROUPS.get(group_name)
    return g.world_size if g else -1


def _group(group_name: str) -> _GroupClient:
    g = _GROUPS.get(group_name)
    if g is None:
        raise RuntimeError(
            f"collective group {group_name!r} is not initialized in this "
            "process; call init_collective_group first"
        )
    return g


def _to_np(tensor) -> np.ndarray:
    return np.asarray(tensor)


def allreduce(tensor, group_name: str = "default", op: ReduceOp = ReduceOp.SUM):
    """All-reduce across the group; returns the reduced array
    (reference: collective.py:258 mutates in place; we are functional)."""
    g = _group(group_name)
    return g.run(_to_np(tensor), {"kind": "allreduce", "op": op.value})


def allgather(tensor, group_name: str = "default") -> List[np.ndarray]:
    """Returns the list of per-rank tensors, rank-ordered (reference:
    collective.py:423 fills a preallocated tensor_list)."""
    g = _group(group_name)
    return g.run(_to_np(tensor), {"kind": "allgather"})


def reducescatter(tensor, group_name: str = "default", op: ReduceOp = ReduceOp.SUM):
    """Reduce across ranks then scatter along axis 0; returns this rank's
    shard (reference: collective.py:472)."""
    g = _group(group_name)
    return g.run(_to_np(tensor), {"kind": "reducescatter", "op": op.value})


def broadcast(tensor, src_rank: int = 0, group_name: str = "default"):
    """Broadcast src_rank's tensor to all ranks (reference: collective.py:373)."""
    g = _group(group_name)
    payload = _to_np(tensor) if g.rank == src_rank else None
    return g.run(payload, {"kind": "broadcast", "src_rank": src_rank})


def alltoall(tensor_list: List[Any], group_name: str = "default") -> List[np.ndarray]:
    """Each rank provides world_size chunks; receives chunk[rank] from every
    rank, rank-ordered."""
    g = _group(group_name)
    if len(tensor_list) != g.world_size:
        raise ValueError(f"need {g.world_size} chunks, got {len(tensor_list)}")
    return g.run([_to_np(t) for t in tensor_list], {"kind": "alltoall"})


def barrier(group_name: str = "default") -> None:
    """Block until every rank reaches the barrier."""
    _group(group_name).run(None, {"kind": "barrier"})


def send(tensor, dst_rank: int, group_name: str = "default") -> None:
    """Point-to-point send (reference: collective.py:531)."""
    import ray_tpu

    g = _group(group_name)
    seq = g.send_seq.get(dst_rank, 0)
    g.send_seq[dst_rank] = seq + 1
    ray_tpu.get(g.actor.p2p_send.remote(g.rank, dst_rank, seq, _to_np(tensor)))


def recv(src_rank: int, group_name: str = "default", timeout_s: Optional[float] = None):
    """Point-to-point receive (reference: collective.py:594). Returns the
    received array (the reference writes into a preallocated tensor)."""
    import ray_tpu

    from .. import pubsub

    g = _group(group_name)
    timeout_s = timeout_s if timeout_s is not None else DEFAULT_TIMEOUT_S
    if timeout_s > _GC_TTL_S:
        raise ValueError(
            f"timeout_s {timeout_s} exceeds the rendezvous GC TTL ({_GC_TTL_S}s)"
        )
    seq = g.recv_seq.get(src_rank, 0)
    deadline = time.monotonic() + timeout_s
    last_seq = 0
    while True:
        state, out = ray_tpu.get(g.actor.p2p_recv.remote(src_rank, g.rank, seq))
        if state == "ready":
            # consume the seq only on success so a timed-out recv can be
            # retried without desynchronizing from the sender
            g.recv_seq[src_rank] = seq + 1
            return out
        remaining = deadline - time.monotonic()
        if remaining <= 0:
            raise TimeoutError(
                f"recv from rank {src_rank} on group {group_name!r} timed out"
            )
        # park until the sender's deposit is published (push, not poll)
        res = pubsub.poll(g._channel, last_seq, min(remaining, 5.0))
        if res is not None:
            last_seq = res[0]
