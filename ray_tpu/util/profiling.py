"""In-process on-demand profiling: CPU stack sampling + memory snapshots.

Reference parity: dashboard/modules/reporter/profile_manager.py — the
reference shells out to py-spy (CPU flamegraph / stack dump) and memray
(allocation tracking) against an arbitrary pid. Neither tool ships in this
environment, and out-of-process attaches need ptrace scope; instead every
ray_tpu worker can profile ITSELF on request (the worker protocol loop stays
responsive while an executor thread grinds — sampling happens from a
dedicated thread reading sys._current_frames()). The output is the standard
collapsed-stack ("flamegraph.pl") format: `root;child;leaf count` lines,
renderable by any flamegraph tool and cheap to aggregate in the dashboard.

Memory profiling uses stdlib tracemalloc: `memory_profile(duration)` diffs
two snapshots taken `duration` apart and reports the top allocation sites
(memray's core use-case: "where is memory going right now").
"""

from __future__ import annotations

import sys
import threading
import time
from collections import Counter
from typing import Dict, List, Optional


def _frame_label(frame) -> str:
    code = frame.f_code
    fname = code.co_filename
    # compact: last two path components are enough to locate a file
    parts = fname.replace("\\", "/").rsplit("/", 2)
    short = "/".join(parts[-2:]) if len(parts) > 1 else fname
    return f"{short}:{code.co_name}"


def _collapse(frame) -> str:
    """Root-first collapsed stack for one thread's current frame."""
    stack: List[str] = []
    while frame is not None:
        stack.append(_frame_label(frame))
        frame = frame.f_back
    stack.reverse()
    return ";".join(stack)


def sample_stacks(
    duration_s: float = 2.0,
    interval_s: float = 0.01,
    include_idle: bool = False,
) -> Dict[str, int]:
    """Sample every thread's Python stack for `duration_s`; returns
    {collapsed_stack: sample_count}. The sampling thread excludes itself.

    `include_idle=False` drops stacks whose leaf is a pure wait (epoll /
    lock.acquire / sleep) — the protocol loop and executor idle-parks would
    otherwise dominate every profile.
    """
    me = threading.get_ident()
    agg: Counter = Counter()
    deadline = time.monotonic() + max(0.05, duration_s)
    idle_leaves = (
        "select.py:select", "selectors.py:select", "threading.py:wait",
        "threading.py:_wait_for_tstate_lock", "queue.py:get",
        "socket.py:accept",
    )
    while time.monotonic() < deadline:
        for tid, frame in sys._current_frames().items():
            if tid == me:
                continue
            stack = _collapse(frame)
            if not include_idle and stack.rsplit(";", 1)[-1].endswith(idle_leaves):
                continue
            agg[stack] += 1
        time.sleep(interval_s)
    return dict(agg)


def collapsed_lines(agg: Dict[str, int], limit: Optional[int] = None) -> List[str]:
    """Render an aggregate as flamegraph-collapsed lines, hottest first."""
    items = sorted(agg.items(), key=lambda kv: -kv[1])
    if limit:
        items = items[:limit]
    return [f"{stack} {n}" for stack, n in items]


def top_functions(agg: Dict[str, int], limit: int = 15) -> List[dict]:
    """Leaf-attributed hot functions (the 'self time' view of a profile)."""
    leaf: Counter = Counter()
    total = 0
    for stack, n in agg.items():
        leaf[stack.rsplit(";", 1)[-1]] += n
        total += n
    return [
        {"fn": fn, "samples": n, "pct": round(100.0 * n / max(1, total), 1)}
        for fn, n in leaf.most_common(limit)
    ]


def cpu_profile(duration_s: float = 2.0, interval_s: float = 0.01) -> dict:
    """The worker-side RPC body: one self-profile, JSON-friendly."""
    t0 = time.monotonic()
    agg = sample_stacks(duration_s, interval_s)
    return {
        "kind": "cpu",
        "duration_s": round(time.monotonic() - t0, 3),
        "samples": sum(agg.values()),
        "collapsed": collapsed_lines(agg, limit=200),
        "top": top_functions(agg),
    }


def memory_profile(duration_s: float = 1.0, top: int = 25) -> dict:
    """Top allocation sites over a window (tracemalloc snapshot diff).
    If tracemalloc was off, turns it on for the window (self-contained)."""
    import tracemalloc

    started_here = not tracemalloc.is_tracing()
    if started_here:
        tracemalloc.start(10)
    try:
        before = tracemalloc.take_snapshot()
        time.sleep(max(0.0, duration_s))
        after = tracemalloc.take_snapshot()
        stats = after.compare_to(before, "lineno")
        cur, peak = tracemalloc.get_traced_memory()
        rows = [
            {
                "site": str(s.traceback[0]) if s.traceback else "?",
                "size_diff_kb": round(s.size_diff / 1024.0, 1),
                "size_kb": round(s.size / 1024.0, 1),
                "count_diff": s.count_diff,
            }
            for s in stats[:top]
        ]
        return {
            "kind": "mem",
            "traced_current_kb": round(cur / 1024.0, 1),
            "traced_peak_kb": round(peak / 1024.0, 1),
            "window_s": duration_s,
            "top": rows,
        }
    finally:
        if started_here:
            tracemalloc.stop()


def stack_dump() -> dict:
    """Instantaneous stack of every thread (py-spy `dump` equivalent)."""
    frames = sys._current_frames()
    me = threading.get_ident()
    names = {t.ident: t.name for t in threading.enumerate()}
    out = {}
    for tid, frame in frames.items():
        if tid == me:
            continue
        out[names.get(tid, str(tid))] = _collapse(frame).split(";")
    return {"kind": "dump", "threads": out}
