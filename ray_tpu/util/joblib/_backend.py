"""Joblib ParallelBackend running batches on pool actors (reference:
ray/util/joblib/ray_backend.py — MultiprocessingBackend subclass whose
pool is actor-backed)."""

from __future__ import annotations

from joblib._parallel_backends import MultiprocessingBackend

from ..multiprocessing import Pool


class RayTpuBackend(MultiprocessingBackend):
    supports_timeout = True

    def effective_n_jobs(self, n_jobs):
        import os

        if n_jobs == 1:
            return 1
        import ray_tpu

        if ray_tpu.is_initialized():
            total = int(ray_tpu.cluster_resources().get("CPU", os.cpu_count() or 1))
        else:
            total = os.cpu_count() or 1
        if n_jobs is None:
            return total
        if n_jobs < 0:  # joblib convention: -1 = all, -2 = all minus one, ...
            return max(1, total + 1 + n_jobs)
        return n_jobs

    def configure(self, n_jobs=1, parallel=None, prefer=None, require=None, **kwargs):
        n_jobs = self.effective_n_jobs(n_jobs)
        self._pool = Pool(processes=n_jobs)
        self.parallel = parallel
        return n_jobs

    def _get_pool(self):
        return self._pool

    def terminate(self):
        if getattr(self, "_pool", None) is not None:
            self._pool.terminate()
            self._pool = None
