"""Joblib backend over ray_tpu actors.

Reference parity: python/ray/util/joblib/ (register_ray +
ray_backend.RayBackend): after `register_ray()`,
`joblib.parallel_backend("ray")` routes scikit-learn/joblib work through
the cluster's multiprocessing Pool shim.
"""

from __future__ import annotations


def register_ray() -> None:
    from joblib.parallel import register_parallel_backend

    from ._backend import RayTpuBackend

    register_parallel_backend("ray", RayTpuBackend)
    register_parallel_backend("ray_tpu", RayTpuBackend)
