"""Multi-slice DCN meshes: slice-aware topology + cross-slice presets.

Real TPU fleets are not one flat ICI torus: they are multiple slices joined
by data-center network (DCN), and the slice boundary is orders of magnitude
slower than ICI (SURVEY §7 M5).  This module makes that boundary a
first-class mesh axis:

    mesh axes = ("dcn",) + AXIS_ORDER      # dcn outermost, slice-major

`SliceTopology` extends `MeshSpec` with the outer `dcn` axis (slice count x
per-slice ICI shape) and validates that the bandwidth-hungry axes — tp, sp,
ep, whose collectives are per-layer all-reduce/ppermute/all-to-all traffic —
stay INSIDE a slice.  Two presets cover the cross-slice parallelisms that
tolerate DCN latency:

  dp-outer  batch sharded over ("dcn", "dp", "fsdp"): the only DCN traffic
            is the gradient all-reduce, once per step (the multi-slice v5e
            fine-tuning configuration, arXiv:2605.25645).
  pp-outer  pipeline stages mapped one stage-group per slice ("stage" ->
            ("dcn", "pp")): ppermute activation traffic crosses DCN exactly
            at stage boundaries, everything else stays on ICI (MPMD
            pipeline over slow inter-group links, arXiv:2412.14374).

The split is observable: `ray_tpu.util.collective.collective_byte_report`
classifies every collective in a compiled step as ICI or DCN by its replica
groups, so tests (and the MULTICHIP two_slice harness row) can PROVE tp/sp/
ep bytes never cross a slice boundary.
"""

from __future__ import annotations

import dataclasses
from typing import List, Optional, Sequence, Tuple

import numpy as np

from .mesh import AXIS_ORDER, MeshSpec
from .sharding import ShardingRules, make_rules

# the canonical slow-axis name; everything downstream (byte counters,
# sharding rules, pipeline placement) keys off this string
DCN_AXIS = "dcn"

# mesh axes whose collectives are per-layer bandwidth (Megatron all-reduce,
# ring ppermute, MoE all-to-all): they must never span a slice boundary
ICI_ONLY_AXES: Tuple[str, ...] = ("tp", "sp", "ep")

# logical axes that map to ICI-only mesh axes in every sane rule table
_ICI_ONLY_LOGICAL = (
    "heads", "kv_heads", "mlp", "vocab",   # tp family
    "seq", "kv_seq",                        # sp family
    "expert",                               # ep family
)

MULTISLICE_AXIS_ORDER: Tuple[str, ...] = (DCN_AXIS,) + AXIS_ORDER


@dataclasses.dataclass(frozen=True)
class SliceTopology:
    """num_slices x per-slice ICI mesh. `slice_spec` axes all live inside
    one slice; the dcn axis is implicit (size = num_slices, outermost)."""

    num_slices: int
    slice_spec: MeshSpec = MeshSpec()

    def __post_init__(self):
        if self.num_slices < 1:
            raise ValueError(f"num_slices must be >= 1, got {self.num_slices}")

    def axis_order(self) -> Tuple[str, ...]:
        return MULTISLICE_AXIS_ORDER

    def total(self) -> int:
        if any(v == -1 for v in self.slice_spec.degrees().values()):
            raise ValueError(
                "slice_spec contains a -1 wildcard; call resolve(n_devices) "
                "before total()/device_slice_ids()"
            )
        return self.num_slices * self.slice_spec.total()

    def resolve(self, n_devices: int) -> "SliceTopology":
        """Fix -1 axes against the PER-SLICE device count and validate that
        every ICI-hungry axis fits inside one slice, raising errors that
        name the offending axis (not an opaque reshape failure)."""
        if n_devices % self.num_slices:
            raise ValueError(
                f"{n_devices} devices do not split into {self.num_slices} "
                f"equal slices"
            )
        per_slice = n_devices // self.num_slices
        for ax in ICI_ONLY_AXES:
            deg = getattr(self.slice_spec, ax)
            if deg > per_slice:
                raise ValueError(
                    f"mesh axis {ax!r}={deg} does not fit inside one slice "
                    f"of {per_slice} devices ({self.num_slices} slices x "
                    f"{per_slice}); bandwidth-hungry axes "
                    f"{ICI_ONLY_AXES} must never cross the DCN slice "
                    f"boundary — shrink {ax!r} or use fewer slices"
                )
        try:
            spec = self.slice_spec.resolve(per_slice)
        except ValueError as e:
            raise ValueError(
                f"per-slice mesh spec does not fit one slice of "
                f"{per_slice} devices ({self.num_slices} slices over "
                f"{n_devices}): {e}"
            ) from None
        return SliceTopology(self.num_slices, spec)

    def device_slice_ids(self, n_devices: Optional[int] = None) -> np.ndarray:
        """slice id of each FLAT mesh-device index (dcn-major layout)."""
        total = n_devices if n_devices is not None else self.total()
        per_slice = total // self.num_slices
        return np.arange(total) // per_slice


def check_rules(rules: ShardingRules, dcn_axis: str = DCN_AXIS) -> None:
    """Reject rule tables that route ICI-only logical axes over DCN."""
    for logical in _ICI_ONLY_LOGICAL:
        mapped = rules.mesh_axes(logical)
        axes = mapped if isinstance(mapped, tuple) else (mapped,)
        if dcn_axis in axes:
            raise ValueError(
                f"logical axis {logical!r} is mapped to {mapped!r}: "
                f"tensor/sequence/expert-parallel traffic is per-layer "
                f"bandwidth and must never cross the {dcn_axis!r} slice "
                "boundary"
            )


def group_devices_by_slice(devices: Sequence, num_slices: int) -> List[list]:
    """Partition devices into per-slice blocks.

    Real multi-slice TPUs expose `device.slice_index`; when present and
    consistent it is authoritative.  Otherwise (CPU virtual meshes,
    single-slice TPUs carved logically) devices are grouped contiguously in
    (process_index, id) order — the gang's host topology: hosts of one
    slice hold consecutive ranks."""
    devices = list(devices)
    if num_slices == 1:
        return [devices]
    if len(devices) % num_slices:
        raise ValueError(
            f"{len(devices)} devices do not split into {num_slices} slices"
        )
    slice_ids = {getattr(d, "slice_index", None) for d in devices}
    if None not in slice_ids and len(slice_ids) == num_slices:
        blocks = {s: [] for s in sorted(slice_ids)}
        for d in devices:
            blocks[d.slice_index].append(d)
        sizes = {len(b) for b in blocks.values()}
        if len(sizes) != 1:
            raise ValueError(
                f"uneven slices: per-slice device counts "
                f"{ {s: len(b) for s, b in blocks.items()} }"
            )
        return [blocks[s] for s in sorted(blocks)]
    per = len(devices) // num_slices
    devices = sorted(
        devices, key=lambda d: (getattr(d, "process_index", 0), d.id)
    )
    return [devices[i * per:(i + 1) * per] for i in range(num_slices)]


def build_multislice_mesh(topology: SliceTopology, devices: Optional[Sequence] = None):
    """Build the two-level Mesh: axes ("dcn",) + AXIS_ORDER, device array
    stacked slice-major so flat index // devices_per_slice == slice id (the
    invariant the collective byte counters classify against).  Within each
    slice the usual topology-aware assignment applies."""
    import jax
    from jax.sharding import Mesh

    devices = list(devices if devices is not None else jax.devices())
    topo = topology.resolve(len(devices))
    inner_shape = tuple(topo.slice_spec.degrees()[a] for a in AXIS_ORDER)
    blocks = []
    for block in group_devices_by_slice(devices, topo.num_slices):
        try:
            from jax.experimental import mesh_utils

            arr = mesh_utils.create_device_mesh(inner_shape, devices=block)
        except Exception:
            arr = np.array(block).reshape(inner_shape)
        blocks.append(arr)
    return Mesh(np.stack(blocks), topo.axis_order())


# --- presets ---------------------------------------------------------------

MULTISLICE_PRESETS = ("dp_outer", "pp_outer")


def multislice_rules(preset: str, **make_rules_kwargs) -> ShardingRules:
    """Slice-aware rule tables.

    dp_outer: batch additionally sharded over dcn — DCN carries ONLY the
              once-per-step gradient all-reduce.
    pp_outer: pipeline stage dim sharded over ("dcn", "pp") — DCN carries
              ONLY the stage-boundary activation ppermutes.
    """
    if preset == "dp_outer":
        rules = make_rules(dcn="dp", **make_rules_kwargs)
    elif preset == "pp_outer":
        # vocab stays unsharded: embed/unembed live OUTSIDE the pipeline
        # stages (stage-replicated), and a tp-sharded vocab dim invites
        # GSPMD to reshard the table over the equal-sized dcn axis for the
        # token gather — a data-movement collective across DCN the byte
        # counters rightly flag. Override with .with_overrides(vocab="tp")
        # if the table dominates HBM and the gather cost is acceptable.
        rules = make_rules(dcn="pp", **make_rules_kwargs).with_overrides(
            vocab=None
        )
    else:
        raise ValueError(
            f"unknown multislice preset {preset!r}; choose from "
            f"{MULTISLICE_PRESETS}"
        )
    check_rules(rules)
    return rules


def dp_outer(
    num_slices: int, slice_spec: MeshSpec = MeshSpec(), **make_rules_kwargs
) -> Tuple[SliceTopology, ShardingRules]:
    """Data parallelism across slices: every slice holds a full model
    replica group; gradients all-reduce over DCN once per step.  The right
    preset when the model fits one slice and you are scaling batch."""
    return (
        SliceTopology(num_slices, slice_spec),
        multislice_rules("dp_outer", **make_rules_kwargs),
    )


def pp_outer(
    num_slices: int,
    slice_spec: MeshSpec = MeshSpec(),
    *,
    stages_per_slice: int = 1,
    virtual_stages_per_device: int = 1,
    **make_rules_kwargs,
) -> Tuple[SliceTopology, ShardingRules]:
    """Pipeline stages across slices: stage i lives on slice
    i // stages_per_slice; only microbatch activations cross DCN, at stage
    boundaries.  The right preset when one slice cannot hold the model and
    activations are small relative to gradients.

    virtual_stages_per_device (v) selects the interleaved-1F1B schedule
    (parallel/pipeline.py): each of the pp = num_slices*stages_per_slice
    stage devices hosts v non-adjacent stage CHUNKS (chunk q on device
    q % pp), shrinking the pipeline bubble from (pp-1)/(n_mb+pp-1) toward
    (pp-1)/(v*n_mb+pp-1) at the cost of v x the activation hop rate — all
    extra hops ride ICI; DCN still sees exactly one boundary transfer per
    tick.  The model must expose pp * v stage rows (e.g.
    TransformerConfig.pp_stages = pp * v with pp_interleave = v) and
    n_microbatches must divide by pp.  v is a schedule knob, not a mesh
    axis, so the returned topology/rules are identical for every v — it is
    threaded here so gang-level code (ScalingConfig.virtual_stages_per_device
    -> session.get_virtual_stages_per_device) validates one number once."""
    if stages_per_slice < 1:
        raise ValueError(f"stages_per_slice must be >= 1, got {stages_per_slice}")
    if virtual_stages_per_device < 1:
        raise ValueError(
            f"virtual_stages_per_device must be >= 1, got "
            f"{virtual_stages_per_device}"
        )
    spec = dataclasses.replace(slice_spec, pp=stages_per_slice)
    return (
        SliceTopology(num_slices, spec),
        multislice_rules("pp_outer", **make_rules_kwargs),
    )
