"""Logical-axis sharding rules (GSPMD annotations).

Arrays are annotated with *logical* axis names; a ShardingRules table maps
them to mesh axes and GSPMD inserts all collectives. This replaces the
reference's entire DP engine zoo (torch DDP wrap train_loop_utils.py:75,
FSDP :92-101, DeepSpeed launcher) with one declarative table:

  DDP        -> batch: (dp, fsdp); params unsharded
  ZeRO/FSDP  -> same + embed/mlp sharded on fsdp
  Megatron   -> heads/mlp on tp, embed replicated
  sequence   -> seq activations on sp (ring attention handles the halo)
  MoE        -> experts on ep
"""

from __future__ import annotations

from typing import Dict, Optional, Tuple, Union

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

# canonical logical axis names used by models/
LOGICAL_AXES = (
    "batch",      # tokens batch dim
    "seq",        # sequence dim of activations
    "kv_seq",     # sequence dim of K/V (ring attention shards this)
    "embed",      # model/hidden dim
    "heads",      # attention heads
    "kv_heads",   # key/value heads (GQA)
    "head_dim",   # per-head dim
    "mlp",        # FFN intermediate dim
    "vocab",      # vocabulary dim
    "layers",     # stacked-layer dim (scanned layers / pipeline stages)
    "expert",     # MoE experts
    "stage",      # pipeline stage dim
)

MeshAxes = Union[None, str, Tuple[str, ...]]


class ShardingRules:
    def __init__(self, rules: Dict[str, MeshAxes]):
        unknown = set(rules) - set(LOGICAL_AXES)
        if unknown:
            raise ValueError(f"Unknown logical axes: {sorted(unknown)}")
        self.rules = dict(rules)

    def mesh_axes(self, logical: Optional[str]) -> MeshAxes:
        if logical is None:
            return None
        return self.rules.get(logical)

    def spec(self, *logical_axes: Optional[str]) -> P:
        out, used = [], set()
        for ax in logical_axes:
            m = self.mesh_axes(ax)
            if isinstance(m, tuple):
                m = tuple(a for a in m if a not in used)
                used.update(m)
                out.append(m if m else None)
            else:
                if m in used:
                    m = None
                if m is not None:
                    used.add(m)
                out.append(m)
        return P(*out)

    def with_overrides(self, **overrides: MeshAxes) -> "ShardingRules":
        r = dict(self.rules)
        r.update(overrides)
        return ShardingRules(r)


# --- presets ---------------------------------------------------------------

def make_rules(
    *,
    fsdp_params: bool = True,
    tensor_parallel: bool = True,
    sequence_parallel: bool = False,
    expert_parallel: bool = False,
) -> ShardingRules:
    rules: Dict[str, MeshAxes] = {
        "batch": ("dp", "fsdp"),
        "seq": "sp" if sequence_parallel else None,
        "kv_seq": "sp" if sequence_parallel else None,
        "embed": "fsdp" if fsdp_params else None,
        "heads": "tp" if tensor_parallel else None,
        "kv_heads": "tp" if tensor_parallel else None,
        "head_dim": None,
        "mlp": "tp" if tensor_parallel else None,
        "vocab": "tp" if tensor_parallel else None,
        "layers": None,
        "expert": "ep" if expert_parallel else None,
        "stage": "pp",
    }
    return ShardingRules(rules)


PRESET_RULES: Dict[str, ShardingRules] = {
    # pure data parallel: params replicated
    "dp": make_rules(fsdp_params=False, tensor_parallel=False),
    # ZeRO-3: params sharded on fsdp along embed
    "fsdp": make_rules(tensor_parallel=False),
    # Megatron TP + FSDP
    "fsdp_tp": make_rules(),
    # + ring-attention sequence parallel
    "fsdp_tp_sp": make_rules(sequence_parallel=True),
    # MoE
    "fsdp_tp_ep": make_rules(expert_parallel=True),
    "full": make_rules(sequence_parallel=True, expert_parallel=True),
}


def logical_spec(rules: ShardingRules, *axes: Optional[str]) -> P:
    return rules.spec(*axes)


def logical_sharding(mesh: Mesh, rules: ShardingRules, *axes: Optional[str]) -> NamedSharding:
    return NamedSharding(mesh, rules.spec(*axes))


def constrain(x, rules: ShardingRules, *axes: Optional[str], mesh: Optional[Mesh] = None):
    """with_sharding_constraint by logical names (inside jit)."""
    spec = rules.spec(*axes)
    if mesh is not None:
        return jax.lax.with_sharding_constraint(x, NamedSharding(mesh, spec))
    return jax.lax.with_sharding_constraint(x, spec)


def tree_shardings(mesh: Mesh, rules: ShardingRules, spec_tree):
    """Map a pytree of logical-axis tuples to NamedShardings."""
    return jax.tree.map(
        lambda axes: NamedSharding(mesh, rules.spec(*axes)),
        spec_tree,
        is_leaf=lambda x: isinstance(x, tuple) and all(a is None or isinstance(a, str) for a in x),
    )
