"""Logical-axis sharding rules (GSPMD annotations).

Arrays are annotated with *logical* axis names; a ShardingRules table maps
them to mesh axes and GSPMD inserts all collectives. This replaces the
reference's entire DP engine zoo (torch DDP wrap train_loop_utils.py:75,
FSDP :92-101, DeepSpeed launcher) with one declarative table:

  DDP        -> batch: (dp, fsdp); params unsharded
  ZeRO/FSDP  -> same + embed/mlp sharded on fsdp
  Megatron   -> heads/mlp on tp, embed replicated
  sequence   -> seq activations on sp (ring attention handles the halo)
  MoE        -> experts on ep
"""

from __future__ import annotations

import contextvars
from typing import Dict, Optional, Tuple, Union

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

# True while TRACING the body of a fully-manual shard_map fallback region
# (shard_map_compat on jax 0.4.x) — constrain() must no-op there
_IN_MANUAL_REGION: "contextvars.ContextVar[bool]" = contextvars.ContextVar(
    "ray_tpu_in_manual_shard_map", default=False
)

# canonical logical axis names used by models/
LOGICAL_AXES = (
    "batch",      # tokens batch dim
    "seq",        # sequence dim of activations
    "kv_seq",     # sequence dim of K/V (ring attention shards this)
    "embed",      # model/hidden dim
    "heads",      # attention heads
    "kv_heads",   # key/value heads (GQA)
    "head_dim",   # per-head dim
    "mlp",        # FFN intermediate dim
    "vocab",      # vocabulary dim
    "layers",     # stacked-layer dim (scanned layers / pipeline stages)
    "expert",     # MoE experts
    "stage",      # pipeline stage dim
)

MeshAxes = Union[None, str, Tuple[str, ...]]


class ShardingRules:
    def __init__(self, rules: Dict[str, MeshAxes]):
        unknown = set(rules) - set(LOGICAL_AXES)
        if unknown:
            raise ValueError(f"Unknown logical axes: {sorted(unknown)}")
        self.rules = dict(rules)

    def mesh_axes(self, logical: Optional[str]) -> MeshAxes:
        if logical is None:
            return None
        return self.rules.get(logical)

    def spec(self, *logical_axes: Optional[str]) -> P:
        out, used = [], set()
        for ax in logical_axes:
            m = self.mesh_axes(ax)
            if isinstance(m, tuple):
                m = tuple(a for a in m if a not in used)
                used.update(m)
                out.append(m if m else None)
            else:
                if m in used:
                    m = None
                if m is not None:
                    used.add(m)
                out.append(m)
        return P(*out)

    def with_overrides(self, **overrides: MeshAxes) -> "ShardingRules":
        r = dict(self.rules)
        r.update(overrides)
        return ShardingRules(r)

    def without_axis(self, axis: str) -> "ShardingRules":
        """Drop one mesh axis from every mapping — e.g. the per-slice view
        of a dcn="dp" table, used inside a vmap(spmd_axis_name="dcn")
        region where the dcn dimension is already spoken for."""
        r: Dict[str, MeshAxes] = {}
        for k, v in self.rules.items():
            if isinstance(v, tuple):
                t = tuple(a for a in v if a != axis)
                r[k] = t if t else None
            else:
                r[k] = None if v == axis else v
        return ShardingRules(r)


# --- presets ---------------------------------------------------------------

def make_rules(
    *,
    fsdp_params: bool = True,
    tensor_parallel: bool = True,
    sequence_parallel: bool = False,
    expert_parallel: bool = False,
    dcn: Optional[str] = None,
) -> ShardingRules:
    """`dcn` places ONE parallelism across the slow slice boundary of a
    multi-slice mesh (parallel/multislice.py):

      dcn="dp"  batch -> ("dcn", "dp", "fsdp"): data-parallel outer loop,
                gradient all-reduce crosses DCN once per step.
      dcn="pp"  stage -> ("dcn", "pp"): pipeline stage-groups mapped one
                per slice, boundary ppermutes cross DCN.

    Bandwidth-hungry axes (tp/sp/ep) are never offered a dcn mapping."""
    if dcn not in (None, "dp", "pp"):
        raise ValueError(
            f"dcn must be None, 'dp' or 'pp' (got {dcn!r}); tp/sp/ep "
            "traffic is per-layer bandwidth and cannot cross the slice "
            "boundary"
        )
    rules: Dict[str, MeshAxes] = {
        "batch": ("dcn", "dp", "fsdp") if dcn == "dp" else ("dp", "fsdp"),
        "seq": "sp" if sequence_parallel else None,
        "kv_seq": "sp" if sequence_parallel else None,
        "embed": "fsdp" if fsdp_params else None,
        "heads": "tp" if tensor_parallel else None,
        "kv_heads": "tp" if tensor_parallel else None,
        "head_dim": None,
        "mlp": "tp" if tensor_parallel else None,
        "vocab": "tp" if tensor_parallel else None,
        "layers": None,
        "expert": "ep" if expert_parallel else None,
        "stage": ("dcn", "pp") if dcn == "pp" else "pp",
    }
    return ShardingRules(rules)


PRESET_RULES: Dict[str, ShardingRules] = {
    # pure data parallel: params replicated
    "dp": make_rules(fsdp_params=False, tensor_parallel=False),
    # ZeRO-3: params sharded on fsdp along embed
    "fsdp": make_rules(tensor_parallel=False),
    # Megatron TP + FSDP
    "fsdp_tp": make_rules(),
    # + ring-attention sequence parallel
    "fsdp_tp_sp": make_rules(sequence_parallel=True),
    # MoE
    "fsdp_tp_ep": make_rules(expert_parallel=True),
    "full": make_rules(sequence_parallel=True, expert_parallel=True),
}


def logical_spec(rules: ShardingRules, *axes: Optional[str]) -> P:
    return rules.spec(*axes)


def logical_sharding(mesh: Mesh, rules: ShardingRules, *axes: Optional[str]) -> NamedSharding:
    return NamedSharding(mesh, rules.spec(*axes))


def constrain(x, rules: ShardingRules, *axes: Optional[str], mesh: Optional[Mesh] = None):
    """with_sharding_constraint by logical names (inside jit). Inside a
    fully-manual shard_map_compat fallback region constraints are a no-op:
    every mesh axis is manual there, and 0.4.x rejects constraints naming
    manual axes (they were only GSPMD layout hints anyway)."""
    if _IN_MANUAL_REGION.get():
        return x
    spec = rules.spec(*axes)
    if mesh is not None:
        return jax.lax.with_sharding_constraint(x, NamedSharding(mesh, spec))
    return jax.lax.with_sharding_constraint(x, spec)


def shard_map_compat(f, mesh: Mesh, in_specs, out_specs, manual_axes):
    """shard_map across jax versions. The public `jax.shard_map`
    (check_vma/axis_names kwargs) landed after 0.4.x and supports
    partial-manual lowering (manual over `manual_axes`, GSPMD elsewhere).
    Older releases only have jax.experimental.shard_map.shard_map, whose
    partial-manual `auto=` mode is the unstable half (all_to_all under
    non-empty auto SIGABRTs 0.4.37) — so the fallback goes FULLY manual:
    axes the specs don't mention are replicated into the region, which
    preserves semantics at the cost of an all-gather when the caller had
    them sharded. Fine for CPU-mesh CI; real TPU installs carry a jax with
    the native path."""
    manual = frozenset(manual_axes)
    if hasattr(jax, "shard_map"):
        return jax.shard_map(
            f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
            check_vma=False, axis_names=manual,
        )
    from jax.experimental.shard_map import shard_map as _shard_map

    def traced(*args):
        token = _IN_MANUAL_REGION.set(True)
        try:
            return f(*args)
        finally:
            _IN_MANUAL_REGION.reset(token)

    return _shard_map(
        traced, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
        check_rep=False,
    )


def tree_shardings(mesh: Mesh, rules: ShardingRules, spec_tree):
    """Map a pytree of logical-axis tuples to NamedShardings."""
    return jax.tree.map(
        lambda axes: NamedSharding(mesh, rules.spec(*axes)),
        spec_tree,
        is_leaf=lambda x: isinstance(x, tuple) and all(a is None or isinstance(a, str) for a in x),
    )
