"""Device meshes for TPU slices.

The backbone of every parallelism in ray_tpu: a named `jax.sharding.Mesh`
with axes (dp, fsdp, sp, tp, pp, ep). The reference's analogue is NCCL
process-group bootstrap (train/torch/config.py:113 dist.init_process_group);
here the "process group" is the mesh and XLA inserts the collectives.

Axis conventions (scaling-book style):
  dp    pure data parallel (gradient all-reduce over ICI/DCN)
  fsdp  fully-sharded data parallel (ZeRO-3: params/opt-state sharded here)
  sp    sequence/context parallel (ring attention neighbors on ICI ring)
  tp    tensor/operator parallel (Megatron-style, innermost = fastest ICI)
  pp    pipeline stages (usually across DCN / multi-slice)
  ep    expert parallel (MoE all-to-all)
"""

from __future__ import annotations

import dataclasses
import math
from typing import Optional, Sequence, Tuple

import jax
import numpy as np
from jax.sharding import Mesh

AXIS_ORDER: Tuple[str, ...] = ("pp", "dp", "fsdp", "ep", "sp", "tp")


@dataclasses.dataclass(frozen=True)
class MeshSpec:
    """Degrees for each parallelism axis. -1 on one axis = use all remaining."""

    dp: int = 1
    fsdp: int = 1
    sp: int = 1
    tp: int = 1
    pp: int = 1
    ep: int = 1

    def degrees(self) -> dict:
        return {a: getattr(self, a) for a in AXIS_ORDER}

    def total(self) -> int:
        t = 1
        for v in self.degrees().values():
            t *= v
        return t

    def resolve(self, n_devices: int) -> "MeshSpec":
        d = self.degrees()
        wild = [a for a, v in d.items() if v == -1]
        if len(wild) > 1:
            raise ValueError("At most one mesh axis may be -1")
        if wild:
            known = 1
            for a, v in d.items():
                if v != -1:
                    known *= v
            if n_devices % known:
                fixed = {a: v for a, v in d.items() if v not in (-1, 1)}
                raise ValueError(
                    f"cannot infer mesh axis {wild[0]!r}: the fixed axes "
                    f"{fixed} use {known} devices, which does not divide "
                    f"the {n_devices} available"
                )
            d[wild[0]] = n_devices // known
        if math.prod(d.values()) != n_devices:
            # name the first axis that fails to divide what remains, so the
            # user sees WHICH degree is wrong instead of an opaque
            # reshape/product error downstream
            rem = n_devices
            for a, v in d.items():
                if v > 1 and (rem % v or v > rem):
                    raise ValueError(
                        f"mesh axis {a!r}={v} does not divide the remaining "
                        f"{rem} of {n_devices} devices (requested degrees "
                        f"{ {k: x for k, x in d.items() if x > 1} })"
                    )
                rem //= max(v, 1)
            raise ValueError(
                f"Mesh degrees {d} use {math.prod(d.values())} devices, have {n_devices}"
            )
        return MeshSpec(**{k: d[k] for k in ("dp", "fsdp", "sp", "tp", "pp", "ep")})


def local_device_count() -> int:
    return len(jax.devices())


def build_mesh(
    spec: MeshSpec | None = None,
    devices: Optional[Sequence] = None,
    axis_order: Tuple[str, ...] = AXIS_ORDER,
) -> Mesh:
    """Build a Mesh laying the innermost axes (tp, sp) on the fastest
    interconnect: jax's device order within a host follows the ICI torus, so
    contiguous device blocks get the last mesh dims (mesh_utils does the
    topology-aware assignment on real slices)."""
    devices = list(devices if devices is not None else jax.devices())
    spec = (spec or MeshSpec()).resolve(len(devices))
    shape = tuple(spec.degrees()[a] for a in axis_order)
    try:
        from jax.experimental import mesh_utils

        dev_array = mesh_utils.create_device_mesh(shape, devices=devices)
    except Exception:
        dev_array = np.array(devices).reshape(shape)
    return Mesh(dev_array, axis_order)


def data_axes() -> Tuple[str, ...]:
    """Mesh axes a global batch is sharded over."""
    return ("dp", "fsdp")


def host_local_mesh(spec: MeshSpec | None = None) -> Mesh:
    return build_mesh(spec, devices=jax.local_devices())
