"""Parallelism library: meshes, sharding rules, collectives, SP/PP/EP.

This is the subsystem the reference does NOT have natively (SURVEY §2.4):
where Ray delegates DP to torch-DDP/NCCL and leaves TP/PP/SP to external
libraries (Alpa), ray_tpu makes every parallelism a first-class mesh axis
lowered by GSPMD/XLA onto ICI/DCN.
"""

from .mesh import MeshSpec, build_mesh, local_device_count  # noqa: F401
from .sharding import (  # noqa: F401
    LOGICAL_AXES,
    ShardingRules,
    PRESET_RULES,
    logical_spec,
    logical_sharding,
    constrain,
    make_rules,
)
from .multislice import (  # noqa: F401
    DCN_AXIS,
    ICI_ONLY_AXES,
    MULTISLICE_PRESETS,
    SliceTopology,
    build_multislice_mesh,
    dp_outer,
    group_devices_by_slice,
    multislice_rules,
    pp_outer,
)
