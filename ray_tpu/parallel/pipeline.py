"""Pipeline parallelism: GPipe-style microbatch pipeline over the `pp` axis.

Net-new vs the reference (SURVEY §2.4: PP "Not in-repo; Alpa release tests
only"). Stages live on the `pp` mesh axis (typically across DCN / multi-
slice); activations hop stage-to-stage with `ppermute`; a scan over
n_microbatches + pp - 1 ticks keeps every stage busy after warmup. The
backward pipeline falls out of autodiff (ppermute transposes to the reverse
permutation), so one combinator serves training and inference.

Runs inside shard_map manual over `pp` only — dp/fsdp/tp/sp stay auto, so
GSPMD still shards each stage's internals from the sharding table.

Multi-slice placement (parallel/multislice.py pp-outer): `axis_name` may be
a PAIR ("dcn", "pp") — slice-major stage→slice placement where global stage
s = slice_index * stages_per_slice + local_stage. The stage-to-stage hop is
then two-tier: intra-slice hops ride a `pp` ppermute (ICI) and the slice-
boundary hop rides ONE `dcn` ppermute (DCN) plus an intra-slice wrap to the
next slice's first stage — with stages_per_slice=1 (the preset default) DCN
therefore carries exactly the boundary activation per tick and nothing
else. Caveat for stages_per_slice>1: the SPMD program is uniform, so the
`dcn` ppermute runs at EVERY inner-stage coordinate and ships
stages_per_slice copies of the microbatch activation across DCN per tick
(only the last inner stage's copy is consumed; the byte counters report
the real, inflated figure). Keep stages_per_slice=1 when DCN bandwidth is
the constraint.
"""

from __future__ import annotations

from functools import partial
from typing import Any, Callable, Tuple, Union

import jax
import jax.numpy as jnp
from jax import lax


def _pipeline_local(
    stage_fn, stage_params, x_mb, *, axis_names: Tuple[str, ...], n_microbatches: int
):
    """Runs on one stage (inside shard_map). x_mb: [n_mb, mb, ...] full input
    (only stage 0 reads it); returns [n_mb, mb, ...] outputs (valid on the
    last stage, zeros elsewhere — caller psums over the stage axes to
    broadcast). axis_names is ("pp",) or ("dcn", "pp") — outer axis first."""
    inner = axis_names[-1]
    outer = axis_names[0] if len(axis_names) == 2 else None
    pp_in = lax.psum(1, inner)
    n_outer = lax.psum(1, outer) if outer is not None else 1
    pp = n_outer * pp_in
    stage = lax.axis_index(inner)
    if outer is not None:
        stage = lax.axis_index(outer) * pp_in + stage
    n_mb = n_microbatches
    total_ticks = n_mb + pp - 1
    mb_shape = x_mb.shape[1:]

    # each device holds pp_stages/pp consecutive stages (leading local dim);
    # apply them in order — with pp=1 this degenerates to the sequential
    # stack with identical microbatch windows, so a single-device run is a
    # bit-for-bit oracle for the sharded pipeline
    def _fwd(x):
        def body(xc, p_one):
            return stage_fn(p_one, xc), None

        y, _ = lax.scan(body, x, stage_params)
        return y

    fwd = jax.checkpoint(_fwd)

    intra_perm = [(i, i + 1) for i in range(pp_in - 1)]
    cross_perm = [(s, s + 1) for s in range(n_outer - 1)]
    wrap_perm = [(pp_in - 1, 0)]

    def hop(y):
        """Pass activations one stage downstream. Single-axis: one ppermute.
        Two-tier: intra-slice neighbors over `inner` (ICI); the slice
        boundary crosses `outer` (DCN) once, then wraps to the next slice's
        stage 0 over `inner` (ICI again). Devices without an upstream
        receive zeros (masked by the stage-0 ingest select)."""
        if outer is None:
            return lax.ppermute(y, inner, intra_perm)
        cross = lax.ppermute(y, outer, cross_perm)
        if pp_in == 1:
            return cross
        intra = lax.ppermute(y, inner, intra_perm)
        cross = lax.ppermute(cross, inner, wrap_perm)
        return jnp.where(lax.axis_index(inner) == 0, cross, intra)

    def tick(carry, t):
        recv, out_buf = carry
        # stage 0 ingests microbatch t (clamped; inactive ticks are masked)
        mb_idx = jnp.clip(t, 0, n_mb - 1)
        x0 = lax.dynamic_index_in_dim(x_mb, mb_idx, axis=0, keepdims=False)
        x_in = jnp.where(stage == 0, x0, recv)
        y = fwd(x_in)
        # pass activations downstream for the next tick
        new_recv = hop(y)
        # last stage stores its (active) output at t - (pp - 1)
        is_active_last = jnp.logical_and(stage == pp - 1, t >= pp - 1)
        store_idx = jnp.clip(t - (pp - 1), 0, n_mb - 1)
        cur = lax.dynamic_index_in_dim(out_buf, store_idx, axis=0, keepdims=False)
        upd = jnp.where(is_active_last, y, cur)
        out_buf = lax.dynamic_update_index_in_dim(out_buf, upd, store_idx, axis=0)
        return (new_recv, out_buf), None

    recv0 = jnp.zeros(mb_shape, x_mb.dtype)
    out0 = jnp.zeros((n_mb,) + mb_shape, x_mb.dtype)
    (recv, out_buf), _ = lax.scan(tick, (recv0, out0), jnp.arange(total_ticks))
    # only the last stage holds real outputs; zero elsewhere then psum to
    # broadcast. psum in f32: bf16 all-reduce hits an XLA CHECK on the CPU
    # backend (hlo_instruction.cc "Invalid binary instruction opcode copy").
    out_buf = jnp.where(stage == pp - 1, out_buf, jnp.zeros_like(out_buf))
    bcast_axes = axis_names if len(axis_names) > 1 else axis_names[0]
    return lax.psum(out_buf.astype(jnp.float32), bcast_axes).astype(out_buf.dtype)


def pipeline_apply(
    stage_fn: Callable[[Any, jnp.ndarray], jnp.ndarray],
    stage_params: Any,
    x: jnp.ndarray,
    *,
    mesh,
    n_microbatches: int,
    axis_name: Union[str, Tuple[str, ...]] = "pp",
    batch_axes: Union[None, str, Tuple[str, ...]] = ("dp", "fsdp"),
):
    """Apply a pipelined stage stack to x: [B, ...].

    stage_params: pytree whose leaves have leading dim = total stages
    (sharded on the stage axes). stage_fn(params_one_stage, x_mb) -> y_mb
    with matching shapes.

    axis_name: mesh axis the stages live on, or a ("dcn", "pp") pair for
    multi-slice stage→slice placement — stages are laid out slice-major
    (dcn-major), so stage s lives on slice s // stages_per_slice.

    batch_axes: mesh axes the batch dim is sharded over (the rule table's
    "batch" mapping). Only used by the jax-0.4.x fully-manual fallback,
    which would otherwise all-gather the batch to full replication at the
    region boundary — a gather GSPMD is then free to route over the slow
    `dcn` axis. Keeping the batch sharded through the region keeps every
    non-pipeline byte on ICI (the multislice byte-counter tests assert
    exactly this).
    """
    from jax.sharding import PartitionSpec as P

    axes = (axis_name,) if isinstance(axis_name, str) else tuple(axis_name)
    if not 1 <= len(axes) <= 2:
        raise ValueError(
            f"axis_name must be one mesh axis or an (outer, inner) pair, "
            f"got {axis_name!r}"
        )
    n_stage_devices = 1
    for a in axes:
        if a not in mesh.shape:
            raise ValueError(f"pipeline axis {a!r} not in mesh axes {tuple(mesh.shape)}")
        n_stage_devices *= mesh.shape[a]
    lead = jax.tree.leaves(stage_params)[0].shape[0]
    if lead % n_stage_devices:
        raise ValueError(
            f"stage_params leading dim {lead} does not divide over the "
            f"{n_stage_devices} stage devices of mesh axes {axes} "
            f"({ {a: mesh.shape[a] for a in axes} })"
        )

    b = x.shape[0]
    if b % n_microbatches:
        raise ValueError(f"batch {b} not divisible by n_microbatches {n_microbatches}")
    mb = b // n_microbatches
    x_mb = x.reshape((n_microbatches, mb) + x.shape[1:])

    x_spec = P()
    if not hasattr(jax, "shard_map"):
        if isinstance(batch_axes, str):
            batch_axes = (batch_axes,)
        bax = tuple(
            a for a in (batch_axes or ()) if a in mesh.shape and a not in axes
        )
        n_bax = 1
        for a in bax:
            n_bax *= mesh.shape[a]
        if n_bax > 1 and mb % n_bax == 0:
            x_spec = P(None, bax)

    stage_spec = P(axes if len(axes) > 1 else axes[0])
    pspec = jax.tree.map(lambda _: stage_spec, stage_params)
    fn = partial(
        _pipeline_local, stage_fn, axis_names=axes, n_microbatches=n_microbatches
    )
    from .sharding import shard_map_compat

    out_mb = shard_map_compat(
        fn, mesh, (pspec, x_spec), x_spec, set(axes)
    )(stage_params, x_mb)
    return out_mb.reshape((b,) + out_mb.shape[2:])
