"""Pipeline parallelism: microbatch pipeline over the `pp` axis.

Net-new vs the reference (SURVEY §2.4: PP "Not in-repo; Alpa release tests
only"). Stages live on the `pp` mesh axis (typically across DCN / multi-
slice); activations hop stage-to-stage with `ppermute`; a scan over
v*n_microbatches + pp - 1 ticks keeps every stage busy after warmup. The
backward pipeline falls out of autodiff (ppermute transposes to the reverse
permutation), so one combinator serves training and inference.

Two schedules share one tick loop:

  GPipe (virtual_stages_per_device=1): each device owns one CONTIGUOUS
  block of stages; bubble fraction (pp-1)/(n_mb + pp - 1).

  Interleaved (virtual_stages_per_device=v>1): each device owns v
  NON-contiguous stage chunks placed round-robin — logical stage chunk q
  lives on device q % pp — so a tick is 1/v of a device's layers and the
  warmup bubble shrinks to (pp-1)/(v*n_mb + pp - 1). Microbatches run in
  groups of pp (n_mb % pp == 0 required); device d executes chunk
  (u//pp) % v on microbatch (u//(pp*v))*pp + u%pp at tick t = u + d, a
  decomposition that is conflict-free (one chunk per device per tick) and
  keeps every activation hop on the same nearest-neighbour ring as GPipe.
  Interleaving multiplies ICI hops (v*n_mb ticks instead of n_mb), but the
  per-tick DCN cost is unchanged: still exactly ONE `dcn` ppermute — the
  byte-counter tests assert this.

Runs inside shard_map manual over `pp` only — dp/fsdp/tp/sp stay auto, so
GSPMD still shards each stage's internals from the sharding table.

Multi-slice placement (parallel/multislice.py pp-outer): `axis_name` may be
a PAIR ("dcn", "pp") — slice-major stage→slice placement where global stage
s = slice_index * stages_per_slice + local_stage. The stage-to-stage hop is
then two-tier: intra-slice hops ride a `pp` ppermute (ICI) and the slice-
boundary hop rides ONE `dcn` ppermute (DCN). For stages_per_slice>1 the
boundary activation is first reduce-scattered over the intra-slice `pp`
axis (ICI), so each device ships only its 1/stages_per_slice shard across
DCN and the receiving slice all-gathers it back (ICI) — DCN carries exactly
one copy of the microbatch activation per tick regardless of
stages_per_slice. (When the microbatch dim does not divide by
stages_per_slice the hop falls back to a masked full-payload ppermute,
which is correct but ships stages_per_slice zero-padded copies — keep the
microbatch divisible to hold the one-copy invariant.)
"""

from __future__ import annotations

from functools import partial
from typing import Any, Callable, Tuple, Union

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax


def bubble_fraction(n_microbatches: int, pp: int, virtual_stages_per_device: int = 1) -> float:
    """Idle fraction of device-tick slots for the schedule this module
    executes: (pp-1)/(v*n_mb + pp - 1). v=1 is the GPipe figure. Derived
    from the same tick count the scan below runs, so bench rows report the
    schedule actually executed."""
    v = virtual_stages_per_device
    return (pp - 1) / (v * n_microbatches + pp - 1)


def interleaved_stage_order(
    n_stage_rows: int, n_stage_devices: int, virtual_stages_per_device: int
) -> np.ndarray:
    """Permutation taking stage rows from MODEL order (row r applied r-th)
    to SCHEDULE order (consecutive-block sharding over the stage devices
    gives device d chunks d, pp+d, ..., (v-1)*pp+d in local rows
    [j*C,(j+1)*C)). Identity when v == 1 or pp == 1."""
    pp, v = n_stage_devices, virtual_stages_per_device
    if n_stage_rows % (pp * v):
        raise ValueError(
            f"{n_stage_rows} stage rows do not divide over {pp} devices x "
            f"{v} virtual stages"
        )
    C = n_stage_rows // (pp * v)
    return np.concatenate(
        [
            np.arange((j * pp + d) * C, (j * pp + d + 1) * C)
            for d in range(pp)
            for j in range(v)
        ]
    )


def _pipeline_local(
    stage_fn,
    stage_params,
    x_mb,
    *,
    axis_names: Tuple[str, ...],
    n_microbatches: int,
    virtual_stages_per_device: int = 1,
):
    """Runs on one stage (inside shard_map). x_mb: [n_mb, mb, ...] full input
    (only stage 0 reads it); returns [n_mb, mb, ...] outputs (valid on the
    last stage, zeros elsewhere — caller psums over the stage axes to
    broadcast). axis_names is ("pp",) or ("dcn", "pp") — outer axis first.
    stage_params rows are in SCHEDULE order (see interleaved_stage_order)."""
    inner = axis_names[-1]
    outer = axis_names[0] if len(axis_names) == 2 else None
    pp_in = lax.psum(1, inner)
    n_outer = lax.psum(1, outer) if outer is not None else 1
    pp = n_outer * pp_in
    v = virtual_stages_per_device
    dev = lax.axis_index(inner)
    if outer is not None:
        dev = lax.axis_index(outer) * pp_in + dev
    n_mb = n_microbatches
    total_ticks = v * n_mb + pp - 1
    mb_shape = x_mb.shape[1:]
    local_rows = jax.tree.leaves(stage_params)[0].shape[0]
    rows_per_chunk = local_rows // v

    # a chunk is rows_per_chunk consecutive local rows applied in order —
    # with pp=1 (and the identity schedule order) this degenerates to the
    # sequential stack with identical microbatch windows, so a single-
    # device run is a bit-for-bit oracle for the sharded pipeline
    def _fwd(x, chunk):
        def body(xc, p_one):
            return stage_fn(p_one, xc), None

        y, _ = lax.scan(body, x, chunk)
        return y

    fwd = jax.checkpoint(_fwd)

    intra_perm = [(i, i + 1) for i in range(pp_in - 1)]
    if v > 1:
        cross_perm = [(s, (s + 1) % n_outer) for s in range(n_outer)]
        ring_perm = [(i, (i + 1) % pp) for i in range(pp)]
    else:
        cross_perm = [(s, s + 1) for s in range(n_outer - 1)]
        ring_perm = intra_perm  # single-axis GPipe: no wrap needed
    wrap_perm = [(pp_in - 1, 0)]

    def hop(y):
        """Pass activations one stage downstream along the global device
        ring. Single-axis: one ppermute. Two-tier: intra-slice neighbors
        over `inner` (ICI); the slice boundary crosses `outer` (DCN) once —
        reduce-scattered over `inner` first so DCN carries ONE copy of the
        activation, re-gathered on the receiving slice (both ICI legs).
        Devices without an upstream receive zeros (masked by the chunk-0
        ingest select)."""
        if pp == 1:
            return y  # chunk-to-chunk handoff on a single device
        if outer is None:
            return lax.ppermute(y, inner, ring_perm)
        if n_outer == 1:
            # degenerate two-tier (one slice): the ring wrap is intra-slice
            cross = (
                lax.ppermute(y, inner, wrap_perm) if v > 1 else jnp.zeros_like(y)
            )
        elif pp_in == 1:
            cross = lax.ppermute(y, outer, cross_perm)
        elif y.shape[0] % pp_in == 0:
            # one-copy DCN hop: scatter the boundary stage's activation
            # across the slice (ICI), ship 1/pp_in per device (DCN),
            # gather on the other side (ICI). psum_scatter in f32: narrow-
            # dtype all-reduce hits an XLA CHECK on the CPU backend.
            boundary = lax.axis_index(inner) == pp_in - 1
            z = jnp.where(boundary, y, jnp.zeros_like(y)).astype(jnp.float32)
            z = lax.psum_scatter(z, inner, scatter_dimension=0, tiled=True)
            z = lax.ppermute(z.astype(y.dtype), outer, cross_perm)
            cross = lax.all_gather(z, inner, axis=0, tiled=True)
        else:
            # fallback (mb not divisible by stages_per_slice): masked full-
            # payload ppermute — non-boundary coordinates ship zeros
            boundary = lax.axis_index(inner) == pp_in - 1
            z = jnp.where(boundary, y, jnp.zeros_like(y))
            cross = lax.ppermute(z, outer, cross_perm)
            cross = lax.ppermute(cross, inner, wrap_perm)
        if pp_in == 1:
            return cross
        intra = lax.ppermute(y, inner, intra_perm)
        return jnp.where(lax.axis_index(inner) == 0, cross, intra)

    def tick(carry, t):
        recv, out_buf = carry
        # schedule decomposition: device d is active at tick t on chunk j,
        # microbatch m (see module docstring); inactive ticks are masked
        u = t - dev
        valid = jnp.logical_and(u >= 0, u < v * n_mb)
        uc = jnp.clip(u, 0, v * n_mb - 1)
        j = (uc // pp) % v
        m = (uc // (pp * v)) * pp + uc % pp
        # first logical stage ingests microbatch m (clamped when masked)
        x0 = lax.dynamic_index_in_dim(x_mb, m, axis=0, keepdims=False)
        is_ingest = jnp.logical_and(dev == 0, j == 0)
        x_in = jnp.where(is_ingest, x0, recv)
        if v == 1:
            chunk = stage_params
        else:
            chunk = jax.tree.map(
                lambda p: lax.dynamic_slice_in_dim(
                    p, j * rows_per_chunk, rows_per_chunk, axis=0
                ),
                stage_params,
            )
        y = fwd(x_in, chunk)
        # pass activations downstream for the next tick
        new_recv = hop(y)
        # final logical stage stores its (active) output for microbatch m
        is_active_last = jnp.logical_and(
            valid, jnp.logical_and(dev == pp - 1, j == v - 1)
        )
        cur = lax.dynamic_index_in_dim(out_buf, m, axis=0, keepdims=False)
        upd = jnp.where(is_active_last, y, cur)
        out_buf = lax.dynamic_update_index_in_dim(out_buf, upd, m, axis=0)
        return (new_recv, out_buf), None

    recv0 = jnp.zeros(mb_shape, x_mb.dtype)
    out0 = jnp.zeros((n_mb,) + mb_shape, x_mb.dtype)
    (recv, out_buf), _ = lax.scan(tick, (recv0, out0), jnp.arange(total_ticks))
    # only the last stage device holds real outputs; zero elsewhere then
    # psum to broadcast. psum in f32: bf16 all-reduce hits an XLA CHECK on
    # the CPU backend (hlo_instruction.cc "Invalid binary instruction
    # opcode copy").
    out_buf = jnp.where(dev == pp - 1, out_buf, jnp.zeros_like(out_buf))
    bcast_axes = axis_names if len(axis_names) > 1 else axis_names[0]
    return lax.psum(out_buf.astype(jnp.float32), bcast_axes).astype(out_buf.dtype)


def pipeline_apply(
    stage_fn: Callable[[Any, jnp.ndarray], jnp.ndarray],
    stage_params: Any,
    x: jnp.ndarray,
    *,
    mesh,
    n_microbatches: int,
    axis_name: Union[str, Tuple[str, ...]] = "pp",
    batch_axes: Union[None, str, Tuple[str, ...]] = ("dp", "fsdp"),
    virtual_stages_per_device: int = 1,
    stage_order: str = "model",
):
    """Apply a pipelined stage stack to x: [B, ...].

    stage_params: pytree whose leaves have leading dim = total stages
    (sharded on the stage axes). stage_fn(params_one_stage, x_mb) -> y_mb
    with matching shapes.

    axis_name: mesh axis the stages live on, or a ("dcn", "pp") pair for
    multi-slice stage→slice placement — stages are laid out slice-major
    (dcn-major), so stage s lives on slice s // stages_per_slice.

    batch_axes: mesh axes the batch dim is sharded over (the rule table's
    "batch" mapping). Only used by the jax-0.4.x fully-manual fallback,
    which would otherwise all-gather the batch to full replication at the
    region boundary — a gather GSPMD is then free to route over the slow
    `dcn` axis. Keeping the batch sharded through the region keeps every
    non-pipeline byte on ICI (the multislice byte-counter tests assert
    exactly this).

    virtual_stages_per_device: v>1 switches to the interleaved schedule —
    each device runs v round-robin stage chunks (stage chunk q on device
    q % pp), cutting the warmup bubble to (pp-1)/(v*n_mb + pp - 1).
    Requires n_microbatches % pp == 0 and stage rows divisible by v*pp.

    stage_order: "model" (default) — stage_params rows are in sequential
    model order and this function permutes them into schedule order (a
    one-time gather over the stage axes per compiled call). "schedule" —
    the caller already permuted rows with interleaved_stage_order(); no
    gather is emitted, which keeps the compiled HLO free of any setup
    collective (the per-tick byte measurements use this).
    """
    from jax.sharding import PartitionSpec as P

    axes = (axis_name,) if isinstance(axis_name, str) else tuple(axis_name)
    if not 1 <= len(axes) <= 2:
        raise ValueError(
            f"axis_name must be one mesh axis or an (outer, inner) pair, "
            f"got {axis_name!r}"
        )
    v = int(virtual_stages_per_device)
    if v < 1:
        raise ValueError(f"virtual_stages_per_device must be >= 1, got {v}")
    if stage_order not in ("model", "schedule"):
        raise ValueError(f"stage_order must be 'model' or 'schedule', got {stage_order!r}")
    n_stage_devices = 1
    for a in axes:
        if a not in mesh.shape:
            raise ValueError(f"pipeline axis {a!r} not in mesh axes {tuple(mesh.shape)}")
        n_stage_devices *= mesh.shape[a]
    lead = jax.tree.leaves(stage_params)[0].shape[0]
    if lead % (n_stage_devices * v):
        raise ValueError(
            f"stage_params leading dim {lead} does not divide over the "
            f"{n_stage_devices} stage devices x {v} virtual stages of mesh "
            f"axes {axes} ({ {a: mesh.shape[a] for a in axes} })"
        )
    if v > 1 and n_microbatches % n_stage_devices:
        raise ValueError(
            f"interleaved schedule needs n_microbatches ({n_microbatches}) "
            f"divisible by the {n_stage_devices} stage devices"
        )
    if v > 1 and stage_order == "model":
        order = interleaved_stage_order(lead, n_stage_devices, v)
        stage_params = jax.tree.map(lambda p: jnp.take(p, order, axis=0), stage_params)

    b = x.shape[0]
    if b % n_microbatches:
        raise ValueError(f"batch {b} not divisible by n_microbatches {n_microbatches}")
    mb = b // n_microbatches
    x_mb = x.reshape((n_microbatches, mb) + x.shape[1:])

    x_spec = P()
    if not hasattr(jax, "shard_map"):
        if isinstance(batch_axes, str):
            batch_axes = (batch_axes,)
        bax = tuple(
            a for a in (batch_axes or ()) if a in mesh.shape and a not in axes
        )
        n_bax = 1
        for a in bax:
            n_bax *= mesh.shape[a]
        if n_bax > 1 and mb % n_bax == 0:
            x_spec = P(None, bax)

    stage_spec = P(axes if len(axes) > 1 else axes[0])
    pspec = jax.tree.map(lambda _: stage_spec, stage_params)
    fn = partial(
        _pipeline_local,
        stage_fn,
        axis_names=axes,
        n_microbatches=n_microbatches,
        virtual_stages_per_device=v,
    )
    from .sharding import shard_map_compat

    out_mb = shard_map_compat(
        fn, mesh, (pspec, x_spec), x_spec, set(axes)
    )(stage_params, x_mb)
    return out_mb.reshape((b,) + out_mb.shape[2:])
