"""Pipeline parallelism: GPipe-style microbatch pipeline over the `pp` axis.

Net-new vs the reference (SURVEY §2.4: PP "Not in-repo; Alpa release tests
only"). Stages live on the `pp` mesh axis (typically across DCN / multi-
slice); activations hop stage-to-stage with `ppermute`; a scan over
n_microbatches + pp - 1 ticks keeps every stage busy after warmup. The
backward pipeline falls out of autodiff (ppermute transposes to the reverse
permutation), so one combinator serves training and inference.

Runs inside shard_map manual over `pp` only — dp/fsdp/tp/sp stay auto, so
GSPMD still shards each stage's internals from the sharding table.
"""

from __future__ import annotations

from functools import partial
from typing import Any, Callable

import jax
import jax.numpy as jnp
from jax import lax


def _pipeline_local(stage_fn, stage_params, x_mb, *, axis_name: str, n_microbatches: int):
    """Runs on one stage (inside shard_map). x_mb: [n_mb, mb, ...] full input
    (only stage 0 reads it); returns [n_mb, mb, ...] outputs (valid on the
    last stage, zeros elsewhere — caller psums over pp to broadcast)."""
    pp = lax.psum(1, axis_name)
    stage = lax.axis_index(axis_name)
    n_mb = n_microbatches
    total_ticks = n_mb + pp - 1
    mb_shape = x_mb.shape[1:]

    # each device holds pp_stages/pp consecutive stages (leading local dim);
    # apply them in order — with pp=1 this degenerates to the sequential
    # stack with identical microbatch windows, so a single-device run is a
    # bit-for-bit oracle for the sharded pipeline
    def _fwd(x):
        def body(xc, p_one):
            return stage_fn(p_one, xc), None

        y, _ = lax.scan(body, x, stage_params)
        return y

    fwd = jax.checkpoint(_fwd)

    send_perm = [(i, i + 1) for i in range(pp - 1)]

    def tick(carry, t):
        recv, out_buf = carry
        # stage 0 ingests microbatch t (clamped; inactive ticks are masked)
        mb_idx = jnp.clip(t, 0, n_mb - 1)
        x0 = lax.dynamic_index_in_dim(x_mb, mb_idx, axis=0, keepdims=False)
        x_in = jnp.where(stage == 0, x0, recv)
        y = fwd(x_in)
        # pass activations downstream for the next tick
        new_recv = lax.ppermute(y, axis_name, send_perm)
        # last stage stores its (active) output at t - (pp - 1)
        is_active_last = jnp.logical_and(stage == pp - 1, t >= pp - 1)
        store_idx = jnp.clip(t - (pp - 1), 0, n_mb - 1)
        cur = lax.dynamic_index_in_dim(out_buf, store_idx, axis=0, keepdims=False)
        upd = jnp.where(is_active_last, y, cur)
        out_buf = lax.dynamic_update_index_in_dim(out_buf, upd, store_idx, axis=0)
        return (new_recv, out_buf), None

    recv0 = jnp.zeros(mb_shape, x_mb.dtype)
    out0 = jnp.zeros((n_mb,) + mb_shape, x_mb.dtype)
    (recv, out_buf), _ = lax.scan(tick, (recv0, out0), jnp.arange(total_ticks))
    # only the last stage holds real outputs; zero elsewhere then psum to
    # broadcast. psum in f32: bf16 all-reduce hits an XLA CHECK on the CPU
    # backend (hlo_instruction.cc "Invalid binary instruction opcode copy").
    out_buf = jnp.where(stage == pp - 1, out_buf, jnp.zeros_like(out_buf))
    return lax.psum(out_buf.astype(jnp.float32), axis_name).astype(out_buf.dtype)


def pipeline_apply(
    stage_fn: Callable[[Any, jnp.ndarray], jnp.ndarray],
    stage_params: Any,
    x: jnp.ndarray,
    *,
    mesh,
    n_microbatches: int,
    axis_name: str = "pp",
):
    """Apply a pp-stage pipeline to x: [B, ...].

    stage_params: pytree whose leaves have leading dim pp (sharded on `pp`).
    stage_fn(params_one_stage, x_mb) -> y_mb with matching shapes.
    """
    from jax.sharding import PartitionSpec as P

    b = x.shape[0]
    if b % n_microbatches:
        raise ValueError(f"batch {b} not divisible by n_microbatches {n_microbatches}")
    x_mb = x.reshape((n_microbatches, b // n_microbatches) + x.shape[1:])

    pspec = jax.tree.map(lambda _: P(axis_name), stage_params)
    fn = partial(
        _pipeline_local, stage_fn, axis_name=axis_name, n_microbatches=n_microbatches
    )
    from .sharding import shard_map_compat

    out_mb = shard_map_compat(
        fn, mesh, (pspec, P()), P(), {axis_name}
    )(stage_params, x_mb)
    return out_mb.reshape((b,) + out_mb.shape[2:])
