"""FunctionNode: a task invocation in a DAG (reference:
python/ray/dag/function_node.py).

Execution submits the task with child results as args — child ObjectRefs
are passed straight through so the scheduler chains dependencies without
materializing intermediates on the driver.
"""

from __future__ import annotations

from typing import Any, Dict

from .dag_node import DAGNode


class FunctionNode(DAGNode):
    def __init__(self, remote_function, args, kwargs):
        super().__init__(args=args, kwargs=kwargs)
        self._remote_function = remote_function

    def _execute_node(self, memo: Dict[int, Any]):
        args, kwargs = self._resolve_args(memo)
        return self._remote_function.remote(*args, **kwargs)

    def options(self, **opts) -> "FunctionNode":
        return FunctionNode(
            self._remote_function.options(**opts), self._bound_args, self._bound_kwargs
        )


def bind_function(remote_function, *args, **kwargs) -> FunctionNode:
    return FunctionNode(remote_function, args, kwargs)
