"""ClassNode / ClassMethodNode: actors in a DAG (reference:
python/ray/dag/class_node.py).

A ClassNode instantiates its actor once (first execution) and reuses it on
subsequent .execute() calls — the Serve-graph semantics, where the DAG
describes a long-lived composition of stateful deployments.
"""

from __future__ import annotations

from typing import Any, Dict

from .dag_node import DAGNode


class ClassNode(DAGNode):
    def __init__(self, actor_class, args, kwargs):
        super().__init__(args=args, kwargs=kwargs)
        self._actor_class = actor_class
        self._actor_handle = None

    def _execute_node(self, memo: Dict[int, Any]):
        if self._actor_handle is None:
            args, kwargs = self._resolve_args(memo)
            self._actor_handle = self._actor_class.remote(*args, **kwargs)
        return self._actor_handle

    def __getattr__(self, name: str):
        if name.startswith("_"):
            raise AttributeError(name)
        return _UnboundClassMethod(self, name)

    def options(self, **opts) -> "ClassNode":
        return ClassNode(
            self._actor_class.options(**opts), self._bound_args, self._bound_kwargs
        )


class _UnboundClassMethod:
    def __init__(self, class_node: ClassNode, method_name: str):
        self._class_node = class_node
        self._method_name = method_name

    def bind(self, *args, **kwargs) -> "ClassMethodNode":
        return ClassMethodNode(self._class_node, self._method_name, args, kwargs)


class ClassMethodNode(DAGNode):
    def __init__(self, receiver, method_name: str, args, kwargs):
        # receiver: ClassNode (actor created at execute time) or a live
        # ActorHandle (bind on an existing actor, actor.py ActorMethod.bind)
        recv_args = (receiver,) if isinstance(receiver, DAGNode) else ()
        super().__init__(args=recv_args + tuple(args), kwargs=kwargs)
        self._receiver = receiver
        self._method_name = method_name
        self._n_recv = len(recv_args)

    def _execute_node(self, memo: Dict[int, Any]):
        args, kwargs = self._resolve_args(memo)
        if self._n_recv:
            handle, *args = args
        else:
            handle = self._receiver
        return getattr(handle, self._method_name).remote(*args, **kwargs)


def bind_class(actor_class, *args, **kwargs) -> ClassNode:
    return ClassNode(actor_class, args, kwargs)


def bind_method(actor_handle, method_name: str, *args, **kwargs) -> ClassMethodNode:
    return ClassMethodNode(actor_handle, method_name, args, kwargs)
