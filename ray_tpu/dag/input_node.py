"""InputNode: the DAG's runtime argument (reference: python/ray/dag/input_node.py).

    with InputNode() as inp:
        out = f.bind(inp)            # whole input
        out2 = g.bind(inp.field)     # attribute access
        out3 = h.bind(inp[0])        # index access
"""

from __future__ import annotations

from typing import Any, Dict

from .dag_node import DAGNode


class InputNode(DAGNode):
    def __init__(self):
        super().__init__()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False

    def _execute_node(self, memo: Dict[int, Any]):
        args, kwargs = memo["__input__"]
        if kwargs or len(args) > 1:
            return _MultiInput(args, kwargs)
        if len(args) == 1:
            return args[0]
        return None

    def __getattr__(self, key: str):
        if key.startswith("_"):
            raise AttributeError(key)
        return InputAttributeNode(self, key, "getattr")

    def __getitem__(self, key):
        return InputAttributeNode(self, key, "getitem")


class _MultiInput:
    """Wrapper when execute() got several args/kwargs: positional access via
    inp[i], keyword via inp.name (reference: DAGInputData)."""

    def __init__(self, args, kwargs):
        self.args = args
        self.kwargs = kwargs


class InputAttributeNode(DAGNode):
    """inp[i] / inp.key — resolved against the RAW execute() arguments:
    integer getitem prefers indexing a single input object, falling back to
    positional args; names read kwargs, falling back to attributes/keys of a
    single input object."""

    def __init__(self, parent: InputNode, key, accessor: str):
        super().__init__(args=(parent,))
        self._key = key
        self._accessor = accessor

    def _execute_node(self, memo: Dict[int, Any]):
        args, kwargs = memo["__input__"]
        single = args[0] if len(args) == 1 and not kwargs else None
        if self._accessor == "getitem" and isinstance(self._key, int):
            if single is not None:
                try:
                    return single[self._key]
                except TypeError:
                    return args[self._key]
            return args[self._key]
        if self._accessor == "getattr":
            if single is not None and hasattr(single, self._key):
                return getattr(single, self._key)
            return kwargs[self._key]
        if single is not None and hasattr(single, "__getitem__"):
            return single[self._key]
        return kwargs[self._key]
