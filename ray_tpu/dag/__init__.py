"""Lazy task/actor DAGs (reference: python/ray/dag/ — DAGNode
dag_node.py:23, FunctionNode, ClassNode, InputNode; executed via
.execute(); used by Serve deployment graphs and Workflow).

A DAG is built with .bind() on remote functions / actor classes / actor
methods, then executed with node.execute(input). Execution submits the
whole graph as tasks whose ObjectRef edges the scheduler resolves —
breadth of the graph runs in parallel with no driver round-trips between
levels.
"""

from .dag_node import DAGNode  # noqa: F401
from .function_node import FunctionNode, bind_function  # noqa: F401
from .class_node import ClassMethodNode, ClassNode, bind_class, bind_method  # noqa: F401
from .input_node import InputAttributeNode, InputNode  # noqa: F401
