"""DAGNode base (reference: python/ray/dag/dag_node.py:23).

Nodes hold bound args (which may contain other DAGNodes); execute() walks
the graph once per call with a per-execution memo so diamond dependencies
submit each node exactly once.
"""

from __future__ import annotations

import itertools
from typing import Any, Dict, List, Tuple

_node_counter = itertools.count()


def _map_structure(obj, fn):
    """Apply fn to every DAGNode found in (possibly nested) args."""
    if isinstance(obj, DAGNode):
        return fn(obj)
    if isinstance(obj, tuple) and hasattr(obj, "_fields"):  # namedtuple
        return type(obj)(*(_map_structure(o, fn) for o in obj))
    if isinstance(obj, (list, tuple)):
        return type(obj)(_map_structure(o, fn) for o in obj)
    if isinstance(obj, dict):
        return {k: _map_structure(v, fn) for k, v in obj.items()}
    return obj


class DAGNode:
    def __init__(self, args: Tuple[Any, ...] = (), kwargs: Dict[str, Any] = None):
        self._bound_args = args
        self._bound_kwargs = kwargs or {}
        self._stable_uuid = next(_node_counter)

    # -- traversal ----------------------------------------------------------
    def _children(self) -> List["DAGNode"]:
        out: List[DAGNode] = []
        _map_structure((self._bound_args, self._bound_kwargs), out.append)
        return out

    def topo_sort(self) -> List["DAGNode"]:
        """All reachable nodes, dependencies before dependents; order is
        deterministic (by creation id within a level's discovery walk)."""
        seen: Dict[int, DAGNode] = {}
        order: List[DAGNode] = []

        def visit(node: "DAGNode"):
            if id(node) in seen:
                return
            seen[id(node)] = node
            for c in node._children():
                visit(c)
            order.append(node)

        visit(self)
        return order

    # -- execution ----------------------------------------------------------
    def _resolve_args(self, memo: Dict[int, Any]):
        resolve = lambda n: n._execute_impl(memo)  # noqa: E731
        args = _map_structure(self._bound_args, resolve)
        kwargs = _map_structure(self._bound_kwargs, resolve)
        return args, kwargs

    def _execute_impl(self, memo: Dict[int, Any]):
        if id(self) in memo:
            return memo[id(self)]
        out = self._execute_node(memo)
        memo[id(self)] = out
        return out

    def _execute_node(self, memo: Dict[int, Any]):
        raise NotImplementedError

    def execute(self, *input_args, **input_kwargs):
        """Execute the DAG rooted here. Returns whatever the root produces
        (an ObjectRef for function/method roots). The single positional
        input feeds InputNode, extras feed InputNode attribute access."""
        memo: Dict[int, Any] = {"__input__": (input_args, input_kwargs)}
        return self._execute_impl(memo)

    def __repr__(self):
        return f"{type(self).__name__}(id={self._stable_uuid})"
