"""Rotary position embeddings (RoPE), llama-style."""

from __future__ import annotations

import jax.numpy as jnp


def rope_frequencies(head_dim: int, max_len: int, theta: float = 10000.0):
    """Returns (cos, sin) tables of shape [max_len, head_dim//2], f32."""
    inv_freq = 1.0 / (theta ** (jnp.arange(0, head_dim, 2, dtype=jnp.float32) / head_dim))
    t = jnp.arange(max_len, dtype=jnp.float32)
    freqs = jnp.outer(t, inv_freq)
    return jnp.cos(freqs), jnp.sin(freqs)


def apply_rope(x: jnp.ndarray, cos: jnp.ndarray, sin: jnp.ndarray, positions=None):
    """x: [..., seq, heads, head_dim]; cos/sin: [max_len, head_dim//2].

    positions: optional [..., seq] int array (for sequence-parallel shards the
    caller passes the global positions of its local block).
    """
    seq = x.shape[-3]
    if positions is None:
        c = cos[:seq][:, None, :]
        s = sin[:seq][:, None, :]
    else:
        c = cos[positions][..., :, None, :]
        s = sin[positions][..., :, None, :]
    x1, x2 = jnp.split(x.astype(jnp.float32), 2, axis=-1)
    out = jnp.concatenate([x1 * c - x2 * s, x2 * c + x1 * s], axis=-1)
    return out.astype(x.dtype)


def apply_rope_bhsd(x: jnp.ndarray, cos: jnp.ndarray, sin: jnp.ndarray):
    """Head-major variant: x [B, H, seq, head_dim].

    Computed WITHOUT splitting the minor axis (a [., D/2] tensor wastes 3/4
    of every 128-lane TPU tile and the split/concat pair shows up as ~10% of
    a 125M train step): rotate-half becomes a lane roll with a sign mask,
    and the tables are pre-duplicated to full head_dim. Compute stays in the
    input dtype — the rotation is a norm-preserving elementwise blend, bf16
    is plenty (and f32 upcasts doubled the HBM traffic)."""
    d = x.shape[-1]
    seq = x.shape[-2]
    c = jnp.concatenate([cos[:seq], cos[:seq]], axis=-1)[None, None].astype(x.dtype)
    s = jnp.concatenate([sin[:seq], sin[:seq]], axis=-1)[None, None].astype(x.dtype)
    sign = jnp.concatenate(
        [-jnp.ones((d // 2,), x.dtype), jnp.ones((d // 2,), x.dtype)]
    )
    rotated = jnp.roll(x, d // 2, axis=-1) * sign
    return x * c + rotated * s
