"""Ring attention: causal attention over a sequence-parallel mesh axis.

Net-new relative to the reference (SURVEY §2.4: SP/CP "Absent — must be
built new"). Each device on the `sp` ring holds one contiguous sequence
block of Q/K/V. K/V blocks rotate around the ring with `ppermute` while a
flash-style (m, l, o) accumulator folds in one block per step — peak memory
is O(block²) instead of O(L²), and XLA overlaps the ICI neighbor exchange
with the block matmuls (the ppermute for step s+1 is independent of step
s's compute).

Designed to run INSIDE shard_map, manual over the `sp` axis only — dp/fsdp
(batch) and tp (heads) stay auto so GSPMD shards the block matmuls as usual.
"""

from __future__ import annotations

from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax

NEG_INF = -1e30


def _block_attend(q, k, v, m, l, o, q_block_idx, kv_block_idx, scale, causal):
    """Fold one K/V block into the (m, l, o) flash accumulator. f32 state."""
    blk_q, blk_k = q.shape[1], k.shape[1]
    n_rep = q.shape[2] // k.shape[2]
    if n_rep > 1:
        b, s, h, d = k.shape
        k = jnp.broadcast_to(k[:, :, :, None, :], (b, s, h, n_rep, d)).reshape(b, s, h * n_rep, d)
        v = jnp.broadcast_to(v[:, :, :, None, :], (b, s, h, n_rep, d)).reshape(b, s, h * n_rep, d)
    logits = jnp.einsum("bqhd,bkhd->bhqk", q, k, preferred_element_type=jnp.float32) * scale
    if causal:
        qpos = q_block_idx * blk_q + jnp.arange(blk_q)[:, None]
        kpos = kv_block_idx * blk_k + jnp.arange(blk_k)[None, :]
        mask = qpos >= kpos  # [blk_q, blk_k]
        logits = jnp.where(mask[None, None], logits, NEG_INF)
    else:
        mask = jnp.ones((blk_q, blk_k), dtype=bool)
    m_new = jnp.maximum(m, jnp.max(logits, axis=-1))  # [B,H,Lq]
    # zero masked probs explicitly: robust even when a row is fully masked
    p = jnp.where(mask[None, None], jnp.exp(logits - m_new[..., None]), 0.0)
    alpha = jnp.exp(m - m_new)  # [B,H,Lq]
    l_new = alpha * l + jnp.sum(p, axis=-1)
    pv = jnp.einsum("bhqk,bkhd->bqhd", p, v.astype(jnp.float32))
    o_new = o * alpha.transpose(0, 2, 1)[..., None] + pv
    return m_new, l_new, o_new


def ring_attention(
    q: jnp.ndarray,  # [B, L/sp, H, D] local block (manual over sp)
    k: jnp.ndarray,  # [B, L/sp, Hkv, D]
    v: jnp.ndarray,
    *,
    axis_name: str = "sp",
    causal: bool = True,
    scale: Optional[float] = None,
) -> jnp.ndarray:
    scale = scale if scale is not None else q.shape[-1] ** -0.5
    sp = lax.psum(1, axis_name)
    my_block = lax.axis_index(axis_name)
    b, blk, h, d = q.shape
    m0 = jnp.full((b, h, blk), NEG_INF, dtype=jnp.float32)
    l0 = jnp.zeros((b, h, blk), dtype=jnp.float32)
    o0 = jnp.zeros((b, blk, h, d), dtype=jnp.float32)
    perm = [(j, (j + 1) % sp) for j in range(sp)]

    def step(s, carry):
        m, l, o, ck, cv = carry
        src_block = (my_block - s) % sp
        m, l, o = _block_attend(q, ck, cv, m, l, o, my_block, src_block, scale, causal)
        # rotate AFTER attending; the last rotation is skipped via cond-free
        # arithmetic (an extra rotate is harmless and keeps the loop uniform)
        ck = lax.ppermute(ck, axis_name, perm)
        cv = lax.ppermute(cv, axis_name, perm)
        return m, l, o, ck, cv

    m, l, o, _, _ = lax.fori_loop(0, sp, step, (m0, l0, o0, k, v))
    out = o / jnp.maximum(l, 1e-30).transpose(0, 2, 1)[..., None]
    return out.astype(q.dtype)


def make_sharded_ring_attention(mesh, axis_name: str = "sp", causal: bool = True):
    """Wrap ring_attention in shard_map: manual over `sp`, auto elsewhere."""
    from jax.sharding import PartitionSpec as P

    spec = P(None, axis_name, None, None)

    from ..parallel.sharding import shard_map_compat

    fn = partial(ring_attention, axis_name=axis_name, causal=causal)
    return shard_map_compat(fn, mesh, (spec, spec, spec), spec, {axis_name})
