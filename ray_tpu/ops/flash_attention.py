"""FlashAttention for TPU in Pallas.

The reference has no fused attention of its own (torch SDPA/CUDA kernels
arrive via integrations; SURVEY.md §2.4 sequence parallel row). This is the
TPU-native equivalent: a Pallas kernel that never materializes the [L, L]
score matrix — online softmax over KV blocks held in VMEM, both matmuls on
the MXU in f32 accumulation.

Layout convention matches ray_tpu.ops.attention: q/k/v are [B, L, H, D].

Grid: (batch, head, q_block, kv_block); the kv axis is innermost, so the
f32 accumulator/max/denominator scratch persists across kv iterations of
one q block (the sequential-last-dim contract of Pallas TPU grids). Causal
skipping is predicated per block pair — fully-masked pairs never touch the
MXU.

Backward is a custom VJP: the kernel saves the log-sum-exp row statistics;
gradients are recomputed blockwise (a lax.scan over KV blocks) so backward
memory is O(L * BLOCK_K) instead of O(L^2) — same rematerialization trade
FlashAttention makes on GPU.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

DEFAULT_BLOCK_Q = 128
DEFAULT_BLOCK_K = 128
NEG_INF = -1e30


def _fa_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref, acc, m_i, l_i, *, scale, causal, block_q, block_k):
    qi = pl.program_id(2)
    kj = pl.program_id(3)
    nk = pl.num_programs(3)

    @pl.when(kj == 0)
    def _init():
        acc[:] = jnp.zeros_like(acc)
        m_i[:] = jnp.full_like(m_i, NEG_INF)
        l_i[:] = jnp.zeros_like(l_i)

    # causal: the whole block pair is masked out iff its lowest q position
    # is below its lowest k position
    run = (not causal) or (qi * block_q + block_q - 1 >= kj * block_k)

    @pl.when(run)
    def _attend():
        q = q_ref[0, 0, :, :].astype(jnp.float32)  # [BQ, D]
        k = k_ref[0, 0, :, :].astype(jnp.float32)  # [BK, D]
        v = v_ref[0, 0, :, :].astype(jnp.float32)
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        ) * scale  # [BQ, BK]
        if causal:
            qpos = qi * block_q + jax.lax.broadcasted_iota(jnp.int32, (block_q, block_k), 0)
            kpos = kj * block_k + jax.lax.broadcasted_iota(jnp.int32, (block_q, block_k), 1)
            s = jnp.where(qpos >= kpos, s, NEG_INF)
        m_prev = m_i[:]
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=1, keepdims=True))
        p = jnp.exp(s - m_new)
        alpha = jnp.exp(m_prev - m_new)
        l_i[:] = alpha * l_i[:] + jnp.sum(p, axis=1, keepdims=True)
        m_i[:] = m_new
        acc[:] = acc[:] * alpha + jax.lax.dot_general(
            p, v, (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32
        )

    @pl.when(kj == nk - 1)
    def _finalize():
        # fully-masked q rows (never occur under causal q>=k layouts, but do
        # with padding) get l=0: emit zeros, not NaNs
        l = l_i[:]
        safe_l = jnp.where(l == 0.0, 1.0, l)
        o_ref[0, 0, :, :] = (acc[:] / safe_l).astype(o_ref.dtype)
        lse_ref[0, 0, :, :] = m_i[:] + jnp.log(safe_l)


def _flash_forward(q, k, v, scale, causal, block_q, block_k, interpret):
    """q/k/v in [B, L, H, D]; kernel runs in [B, H, L, D] (Mosaic requires
    the last two BLOCK dims to tile (8, 128) or equal the array dims, so L
    and D must be innermost). Returns out [B, Lq, H, D], lse [B, H, Lq]."""
    b, lq, h, d = q.shape
    lk = k.shape[1]
    qt = q.transpose(0, 2, 1, 3)
    kt = k.transpose(0, 2, 1, 3)
    vt = v.transpose(0, 2, 1, 3)
    nq = lq // block_q
    nk = lk // block_k
    grid = (b, h, nq, nk)
    kernel = functools.partial(
        _fa_kernel, scale=scale, causal=causal, block_q=block_q, block_k=block_k
    )
    out, lse = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, 1, block_q, d), lambda b_, h_, i, j: (b_, h_, i, 0)),
            pl.BlockSpec((1, 1, block_k, d), lambda b_, h_, i, j: (b_, h_, j, 0)),
            pl.BlockSpec((1, 1, block_k, d), lambda b_, h_, i, j: (b_, h_, j, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, 1, block_q, d), lambda b_, h_, i, j: (b_, h_, i, 0)),
            pl.BlockSpec((1, 1, block_q, 1), lambda b_, h_, i, j: (b_, h_, i, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((b, h, lq, d), q.dtype),
            jax.ShapeDtypeStruct((b, h, lq, 1), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_q, d), jnp.float32),
            pltpu.VMEM((block_q, 1), jnp.float32),
            pltpu.VMEM((block_q, 1), jnp.float32),
        ],
        interpret=interpret,
    )(qt, kt, vt)
    return out.transpose(0, 2, 1, 3), lse[..., 0]


def _flash_backward(scale, causal, block_k, res, do):
    """Blockwise recompute backward (plain JAX, O(L*BLOCK_K) live memory)."""
    q, k, v, out, lse = res
    b, lq, h, d = q.shape
    lk = k.shape[1]
    qf = q.astype(jnp.float32)
    kf = k.astype(jnp.float32)
    vf = v.astype(jnp.float32)
    dof = do.astype(jnp.float32)
    # Delta_i = rowsum(dO * O)  [B, L, H]
    delta = jnp.einsum("blhd,blhd->blh", dof, out.astype(jnp.float32))
    qpos = jnp.arange(lq)

    nk = lk // block_k
    kfb = kf.reshape(b, nk, block_k, h, d).transpose(1, 0, 2, 3, 4)
    vfb = vf.reshape(b, nk, block_k, h, d).transpose(1, 0, 2, 3, 4)

    def kv_step(dq_acc, inp):
        j, k_j, v_j = inp  # [B, BK, H, D]
        s = jnp.einsum("bqhd,bkhd->bhqk", qf, k_j) * scale
        if causal:
            kpos = j * block_k + jnp.arange(block_k)
            s = jnp.where(qpos[:, None] >= kpos[None, :], s, NEG_INF)
        p = jnp.exp(s - lse[:, :, :, None])  # [B, H, L, BK]
        dv_j = jnp.einsum("bhqk,bqhd->bkhd", p, dof)
        dp = jnp.einsum("bqhd,bkhd->bhqk", dof, v_j)
        ds = p * (dp - delta.transpose(0, 2, 1)[:, :, :, None])  # [B,H,L,BK]
        dq_acc = dq_acc + jnp.einsum("bhqk,bkhd->bqhd", ds, k_j) * scale
        dk_j = jnp.einsum("bhqk,bqhd->bkhd", ds, qf) * scale
        return dq_acc, (dk_j, dv_j)

    dq, (dk_blocks, dv_blocks) = jax.lax.scan(
        kv_step, jnp.zeros_like(qf), (jnp.arange(nk), kfb, vfb)
    )
    dk = dk_blocks.transpose(1, 0, 2, 3, 4).reshape(b, lk, h, d)
    dv = dv_blocks.transpose(1, 0, 2, 3, 4).reshape(b, lk, h, d)
    return dq.astype(q.dtype), dk.astype(k.dtype), dv.astype(v.dtype)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7))
def _flash(q, k, v, scale, causal, block_q, block_k, interpret):
    out, _ = _flash_forward(q, k, v, scale, causal, block_q, block_k, interpret)
    return out


def _flash_fwd(q, k, v, scale, causal, block_q, block_k, interpret):
    out, lse = _flash_forward(q, k, v, scale, causal, block_q, block_k, interpret)
    return out, (q, k, v, out, lse)


def _flash_bwd(scale, causal, block_q, block_k, interpret, res, do):
    return _flash_backward(scale, causal, block_k, res, do)


_flash.defvjp(_flash_fwd, _flash_bwd)


def flash_attention(
    q: jnp.ndarray,  # [B, Lq, H, D]
    k: jnp.ndarray,  # [B, Lk, Hkv, D]
    v: jnp.ndarray,  # [B, Lk, Hkv, D]
    *,
    scale: Optional[float] = None,
    causal: bool = True,
    block_q: int = DEFAULT_BLOCK_Q,
    block_k: int = DEFAULT_BLOCK_K,
    interpret: Optional[bool] = None,
) -> jnp.ndarray:
    """Drop-in replacement for ops.attention.causal_attention on block-
    aligned shapes; GQA handled by repeating KV heads outside the kernel
    (gradients flow through the broadcast). Falls back to the dense einsum
    path when the sequence doesn't tile evenly."""
    from .attention import causal_attention, _repeat_kv

    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    block_q = min(block_q, q.shape[1])
    block_k = min(block_k, k.shape[1])
    if q.shape[1] % block_q or k.shape[1] % block_k:
        return causal_attention(q, k, v, scale=scale, causal=causal)
    n_rep = q.shape[2] // k.shape[2]
    k = _repeat_kv(k, n_rep)
    v = _repeat_kv(v, n_rep)
    scale = scale if scale is not None else q.shape[-1] ** -0.5
    return _flash(q, k, v, scale, causal, block_q, block_k, interpret)
