"""FlashAttention for TPU in Pallas.

The reference has no fused attention of its own (torch SDPA/CUDA kernels
arrive via integrations; SURVEY.md §2.4 sequence parallel row). This is the
TPU-native equivalent: a Pallas kernel that never materializes the [L, L]
score matrix — online softmax over KV blocks held in VMEM, both matmuls on
the MXU in f32 accumulation.

Layout convention matches ray_tpu.ops.attention: q/k/v are [B, L, H, D].

Grid: (batch, head, q_block, kv_block); the kv axis is innermost, so the
f32 accumulator/max/denominator scratch persists across kv iterations of
one q block (the sequential-last-dim contract of Pallas TPU grids). Causal
skipping is predicated per block pair — fully-masked pairs never touch the
MXU.

Backward is a custom VJP with two more Pallas kernels (FlashAttention-2
structure): a dq kernel (grid over q blocks, kv innermost, dq accumulator in
VMEM) and a dk/dv kernel (grid over kv blocks, q innermost). Probabilities
are recomputed from the saved log-sum-exp rows, so backward memory is
O(L * BLOCK) instead of O(L^2) and all four matmuls per block pair run on
the MXU in f32 accumulation. Causally-dead block pairs are skipped in both
kernels.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

DEFAULT_BLOCK_Q = 128
DEFAULT_BLOCK_K = 128
NEG_INF = -1e30


def _fa_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref, acc, m_i, l_i, *, scale, causal, block_q, block_k):
    qi = pl.program_id(2)
    kj = pl.program_id(3)
    nk = pl.num_programs(3)

    @pl.when(kj == 0)
    def _init():
        acc[:] = jnp.zeros_like(acc)
        m_i[:] = jnp.full_like(m_i, NEG_INF)
        l_i[:] = jnp.zeros_like(l_i)

    # causal: the whole block pair is masked out iff its lowest q position
    # is below its lowest k position
    run = (not causal) or (qi * block_q + block_q - 1 >= kj * block_k)

    @pl.when(run)
    def _attend():
        # matmul inputs stay bf16 (f32 operands run the MXU at a fraction of
        # bf16 rate); accumulation is f32 via preferred_element_type
        q = q_ref[0, 0, :, :]  # [BQ, D]
        k = k_ref[0, 0, :, :]  # [BK, D]
        v = v_ref[0, 0, :, :]
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        ) * scale  # [BQ, BK]
        if causal:
            qpos = qi * block_q + jax.lax.broadcasted_iota(jnp.int32, (block_q, block_k), 0)
            kpos = kj * block_k + jax.lax.broadcasted_iota(jnp.int32, (block_q, block_k), 1)
            s = jnp.where(qpos >= kpos, s, NEG_INF)
        m_prev = m_i[:]
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=1, keepdims=True))
        p = jnp.exp(s - m_new)
        alpha = jnp.exp(m_prev - m_new)
        l_i[:] = alpha * l_i[:] + jnp.sum(p, axis=1, keepdims=True)
        m_i[:] = m_new
        acc[:] = acc[:] * alpha + jax.lax.dot_general(
            p.astype(v.dtype), v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )

    @pl.when(kj == nk - 1)
    def _finalize():
        # fully-masked q rows (never occur under causal q>=k layouts, but do
        # with padding) get l=0: emit zeros, not NaNs
        l = l_i[:]
        safe_l = jnp.where(l == 0.0, 1.0, l)
        o_ref[0, 0, :, :] = (acc[:] / safe_l).astype(o_ref.dtype)
        lse_ref[0, 0, :, :] = m_i[:] + jnp.log(safe_l)


def _flash_forward(q, k, v, scale, causal, block_q, block_k, interpret):
    """q in [B, H, L, D], k/v in [B, Hkv, L, D] — the kernel's native
    layout (Mosaic requires the last two BLOCK dims to tile (8, 128) or
    equal the array dims, so L and D must be innermost). GQA is folded
    into the k/v index maps (q head h reads kv head h // n_rep), so
    repeated KV heads are never materialized. Returns out [B, H, Lq, D],
    lse [B, H, Lq]."""
    b, h, lq, d = q.shape
    lk = k.shape[2]
    n_rep = h // k.shape[1]
    qt, kt, vt = q, k, v
    nq = lq // block_q
    nk = lk // block_k
    grid = (b, h, nq, nk)
    kernel = functools.partial(
        _fa_kernel, scale=scale, causal=causal, block_q=block_q, block_k=block_k
    )
    out, lse = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, 1, block_q, d), lambda b_, h_, i, j: (b_, h_, i, 0)),
            pl.BlockSpec((1, 1, block_k, d), lambda b_, h_, i, j: (b_, h_ // n_rep, j, 0)),
            pl.BlockSpec((1, 1, block_k, d), lambda b_, h_, i, j: (b_, h_ // n_rep, j, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, 1, block_q, d), lambda b_, h_, i, j: (b_, h_, i, 0)),
            pl.BlockSpec((1, 1, block_q, 1), lambda b_, h_, i, j: (b_, h_, i, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((b, h, lq, d), q.dtype),
            jax.ShapeDtypeStruct((b, h, lq, 1), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_q, d), jnp.float32),
            pltpu.VMEM((block_q, 1), jnp.float32),
            pltpu.VMEM((block_q, 1), jnp.float32),
        ],
        interpret=interpret,
    )(qt, kt, vt)
    return out, lse[..., 0]


def _recompute_p_ds(refs, qi, kj, *, scale, causal, block_q, block_k):
    """Shared backward recompute for one (q block, kv block) pair: rebuilds
    the probabilities from the saved lse row stats and derives dS. Inputs
    stay bf16 into the MXU; accumulation is f32. Returns (p, ds, q, k, v, do)."""
    q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref = refs
    q = q_ref[0, 0, :, :]                          # [BQ, D] bf16
    k = k_ref[0, 0, :, :]                          # [BK, D]
    v = v_ref[0, 0, :, :]                          # [BK, D]
    do = do_ref[0, 0, :, :]                        # [BQ, D]
    lse = lse_ref[0, 0, :, :]                      # [BQ, 1]
    delta = delta_ref[0, 0, :, :]                  # [BQ, 1]
    s = jax.lax.dot_general(
        q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
    ) * scale
    if causal:
        qpos = qi * block_q + jax.lax.broadcasted_iota(jnp.int32, (block_q, block_k), 0)
        kpos = kj * block_k + jax.lax.broadcasted_iota(jnp.int32, (block_q, block_k), 1)
        s = jnp.where(qpos >= kpos, s, NEG_INF)
    p = jnp.exp(s - lse)                           # [BQ, BK] f32
    dp = jax.lax.dot_general(
        do, v, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
    )
    ds = (p * (dp - delta) * scale).astype(k.dtype)
    return p, ds, q, k, v, do


def _dq_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, dq_ref, dq_acc,
               *, scale, causal, block_q, block_k):
    qi = pl.program_id(2)
    kj = pl.program_id(3)
    nk = pl.num_programs(3)

    @pl.when(kj == 0)
    def _init():
        dq_acc[:] = jnp.zeros_like(dq_acc)

    run = (not causal) or (qi * block_q + block_q - 1 >= kj * block_k)

    @pl.when(run)
    def _accum():
        _, ds, _, k, _, _ = _recompute_p_ds(
            (q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref), qi, kj,
            scale=scale, causal=causal, block_q=block_q, block_k=block_k,
        )
        dq_acc[:] += jax.lax.dot_general(
            ds, k, (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32
        )

    @pl.when(kj == nk - 1)
    def _finalize():
        dq_ref[0, 0, :, :] = dq_acc[:].astype(dq_ref.dtype)


def _dkv_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, dk_ref, dv_ref,
                dk_acc, dv_acc, *, scale, causal, block_q, block_k, nq):
    """Grid (b, kv_head, kv_block, n_rep * nq): the innermost axis walks
    every (q head in the GQA group, q block) pair while the dk/dv output
    block stays fixed, so the group-sum over repeated q heads lands in the
    same VMEM accumulator that already sums over q blocks — the repeated-KV
    materialization (and its gradient reduction) never exists."""
    kj = pl.program_id(2)
    i = pl.program_id(3)
    ni = pl.num_programs(3)
    qi = i % nq

    @pl.when(i == 0)
    def _init():
        dk_acc[:] = jnp.zeros_like(dk_acc)
        dv_acc[:] = jnp.zeros_like(dv_acc)

    run = (not causal) or (qi * block_q + block_q - 1 >= kj * block_k)

    @pl.when(run)
    def _accum():
        p, ds, q, _, _, do = _recompute_p_ds(
            (q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref), qi, kj,
            scale=scale, causal=causal, block_q=block_q, block_k=block_k,
        )
        # dV += P^T @ dO
        dv_acc[:] += jax.lax.dot_general(
            p.astype(do.dtype), do, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        # dK += dS^T @ Q
        dk_acc[:] += jax.lax.dot_general(
            ds, q, (((0,), (0,)), ((), ())), preferred_element_type=jnp.float32
        )

    @pl.when(i == ni - 1)
    def _finalize():
        dk_ref[0, 0, :, :] = dk_acc[:].astype(dk_ref.dtype)
        dv_ref[0, 0, :, :] = dv_acc[:].astype(dv_ref.dtype)


def _dqkv_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                 dq_ref, dk_ref, dv_ref, *, scale, causal, block_q, block_k):
    """Fused backward for the single-block-pair case (nq == nk == 1): the
    recomputed s/p serve dq AND dk/dv in one pass — 5 MXU matmuls + 1 exp
    instead of the 7 + 2 the split kernels pay. Every output block is
    written exactly once per (b, h), so no cross-iteration accumulation is
    needed."""
    p, ds, q, k, v, do = _recompute_p_ds(
        (q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref),
        pl.program_id(2), pl.program_id(3),
        scale=scale, causal=causal, block_q=block_q, block_k=block_k,
    )
    dq_ref[0, 0, :, :] = jax.lax.dot_general(
        ds, k, (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32
    ).astype(dq_ref.dtype)
    dv_ref[0, 0, :, :] = jax.lax.dot_general(
        p.astype(do.dtype), do, (((0,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    ).astype(dv_ref.dtype)
    dk_ref[0, 0, :, :] = jax.lax.dot_general(
        ds, q, (((0,), (0,)), ((), ())), preferred_element_type=jnp.float32
    ).astype(dk_ref.dtype)


def _flash_backward(scale, causal, block_q, block_k, interpret, res, do):
    """FlashAttention-2 backward: two Pallas kernels over [B, H, L, D]
    (fused into one when the whole sequence fits a single block pair and
    there is no GQA group to reduce). k/v/dk/dv stay [B, Hkv, L, D]: the
    group fold lives in the index maps (dq) and the folded innermost grid
    axis (dk/dv)."""
    q, k, v, out, lse = res
    b, h, lq, d = q.shape
    h_kv = k.shape[1]
    n_rep = h // h_kv
    lk = k.shape[2]
    qt, kt, vt, dot = q, k, v, do
    # Delta_i = rowsum(dO * O)  [B, H, L, 1]
    delta = jnp.einsum(
        "bhld,bhld->bhl", do.astype(jnp.float32), out.astype(jnp.float32)
    )[..., None]
    lse4 = lse[..., None]  # [B, H, L, 1]
    nq = lq // block_q
    nk = lk // block_k

    q_spec = pl.BlockSpec((1, 1, block_q, d), lambda b_, h_, i, j: (b_, h_, i, 0))
    k_spec = pl.BlockSpec((1, 1, block_k, d), lambda b_, h_, i, j: (b_, h_ // n_rep, j, 0))
    row_spec = pl.BlockSpec((1, 1, block_q, 1), lambda b_, h_, i, j: (b_, h_, i, 0))
    if nq == 1 and nk == 1 and n_rep == 1:
        dq, dk, dv = pl.pallas_call(
            functools.partial(
                _dqkv_kernel, scale=scale, causal=causal,
                block_q=block_q, block_k=block_k,
            ),
            grid=(b, h, 1, 1),
            in_specs=[q_spec, k_spec, k_spec, q_spec, row_spec, row_spec],
            out_specs=[q_spec, k_spec, k_spec],
            out_shape=[
                jax.ShapeDtypeStruct((b, h, lq, d), q.dtype),
                jax.ShapeDtypeStruct((b, h, lk, d), k.dtype),
                jax.ShapeDtypeStruct((b, h, lk, d), v.dtype),
            ],
            interpret=interpret,
        )(qt, kt, vt, dot, lse4, delta)
        return dq, dk, dv
    dq = pl.pallas_call(
        functools.partial(
            _dq_kernel, scale=scale, causal=causal, block_q=block_q, block_k=block_k
        ),
        grid=(b, h, nq, nk),
        in_specs=[q_spec, k_spec, k_spec, q_spec, row_spec, row_spec],
        out_specs=[q_spec],
        out_shape=[jax.ShapeDtypeStruct((b, h, lq, d), q.dtype)],
        scratch_shapes=[pltpu.VMEM((block_q, d), jnp.float32)],
        interpret=interpret,
    )(qt, kt, vt, dot, lse4, delta)[0]

    # kv kernel: grid (b, kv_head, kv_block, n_rep * q_blocks) — the whole
    # GQA group runs while the dk/dv block is resident, so group-sum and
    # q-block-sum share one accumulator (see _dkv_kernel)
    def _qh(g_, i_):
        return g_ * n_rep + i_ // nq

    qi_spec = pl.BlockSpec(
        (1, 1, block_q, d), lambda b_, g_, j_, i_: (b_, _qh(g_, i_), i_ % nq, 0)
    )
    kj_spec = pl.BlockSpec(
        (1, 1, block_k, d), lambda b_, g_, j_, i_: (b_, g_, j_, 0)
    )
    rowi_spec = pl.BlockSpec(
        (1, 1, block_q, 1), lambda b_, g_, j_, i_: (b_, _qh(g_, i_), i_ % nq, 0)
    )
    dk, dv = pl.pallas_call(
        functools.partial(
            _dkv_kernel, scale=scale, causal=causal, block_q=block_q,
            block_k=block_k, nq=nq,
        ),
        grid=(b, h_kv, nk, nq * n_rep),
        in_specs=[qi_spec, kj_spec, kj_spec, qi_spec, rowi_spec, rowi_spec],
        out_specs=[kj_spec, kj_spec],
        out_shape=[
            jax.ShapeDtypeStruct((b, h_kv, lk, d), k.dtype),
            jax.ShapeDtypeStruct((b, h_kv, lk, d), v.dtype),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_k, d), jnp.float32),
            pltpu.VMEM((block_k, d), jnp.float32),
        ],
        interpret=interpret,
    )(qt, kt, vt, dot, lse4, delta)

    return dq, dk, dv


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7))
def _flash(q, k, v, scale, causal, block_q, block_k, interpret):
    out, _ = _flash_forward(q, k, v, scale, causal, block_q, block_k, interpret)
    return out


def _flash_fwd(q, k, v, scale, causal, block_q, block_k, interpret):
    from jax.ad_checkpoint import checkpoint_name

    out, lse = _flash_forward(q, k, v, scale, causal, block_q, block_k, interpret)
    # name the residuals so remat policies can SAVE them — without this the
    # forward kernel re-runs inside backward just to regenerate lse
    out = checkpoint_name(out, "flash_out")
    lse = checkpoint_name(lse, "flash_lse")
    return out, (q, k, v, out, lse)


def _flash_bwd(scale, causal, block_q, block_k, interpret, res, do):
    return _flash_backward(scale, causal, block_q, block_k, interpret, res, do)


_flash.defvjp(_flash_fwd, _flash_bwd)


def flash_attention(
    q: jnp.ndarray,  # [B, Lq, H, D]  (or [B, H, Lq, D] with layout="bhsd")
    k: jnp.ndarray,  # [B, Lk, Hkv, D]
    v: jnp.ndarray,  # [B, Lk, Hkv, D]
    *,
    scale: Optional[float] = None,
    causal: bool = True,
    block_q: int = DEFAULT_BLOCK_Q,
    block_k: int = DEFAULT_BLOCK_K,
    interpret: Optional[bool] = None,
    layout: str = "bshd",
) -> jnp.ndarray:
    """Drop-in replacement for ops.attention.causal_attention on block-
    aligned shapes. GQA is folded into the kernel's k/v index maps (q head
    h reads kv head h // n_rep, forward and backward) — repeated KV heads
    are never materialized and dk/dv group-sum inside the kernel. Falls
    back to the dense einsum path when the sequence doesn't tile evenly.

    layout="bhsd" runs the kernel on head-major inputs with NO relayout —
    the fast path the model uses (transposes around the kernel cost more
    than the attention itself at small d_head)."""
    from .attention import causal_attention, causal_attention_bhsd

    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    seq_axis = 2 if layout == "bhsd" else 1
    head_axis = 1 if layout == "bhsd" else 2
    block_q = min(block_q, q.shape[seq_axis])
    block_k = min(block_k, k.shape[seq_axis])
    if q.shape[seq_axis] % block_q or k.shape[seq_axis] % block_k:
        dense = causal_attention_bhsd if layout == "bhsd" else causal_attention
        return dense(q, k, v, scale=scale, causal=causal)
    if q.shape[head_axis] % k.shape[head_axis]:
        raise ValueError(
            f"q heads {q.shape[head_axis]} not a multiple of kv heads "
            f"{k.shape[head_axis]}"
        )
    scale = scale if scale is not None else q.shape[-1] ** -0.5
    if layout == "bhsd":
        return _flash(q, k, v, scale, causal, block_q, block_k, interpret)
    out = _flash(
        q.transpose(0, 2, 1, 3),
        k.transpose(0, 2, 1, 3),
        v.transpose(0, 2, 1, 3),
        scale, causal, block_q, block_k, interpret,
    )
    return out.transpose(0, 2, 1, 3)
