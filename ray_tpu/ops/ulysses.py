"""Ulysses-style sequence parallelism: all-to-all head scatter.

Alternative to ring attention for the `sp` axis: instead of rotating K/V
around a ring, one all-to-all converts sequence-sharded activations into
head-sharded ones, dense attention runs locally on full sequences, and a
second all-to-all converts back. Cheaper than ring for moderate L (2
all-to-alls vs sp-1 neighbor steps) but requires heads % sp == 0.
"""

from __future__ import annotations

from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax

from .attention import causal_attention


def ulysses_attention(
    q: jnp.ndarray,  # [B, L/sp, H, D] local block (manual over sp)
    k: jnp.ndarray,  # [B, L/sp, Hkv, D]
    v: jnp.ndarray,
    *,
    axis_name: str = "sp",
    causal: bool = True,
    scale: Optional[float] = None,
) -> jnp.ndarray:
    sp = lax.psum(1, axis_name)
    h, hkv = q.shape[2], k.shape[2]
    if h % sp:
        raise ValueError(f"heads ({h}) must be divisible by sp ({sp})")
    if hkv % sp:
        # GQA with fewer kv heads than sp: replicate kv heads up to sp
        rep = sp // hkv if sp % hkv == 0 else None
        if rep is None:
            raise ValueError(f"kv_heads ({hkv}) must divide or be divisible by sp ({sp})")
        b, s, _, d = k.shape
        k = jnp.broadcast_to(k[:, :, :, None, :], (b, s, hkv, rep, d)).reshape(b, s, hkv * rep, d)
        v = jnp.broadcast_to(v[:, :, :, None, :], (b, s, hkv, rep, d)).reshape(b, s, hkv * rep, d)
    # seq-sharded -> head-sharded: split heads, concat seq
    qg = lax.all_to_all(q, axis_name, split_axis=2, concat_axis=1, tiled=True)
    kg = lax.all_to_all(k, axis_name, split_axis=2, concat_axis=1, tiled=True)
    vg = lax.all_to_all(v, axis_name, split_axis=2, concat_axis=1, tiled=True)
    out = causal_attention(qg, kg, vg, causal=causal, scale=scale)
    # head-sharded -> seq-sharded
    return lax.all_to_all(out, axis_name, split_axis=1, concat_axis=2, tiled=True)


def make_sharded_ulysses_attention(mesh, axis_name: str = "sp", causal: bool = True):
    from jax.sharding import PartitionSpec as P

    spec = P(None, axis_name, None, None)

    from ..parallel.sharding import shard_map_compat

    fn = partial(ulysses_attention, axis_name=axis_name, causal=causal)
    return shard_map_compat(fn, mesh, (spec, spec, spec), spec, {axis_name})
