"""Fused multi-query paged attention for TPU in Pallas.

The paged decode step (ray_tpu/models/transformer.py make_paged_decoder)
historically gathered every slot's logical sequence through its block
table inside the jit — materializing [B, Nmax*block_tokens] keys AND
values per layer before attending. At long contexts that gather, not the
matmuls, is what caps tokens/s/chip: attention reads every live KV byte
once per step, so doubling the traffic halves the rate.

This kernel attends block-in-place over the pool layout instead, for ANY
number of queries per slot — one fused implementation serves

  decode            q = 1   (the original single-query walk)
  speculative verify q = k+1 (the draft window scored in one pass)
  prefill           q = chunk (chunked prefill of a prompt span)

  grid = (batch, q_tile, block)   block innermost, so the online-softmax
                          scratch (f32 acc / running max / denominator)
                          persists across one (slot, q-tile)'s walk of the
                          slot's block table
  k/v BlockSpec           index_map reads the slot's block table (a
                          scalar-prefetch operand) and DMAs physical
                          block `table[b, j]` directly from the pool —
                          no gathered copy ever exists
  dead entries            table entries < 0 (padding, inactive slots,
                          out-of-shard blocks) clamp to block 0 in the
                          index map — Pallas skips the re-fetch when the
                          block index repeats — and are masked in-body
  causal masking          query i sits at global position positions[b]+i;
                          key position j*block + t is visible iff
                          t' <= positions[b]+i AND t' < kv_len[b]. The
                          kv_len cap is what lets the verify step attend a
                          window that does NOT yet contain the in-flight
                          tokens (kv_len = positions, strictly before the
                          first query), while prefill uses pure causality
                          over keys its own layer pass just wrote.

GQA never materializes repeated KV heads: q is reshaped so both matmuls
run batched over the kv-head dim, with the query tile folded into the
repeat dim.

int8 pools (per-block, per-kv-head fp32 scales — see
transformer.init_paged_kv_cache) dequantize INSIDE the kernel: the HBM
read is half the bytes of bf16, which is the whole point at decode.

Sharded pools (blocks split across dp/fsdp shards) run the kernel
per-shard with `partial_out=True`: the kernel returns the unnormalized
accumulator plus the online-softmax (m, l) statistics, and the caller
merges shards with the standard log-sum-exp combine (see
`merge_partials`). kv_heads sharded on tp need no merge — heads are
independent. The same partial triple is how the verify step folds its
tiny in-flight K1 x K1 causal tail into the fused window pass.

A chunked XLA implementation (`impl="xla"`) computes the identical
online-softmax walk without Pallas — the CPU/CI path (interpret-mode
Pallas is a python-per-grid-step debugger, not an implementation), and
the reference the kernel is tested against.
"""

from __future__ import annotations

import functools
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

NEG_INF = -1e30


def _mosaic_params(dimension_semantics):
    """compiler_params across jax versions: the dataclass was named
    TPUCompilerParams on 0.4.x/0.5.x, CompilerParams later; before either,
    pallas_call took a {"mosaic": {...}} dict."""
    cls = getattr(pltpu, "CompilerParams", None) or getattr(
        pltpu, "TPUCompilerParams", None
    )
    if cls is not None:
        return cls(dimension_semantics=dimension_semantics)
    return dict(mosaic=dict(dimension_semantics=dimension_semantics))

# last resolved implementation ("kernel" | "xla"), recorded at trace time —
# test observability: parity suites assert the path they intended to
# exercise actually ran instead of silently falling back
_LAST_IMPL: Optional[str] = None


def _group_scores(q, k):
    """[KV, R, D] x [bt, KV, D] -> [KV, R, bt] without repeating KV heads
    (batched over the kv-head dim; R folds n_rep and the query tile)."""
    kt = k.transpose(1, 0, 2)  # [KV, bt, D]
    return lax.dot_general(
        q, kt, (((2,), (2,)), ((0,), (0,))), preferred_element_type=jnp.float32
    )


def _group_values(p, v):
    """[KV, R, bt] x [bt, KV, D] -> [KV, R, D]."""
    vt = v.transpose(1, 0, 2)  # [KV, bt, D]
    return lax.dot_general(
        p, vt, (((2,), (1,)), ((0,), (0,))), preferred_element_type=jnp.float32
    )


def _pa_kernel(tables_ref, pos_ref, kvlen_ref, q_ref, k_ref, v_ref, *rest,
               bt, qb, n_rep, scale, quantized, partial_out, out_dtype):
    if quantized:
        ks_ref, vs_ref = rest[0], rest[1]
        rest = rest[2:]
    if partial_out:
        o_ref, m_ref, l_ref = rest[:3]
        acc, m_i, l_i = rest[3:]
    else:
        o_ref = rest[0]
        acc, m_i, l_i = rest[1:]
    b = pl.program_id(0)
    qt = pl.program_id(1)
    j = pl.program_id(2)
    nj = pl.num_programs(2)
    kv_heads = k_ref.shape[2]
    rows = qb * n_rep  # scratch rows per kv head (query tile x GQA repeat)

    @pl.when(j == 0)
    def _init():
        acc[:] = jnp.zeros_like(acc)
        m_i[:] = jnp.full_like(m_i, NEG_INF)
        l_i[:] = jnp.zeros_like(l_i)

    entry = tables_ref[b, j]
    pos = pos_ref[b]
    kvl = kvlen_ref[b]
    qbase = pos + qt * qb  # global position of this tile's first query
    # the block matters iff any of the tile's queries can see any key in it:
    # its first key must precede both the kv_len cap and the LAST query
    live = jnp.logical_and(
        entry >= 0,
        jnp.logical_and(j * bt < kvl, j * bt <= qbase + qb - 1),
    )

    @pl.when(live)
    def _attend():
        k = k_ref[0]  # [bt, KV, D]
        v = v_ref[0]
        if quantized:
            blk = jnp.maximum(entry, 0)
            k = k.astype(jnp.float32) * ks_ref[blk][None, :, None]
            v = v.astype(jnp.float32) * vs_ref[blk][None, :, None]
        else:
            k = k.astype(jnp.float32)
            v = v.astype(jnp.float32)
        d = k.shape[2]
        # [qb, H, D] -> [KV, qb*n_rep, D]: fold the query tile into the GQA
        # repeat dim so both matmuls stay batched over kv heads
        qr = q_ref[0].astype(jnp.float32)
        qr = qr.reshape(qb, kv_heads, n_rep, d).transpose(1, 0, 2, 3)
        qr = qr.reshape(kv_heads, rows, d)
        s = (_group_scores(qr, k) * scale).reshape(kv_heads * rows, bt)
        # flat row = g*rows + qi*n_rep + r  ->  query index (row % rows)//n_rep
        kpos = j * bt + jax.lax.broadcasted_iota(
            jnp.int32, (kv_heads * rows, bt), 1
        )
        qi = (jax.lax.broadcasted_iota(
            jnp.int32, (kv_heads * rows, bt), 0
        ) % rows) // n_rep
        mask = jnp.logical_and(kpos <= qbase + qi, kpos < kvl)
        s = jnp.where(mask, s, NEG_INF)
        m_prev = m_i[:]
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=1, keepdims=True))
        # masked p, not exp(NEG_INF - m): a row whose every key this block
        # is masked (an early query under a later block) keeps m_new at
        # NEG_INF, and exp(s - m_new) would be exp(0) = 1 garbage
        p = jnp.where(mask, jnp.exp(s - m_new), 0.0)
        alpha = jnp.exp(m_prev - m_new)
        l_i[:] = alpha * l_i[:] + jnp.sum(p, axis=1, keepdims=True)
        m_i[:] = m_new
        pv = _group_values(p.reshape(kv_heads, rows, bt), v)
        acc[:] = acc[:] * alpha + pv.reshape(kv_heads * rows, d)

    @pl.when(j == nj - 1)
    def _finalize():
        def unflat(x):  # [KV*qb*n_rep, X] -> [qb, H, X]
            x = x.reshape(kv_heads, qb, n_rep, x.shape[-1])
            return x.transpose(1, 0, 2, 3).reshape(
                qb, kv_heads * n_rep, x.shape[-1]
            )

        if partial_out:
            o_ref[0] = unflat(acc[:])
            m_ref[0] = unflat(m_i[:])
            l_ref[0] = unflat(l_i[:])
        else:
            l = l_i[:]
            safe_l = jnp.where(l == 0.0, 1.0, l)
            o_ref[0] = unflat(acc[:] / safe_l).astype(out_dtype)


def _paged_attention_pallas(q, k_pool, v_pool, ptable, positions, kv_len,
                            k_scale, v_scale, scale, partial_out, interpret,
                            block_q):
    b, Q, h, d = q.shape
    _, bt, kv, _ = k_pool.shape
    nmax = ptable.shape[1]
    n_rep = h // kv
    quantized = k_scale is not None
    qb = max(1, min(int(block_q), Q))
    qp = -(-Q // qb) * qb
    if qp != Q:
        # padded queries sit past every real one; their rows mask to zeros
        # and are sliced off below
        q = jnp.pad(q, ((0, 0), (0, qp - Q), (0, 0), (0, 0)))
    grid = (b, qp // qb, nmax)

    q_spec = pl.BlockSpec((1, qb, h, d), lambda b_, qt_, j_, *_: (b_, qt_, 0, 0))
    kv_spec = pl.BlockSpec(
        (1, bt, kv, d),
        # dead entries (< 0) clamp to block 0: repeated indices skip the
        # DMA, so a slot's padding tail costs one null-block fetch total
        lambda b_, qt_, j_, tbl, pos, kvl: (jnp.maximum(tbl[b_, j_], 0), 0, 0, 0),
    )
    in_specs = [q_spec, kv_spec, kv_spec]
    operands = [q, k_pool, v_pool]
    if quantized:
        # scales ride whole in VMEM ([N, KV] f32 is tiny) and are indexed
        # in-body — a (1, KV) block would fight the sublane tiling rules
        in_specs += [pl.BlockSpec(memory_space=pltpu.ANY)] * 2
        operands += [k_scale, v_scale]
    o_map = lambda b_, qt_, j_, *_: (b_, qt_, 0, 0)
    if partial_out:
        out_specs = [
            pl.BlockSpec((1, qb, h, d), o_map),
            pl.BlockSpec((1, qb, h, 1), o_map),
            pl.BlockSpec((1, qb, h, 1), o_map),
        ]
        out_shape = [
            jax.ShapeDtypeStruct((b, qp, h, d), jnp.float32),
            jax.ShapeDtypeStruct((b, qp, h, 1), jnp.float32),
            jax.ShapeDtypeStruct((b, qp, h, 1), jnp.float32),
        ]
    else:
        out_specs = [pl.BlockSpec((1, qb, h, d), o_map)]
        out_shape = [jax.ShapeDtypeStruct((b, qp, h, d), q.dtype)]

    kernel = functools.partial(
        _pa_kernel, bt=bt, qb=qb, n_rep=n_rep, scale=scale,
        quantized=quantized, partial_out=partial_out, out_dtype=q.dtype,
    )
    outs = pl.pallas_call(
        kernel,
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=3,
            grid=grid,
            in_specs=in_specs,
            out_specs=out_specs,
            scratch_shapes=[
                pltpu.VMEM((qb * h, d), jnp.float32),
                pltpu.VMEM((qb * h, 1), jnp.float32),
                pltpu.VMEM((qb * h, 1), jnp.float32),
            ],
        ),
        out_shape=out_shape,
        # batch and q-tile iterations are independent (scratch re-inits at
        # j == 0); the block walk is sequential — it carries the
        # online-softmax scratch. Telling Mosaic lets it
        # parallelize/pipeline over (b, qt) while keeping each walk ordered.
        compiler_params=_mosaic_params(("parallel", "parallel", "arbitrary")),
        interpret=interpret,
    )(ptable, positions, kv_len, *operands)
    if partial_out:
        acc, m, l = outs
        return acc[:, :Q], m[:, :Q, :, 0], l[:, :Q, :, 0]
    return outs[0][:, :Q]


def _paged_attention_xla(q, k_pool, v_pool, ptable, positions, kv_len,
                         k_scale, v_scale, scale, partial_out, chunk_blocks):
    """The same block walk as the kernel, chunked for XLA: each chunk
    gathers `chunk_blocks` physical blocks and folds them into the online
    softmax. Never materializes the full [B, Nmax*bt] window or repeated
    KV heads — on CPU this beats the gather path on exactly the traffic
    the kernel saves on TPU."""
    b, Q, h, d = q.shape
    _, bt, kv, _ = k_pool.shape
    nmax = ptable.shape[1]
    n_rep = h // kv
    quantized = k_scale is not None
    cb = max(1, min(chunk_blocks, nmax))
    nch = -(-nmax // cb)
    if nch * cb != nmax:
        ptable = jnp.pad(ptable, ((0, 0), (0, nch * cb - nmax)),
                         constant_values=-1)
    qr = (q.astype(jnp.float32) * scale).reshape(b, Q, kv, n_rep, d)
    m = jnp.full((b, Q, h, 1), NEG_INF, jnp.float32)
    l = jnp.zeros((b, Q, h, 1), jnp.float32)
    acc = jnp.zeros((b, Q, h, d), jnp.float32)
    qpos = positions[:, None].astype(jnp.int32) + jnp.arange(Q)[None, :]
    kvl = kv_len.astype(jnp.int32)[:, None, None, None]
    for c in range(nch):
        tb = ptable[:, c * cb:(c + 1) * cb]  # [B, cb]
        idx = jnp.maximum(tb, 0)
        kc = k_pool[idx]  # [B, cb, bt, KV, D]
        vc = v_pool[idx]
        if quantized:
            kc = kc.astype(jnp.float32) * k_scale[idx][:, :, None, :, None]
            vc = vc.astype(jnp.float32) * v_scale[idx][:, :, None, :, None]
        kc = kc.astype(jnp.float32).reshape(b, cb * bt, kv, d)
        vc = vc.astype(jnp.float32).reshape(b, cb * bt, kv, d)
        s = jnp.einsum(
            "bqgnd,btgd->bqgnt", qr, kc, preferred_element_type=jnp.float32
        ).reshape(b, Q, h, cb * bt)
        kpos = c * cb * bt + jnp.arange(cb * bt)
        live = jnp.repeat(tb >= 0, bt, axis=1)[:, None, None, :]
        mask = (
            live
            & (kpos[None, None, None, :] <= qpos[:, :, None, None])
            & (kpos[None, None, None, :] < kvl)
        )
        s = jnp.where(mask, s, NEG_INF)
        m_new = jnp.maximum(m, jnp.max(s, axis=-1, keepdims=True))
        # NEG_INF is finite: a fully-masked row would otherwise see
        # exp(NEG_INF - NEG_INF) = 1 and sum garbage into l/acc
        p = jnp.where(mask, jnp.exp(s - m_new), 0.0)
        alpha = jnp.exp(m - m_new)
        l = alpha * l + jnp.sum(p, axis=-1, keepdims=True)
        pv = jnp.einsum(
            "bqgnt,btgd->bqgnd", p.reshape(b, Q, kv, n_rep, cb * bt), vc,
            preferred_element_type=jnp.float32,
        ).reshape(b, Q, h, d)
        acc = acc * alpha + pv
        m = m_new
    if partial_out:
        return acc, m[..., 0], l[..., 0]
    safe_l = jnp.where(l == 0.0, 1.0, l)
    return (acc / safe_l).astype(q.dtype)


def paged_attention(
    q: jnp.ndarray,        # [B, H, D] one query per slot, or [B, Q, H, D]
    k_pool: jnp.ndarray,   # [N, block_tokens, KV, D] physical blocks
    v_pool: jnp.ndarray,   # [N, block_tokens, KV, D]
    tables: jnp.ndarray,   # [B, Nmax] int32 block table per slot
    positions: jnp.ndarray,  # [B] int32 global position of query 0
    *,
    k_scale: Optional[jnp.ndarray] = None,  # [N, KV] f32 (int8 pools)
    v_scale: Optional[jnp.ndarray] = None,
    scale: Optional[float] = None,
    impl: str = "auto",            # auto | kernel | xla
    interpret: Optional[bool] = None,
    signed_tables: bool = False,   # True: entries < 0 are dead (sharded
                                   # callers pre-remap); False: entry 0 is
                                   # the null-block sentinel
    partial_out: bool = False,     # return (acc, m, l) for cross-shard merge
    chunk_blocks: int = 8,
    kv_len: Optional[jnp.ndarray] = None,  # [B] live cached keys; keys at
                                   # kpos >= kv_len are dead regardless of
                                   # causality (verify: kv_len = positions;
                                   # default positions + Q covers decode
                                   # and prefill, whose own K/V is written)
    block_q: int = 16,             # kernel query-tile size (q axis padded
                                   # to a multiple; XLA handles Q whole)
) -> jnp.ndarray | Tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """Multi-query paged attention over a block pool (module docstring).

    Query i of slot b sits at global position positions[b] + i and
    attends key position t iff t <= positions[b] + i and t < kv_len[b].
    Returns out in q's dtype and shape ([B, H, D] for 3-D q, else
    [B, Q, H, D]), or with `partial_out=True` the unnormalized f32
    (acc, m, l) triple for `merge_partials` (m/l drop the head_dim axis).
    Slots whose table is fully dead return zeros."""
    global _LAST_IMPL
    squeeze = q.ndim == 3
    if squeeze:
        q = q[:, None]
    if (k_scale is None) != (v_scale is None):
        raise ValueError("k_scale and v_scale must be passed together")
    if q.shape[2] % k_pool.shape[2]:
        raise ValueError(
            f"q heads {q.shape[2]} not a multiple of kv heads {k_pool.shape[2]}"
        )
    if impl not in ("auto", "kernel", "xla"):
        raise ValueError(f"impl must be auto|kernel|xla, got {impl!r}")
    if impl == "auto":
        impl = "kernel" if jax.default_backend() == "tpu" else "xla"
    scale = float(scale) if scale is not None else q.shape[-1] ** -0.5
    if signed_tables:
        ptable = tables.astype(jnp.int32)
    else:
        ptable = jnp.where(tables > 0, tables, -1).astype(jnp.int32)
    positions = positions.astype(jnp.int32)
    if kv_len is None:
        kv_len = positions + q.shape[1]
    kv_len = kv_len.astype(jnp.int32)
    _LAST_IMPL = impl
    if impl == "kernel":
        if interpret is None:
            interpret = jax.default_backend() != "tpu"
        out = _paged_attention_pallas(
            q, k_pool, v_pool, ptable, positions, kv_len, k_scale, v_scale,
            scale, partial_out, interpret, block_q,
        )
    else:
        out = _paged_attention_xla(
            q, k_pool, v_pool, ptable, positions, kv_len, k_scale, v_scale,
            scale, partial_out, chunk_blocks,
        )
    if squeeze:
        if partial_out:
            acc, m, l = out
            return acc[:, 0], m[:, 0], l[:, 0]
        return out[:, 0]
    return out


def merge_partials(acc, m, l, axis_names=None, out_dtype=jnp.float32):
    """Combine per-shard online-softmax partials into the final output.

    acc [..., D] unnormalized, m/l [...] (any shared leading shape —
    [B, H] for single-query, [B, Q, H] for multi-query). With
    `axis_names`, the combine runs across those shard_map axes (pmax +
    psum); without, acc/m/l carry a leading shard dim to reduce over.
    Rows with no live keys anywhere (l == 0 everywhere) come out zero,
    mirroring the kernel."""
    if axis_names:
        m_g = lax.pmax(m, axis_names)
        e = jnp.exp(m - m_g)
        num = lax.psum(acc * e[..., None], axis_names)
        den = lax.psum(l * e, axis_names)
    else:
        m_g = jnp.max(m, axis=0)
        e = jnp.exp(m - m_g)
        num = jnp.sum(acc * e[..., None], axis=0)
        den = jnp.sum(l * e, axis=0)
    safe = jnp.where(den == 0.0, 1.0, den)
    return (num / safe[..., None]).astype(out_dtype)
