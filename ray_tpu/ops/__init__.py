"""TPU-first op library: fused normalization, rotary embeddings, attention
(dense / ring / Ulysses), and losses. Pure-jax reference implementations that
XLA fuses well on the MXU, with Pallas fast paths where they pay off."""

from .norm import rms_norm  # noqa: F401
from .rope import apply_rope, rope_frequencies  # noqa: F401
from .attention import causal_attention  # noqa: F401
from .flash_attention import flash_attention  # noqa: F401
from .paged_attention import paged_attention  # noqa: F401
from .ring_attention import ring_attention  # noqa: F401
from .ulysses import ulysses_attention  # noqa: F401
from .losses import softmax_cross_entropy_with_int_labels  # noqa: F401
