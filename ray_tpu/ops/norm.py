"""RMSNorm. Computed in f32 regardless of input dtype (TPU numerics: bf16
accumulation of squares loses ~3 decimal digits), cast back on output; XLA
fuses the whole thing into neighbouring ops so no Pallas kernel is needed."""

from __future__ import annotations

import jax.numpy as jnp


def rms_norm(x: jnp.ndarray, scale: jnp.ndarray, eps: float = 1e-6) -> jnp.ndarray:
    orig_dtype = x.dtype
    x32 = x.astype(jnp.float32)
    var = jnp.mean(x32 * x32, axis=-1, keepdims=True)
    y = x32 * jnp.reciprocal(jnp.sqrt(var + eps))
    return (y * scale.astype(jnp.float32)).astype(orig_dtype)
