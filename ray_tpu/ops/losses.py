"""Losses. Cross-entropy computed blockwise-stable in f32 without
materializing one-hot labels (vocab can be sharded on tp; XLA keeps the
log-softmax fused with the unembed matmul)."""

from __future__ import annotations

import jax
import jax.numpy as jnp


def softmax_cross_entropy_with_int_labels(
    logits: jnp.ndarray,  # [..., vocab]
    labels: jnp.ndarray,  # [...], int
    where=None,  # optional bool mask [...]
):
    """Returns (mean_loss, total_weight)."""
    logits = logits.astype(jnp.float32)
    m = jnp.max(logits, axis=-1, keepdims=True)
    shifted = logits - jax.lax.stop_gradient(m)
    lse = jnp.log(jnp.sum(jnp.exp(shifted), axis=-1)) + m[..., 0]
    label_logits = jnp.take_along_axis(logits, labels[..., None], axis=-1)[..., 0]
    nll = lse - label_logits
    if where is not None:
        w = where.astype(jnp.float32)
        total = jnp.maximum(jnp.sum(w), 1.0)
        return jnp.sum(nll * w) / total, total
    return jnp.mean(nll), jnp.array(nll.size, jnp.float32)
