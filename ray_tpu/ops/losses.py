"""Losses. Cross-entropy computed blockwise-stable in f32 without
materializing one-hot labels (vocab can be sharded on tp; XLA keeps the
log-softmax fused with the unembed matmul).

`blockwise_softmax_cross_entropy` additionally avoids materializing the
full [tokens, vocab] logits tensor: it chunks the sequence axis, computes
each chunk's unembed-matmul + log-softmax under `jax.checkpoint`, and
accumulates scalar (sum_nll, sum_weight) through a `lax.scan`. Backward
recomputes one chunk's logits at a time, so peak HBM for the loss head is
O(chunk * vocab) instead of O(batch * seq * vocab) — at GPT-2 shapes
(16k tokens x 50k vocab f32) that frees ~3 GB of residuals, enough to
raise the train batch on a 16G chip.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax


def _token_nll(logits: jnp.ndarray, labels: jnp.ndarray) -> jnp.ndarray:
    """Per-token negative log-likelihood, computed max-shift-stable in f32.

    The max must be a CONSTANT for grad purposes everywhere it appears: the
    shift cancels in value, and with m fully stop-gradded the gradient is
    exactly (softmax - onehot(label)). Stop-gradding only one occurrence
    leaks a spurious +onehot(argmax) term into the gradient.
    """
    logits = logits.astype(jnp.float32)
    m = lax.stop_gradient(jnp.max(logits, axis=-1, keepdims=True))
    lse = jnp.log(jnp.sum(jnp.exp(logits - m), axis=-1)) + m[..., 0]
    label_logits = jnp.take_along_axis(logits, labels[..., None], axis=-1)[..., 0]
    return lse - label_logits


def softmax_cross_entropy_with_int_labels(
    logits: jnp.ndarray,  # [..., vocab]
    labels: jnp.ndarray,  # [...], int
    where=None,  # optional bool mask [...]
):
    """Returns (mean_loss, total_weight)."""
    nll = _token_nll(logits, labels)
    if where is not None:
        w = where.astype(jnp.float32)
        total = jnp.maximum(jnp.sum(w), 1.0)
        return jnp.sum(nll * w) / total, total
    return jnp.mean(nll), jnp.array(nll.size, jnp.float32)


def blockwise_softmax_cross_entropy(
    x: jnp.ndarray,  # [batch, seq, d_model] final hidden states
    unembed: jnp.ndarray,  # [d_model, vocab]
    labels: jnp.ndarray,  # [batch, seq], int
    where=None,  # optional bool mask [batch, seq]
    chunk: int = 1024,
    constrain_logits=None,  # optional fn applied to each chunk's logits
):
    """Memory-efficient CE over the unembed projection; returns
    (mean_loss, total_weight), numerically identical to projecting the full
    logits and calling `softmax_cross_entropy_with_int_labels`.

    Chunks along the SEQUENCE axis (batch stays the leading, possibly
    dp-sharded axis of every chunk, so GSPMD layouts are undisturbed).
    """
    b, s, _ = x.shape
    chunk = min(chunk, s)
    n = -(-s // chunk)
    pad = n * chunk - s
    w = (
        jnp.ones((b, s), jnp.float32)
        if where is None
        else where.astype(jnp.float32)
    )
    if pad:
        x = jnp.pad(x, ((0, 0), (0, pad), (0, 0)))
        labels = jnp.pad(labels, ((0, 0), (0, pad)))
        w = jnp.pad(w, ((0, 0), (0, pad)))  # zero weight: padding never counts
    # [b, n, c, ...] -> scan-major [n, b, c, ...]
    xs = x.reshape(b, n, chunk, x.shape[-1]).transpose(1, 0, 2, 3)
    ls = labels.reshape(b, n, chunk).transpose(1, 0, 2)
    ws = w.reshape(b, n, chunk).transpose(1, 0, 2)

    def body(carry, inp):
        x_c, l_c, w_c = inp
        logits = jnp.einsum("bsd,dv->bsv", x_c, unembed)
        if constrain_logits is not None:
            logits = constrain_logits(logits)
        nll = _token_nll(logits, l_c)
        s_nll, s_w = carry
        return (s_nll + jnp.sum(nll * w_c), s_w + jnp.sum(w_c)), None

    zero = jnp.zeros((), jnp.float32)
    (sum_nll, sum_w), _ = lax.scan(jax.checkpoint(body), (zero, zero), (xs, ls, ws))
    total = jnp.maximum(sum_w, 1.0)
    return sum_nll / total, total
