"""Dense causal attention with GQA.

The einsum formulation keeps both matmuls on the MXU with a single fused
softmax between them; logits accumulate in f32. For long sequences use
ring_attention (sequence-parallel) — this kernel materializes [B,H,L,L]
scores and is intended for L up to a few thousand per shard.
"""

from __future__ import annotations

from typing import Optional

import jax.numpy as jnp

NEG_INF = -1e30


def _repeat_kv(k: jnp.ndarray, n_rep: int) -> jnp.ndarray:
    if n_rep == 1:
        return k
    b, s, h, d = k.shape
    return jnp.broadcast_to(k[:, :, :, None, :], (b, s, h, n_rep, d)).reshape(b, s, h * n_rep, d)


def _repeat_kv_bhsd(k: jnp.ndarray, n_rep: int) -> jnp.ndarray:
    if n_rep == 1:
        return k
    b, h, s, d = k.shape
    return jnp.broadcast_to(k[:, :, None, :, :], (b, h, n_rep, s, d)).reshape(b, h * n_rep, s, d)


def causal_attention_bhsd(
    q: jnp.ndarray,  # [B, H, Lq, D]
    k: jnp.ndarray,  # [B, Hkv, Lk, D]
    v: jnp.ndarray,  # [B, Hkv, Lk, D]
    *,
    scale: Optional[float] = None,
    causal: bool = True,
    q_offset: int = 0,
) -> jnp.ndarray:
    """Head-major dense attention: same math as causal_attention but the
    whole computation stays in [B, H, L, D], the layout the MXU and the
    Pallas kernels want — no relayout transposes on the hot path."""
    scale = scale if scale is not None else q.shape[-1] ** -0.5
    k = _repeat_kv_bhsd(k, q.shape[1] // k.shape[1])
    v = _repeat_kv_bhsd(v, q.shape[1] // v.shape[1])
    logits = jnp.einsum("bhqd,bhkd->bhqk", q, k, preferred_element_type=jnp.float32)
    logits = logits * scale
    if causal:
        lq, lk = q.shape[2], k.shape[2]
        qpos = jnp.arange(lq)[:, None] + q_offset
        kpos = jnp.arange(lk)[None, :]
        logits = jnp.where(qpos >= kpos, logits, NEG_INF)
    probs = jnp.exp(logits - jnp.max(logits, axis=-1, keepdims=True))
    probs = probs / jnp.sum(probs, axis=-1, keepdims=True)
    return jnp.einsum("bhqk,bhkd->bhqd", probs.astype(v.dtype), v)


def causal_attention(
    q: jnp.ndarray,  # [B, Lq, H, D]
    k: jnp.ndarray,  # [B, Lk, Hkv, D]
    v: jnp.ndarray,  # [B, Lk, Hkv, D]
    *,
    scale: Optional[float] = None,
    causal: bool = True,
    q_offset: int = 0,
    segment_ids: Optional[jnp.ndarray] = None,
) -> jnp.ndarray:
    """q_offset: global position of q[0] relative to k[0] (for decode steps
    and sequence-parallel blocks)."""
    scale = scale if scale is not None else q.shape[-1] ** -0.5
    n_rep = q.shape[2] // k.shape[2]
    k = _repeat_kv(k, n_rep)
    v = _repeat_kv(v, n_rep)
    logits = jnp.einsum("bqhd,bkhd->bhqk", q, k, preferred_element_type=jnp.float32)
    logits = logits * scale
    if causal:
        lq, lk = q.shape[1], k.shape[1]
        qpos = jnp.arange(lq)[:, None] + q_offset
        kpos = jnp.arange(lk)[None, :]
        logits = jnp.where(qpos >= kpos, logits, NEG_INF)
    if segment_ids is not None:
        mask = segment_ids[:, None, :, None] == segment_ids[:, None, None, :]
        logits = jnp.where(mask, logits, NEG_INF)
    probs = jnp.exp(logits - jnp.max(logits, axis=-1, keepdims=True))
    probs = probs / jnp.sum(probs, axis=-1, keepdims=True)
    out = jnp.einsum("bhqk,bkhd->bqhd", probs.astype(v.dtype), v)
    return out
