"""Job submission: run an entrypoint command against a live cluster.

Reference parity: python/ray/dashboard/modules/job/ (JobSubmissionClient
sdk.py, JobStatus, job_manager.py JobSupervisor). A submitted job is a
shell entrypoint spawned by the head with RAY_TPU_ADDRESS pointing at the
cluster, so `ray_tpu.init(address="auto")` inside the job attaches to the
SAME cluster; stdout/stderr stream to a per-job log in the session dir.
"""

from __future__ import annotations

import enum
import time
from typing import Any, Dict, List, Optional


class JobStatus(str, enum.Enum):
    PENDING = "PENDING"
    RUNNING = "RUNNING"
    SUCCEEDED = "SUCCEEDED"
    FAILED = "FAILED"
    STOPPED = "STOPPED"

    def is_terminal(self) -> bool:
        return self in (JobStatus.SUCCEEDED, JobStatus.FAILED, JobStatus.STOPPED)


class JobSubmissionClient:
    """Submits/inspects jobs. With no address, uses the current driver's
    connection (ray_tpu.init must have run); with address, attaches to that
    head socket ('auto' = newest live session)."""

    def __init__(self, address: Optional[str] = None):
        import ray_tpu
        from ray_tpu._private.worker import global_worker

        if not global_worker.connected:
            ray_tpu.init(address=address or "auto")
        self._worker = global_worker

    def _request(self, msg: dict) -> Any:
        return self._worker.request(msg)

    def submit_job(
        self,
        *,
        entrypoint: str,
        runtime_env: Optional[dict] = None,
        submission_id: Optional[str] = None,
        metadata: Optional[Dict[str, str]] = None,
    ) -> str:
        from ..runtime_env import RuntimeEnv

        return self._request(
            {
                "t": "submit_job",
                "entrypoint": entrypoint,
                "runtime_env": RuntimeEnv.validate(runtime_env),
                "submission_id": submission_id,
                "metadata": metadata,
            }
        )

    def get_job_status(self, submission_id: str) -> JobStatus:
        return JobStatus(self._request({"t": "job_status", "submission_id": submission_id}))

    def get_job_info(self, submission_id: str) -> dict:
        return self._request({"t": "job_info", "submission_id": submission_id})

    def get_job_logs(self, submission_id: str) -> str:
        return self._request({"t": "job_logs", "submission_id": submission_id})

    def list_jobs(self) -> List[dict]:
        return self._request({"t": "list_jobs"})

    def stop_job(self, submission_id: str) -> bool:
        return self._request({"t": "stop_job", "submission_id": submission_id})

    def wait_until_status(
        self,
        submission_id: str,
        statuses=None,
        timeout: float = 120.0,
    ) -> JobStatus:
        """Poll until the job reaches a terminal (or given) status."""
        deadline = time.monotonic() + timeout
        while True:
            status = self.get_job_status(submission_id)
            if statuses is not None:
                if status in statuses:
                    return status
            elif status.is_terminal():
                return status
            if time.monotonic() > deadline:
                raise TimeoutError(f"job {submission_id} still {status} after {timeout}s")
            time.sleep(0.2)
