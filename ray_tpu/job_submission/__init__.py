"""Job submission: run an entrypoint command against a live cluster.

Reference parity: python/ray/dashboard/modules/job/ (JobSubmissionClient
sdk.py, JobStatus, job_manager.py JobSupervisor, job_head.py REST routes).
A submitted job is a shell entrypoint spawned by the head with
RAY_TPU_ADDRESS pointing at the cluster, so `ray_tpu.init(address="auto")`
inside the job attaches to the SAME cluster; stdout/stderr stream to a
per-job log in the session dir.

Two transports, same client API (mirrors the reference, whose SDK always
speaks HTTP to the dashboard):
- native: pickle protocol over the head socket (address=None/'auto'/socket)
- HTTP:   the dashboard's /api/jobs/ REST routes (address='http://host:port')
  — with automatic working-dir zip upload (PUT /api/packages/pkg/<name>),
  matching job_head.py:140,273 + packaging upload semantics.
"""

from __future__ import annotations

import enum
import time
from typing import Any, Dict, List, Optional


class JobStatus(str, enum.Enum):
    PENDING = "PENDING"
    RUNNING = "RUNNING"
    SUCCEEDED = "SUCCEEDED"
    FAILED = "FAILED"
    STOPPED = "STOPPED"

    def is_terminal(self) -> bool:
        return self in (JobStatus.SUCCEEDED, JobStatus.FAILED, JobStatus.STOPPED)


class _HttpBackend:
    """Speaks the dashboard's Job REST API with only stdlib http.client."""

    def __init__(self, address: str):
        from urllib.parse import urlparse

        parsed = urlparse(address)
        if parsed.scheme not in ("http", ""):
            raise ValueError(f"unsupported scheme {parsed.scheme!r} (http only)")
        netloc = parsed.netloc or parsed.path  # tolerate 'host:port' w/o scheme
        host, _, port = netloc.partition(":")
        self.host, self.port = host, int(port or 80)

    def _http(self, method: str, path: str, body: Optional[bytes] = None,
              ctype: str = "application/json") -> tuple:
        import http.client
        import json

        conn = http.client.HTTPConnection(self.host, self.port, timeout=120)
        try:
            headers = {"Content-Type": ctype} if body is not None else {}
            conn.request(method, path, body=body, headers=headers)
            resp = conn.getresponse()
            raw = resp.read()
            try:
                data = json.loads(raw) if raw else None
            except ValueError:
                data = raw.decode(errors="replace")
            return resp.status, data
        finally:
            conn.close()

    def _ok(self, method: str, path: str, body: Optional[bytes] = None) -> Any:
        status, data = self._http(method, path, body)
        if status >= 400:
            err = data.get("error") if isinstance(data, dict) else data
            raise RuntimeError(f"{method} {path} -> {status}: {err}")
        return data

    # never shipped in a working-dir package (reference: packaging.py
    # always-excluded patterns + user `excludes`)
    _DEFAULT_EXCLUDES = (".git", "__pycache__", ".venv", "*.pyc")

    def _upload_working_dir(self, working_dir: str, excludes=()) -> str:
        """Zip a local directory and upload it; return its pkg:// URI.
        Content-hashed name so identical dirs dedupe (reference:
        packaging.py get_uri_for_directory + upload_package_if_needed)."""
        import fnmatch
        import hashlib
        import io
        import os
        import zipfile

        patterns = list(self._DEFAULT_EXCLUDES) + list(excludes)

        def _excluded(rel: str) -> bool:
            parts = rel.split(os.sep)
            return any(
                fnmatch.fnmatch(part, pat) or fnmatch.fnmatch(rel, pat)
                for part in parts
                for pat in patterns
            )

        h = hashlib.sha1()
        buf = io.BytesIO()
        with zipfile.ZipFile(buf, "w", zipfile.ZIP_DEFLATED) as zf:
            for root, dirs, files in os.walk(working_dir):
                dirs[:] = sorted(
                    d for d in dirs
                    if not _excluded(os.path.relpath(os.path.join(root, d), working_dir))
                )
                for fname in sorted(files):
                    p = os.path.join(root, fname)
                    rel = os.path.relpath(p, working_dir)
                    if _excluded(rel):
                        continue
                    with open(p, "rb") as f:
                        data = f.read()
                    h.update(rel.encode())
                    h.update(data)
                    # fixed timestamp -> deterministic zip for the same tree
                    info = zipfile.ZipInfo(rel, date_time=(2020, 1, 1, 0, 0, 0))
                    zf.writestr(info, data)
        name = f"ray-pkg-{h.hexdigest()[:20]}.zip"
        status, _ = self._http("GET", f"/api/packages/pkg/{name}")
        if status != 200:
            self._ok("PUT", f"/api/packages/pkg/{name}", buf.getvalue())
        return f"pkg://{name}"

    def submit(self, entrypoint, runtime_env, submission_id, metadata) -> str:
        import json
        import os

        runtime_env = dict(runtime_env or {})
        wd = runtime_env.get("working_dir")
        excludes = runtime_env.pop("excludes", ())
        if wd and not str(wd).startswith("pkg://"):
            if not os.path.isdir(wd):
                raise ValueError(f"working_dir {wd!r} is not a directory")
            runtime_env["working_dir"] = self._upload_working_dir(wd, excludes)
        body = json.dumps(
            {
                "entrypoint": entrypoint,
                "runtime_env": runtime_env,
                "submission_id": submission_id,
                "metadata": metadata,
            }
        ).encode()
        return self._ok("POST", "/api/jobs/", body)["submission_id"]

    def status(self, sid: str) -> str:
        return self._ok("GET", f"/api/jobs/{sid}")["status"]

    def info(self, sid: str) -> dict:
        return self._ok("GET", f"/api/jobs/{sid}")

    def logs(self, sid: str) -> str:
        return self._ok("GET", f"/api/jobs/{sid}/logs")["logs"]

    def list(self) -> List[dict]:
        return self._ok("GET", "/api/jobs/")

    def stop(self, sid: str) -> bool:
        return self._ok("POST", f"/api/jobs/{sid}/stop")["stopped"]

    def delete(self, sid: str) -> bool:
        return self._ok("DELETE", f"/api/jobs/{sid}")["deleted"]


class _NativeBackend:
    """Head-socket pickle protocol (in-process driver connection)."""

    def __init__(self, address: Optional[str]):
        import ray_tpu
        from ray_tpu._private.worker import global_worker

        if not global_worker.connected:
            ray_tpu.init(address=address or "auto")
        self._worker = global_worker

    def _request(self, msg: dict) -> Any:
        return self._worker.request(msg)

    def submit(self, entrypoint, runtime_env, submission_id, metadata) -> str:
        from ..runtime_env import RuntimeEnv

        runtime_env = dict(runtime_env or {})
        # 'excludes' only shapes the HTTP upload zip; the native path stages
        # the directory in place — accept and ignore it so the same
        # submit_job call works on both transports
        runtime_env.pop("excludes", None)
        return self._request(
            {
                "t": "submit_job",
                "entrypoint": entrypoint,
                "runtime_env": RuntimeEnv.validate(runtime_env),
                "submission_id": submission_id,
                "metadata": metadata,
            }
        )

    def status(self, sid: str) -> str:
        return self._request({"t": "job_status", "submission_id": sid})

    def info(self, sid: str) -> dict:
        return self._request({"t": "job_info", "submission_id": sid})

    def logs(self, sid: str) -> str:
        return self._request({"t": "job_logs", "submission_id": sid})

    def list(self) -> List[dict]:
        return self._request({"t": "list_jobs"})

    def stop(self, sid: str) -> bool:
        return self._request({"t": "stop_job", "submission_id": sid})

    def delete(self, sid: str) -> bool:
        return self._request({"t": "delete_job", "submission_id": sid})


class JobSubmissionClient:
    """Submits/inspects jobs.

    address=None/'auto'/<socket path>: native head-socket transport using the
    current driver connection (ray_tpu.init runs if needed).
    address='http://host:port': the dashboard's REST API — usable from a
    process with no cluster connection at all, like the reference SDK.
    """

    def __init__(self, address: Optional[str] = None):
        if address is not None and str(address).startswith("http"):
            self._backend = _HttpBackend(address)
        else:
            self._backend = _NativeBackend(address)

    def submit_job(
        self,
        *,
        entrypoint: str,
        runtime_env: Optional[dict] = None,
        submission_id: Optional[str] = None,
        metadata: Optional[Dict[str, str]] = None,
    ) -> str:
        return self._backend.submit(entrypoint, runtime_env, submission_id, metadata)

    def get_job_status(self, submission_id: str) -> JobStatus:
        return JobStatus(self._backend.status(submission_id))

    def get_job_info(self, submission_id: str) -> dict:
        return self._backend.info(submission_id)

    def get_job_logs(self, submission_id: str) -> str:
        return self._backend.logs(submission_id)

    def list_jobs(self) -> List[dict]:
        return self._backend.list()

    def stop_job(self, submission_id: str) -> bool:
        return self._backend.stop(submission_id)

    def delete_job(self, submission_id: str) -> bool:
        return self._backend.delete(submission_id)

    def wait_until_status(
        self,
        submission_id: str,
        statuses=None,
        timeout: float = 120.0,
    ) -> JobStatus:
        """Poll until the job reaches a terminal (or given) status."""
        deadline = time.monotonic() + timeout
        while True:
            status = self.get_job_status(submission_id)
            if statuses is not None:
                if status in statuses:
                    return status
            elif status.is_terminal():
                return status
            if time.monotonic() > deadline:
                raise TimeoutError(f"job {submission_id} still {status} after {timeout}s")
            time.sleep(0.2)
