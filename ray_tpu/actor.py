"""Actor classes and handles.

Reference parity: python/ray/actor.py (ActorClass.options/._remote,
ActorMethod._remote → submit_actor_task; max_restarts plumbed like
actor.py:332-351).
"""

from __future__ import annotations

import inspect
from typing import Any, Dict, Optional

from ._private.options import resolve_task_resources, validate_options
from .remote_function import _strategy_to_wire, _validated_runtime_env


class ActorMethod:
    def __init__(self, handle: "ActorHandle", method_name: str, num_returns: int = 1):
        self._handle = handle
        self._method_name = method_name
        self._num_returns = num_returns

    def __call__(self, *args, **kwargs):
        raise TypeError(
            f"Actor methods cannot be called directly. Use "
            f"actor.{self._method_name}.remote() instead."
        )

    def options(self, num_returns: int = 1, **_):
        return ActorMethod(self._handle, self._method_name, num_returns)

    def remote(self, *args, **kwargs):
        from ._private.worker import global_worker

        if isinstance(self._num_returns, str):
            raise ValueError(
                "streaming/dynamic generator returns are supported for tasks "
                "only, not actor methods"
            )
        refs = global_worker.submit_actor_task(
            self._handle._actor_id,
            self._method_name,
            args,
            kwargs,
            num_returns=self._num_returns,
        )
        if self._num_returns == 1:
            return refs[0]
        return refs

    @property
    def bind(self):
        from .dag.class_node import bind_method

        import functools

        return functools.partial(bind_method, self._handle, self._method_name)


class ActorHandle:
    def __init__(self, actor_id: str, method_names=None, class_name: str = ""):
        self._actor_id = actor_id
        self._method_names = set(method_names or [])
        self._class_name = class_name

    def __getattr__(self, name: str):
        if name.startswith("_"):
            raise AttributeError(name)
        if self._method_names and name not in self._method_names:
            raise AttributeError(
                f"Actor {self._class_name or self._actor_id} has no method {name!r}"
            )
        return ActorMethod(self, name)

    def __repr__(self):
        return f"ActorHandle({self._class_name}, {self._actor_id[:12]})"

    def __reduce__(self):
        return (ActorHandle, (self._actor_id, tuple(self._method_names), self._class_name))

    def _state(self) -> Optional[str]:
        from ._private.worker import global_worker

        return global_worker.request({"t": "actor_state", "actor_id": self._actor_id})


class ActorClass:
    def __init__(self, cls, **default_options):
        self._cls = cls
        self._default_options = validate_options(default_options)

    def __call__(self, *args, **kwargs):
        raise TypeError(
            f"Actors cannot be instantiated directly. "
            f"Use {self._cls.__name__}.remote() instead."
        )

    def options(self, **actor_options) -> "ActorClass":
        opts = dict(self._default_options)
        opts.update(actor_options)
        return ActorClass(self._cls, **opts)

    def remote(self, *args, **kwargs) -> ActorHandle:
        from ._private.worker import global_worker

        opts = self._default_options
        actor_id = global_worker.create_actor(
            self._cls,
            args,
            kwargs,
            name=opts.get("name"),
            namespace=opts.get("namespace"),
            resources=resolve_task_resources(opts, is_actor=True),
            max_restarts=opts.get("max_restarts", 0),
            max_concurrency=opts.get("max_concurrency", 1),
            scheduling_strategy=_strategy_to_wire(opts.get("scheduling_strategy")),
            lifetime=opts.get("lifetime"),
            runtime_env=_validated_runtime_env(opts.get("runtime_env")),
        )
        return ActorHandle(actor_id, self._method_names(), self._cls.__name__)

    def _method_names(self):
        return [
            n
            for n, m in inspect.getmembers(self._cls, predicate=callable)
            if not n.startswith("__")
        ] + ["__ray_terminate__"]

    @property
    def bind(self):
        from .dag.class_node import bind_class

        import functools

        return functools.partial(bind_class, self)
