"""Shared real-text drafter measurement (bench.py + microbench.py).

One implementation of the drive loop both bench surfaces report: load a
hub checkpoint, run the n-gram drafter over tokenizer-encoded English
prompts through a speculative PagedDecodeEngine, and return the measured
accept rate with the model's identity. MEASURED, never asserted —
drafter yield on real text is a property of the model's output
distribution, and the whole point of the row is to observe it
(ROADMAP item 1 / PR 7's open question).

Raises on missing/unreadable checkpoints; callers choose their own
degradation (bench rows fall back to a "synthetic" identity).
"""

from __future__ import annotations

import json
import os
from typing import Dict, List, Optional

_DEFAULT_PROMPTS = [
    "The quick brown fox jumps over the lazy dog.",
    "In the morning the sun was shining over the hills.",
]


def measure_realtext_spec(
    path: str,
    k: int = 4,
    new_tokens: int = 48,
    prompts: Optional[List[str]] = None,
) -> Dict[str, object]:
    """Returns {model_id, params_source, spec_accept_rate,
    spec_tokens_per_step} for the checkpoint directory at `path` (its
    reference.json supplies the prompt set when present)."""
    from ..kv_paging import PagedDecodeEngine
    from .checkpoint import load_model

    bundle = load_model(path)
    if prompts is None:
        ref_path = os.path.join(path, "reference.json")
        if os.path.exists(ref_path):
            with open(ref_path, encoding="utf-8") as f:
                prompts = json.load(f)["prompts"]
        else:
            prompts = _DEFAULT_PROMPTS
    eng = PagedDecodeEngine(
        bundle.cfg, bundle.params, max_batch_size=1, seed=0,
        eos_id=bundle.eos_id, speculative_k=k, drafter="ngram",
    )
    eng.warmup_verify()
    for text in prompts:
        ids = bundle.tokenizer.encode(text)
        _, done = eng.admit(0, {"tokens": ids, "max_new_tokens": new_tokens})
        while not done:
            (_, done), = eng.step([0]).values()
        eng.release(0)
    stats = eng.stats()
    return {
        "model_id": bundle.model_id,
        "params_source": bundle.params_source,
        "spec_accept_rate": stats["spec_accept_rate"],
        "spec_tokens_per_step": stats["spec_tokens_per_step"],
    }
