"""Minimal safetensors I/O: lazy per-tensor mmap reads, no torch.

Format (https://github.com/huggingface/safetensors, stable since 0.3):

    [8 bytes] little-endian u64 N = header length
    [N bytes] JSON header: {tensor_name: {"dtype": "F32", "shape": [..],
              "data_offsets": [begin, end]}, ...} plus an optional
              "__metadata__" str->str dict
    [  ...  ] tensor data, offsets relative to the end of the header

The reader maps the file once (`mmap`, read-only) and materializes ONE
tensor per `tensor()` call as a numpy array viewing the mapped pages —
the OS pages in only the bytes actually touched, so loading a sharded
model reads each shard's bytes once and never the whole file into an
anonymous buffer. This is the property the checkpoint loader builds on:
transform + device_put one tensor at a time, peak host memory stays
O(largest tensor), not O(model).

The writer exists for fixture generation and round-trip tests; it writes
the same layout the reference implementation produces (sorted keys,
contiguous C-order data).
"""

from __future__ import annotations

import json
import mmap
import struct
from typing import Any, Dict, Iterator, List, Optional, Tuple

import numpy as np

# safetensors dtype tag <-> numpy dtype. BF16 needs ml_dtypes (jax ships
# it); resolved lazily so pure-f32 files work even without it.
_DTYPES: Dict[str, Any] = {
    "F64": np.float64, "F32": np.float32, "F16": np.float16,
    "I64": np.int64, "I32": np.int32, "I16": np.int16, "I8": np.int8,
    "U64": np.uint64, "U32": np.uint32, "U16": np.uint16, "U8": np.uint8,
    "BOOL": np.bool_,
}


def _np_dtype(tag: str) -> np.dtype:
    if tag == "BF16":
        import ml_dtypes

        return np.dtype(ml_dtypes.bfloat16)
    try:
        return np.dtype(_DTYPES[tag])
    except KeyError:
        raise ValueError(f"unsupported safetensors dtype {tag!r}") from None


def _tag_for(dtype: np.dtype) -> str:
    dtype = np.dtype(dtype)
    if dtype.name == "bfloat16":
        return "BF16"
    for tag, np_t in _DTYPES.items():
        if np.dtype(np_t) == dtype:
            return tag
    raise ValueError(f"unsupported numpy dtype {dtype!r}")


class SafetensorsFile:
    """Lazy reader over one .safetensors file.

    Usage:
        with SafetensorsFile(path) as f:
            for name in f.keys():
                arr = f.tensor(name)        # np view onto the mmap
                ...                         # copy/transform before close

    `tensor()` returns a READ-ONLY array viewing the mapped file; callers
    that outlive the file (or need to mutate) must copy. `np.ascontiguousarray`
    / any arithmetic already copies, which is what the checkpoint mapper's
    transforms do anyway.
    """

    def __init__(self, path: str):
        self.path = str(path)
        self._f = open(self.path, "rb")
        try:
            head = self._f.read(8)
            if len(head) != 8:
                raise ValueError(f"{path}: truncated safetensors header")
            (n,) = struct.unpack("<Q", head)
            # guard before allocating: a corrupt length must not OOM
            if n > 100 * (1 << 20):
                raise ValueError(f"{path}: implausible header length {n}")
            try:
                header = json.loads(self._f.read(n))
            except json.JSONDecodeError as e:
                raise ValueError(f"{path}: malformed safetensors header: {e}")
            self.metadata: Dict[str, str] = header.pop("__metadata__", {}) or {}
            self._entries: Dict[str, Dict[str, Any]] = header
            self._data_start = 8 + n
            self._mm = mmap.mmap(
                self._f.fileno(), 0, access=mmap.ACCESS_READ
            )
        except Exception:
            self._f.close()
            raise

    # ------------------------------------------------------------- contents

    def keys(self) -> List[str]:
        return list(self._entries.keys())

    def __contains__(self, name: str) -> bool:
        return name in self._entries

    def __iter__(self) -> Iterator[str]:
        return iter(self._entries)

    def shape(self, name: str) -> Tuple[int, ...]:
        return tuple(self._entries[name]["shape"])

    def dtype(self, name: str) -> np.dtype:
        return _np_dtype(self._entries[name]["dtype"])

    def nbytes(self, name: str) -> int:
        b, e = self._entries[name]["data_offsets"]
        return int(e) - int(b)

    def tensor(self, name: str) -> np.ndarray:
        """One tensor as a read-only numpy view onto the mapped file —
        only these pages fault in; nothing else is read."""
        ent = self._entries.get(name)
        if ent is None:
            raise KeyError(
                f"{self.path}: no tensor {name!r} "
                f"(has {sorted(self._entries)[:8]}...)"
            )
        dtype = _np_dtype(ent["dtype"])
        shape = tuple(ent["shape"])
        begin, end = (int(x) for x in ent["data_offsets"])
        expect = int(np.prod(shape, dtype=np.int64)) * dtype.itemsize
        if end - begin != expect:
            raise ValueError(
                f"{self.path}: tensor {name!r} spans {end - begin} bytes, "
                f"shape {shape} x {dtype} needs {expect}"
            )
        # offsets are relative to the data section: negative or
        # past-the-end values would silently reinterpret header bytes (or
        # nothing) as weights via the whole-file mmap
        data_len = len(self._mm) - self._data_start
        if not 0 <= begin <= end <= data_len:
            raise ValueError(
                f"{self.path}: tensor {name!r} offsets [{begin}, {end}] "
                f"fall outside the {data_len}-byte data section"
            )
        arr = np.frombuffer(
            self._mm, dtype=dtype, count=expect // dtype.itemsize,
            offset=self._data_start + begin,
        ).reshape(shape)
        arr.flags.writeable = False
        return arr

    # ------------------------------------------------------------ lifecycle

    def close(self) -> None:
        try:
            self._mm.close()
        except BufferError:
            # live tensor() views still reference the mapping: leave it to
            # die with them (the OS mapping outlives the fd close below)
            pass
        finally:
            self._f.close()

    def __enter__(self) -> "SafetensorsFile":
        return self

    def __exit__(self, *exc) -> None:
        self.close()


def load_file(path: str) -> Dict[str, np.ndarray]:
    """Eager convenience: every tensor COPIED out (safe after close).
    Prefer SafetensorsFile + per-tensor reads for anything model-sized."""
    with SafetensorsFile(path) as f:
        return {k: np.array(f.tensor(k)) for k in f.keys()}


def save_file(
    tensors: Dict[str, np.ndarray],
    path: str,
    metadata: Optional[Dict[str, str]] = None,
) -> None:
    """Write `tensors` in safetensors layout (sorted names, C-order)."""
    header: Dict[str, Any] = {}
    if metadata:
        header["__metadata__"] = {str(k): str(v) for k, v in metadata.items()}
    arrays: List[np.ndarray] = []
    offset = 0
    for name in sorted(tensors):
        arr = np.ascontiguousarray(tensors[name])
        header[name] = {
            "dtype": _tag_for(arr.dtype),
            "shape": list(arr.shape),
            "data_offsets": [offset, offset + arr.nbytes],
        }
        arrays.append(arr)
        offset += arr.nbytes
    payload = json.dumps(header, separators=(",", ":")).encode()
    with open(path, "wb") as f:
        f.write(struct.pack("<Q", len(payload)))
        f.write(payload)
        for arr in arrays:
            f.write(arr.tobytes())
