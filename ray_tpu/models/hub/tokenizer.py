"""GPT-2-family byte-level BPE tokenizer (vocab.json + merges.txt).

Byte-level BPE never fails on input: text is UTF-8-encoded to bytes,
bytes map 1:1 to 256 printable unicode "byte tokens" (the GPT-2 table —
control/whitespace bytes are remapped above U+0100 so vocab/merges files
stay readable), and BPE merges only ever combine those. Every merge
learned on English text therefore starts from the same 256-symbol base
alphabet; the classic GPT-2 quirk that words carry their LEADING SPACE
("Ġthe" = " the") falls out of the pre-tokenizer keeping the space
attached to the following word.

Streaming: one token is NOT one unicode character — a multi-byte UTF-8
sequence (emoji, CJK) routinely splits across tokens, so decoding tokens
independently yields mojibake. `IncrementalDetokenizer` feeds token bytes
through an incremental UTF-8 decoder that holds back incomplete tail
sequences; the SSE path emits exactly the complete characters available
so far and flushes the remainder (replacement-charred if truly invalid)
at end of stream.

No external deps: the exact GPT-2 pre-tokenizer pattern needs the
`regex` module for \\p{L}/\\p{N}; when unavailable we fall back to an
`re`-based approximation ([^\\W\\d_] for letters, \\d for digits) that
agrees with it on ASCII + most scripts. Fixture reference encodings are
generated and checked with the SAME implementation, so tests are
self-consistent either way.
"""

from __future__ import annotations

import codecs
import functools
import json
import os
from typing import Dict, Iterable, List, Optional, Sequence, Tuple

_GPT2_SPLIT = (
    r"'s|'t|'re|'ve|'m|'ll|'d"
    r"| ?\p{L}+| ?\p{N}+| ?[^\s\p{L}\p{N}]+|\s+(?!\S)|\s+"
)


def _compile_split():
    try:
        import regex

        return regex.compile(_GPT2_SPLIT)
    except ImportError:
        import re

        # \p{L} ~ [^\W\d_] under re.UNICODE; \p{N} ~ \d — close enough
        # for the scripts the fixtures cover, and self-consistent with
        # the fixture generator (which uses the same fallback). The
        # punctuation class must include "_" explicitly: GPT-2's
        # [^\s\p{L}\p{N}] treats it as punctuation, but _ is \w in re —
        # a bare [^\s\w] would DROP underscores from the input (findall
        # skips unmatched chars), and no input may ever be dropped.
        return re.compile(
            r"'s|'t|'re|'ve|'m|'ll|'d"
            r"| ?[^\W\d_]+| ?\d+| ?(?:[^\s\w]|_)+|\s+(?!\S)|\s+"
        )


@functools.lru_cache()
def bytes_to_unicode() -> Dict[int, str]:
    """The GPT-2 byte<->unicode table: printable latin-1 bytes map to
    themselves, the rest shift above U+0100 — a bijection over all 256
    byte values whose images are all printable (so vocab.json and
    merges.txt are plain readable text files)."""
    bs = (
        list(range(ord("!"), ord("~") + 1))
        + list(range(ord("\xa1"), ord("\xac") + 1))
        + list(range(ord("\xae"), ord("\xff") + 1))
    )
    cs = bs[:]
    n = 0
    for b in range(256):
        if b not in bs:
            bs.append(b)
            cs.append(256 + n)
            n += 1
    return dict(zip(bs, (chr(c) for c in cs)))


def _get_pairs(word: Tuple[str, ...]):
    return {(word[i], word[i + 1]) for i in range(len(word) - 1)}


class ByteBPETokenizer:
    """vocab.json (token string -> id) + merges.txt (rank-ordered pairs).

    Special tokens (e.g. "<|endoftext|>") are matched as literal spans
    BEFORE pre-tokenization, so their text never byte-encodes; any vocab
    entry shaped like <|...|> is auto-registered."""

    def __init__(
        self,
        vocab: Dict[str, int],
        merges: Sequence[Tuple[str, str]],
        special_tokens: Optional[Iterable[str]] = None,
    ):
        self.encoder: Dict[str, int] = dict(vocab)
        self.decoder: Dict[int, str] = {v: k for k, v in self.encoder.items()}
        if len(self.decoder) != len(self.encoder):
            raise ValueError("vocab maps two tokens to one id")
        self.bpe_ranks: Dict[Tuple[str, str], int] = {
            tuple(m): i for i, m in enumerate(merges)
        }
        self.byte_encoder = bytes_to_unicode()
        self.byte_decoder = {v: k for k, v in self.byte_encoder.items()}
        specials = set(special_tokens or ())
        specials.update(
            t for t in self.encoder
            if t.startswith("<|") and t.endswith("|>")
        )
        unknown = sorted(t for t in specials if t not in self.encoder)
        if unknown:
            raise ValueError(f"special tokens not in vocab: {unknown}")
        # longest-first so overlapping specials match greedily
        self.special_tokens: List[str] = sorted(specials, key=len, reverse=True)
        self._special_ids = {self.encoder[t] for t in self.special_tokens}
        self._split = _compile_split()
        self._cache: Dict[str, List[str]] = {}
        self.eos_token = (
            "<|endoftext|>" if "<|endoftext|>" in self.encoder else None
        )
        self.eos_id = (
            self.encoder[self.eos_token] if self.eos_token is not None else None
        )

    # -------------------------------------------------------------- loading

    @classmethod
    def from_files(
        cls,
        vocab_path: str,
        merges_path: str,
        special_tokens: Optional[Iterable[str]] = None,
    ) -> "ByteBPETokenizer":
        with open(vocab_path, encoding="utf-8") as f:
            vocab = json.load(f)
        merges: List[Tuple[str, str]] = []
        with open(merges_path, encoding="utf-8") as f:
            for i, line in enumerate(f):
                line = line.rstrip("\n")
                # ONLY the first line may be the "#version: ..." header:
                # '#' is a legitimate merge symbol ("# #" -> "##" in real
                # gpt2 vocabularies), so a blanket comment skip would
                # silently drop merges and break tokenization parity
                if not line or (i == 0 and line.startswith("#version")):
                    continue
                a, _, b = line.partition(" ")
                if not b:
                    raise ValueError(f"malformed merge line {line!r}")
                merges.append((a, b))
        return cls(vocab, merges, special_tokens)

    @classmethod
    def from_dir(cls, path: str, **kw) -> "ByteBPETokenizer":
        return cls.from_files(
            os.path.join(path, "vocab.json"),
            os.path.join(path, "merges.txt"),
            **kw,
        )

    def __len__(self) -> int:
        return len(self.encoder)

    # ------------------------------------------------------------------ BPE

    def _bpe(self, token: str) -> List[str]:
        cached = self._cache.get(token)
        if cached is not None:
            return cached
        word: Tuple[str, ...] = tuple(token)
        pairs = _get_pairs(word)
        while pairs:
            best = min(
                pairs, key=lambda p: self.bpe_ranks.get(p, float("inf"))
            )
            if best not in self.bpe_ranks:
                break
            a, b = best
            merged: List[str] = []
            i = 0
            while i < len(word):
                if i < len(word) - 1 and word[i] == a and word[i + 1] == b:
                    merged.append(a + b)
                    i += 2
                else:
                    merged.append(word[i])
                    i += 1
            word = tuple(merged)
            if len(word) == 1:
                break
            pairs = _get_pairs(word)
        out = list(word)
        if len(self._cache) < 16384:  # bounded; hot words dominate anyway
            self._cache[token] = out
        return out

    def _encode_ordinary(self, text: str) -> List[int]:
        ids: List[int] = []
        for piece in self._split.findall(text):
            mapped = "".join(
                self.byte_encoder[b] for b in piece.encode("utf-8")
            )
            for sub in self._bpe(mapped):
                tid = self.encoder.get(sub)
                if tid is None:
                    # unmerged base symbol missing from a truncated vocab:
                    # fall back to its byte tokens (never drop input)
                    for ch in sub:
                        ids.append(self.encoder[ch])
                else:
                    ids.append(tid)
        return ids

    def encode(self, text: str) -> List[int]:
        """Text -> token ids; special-token literals become their ids."""
        if not self.special_tokens:
            return self._encode_ordinary(text)
        ids: List[int] = []
        rest = text
        while rest:
            hit, pos = None, len(rest)
            for sp in self.special_tokens:
                i = rest.find(sp)
                if i != -1 and i < pos:
                    hit, pos = sp, i
            if hit is None:
                ids.extend(self._encode_ordinary(rest))
                break
            if pos:
                ids.extend(self._encode_ordinary(rest[:pos]))
            ids.append(self.encoder[hit])
            rest = rest[pos + len(hit):]
        return ids

    # --------------------------------------------------------------- decode

    def token_bytes(self, token_id: int) -> bytes:
        """The raw bytes one token contributes to the output stream."""
        tok = self.decoder.get(int(token_id))
        if tok is None:
            return b""
        if token_id in self._special_ids:
            return tok.encode("utf-8")
        return bytes(self.byte_decoder[c] for c in tok)

    def decode_bytes(self, ids: Sequence[int]) -> bytes:
        return b"".join(self.token_bytes(i) for i in ids)

    def decode(self, ids: Sequence[int]) -> str:
        return self.decode_bytes(ids).decode("utf-8", errors="replace")

    def detokenizer(self) -> "IncrementalDetokenizer":
        return IncrementalDetokenizer(self)


class IncrementalDetokenizer:
    """Token-at-a-time detokenization that never splits a character: feed
    ids with push(), get back only the COMPLETE text available so far;
    incomplete UTF-8 tails stay buffered until their continuation bytes
    arrive (or flush() force-decodes them with replacement chars)."""

    def __init__(self, tok: ByteBPETokenizer):
        self._tok = tok
        self._dec = codecs.getincrementaldecoder("utf-8")(errors="replace")

    def push(self, token_id: int) -> str:
        return self._dec.decode(self._tok.token_bytes(token_id), False)

    def push_many(self, ids: Sequence[int]) -> str:
        return self._dec.decode(self._tok.decode_bytes(ids), False)

    def flush(self) -> str:
        return self._dec.decode(b"", True)
