"""Model hub: real checkpoints + real tokenizers for the serving stack.

Everything in the serving stack below this package (paged KV, prefix
reuse, int8 blocks, fused attention, speculative decoding) is certified
on synthetic vocab/weights. This package closes that gap with three
pieces that together make the engine a believable product:

  safetensors_io  minimal safetensors reader/writer: 8-byte header-length
                  prefix + JSON header + raw tensor bytes. Reads are LAZY
                  per-tensor mmap slices (no torch, no full-file load);
                  the writer exists for fixtures and round-trip tests.
  tokenizer       GPT-2-family byte-level BPE: vocab.json/merges.txt
                  loader, the byte<->unicode tables, special-token
                  handling, and an IncrementalDetokenizer that holds back
                  incomplete UTF-8 sequences so token-at-a-time streaming
                  never emits mojibake.
  checkpoint      gpt2-class safetensors -> the transformer's param tree:
                  name-mapping table, Conv1D->dense layout detection,
                  fused-qkv splitting, tied embeddings, and per-leaf
                  sharded device_put by the existing partition rules so a
                  host never materializes the full model twice.

`load_model(path)` ties them together into a ModelBundle (config, params,
tokenizer, eos id, model id) ready to drop into DecodeEngine /
PagedDecodeEngine; `ray_tpu.serve.openai_api` serves such a bundle behind
an OpenAI-compatible `/v1/completions` endpoint.
"""

from .safetensors_io import (  # noqa: F401
    SafetensorsFile,
    load_file,
    save_file,
)
from .tokenizer import (  # noqa: F401
    ByteBPETokenizer,
    IncrementalDetokenizer,
    bytes_to_unicode,
)
from .checkpoint import (  # noqa: F401
    GPT2_NAME_MAP,
    ModelBundle,
    config_from_json,
    load_gpt2_params,
    load_model,
)
from .measure import measure_realtext_spec  # noqa: F401
