"""gpt2-class safetensors checkpoints -> the transformer's param tree.

The in-repo transformer (models/transformer.py) is llama-family: rope
positions, rms-norm, no biases. A gpt2-class checkpoint carries learned
position embeddings, layernorm biases and matmul biases; the mapping is
therefore an ARCHITECTURE ADAPTER, faithful for every weight matrix and
explicit about what it drops:

    wte.weight                 -> embed [V, E]  (tied unembed when no
                                  lm_head.weight is present)
    lm_head.weight [V, E]      -> unembed (transposed to [E, V])
    h.{i}.ln_1.weight          -> layers.attn_norm [L, E]
    h.{i}.attn.c_attn.weight   -> layers.wq/wk/wv (fused [E, 3E] split
                                  three ways, reshaped to [E, H, D])
    h.{i}.attn.c_proj.weight   -> layers.wo [L, H, D, E]
    h.{i}.ln_2.weight          -> layers.mlp_norm [L, E]
    h.{i}.mlp.c_fc.weight      -> layers.w_up [L, E, F]
    h.{i}.mlp.c_proj.weight    -> layers.w_down [L, F, E]
    ln_f.weight                -> final_norm [E]

    dropped (reported, never silently): wpe.weight (rope replaces learned
    positions), every *.bias (the tree has none), attn.bias /
    attn.masked_bias (causal-mask buffers).

GPT-2 stores matmuls as Conv1D — weight laid out [in, out], the
TRANSPOSE of torch Linear's [out, in]. Our einsums are input-major
("bse,ef->bsf"), i.e. Conv1D layout is already native; Linear-layout
checkpoints are detected by shape and transposed. The fused c_attn is
split into q/k/v thirds (n_kv_heads == n_heads: gpt2 is MHA).

Loading is lazy + shard-aware: tensors are read one at a time as mmap
views (safetensors_io), per-layer slices are stacked into each leaf's
[L, ...] array, and with a mesh + rules each finished leaf is
device_put with the SAME logical sharding the partition rules give
activations/params everywhere else — so a host materializes each leaf
once on its way to the devices, never a second full-model copy.

`mlp_variant="gelu"` on the derived config makes the adapter structurally
complete: gpt2's two-matmul gelu MLP loads as w_up/w_down with no
synthesized gate. Exact logit parity with the original gpt2 stack is NOT
claimed (norm/position differences above); the contract certified by
tests is that engines fed hub-loaded params match the in-repo dense
reference forward token-for-token.
"""

from __future__ import annotations

import dataclasses
import json
import os
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from ..transformer import TransformerConfig, param_specs
from .safetensors_io import SafetensorsFile
from .tokenizer import ByteBPETokenizer

# documentation + test surface: checkpoint name pattern -> param tree path
GPT2_NAME_MAP: Dict[str, str] = {
    "wte.weight": "embed",
    "lm_head.weight": "unembed",
    "ln_f.weight": "final_norm",
    "h.{i}.ln_1.weight": "layers.attn_norm",
    "h.{i}.attn.c_attn.weight": "layers.wq|wk|wv",
    "h.{i}.attn.c_proj.weight": "layers.wo",
    "h.{i}.ln_2.weight": "layers.mlp_norm",
    "h.{i}.mlp.c_fc.weight": "layers.w_up",
    "h.{i}.mlp.c_proj.weight": "layers.w_down",
}

# buffers/params the llama-family tree has no slot for — dropped loudly
_DROP_SUFFIXES = (".bias",)
_DROP_NAMES = ("wpe.weight",)


def _strip_prefix(name: str) -> str:
    for p in ("transformer.", "model."):
        if name.startswith(p):
            return name[len(p):]
    return name


def config_from_json(path: str) -> TransformerConfig:
    """Derive a TransformerConfig from an HF-style gpt2 config.json."""
    with open(path, encoding="utf-8") as f:
        cj = json.load(f)
    mt = cj.get("model_type", "gpt2")
    if mt not in ("gpt2",):
        raise ValueError(f"unsupported model_type {mt!r} (gpt2-class only)")
    # the in-repo gelu variant is tanh-approx (gelu_new); a checkpoint
    # trained with a different activation must refuse, not serve silently
    # wrong logits ("reported, never silently" covers ignored config too)
    act = cj.get("activation_function", "gelu_new")
    if act not in ("gelu_new", "gelu_pytorch_tanh"):
        raise ValueError(
            f"unsupported activation_function {act!r}: the transformer's "
            "gelu MLP is tanh-approx (gelu_new/gelu_pytorch_tanh)"
        )
    E = int(cj["n_embd"])
    H = int(cj["n_head"])
    if E % H:
        raise ValueError(f"n_embd {E} not divisible by n_head {H}")
    return TransformerConfig(
        vocab_size=int(cj["vocab_size"]),
        d_model=E,
        n_layers=int(cj["n_layer"]),
        n_heads=H,
        n_kv_heads=H,  # gpt2 is MHA
        d_head=E // H,
        d_ff=int(cj.get("n_inner") or 4 * E),
        max_seq_len=int(cj.get("n_positions", 1024)),
        tie_embeddings=True,  # flipped off below if lm_head.weight exists
        mlp_variant="gelu",
    )


def _oriented(arr: np.ndarray, in_dim: int, out_dim: int, name: str,
              linear_layout: bool = False) -> np.ndarray:
    """Return `arr` laid out [in_dim, out_dim]: Conv1D checkpoints already
    are; Linear ([out, in]) ones transpose. Square matrices carry no
    orientation signal of their own, so they follow `linear_layout` —
    the file-global verdict probed on the (always non-square) fused
    c_attn; a per-tensor guess would load a Linear checkpoint's
    attn.c_proj silently half-transposed."""
    if in_dim == out_dim and arr.shape == (in_dim, out_dim):
        return arr.T if linear_layout else arr
    if arr.shape == (in_dim, out_dim):
        return arr
    if arr.shape == (out_dim, in_dim):
        return arr.T
    raise ValueError(
        f"{name}: shape {arr.shape} fits neither [in={in_dim}, out={out_dim}] "
        "nor its transpose"
    )


def load_gpt2_params(
    path: str,
    cfg: Optional[TransformerConfig] = None,
    mesh=None,
    rules=None,
    strict: bool = True,
    pad_vocab_to_multiple: Optional[int] = None,
) -> Tuple[Dict[str, Any], TransformerConfig, Dict[str, Any]]:
    """Load a gpt2-class safetensors checkpoint into the transformer's
    param tree. `path` is a directory (model.safetensors [+ config.json])
    or the .safetensors file itself; `cfg=None` derives the config from
    config.json. With mesh+rules each finished leaf is device_put sharded
    by the existing partition rules (param_specs); otherwise leaves stay
    host numpy (engines accept either).

    Returns (params, cfg, report) where report lists mapped/dropped/
    unknown tensor names. strict=True raises on unknown (non-dropped)
    names — a silently half-loaded model must never serve.

    Vocab padding: checkpoints ship odd vocab sizes (gpt2's 50257) that
    no tp mesh divides. `pad_vocab_to_multiple` (derived automatically
    from the mesh's "vocab" axes when a mesh is given) zero-pads the
    embed rows / unembed columns up to the next multiple and records the
    pad in cfg.vocab_pad — the decoders' samplers mask those trailing
    logits to -inf, so a padded id can never be emitted.
    """
    if os.path.isdir(path):
        st_path = os.path.join(path, "model.safetensors")
        if not os.path.exists(st_path):
            cands = [f for f in sorted(os.listdir(path))
                     if f.endswith(".safetensors")]
            if len(cands) != 1:
                raise FileNotFoundError(
                    f"{path}: need model.safetensors (found {cands})"
                )
            st_path = os.path.join(path, cands[0])
        cfg_path = os.path.join(path, "config.json")
    else:
        st_path, cfg_path = path, os.path.join(
            os.path.dirname(path), "config.json"
        )

    with SafetensorsFile(st_path) as f:
        names = {_strip_prefix(n): n for n in f.keys()}
        tied = "lm_head.weight" not in names
        if cfg is None:
            if not os.path.exists(cfg_path):
                raise FileNotFoundError(
                    f"{st_path}: no config.json next to the checkpoint and "
                    "no explicit TransformerConfig"
                )
            cfg = config_from_json(cfg_path)
        if cfg.tie_embeddings != tied:
            cfg = dataclasses.replace(cfg, tie_embeddings=tied)
        if cfg.mlp_variant != "gelu":
            raise ValueError(
                "gpt2-class checkpoints need mlp_variant='gelu' (two-matmul "
                f"MLP, no gate), got {cfg.mlp_variant!r}"
            )
        L, E, H, KV, D, F = (
            cfg.n_layers, cfg.d_model, cfg.n_heads, cfg.n_kv_heads,
            cfg.d_head, cfg.d_ff,
        )

        mapped: List[str] = []
        dropped: List[str] = []

        # Conv1D ([in, out]) vs Linear ([out, in]) is a FILE-level
        # property; probe it once on the fused c_attn, whose [E, 3E]
        # shape is never square, so square tensors (attn.c_proj, and
        # mlp matrices when d_ff == d_model) orient correctly too
        probe = names.get("h.0.attn.c_attn.weight")
        if probe is None:
            raise KeyError(f"{st_path}: missing tensor 'h.0.attn.c_attn.weight'")
        linear_layout = tuple(f.shape(probe)) == (3 * E, E)

        def read_view(short: str, shape: Tuple[int, ...],
                      orient: Optional[Tuple[int, int]] = None) -> np.ndarray:
            """The oriented mmap VIEW — callers copy it out exactly once."""
            raw = names.get(short)
            if raw is None:
                raise KeyError(f"{st_path}: missing tensor {short!r}")
            arr = f.tensor(raw)
            if orient is not None:
                arr = _oriented(arr, *orient, name=short,
                                linear_layout=linear_layout)
            if tuple(arr.shape) != shape:
                raise ValueError(
                    f"{short}: shape {arr.shape}, config expects {shape}"
                )
            mapped.append(short)
            return arr

        def read(short: str, shape: Tuple[int, ...],
                 orient: Optional[Tuple[int, int]] = None) -> np.ndarray:
            # explicit copy: same-dtype asarray would return the mmap VIEW,
            # pinning the whole file mapping past the loader's lifetime
            return read_view(short, shape, orient).astype(
                np.float32, copy=True)

        def stack(fmt: str, shape: Tuple[int, ...],
                  orient: Optional[Tuple[int, int]] = None) -> np.ndarray:
            # one preallocated [L, ...] leaf, filled a layer at a time
            # DIRECTLY from the mmap views (the assignment is the single
            # copy+cast) — peak host memory for the leaf is the leaf
            out = np.empty((L,) + shape, np.float32)
            for i in range(L):
                out[i] = read_view(fmt.format(i=i), shape, orient)
            return out

        embed = read("wte.weight", (cfg.vocab_size, E))
        layer: Dict[str, np.ndarray] = {}
        # fused qkv: [E, 3E] split into thirds, head-reshaped
        c_attn = stack("h.{i}.attn.c_attn.weight", (E, 3 * E), (E, 3 * E))
        layer["wq"] = np.ascontiguousarray(
            c_attn[:, :, :E].reshape(L, E, H, D))
        layer["wk"] = np.ascontiguousarray(
            c_attn[:, :, E:2 * E].reshape(L, E, KV, D))
        layer["wv"] = np.ascontiguousarray(
            c_attn[:, :, 2 * E:].reshape(L, E, KV, D))
        del c_attn
        layer["wo"] = stack(
            "h.{i}.attn.c_proj.weight", (E, E), (E, E)
        ).reshape(L, H, D, E)
        layer["attn_norm"] = stack("h.{i}.ln_1.weight", (E,))
        layer["mlp_norm"] = stack("h.{i}.ln_2.weight", (E,))
        layer["w_up"] = stack("h.{i}.mlp.c_fc.weight", (E, F), (E, F))
        layer["w_down"] = stack("h.{i}.mlp.c_proj.weight", (F, E), (F, E))
        params: Dict[str, Any] = {
            "embed": embed,
            "layers": layer,
            "final_norm": read("ln_f.weight", (E,)),
        }
        if not tied:
            params["unembed"] = np.ascontiguousarray(
                read("lm_head.weight", (cfg.vocab_size, E)).T
            )

        consumed = set(mapped)
        for short in names:
            if short in consumed:
                continue
            if short in _DROP_NAMES or short.endswith(_DROP_SUFFIXES) or (
                short.startswith("h.") and short.split(".")[-1] in
                ("bias", "masked_bias")
            ):
                dropped.append(short)
            elif strict:
                raise ValueError(
                    f"{st_path}: unknown tensor {short!r} — not in the gpt2 "
                    "name map and not a known droppable (pass strict=False "
                    "to skip it)"
                )
            else:
                dropped.append(short)

    pad_mult = pad_vocab_to_multiple
    if pad_mult is None and mesh is not None and rules is not None:
        axes = rules.mesh_axes("vocab") or ()
        if isinstance(axes, str):
            axes = (axes,)
        pad_mult = 1
        for a in axes:
            pad_mult *= dict(mesh.shape).get(a, 1)
    vocab_padding = 0
    if pad_mult and pad_mult > 1:
        vocab_padding = (-cfg.vocab_size) % pad_mult
        if vocab_padding:
            params["embed"] = np.pad(
                params["embed"], ((0, vocab_padding), (0, 0))
            )
            if "unembed" in params:
                params["unembed"] = np.pad(
                    params["unembed"], ((0, 0), (0, vocab_padding))
                )
            cfg = dataclasses.replace(
                cfg,
                vocab_size=cfg.vocab_size + vocab_padding,
                vocab_pad=cfg.vocab_pad + vocab_padding,
            )

    if mesh is not None and rules is not None:
        from ...parallel.sharding import logical_sharding

        import jax

        specs = param_specs(cfg)

        def put(leaf: np.ndarray, spec: Tuple[Optional[str], ...]):
            return jax.device_put(leaf, logical_sharding(mesh, rules, *spec))

        params["embed"] = put(params["embed"], specs["embed"])
        params["final_norm"] = put(params["final_norm"], specs["final_norm"])
        if "unembed" in params:
            params["unembed"] = put(params["unembed"], specs["unembed"])
        for k in list(params["layers"]):
            params["layers"][k] = put(params["layers"][k],
                                      specs["layers"][k])

    report = {
        "source": st_path,
        "mapped": sorted(mapped),
        "dropped": sorted(dropped),
        "tied_embeddings": tied,
        "vocab_pad": vocab_padding,
    }
    return params, cfg, report


@dataclasses.dataclass
class ModelBundle:
    """Everything a serving replica needs from one checkpoint directory."""

    cfg: TransformerConfig
    params: Dict[str, Any]
    tokenizer: ByteBPETokenizer
    eos_id: Optional[int]
    model_id: str
    params_source: str
    report: Dict[str, Any]


def load_model(
    path: str,
    mesh=None,
    rules=None,
    model_id: Optional[str] = None,
    strict: bool = True,
) -> ModelBundle:
    """Load checkpoint + tokenizer from one directory (model.safetensors,
    config.json, vocab.json, merges.txt) into a ModelBundle ready for
    DecodeEngine / PagedDecodeEngine (pass eos_id + params + cfg)."""
    if not os.path.isdir(path):
        raise NotADirectoryError(
            f"load_model takes a checkpoint DIRECTORY, got {path!r}"
        )
    params, cfg, report = load_gpt2_params(
        path, mesh=mesh, rules=rules, strict=strict
    )
    tokenizer = ByteBPETokenizer.from_dir(path)
    if len(tokenizer) > cfg.vocab_size:
        raise ValueError(
            f"tokenizer has {len(tokenizer)} entries but the model's vocab "
            f"is {cfg.vocab_size}"
        )
    return ModelBundle(
        cfg=cfg,
        params=params,
        tokenizer=tokenizer,
        eos_id=tokenizer.eos_id,
        model_id=model_id or os.path.basename(os.path.normpath(path)),
        params_source=report["source"],
        report=report,
    )
