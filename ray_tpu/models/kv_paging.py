"""Paged KV-cache subsystem: block allocator, prefix reuse, preemption.

The dense `DecodeEngine` allocates one max-length cache slab per slot, so
HBM — not compute — caps concurrency and every request pays for its
worst-case length up front. This module replaces the slab with a POOL of
fixed-size token blocks (the vLLM PagedAttention memory model) plus the
host-side machinery that makes the pool safe to oversubscribe:

  BlockAllocator    refcounted free-list over the physical blocks; block 0
                    is the reserved null block (padding writes and padded
                    table entries route there, never into live data).
  PrefixCache       hash-trie over FULL prompt blocks with chained keys:
                    identical system-prompt prefixes map to the same
                    physical blocks, so a prefix hit admits by increfing
                    blocks instead of recomputing prefill for the shared
                    span. Cache-held blocks are evicted LRU-leaf-first
                    under pool pressure.
  PagedDecodeEngine the `ContinuousBatcher` engine contract (admit / step /
                    release) over the pool, plus:
                      can_admit(request)  worst-case block-budget admission
                      fork(src, dst)      share ALL blocks (copy-on-write
                                          isolates the forks on first
                                          divergent write)
                      take_preempted()    generations evicted under pool
                                          exhaustion, parked as
                                          recompute-on-readmit requests

Preemption contract: when a decode step needs blocks the pool cannot
supply (even after cache eviction), the NEWEST generations are preempted —
their blocks freed, their full token history parked — until the rest fit.
A parked generation readmits as a plain prefill of prompt + generated
tokens; with greedy sampling the resumed stream is token-for-token what
the uninterrupted run would have produced. The engine therefore never
OOMs the replica: admission past capacity degrades to recompute, not to a
crash.

Speculative decoding (`speculative_k > 0`) layers propose/verify/commit on
top of the same machinery: a drafter (models/speculative.py; self-drafting
n-gram lookup by default, any propose(tokens, k) object as the
small-draft-model hook) guesses up to k tokens per slot between steps, and
ONE batched verify step scores all k+1 positions (transformer.py
`paged_verify_step`). Accepted tokens commit through the normal block-table
append; the rejected tail is rolled back by truncating the slot's table —
freed blocks return to the allocator, and on int8 pools the partial last
block is requantized by the verify commit itself (it replays the
single-token RMW history for accepted tokens only). A step may therefore
emit 1..k+1 tokens per slot: step() returns token LISTS when speculation
is enabled. Greedy output is token-for-token identical to non-speculative
decode by construction (acceptance compares drafts against the model's own
argmax); speculation is greedy-only.

Not thread-safe: one loop thread (the batcher's) owns admit/step/release;
stats() reads are safe from other threads (plain int reads).
"""

from __future__ import annotations

import hashlib
import time
from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np

from .decoding import default_prefill_buckets
from .transformer import (
    NEG_INF,
    TransformerConfig,
    init_paged_kv_cache,
    init_params,
    make_paged_decoder,
    paged_kv_block_bytes,
)


class InsufficientBlocksError(RuntimeError):
    """The pool cannot cover an admission's block need even after cache
    eviction. Raised by admit(); ContinuousBatcher parks the request for
    retry instead of failing it (blocks free as generations retire)."""


class BlockAllocator:
    """Refcounted fixed pool of KV blocks. Block 0 is the permanently-held
    null block: padded block-table entries and masked token writes target
    it, so it is never handed out."""

    def __init__(self, num_blocks: int):
        if num_blocks < 2:
            raise ValueError(f"need >= 2 blocks (1 is the null block), got {num_blocks}")
        self.num_blocks = int(num_blocks)
        self._ref = np.zeros(self.num_blocks, np.int32)
        self._ref[0] = 1  # null block: never allocated, never freed
        self._free: List[int] = list(range(self.num_blocks - 1, 0, -1))

    @property
    def num_free(self) -> int:
        return len(self._free)

    @property
    def num_usable(self) -> int:
        return self.num_blocks - 1

    def alloc(self, n: int = 1) -> List[int]:
        if n > len(self._free):
            raise InsufficientBlocksError(
                f"need {n} KV blocks, {len(self._free)} free"
            )
        out = [self._free.pop() for _ in range(n)]
        for b in out:
            self._ref[b] = 1
        return out

    def incref(self, block: int) -> None:
        if self._ref[block] <= 0:
            raise ValueError(f"incref of free block {block}")
        self._ref[block] += 1

    def decref(self, block: int) -> None:
        if self._ref[block] <= 0:
            raise ValueError(f"decref of free block {block}")
        self._ref[block] -= 1
        if self._ref[block] == 0:
            self._free.append(block)

    def refcount(self, block: int) -> int:
        return int(self._ref[block])


class PrefixCache:
    """Hash-trie over full prompt blocks.

    A node's key is sha1(parent_key || block tokens), so a chain of keys
    identifies a prompt prefix by content AND position — two prompts share
    a node iff they share every token up to and including that block. The
    cache holds its own reference on every registered block; a block whose
    only reference is the cache's (refcount 1) is evictable, leaf-first in
    LRU order so chains never dangle."""

    def __init__(self, allocator: BlockAllocator, block_tokens: int):
        self._alloc = allocator
        self.block_tokens = int(block_tokens)
        # key -> {"block": int, "parent": key, "ts": int}
        self._nodes: Dict[bytes, Dict[str, Any]] = {}
        self._children: Dict[bytes, set] = {}
        self._clock = 0
        self.hits = 0
        self.evictions = 0
        self.flushes = 0

    def __len__(self) -> int:
        return len(self._nodes)

    def _tick(self) -> int:
        self._clock += 1
        return self._clock

    def _child_key(self, parent: bytes, block_tokens: np.ndarray) -> bytes:
        h = hashlib.sha1()
        h.update(parent or b"root")
        h.update(np.ascontiguousarray(block_tokens, np.int32).tobytes())
        return h.digest()

    def _chain(self, prompt: np.ndarray, max_blocks: int):
        bt = self.block_tokens
        key = b""
        for bi in range(max_blocks):
            key = self._child_key(key, prompt[bi * bt:(bi + 1) * bt])
            node = self._nodes.get(key)
            if node is None:
                return
            yield key, node

    def lookup(self, prompt: np.ndarray, max_blocks: int) -> List[int]:
        """Longest cached chain of full blocks matching the prompt prefix;
        returns the physical block ids (LRU-touched, NOT increfed — the
        caller takes its references)."""
        out = []
        for _, node in self._chain(prompt, max_blocks):
            node["ts"] = self._tick()
            out.append(node["block"])
        if out:
            self.hits += 1
        return out

    def match_count(self, prompt: np.ndarray, max_blocks: int) -> int:
        """lookup() length without the LRU touch (admission budgeting)."""
        return sum(1 for _ in self._chain(prompt, max_blocks))

    def match_blocks(self, prompt: np.ndarray, max_blocks: int) -> List[int]:
        """lookup() without the LRU touch (admission budgeting)."""
        return [node["block"] for _, node in self._chain(prompt, max_blocks)]

    def register(self, prompt: np.ndarray, blocks: Sequence[int]) -> None:
        """Insert the prompt's first len(blocks) full blocks. New nodes
        incref their block (the cache's own reference); existing nodes are
        only LRU-touched (their block is already the canonical one)."""
        key = b""
        for bi, block in enumerate(blocks):
            parent = key
            key = self._child_key(
                key, prompt[bi * self.block_tokens:(bi + 1) * self.block_tokens]
            )
            node = self._nodes.get(key)
            if node is None:
                self._nodes[key] = {"block": int(block), "parent": parent,
                                    "ts": self._tick()}
                self._children.setdefault(parent, set()).add(key)
                self._alloc.incref(int(block))
            else:
                node["ts"] = self._tick()

    def evictable(self) -> int:
        """Blocks the cache could eventually free: held only by the cache
        (refcount 1). Counts non-leaves too — leaf-first eviction reaches
        them once their children go. Safe to call off the loop thread
        (stats polling): iterates an atomic snapshot of the node table."""
        return sum(
            1 for n in list(self._nodes.values())
            if self._alloc.refcount(n["block"]) == 1
        )

    def evict(self, n: int) -> int:
        """Free up to n blocks, LRU childless-first; returns blocks freed.

        One scan collects every current victim (childless, cache-only) and
        evicts LRU-first from that batch; the outer loop re-scans only when
        a whole batch was consumed and more is needed (evicting leaves can
        expose their parents) — O(passes * nodes), not O(n * nodes)."""
        freed = 0
        while freed < n:
            candidates = sorted(
                (node["ts"], key) for key, node in self._nodes.items()
                if not self._children.get(key)
                and self._alloc.refcount(node["block"]) == 1
            )
            if not candidates:
                break
            for _, key in candidates:
                if freed >= n:
                    break
                node = self._nodes.pop(key)
                self._children.get(node["parent"], set()).discard(key)
                self._children.pop(key, None)
                self._alloc.decref(node["block"])
                self.evictions += 1
                freed += 1
        return freed

    def flush(self) -> int:
        """Drop EVERY node unconditionally — the weight-hot-swap path:
        cached KV was computed under the OLD weights and must never be
        spliced under a new-weight admission. Only the cache's own
        references are released; a block shared with a live slot simply
        loses the cache ref and frees when the slot retires. Returns the
        number of nodes dropped."""
        n = len(self._nodes)
        for node in self._nodes.values():
            self._alloc.decref(node["block"])
        self._nodes.clear()
        self._children.clear()
        self.flushes += 1
        return n


class PagedDecodeEngine:
    """Block-granular KV-cache decode engine (module docstring has the
    architecture). Drop-in for `DecodeEngine` under ContinuousBatcher —
    same admit/step/release contract — plus paging APIs the batcher
    discovers by duck-typing: can_admit, take_preempted."""

    def __init__(
        self,
        cfg: TransformerConfig,
        params=None,
        *,
        max_batch_size: int = 8,
        rules=None,
        mesh=None,
        eos_id: Optional[int] = None,
        temperature: float = 0.0,
        default_max_new_tokens: int = 64,
        max_seq_len: Optional[int] = None,
        prefill_buckets: Optional[Sequence[int]] = None,
        seed: int = 0,
        block_tokens: Optional[int] = None,
        num_blocks: Optional[int] = None,
        prefix_cache: Optional[bool] = None,
        kv_cache_dtype: Optional[str] = None,
        attention_impl: Optional[str] = None,
        pool_bytes: Optional[int] = None,
        chunk_blocks: Optional[int] = None,
        speculative_k: Optional[int] = None,
        drafter=None,
        prefill_chunk_tokens: Optional[int] = None,
        telemetry=None,
        model_id: Optional[str] = None,
        logprobs: bool = False,
    ):
        import jax
        import jax.numpy as jnp

        from ray_tpu._private.config import GLOBAL_CONFIG as gcfg

        # telemetry plane (serve/telemetry.py): None resolves the process
        # singleton per the serve_telemetry flag, False disables for this
        # engine (benches compare on-vs-off), an object is used AS-IS.
        # The None case only consults serve telemetry when that module is
        # ALREADY imported (serving processes are — Replica.__init__
        # loads it before user code builds engines): a bare engine in a
        # training/bench process must not pull the whole serve package in
        # at construction, and an injected object can never be dropped by
        # a serve import fault.
        if telemetry is None:
            try:
                import sys as _sys

                tmod = _sys.modules.get("ray_tpu.serve.telemetry")
                telemetry = (
                    tmod.get_telemetry() if tmod is not None else None
                )
            except Exception:
                telemetry = None
        self._tel = telemetry or None
        self._rec = self._tel.recorder if self._tel is not None else None

        self.cfg = cfg
        self.max_batch_size = int(max_batch_size)
        self.eos_id = eos_id
        self.default_max_new_tokens = int(default_max_new_tokens)
        self.max_seq_len = int(max_seq_len or cfg.max_seq_len)
        if self.max_seq_len > cfg.max_seq_len:
            raise ValueError("max_seq_len exceeds the model's rope tables")
        self.block_tokens = int(block_tokens or gcfg.serve_kv_block_tokens)
        bt = self.block_tokens
        self.blocks_per_slot = -(-self.max_seq_len // bt)

        kv_cache_dtype = kv_cache_dtype or gcfg.serve_kv_cache_dtype
        if kv_cache_dtype not in ("fp", "int8"):
            raise ValueError(
                f"kv_cache_dtype must be 'fp' or 'int8', got {kv_cache_dtype!r}"
            )
        self.kv_cache_dtype = kv_cache_dtype
        kv_dtype = jnp.int8 if kv_cache_dtype == "int8" else cfg.dtype
        self.kv_block_bytes = paged_kv_block_bytes(cfg, bt, kv_dtype)

        # cross-replica transfer identity (serve/kv_transfer.py): two
        # engines produce matching export keys iff they agree on every
        # byte-layout-relevant knob — model identity, block geometry,
        # pool storage dtype, layer/head shape, and (once a hot swap has
        # happened) the WEIGHT VERSION. The signature SEEDS the
        # content-addressed key chain, so keys minted under a different
        # model / dtype / geometry / weight version can never collide
        # with this pool's (the int8-into-fp poison case — and the
        # stale-weights-KV poison case — are unrepresentable by key
        # construction, not merely checked at import).
        self.model_id = str(
            model_id if model_id is not None else gcfg.serve_model_id or ""
        )
        self._kv_store_dtype = np.dtype(kv_dtype).name
        self.weight_version = 0
        self.transfer_sig = self._compute_transfer_sig()

        attention_impl = attention_impl or gcfg.serve_paged_attention
        fused_impl = "auto"
        if attention_impl.startswith("fused:"):
            attention_impl, fused_impl = "fused", attention_impl[6:]
        if attention_impl == "auto":
            # the fused kernel is the TPU fast path; the gather step stays
            # the exact (and cheapest-to-dispatch) path on CPU CI hosts
            attention_impl = (
                "fused" if jax.default_backend() == "tpu" else "gather"
            )
        if attention_impl not in ("gather", "fused") or fused_impl not in (
            "auto", "kernel", "xla"
        ):
            # fail at construction, not at the first decode step's trace —
            # a serve replica must reject a typo'd flag before admitting
            raise ValueError(
                "attention_impl must be auto|gather|fused[:kernel|:xla], "
                f"got {attention_impl!r}"
                + (f" with backend {fused_impl!r}" if fused_impl != "auto"
                   else "")
            )
        self.attention_impl = attention_impl
        chunk_blocks = int(
            chunk_blocks if chunk_blocks is not None
            else gcfg.serve_paged_attention_chunk_blocks
        )
        if chunk_blocks <= 0:
            # same contract as the impl flags: a bad tuning knob fails at
            # replica construction, not at the first decode step's trace
            raise ValueError(
                f"chunk_blocks must be positive, got {chunk_blocks}"
            )
        self.chunk_blocks = chunk_blocks

        prefill_chunk_tokens = int(
            gcfg.serve_prefill_chunk_tokens if prefill_chunk_tokens is None
            else prefill_chunk_tokens
        )
        if prefill_chunk_tokens < 0:
            raise ValueError(
                f"prefill_chunk_tokens must be >= 0 (0 = whole-prompt "
                f"prefill), got {prefill_chunk_tokens}"
            )
        self.prefill_chunk_tokens = prefill_chunk_tokens

        speculative_k = int(
            gcfg.serve_speculative_k if speculative_k is None
            else speculative_k
        )
        if speculative_k < 0:
            raise ValueError(f"speculative_k must be >= 0, got {speculative_k}")
        self.speculative_k = speculative_k
        self.drafter = None
        if speculative_k:
            if temperature > 0.0:
                # acceptance compares drafts against the model's argmax;
                # per-position sampling would not preserve the temperature
                # distribution — refuse at construction, not mid-stream
                raise ValueError(
                    "speculative decoding is greedy-only (temperature 0), "
                    f"got temperature={temperature}"
                )
            from .speculative import resolve_drafter

            self.drafter = resolve_drafter(
                drafter if drafter is not None
                else gcfg.serve_speculative_drafter
            )
            if self.drafter is None:
                raise ValueError(
                    f"speculative_k={speculative_k} needs a drafter, but "
                    "the drafter resolved to 'off'"
                )
            # draft lengths bucket to powers of two (plus k itself) so a
            # jittery drafter compiles O(log k) verify shapes, not O(k)
            buckets, b = [], 1
            while b < speculative_k:
                buckets.append(b)
                b *= 2
            buckets.append(speculative_k)
            self._k_buckets = tuple(buckets)
        elif drafter is not None:
            # same strictness as the other conflicting-knob pairs: a
            # drafter that can never run is a misconfiguration, not a noop
            raise ValueError(
                "drafter given but speculative_k is 0 — pass "
                "speculative_k > 0 (or serve_speculative_k) to enable "
                "speculative decoding"
            )

        # per-token logprobs (generation-based RL, rl/llm): each emitted
        # token becomes a (token, logprob) pair — the logprob of the
        # SAMPLED id under the exact distribution the sampler drew from
        # (same vocab_pad masking, same temperature scaling, fp32), so a
        # dense re-forward reproduces it bit-for-tolerance. Restricted to
        # speculative_k == 0: the verify step commits accepted drafts
        # without returning per-position logits.
        self.logprobs = bool(logprobs)
        if self.logprobs and self.speculative_k:
            raise ValueError(
                "logprobs=True requires speculative_k == 0 — the verify "
                "step returns no per-position logits to score"
            )
        self.temperature = float(temperature)
        if self.logprobs:
            vocab_pad = int(getattr(cfg, "vocab_pad", 0) or 0)
            temp = self.temperature

            def _lp(logits, toks):
                logits = logits.astype(jnp.float32)
                if vocab_pad:
                    V = logits.shape[-1]
                    pad = jnp.arange(V) >= V - vocab_pad
                    logits = jnp.where(pad, NEG_INF, logits)
                if temp > 0.0:
                    logits = logits / temp
                lp = jax.nn.log_softmax(logits, axis=-1)
                return jnp.take_along_axis(
                    lp, toks[:, None].astype(jnp.int32), axis=-1
                )[:, 0]

            self._lp_fn = jax.jit(_lp)

        if num_blocks is not None and pool_bytes is not None:
            raise ValueError(
                "num_blocks and pool_bytes are conflicting pool sizes — "
                "pass one (the byte budget is a ceiling, the block count "
                "a floor)"
            )
        if num_blocks is None and pool_bytes is None:
            pool_bytes = int(gcfg.serve_kv_pool_mb) * (1 << 20) or None
        from_budget = num_blocks is None and pool_bytes is not None
        if from_budget:
            # byte-budget sizing: int8 pools fit ~2x the blocks of bf16
            # ones — capacity and autoscaling see the doubling directly.
            # The budget is a CEILING (the operator's HBM headroom), so
            # the null block counts against it and a budget that cannot
            # hold it plus one usable block is an error, not a tiny pool
            num_blocks = int(pool_bytes) // self.kv_block_bytes
            if num_blocks < 2:
                raise ValueError(
                    f"pool_bytes={pool_bytes} holds {num_blocks} blocks of "
                    f"{self.kv_block_bytes} bytes; need >= 2 (null + 1 usable)"
                )
        if num_blocks is None:
            num_blocks = int(gcfg.serve_kv_cache_blocks) or 0
        if not num_blocks:
            # dense-equivalent HBM budget (+1 for the null block): paging
            # then wins by oversubscription (admission past this is what
            # prefix reuse + preemption make safe)
            num_blocks = 1 + self.max_batch_size * self.blocks_per_slot
        if mesh is not None and rules is not None:
            # the pool's block dim shards on the "batch" mesh axes: every
            # shard must be whole — round DOWN under a byte budget (the
            # budget is a ceiling) and UP otherwise (counts are a floor)
            axes = rules.mesh_axes("batch") or ()
            if isinstance(axes, str):
                axes = (axes,)
            m = 1
            for a in axes:
                m *= dict(mesh.shape)[a]
            if from_budget:
                num_blocks = (num_blocks // m) * m
                if num_blocks < 2:
                    raise ValueError(
                        f"pool_bytes={pool_bytes} cannot hold a whole "
                        f"{m}-shard block set plus the null block"
                    )
            else:
                num_blocks = -(-num_blocks // m) * m
        self.num_blocks = int(num_blocks)

        self.params = (
            params if params is not None
            else init_params(jax.random.PRNGKey(seed), cfg)
        )
        # swap-time device_put (serve/weight_swap.py) re-distributes a
        # pulled host tree by THIS engine's partition rules
        self._rules = rules
        self._mesh = mesh
        self.allocator = BlockAllocator(self.num_blocks)
        if prefix_cache is None:
            prefix_cache = bool(gcfg.serve_kv_prefix_cache)
        self.prefix_cache = (
            PrefixCache(self.allocator, bt) if prefix_cache else None
        )
        self.pool = init_paged_kv_cache(
            cfg, self.num_blocks, bt, mesh=mesh, rules=rules, dtype=kv_dtype
        )
        self._prefill, self._decode_step, self._verify_step, self._copy_blocks = (
            make_paged_decoder(
                cfg, rules=rules, mesh=mesh, temperature=temperature,
                block_tokens=bt, kv_dtype=kv_dtype,
                attention_impl=attention_impl, fused_impl=fused_impl,
                chunk_blocks=chunk_blocks,
            )
        )
        buckets = sorted(set(
            prefill_buckets or default_prefill_buckets(self.max_seq_len)
        ))
        # readmission after preemption prefills prompt + generated-so-far,
        # which can be LONGER than any original prompt: extend the caller's
        # bucket table (doubling) until it covers max_seq_len, or a parked
        # generation could never be readmitted
        b = buckets[-1]
        while b < self.max_seq_len:
            b = min(b * 2, self.max_seq_len)
            buckets.append(b)
        self.buckets = tuple(buckets)
        self._key = jax.random.PRNGKey(seed + 1)

        B = self.max_batch_size
        self._tables = np.zeros((B, self.blocks_per_slot), np.int32)
        self._row_blocks = np.zeros(B, np.int32)  # allocated entries per row
        self._live = np.zeros(B, bool)
        self._positions = np.zeros(B, np.int32)
        self._last_tokens = np.zeros(B, np.int32)
        self._new_counts = np.zeros(B, np.int64)
        self._max_new = np.full(B, self.default_max_new_tokens, np.int64)
        self._history: List[Optional[List[int]]] = [None] * B
        # chunked prefill: the slot's FULL prompt while its prefill is
        # still streaming in chunks (committed span = _positions[slot]);
        # None once the slot is generating
        self._chunk_state: List[Optional[np.ndarray]] = [None] * B
        self._admit_seq = np.zeros(B, np.int64)
        self._seq = 0
        self._preempted: List[Tuple[int, Dict[str, Any]]] = []
        # logprob of the pending first sampled token per slot (set by the
        # completing prefill chunk, read by admit()/step() when emitting)
        self._lp_pending = np.zeros(B, np.float64)

        # counters (bench/observability/tests)
        self.tokens_generated = 0
        self.prefills = 0
        self.prefill_tokens = 0
        self.prefill_chunks = 0     # paged-prefill dispatches (>= prefills)
        self.chunked_prefills = 0   # admissions that streamed in chunks
        self.decode_steps = 0
        self.prefix_hits = 0
        self.prefix_tokens_reused = 0
        self.preemptions = 0
        self.cow_copies = 0
        self.prefill_shapes: set = set()  # (ctx_blocks, suffix_blocks) keys
        # speculative decoding counters
        self.spec_steps = 0
        self.spec_slot_steps = 0  # (slot, verify-step) participations
        self.spec_proposed = 0
        self.spec_accepted = 0
        self.spec_emitted = 0
        self.spec_shapes: set = set()  # K1 widths the verify step compiled
        # cross-replica KV transfer counters (serve/kv_transfer.py)
        self.kv_exports = 0
        self.kv_blocks_exported = 0
        self.kv_imports = 0
        self.kv_blocks_imported = 0
        self.kv_tokens_imported = 0
        self.kv_import_rejects = 0
        # live weight hot-swap counters (serve/weight_swap.py)
        self.weight_swaps = 0

    # ------------------------------------------------------------- internals

    def _compute_transfer_sig(self) -> bytes:
        sig = hashlib.sha1()
        sig.update(b"ray_tpu.kv_transfer.v1|")
        sig.update(self.model_id.encode())
        sig.update(
            f"|bt={self.block_tokens}|kv={self.kv_cache_dtype}"
            f"|sd={self._kv_store_dtype}"
            f"|L={self.cfg.n_layers}|H={self.cfg.n_kv_heads}"
            f"|D={self.cfg.d_head}".encode()
        )
        # version 0 (never swapped) keeps the original byte layout, so
        # engines that never hot-swap interoperate with older peers; any
        # swap moves the whole key space
        if self.weight_version:
            sig.update(f"|wv={self.weight_version}".encode())
        return sig.digest()

    def _next_key(self):
        import jax

        self._key, sub = jax.random.split(self._key)
        return sub

    def _bucket(self, length: int) -> int:
        for b in self.buckets:
            if b >= length:
                return b
        raise ValueError(
            f"prompt of {length} tokens exceeds max_seq_len {self.max_seq_len}"
        )

    def _ctx_bucket_blocks(self, ctx_len: int) -> int:
        """Pad the context block count to the same bucket boundaries as
        prompt lengths, so a prefix hit of 65 and one of 120 tokens reuse
        ONE paged-prefill compilation instead of compiling per block-count."""
        if ctx_len <= 0:
            return 0
        bucketed = min(self._bucket(ctx_len), self.max_seq_len)
        return min(-(-bucketed // self.block_tokens), self.blocks_per_slot)

    def _done(self, slot: int, token: int) -> bool:
        if self.eos_id is not None and token == self.eos_id:
            return True
        if self._new_counts[slot] >= self._max_new[slot]:
            return True
        return int(self._positions[slot]) >= self.max_seq_len

    def _release_blocks(self, slot: int) -> None:
        for bi in range(int(self._row_blocks[slot])):
            b = int(self._tables[slot, bi])
            if b:
                self.allocator.decref(b)
        self._tables[slot, :] = 0
        self._row_blocks[slot] = 0
        self._live[slot] = False
        self._history[slot] = None
        self._chunk_state[slot] = None

    def _reclaim(self, need: int) -> None:
        """Evict cache-only blocks until `need` blocks are free (best
        effort — callers decide between raising and preempting)."""
        short = need - self.allocator.num_free
        if short > 0 and self.prefix_cache is not None:
            self.prefix_cache.evict(short)

    def _preempt(self, slot: int) -> None:
        remaining = int(self._max_new[slot] - self._new_counts[slot])
        parked = {
            # full history (prompt + generated, incl. the pending last
            # token): readmission prefills it and the NEXT sampled token
            # continues the stream exactly where it stopped (greedy)
            "tokens": list(self._history[slot] or []),
            "max_new_tokens": max(1, remaining),
        }
        self._preempted.append((slot, parked))
        self.preemptions += 1
        if self._rec is not None:
            self._rec.record("preempt", slot=slot,
                             args={"tokens": len(parked["tokens"])})
        self._release_blocks(slot)

    # ----------------------------------------------------------- engine API

    def worst_case_blocks(self, prompt_len: int, max_new: int) -> int:
        """Blocks a request can EVER need: its full prompt + max_new span,
        capped at max_seq_len. The single formula behind admission (both
        the hard-fail and the budget check) and the serving API's
        submit-time validation — one definition, so a doomed request is
        judged identically at every gate."""
        span = min(int(prompt_len) + int(max_new), self.max_seq_len)
        return -(-span // self.block_tokens)

    def can_admit(self, request: Dict[str, Any]) -> bool:
        """Worst-case block-budget admission check: free + cache-evictable
        blocks must cover the request's full prompt + max_new_tokens span,
        minus the blocks a prefix hit would reuse. The batcher calls this
        BEFORE taking a slot, so over-capacity requests queue instead of
        thrashing the pool."""
        prompt = np.asarray(request["tokens"], np.int32)
        length = int(prompt.size)
        if length == 0 or length > self.max_seq_len:
            return True  # let admit() raise the real validation error
        mnt = request.get("max_new_tokens")
        mnt = self.default_max_new_tokens if mnt is None else max(1, int(mnt))
        worst = self.worst_case_blocks(length, mnt)
        if worst > self.allocator.num_usable:
            # can NEVER fit: report admissible so the batcher routes it to
            # admit(), whose worst-case check fails it with the hard
            # ValueError — parking it would wedge the admission line
            return True
        reusable = 0
        evictable = 0
        if self.prefix_cache is not None:
            evictable = self.prefix_cache.evictable()
            if length > 1:
                hits = self.prefix_cache.match_blocks(
                    prompt, (length - 1) // self.block_tokens
                )
                reusable = len(hits)
                # a cache-only hit block is counted in evictable() but
                # admission will PIN it (incref), not evict it — counting
                # it in both the reuse discount and the eviction budget
                # would approve admissions that deterministically fail
                evictable -= sum(
                    1 for b in hits if self.allocator.refcount(b) == 1
                )
        budget = self.allocator.num_free + max(0, evictable)
        return budget >= worst - reusable

    def admit(
        self, slot: int, request: Dict[str, Any]
    ) -> Tuple[Optional[int], bool]:
        """Prefill `request` into `slot`, reusing cached prefix blocks.

        With `prefill_chunk_tokens > 0` a prompt longer than one chunk
        admits CHUNKED: only the first chunk prefills here and the call
        returns (None, False) — step() advances one chunk per engine step
        (interleaved with other slots' decode) until the prompt is
        consumed and the first token samples. Shorter prompts (and
        chunking off) prefill whole and return (first_token, done) as
        before.

        Raises InsufficientBlocksError (retryable: the batcher parks the
        request) when the pool cannot cover the prompt itself."""
        bt = self.block_tokens
        prompt = np.asarray(request["tokens"], np.int32)
        if prompt.ndim != 1 or prompt.size == 0:
            raise ValueError("request['tokens'] must be a non-empty 1-D seq")
        length = int(prompt.size)
        # length == max_seq_len is admittable (unlike the dense engine): it
        # emits exactly ONE token and finishes without a cache write —
        # which is also what makes a generation preempted at its very last
        # position readmittable (its parked history fills the window)
        if length > self.max_seq_len:
            raise ValueError(
                f"prompt of {length} tokens exceeds max_seq_len "
                f"{self.max_seq_len}"
            )
        mnt = request.get("max_new_tokens")
        mnt = self.default_max_new_tokens if mnt is None else max(1, int(mnt))
        # a request whose WORST-CASE span can never fit the pool is
        # rejected before any token flows (predictability over optimism:
        # admitting it would stream tokens until self-preemption, then die
        # on readmission). length + max_new is invariant across preemption
        # cycles, so passing this check once means readmission can never
        # hard-fail by size.
        worst = self.worst_case_blocks(length, mnt)
        if worst > self.allocator.num_usable:
            raise ValueError(
                f"request worst case of {worst} KV blocks "
                f"({length} prompt + up to {mnt} new tokens) exceeds the "
                f"pool's {self.allocator.num_usable} blocks"
            )
        if self._live[slot]:
            self._release_blocks(slot)

        # cross-replica import: a transfer payload riding the request is
        # applied BEFORE the prefix lookup, so the imported chain is hit
        # by the normal admission path below (refcounted exactly like
        # locally-computed blocks). A payload that fails verification is
        # dropped — the lookup just misses and the span prefills from
        # scratch (the recompute fallback).
        kv_payload = request.get("kv_import")
        if kv_payload is not None:
            self.import_prefix(kv_payload, slot=slot)

        # prefix reuse: longest chain of cached FULL blocks, capped at
        # length-1 so at least one real token remains to prefill (its
        # hidden state produces the first sampled token)
        hit_blocks: List[int] = []
        if self.prefix_cache is not None and length > 1:
            hit_blocks = self.prefix_cache.lookup(prompt, (length - 1) // bt)
        p_hit = len(hit_blocks) * bt
        for b in hit_blocks:
            self.allocator.incref(b)

        total_prompt_blocks = -(-length // bt)
        need = total_prompt_blocks - len(hit_blocks)
        self._reclaim(need)
        try:
            new_blocks = self.allocator.alloc(need)
        except InsufficientBlocksError:
            for b in hit_blocks:
                self.allocator.decref(b)
            # retrying only helps if waiting can free blocks: another live
            # generation retiring. Without one, everything evictable was
            # already evicted (reclaim cascades the whole cache), so the
            # failure is PERMANENT — fail the request with a hard error
            # instead of letting the batcher park-and-retry it forever.
            others_live = any(
                self._live[s] for s in range(self.max_batch_size)
                if s != slot
            )
            if total_prompt_blocks > self.allocator.num_usable or not others_live:
                raise ValueError(
                    f"prompt needs {total_prompt_blocks} KV blocks "
                    f"({need} beyond its prefix hits) but only "
                    f"{self.allocator.num_free} of "
                    f"{self.allocator.num_usable} can free up"
                ) from None
            raise
        row = hit_blocks + new_blocks
        self._tables[slot, :] = 0
        self._tables[slot, :len(row)] = row
        self._row_blocks[slot] = len(row)
        self._live[slot] = True

        self._positions[slot] = p_hit  # committed span so far
        self._max_new[slot] = mnt
        self._new_counts[slot] = 0
        self._history[slot] = [int(t) for t in prompt[:length]]
        self._chunk_state[slot] = np.ascontiguousarray(
            prompt[:length], dtype=np.int32
        )
        self._seq += 1
        self._admit_seq[slot] = self._seq
        self.prefills += 1
        if hit_blocks:
            self.prefix_hits += 1
            self.prefix_tokens_reused += p_hit
        if self._rec is not None:
            self._rec.record("admit", slot=slot,
                             args={"prompt": length, "hit_tokens": p_hit})

        chunk = self.prefill_chunk_tokens
        if chunk and length - p_hit > chunk:
            # chunked admission: run the FIRST chunk now; step() advances
            # one chunk per engine step, interleaved with everyone else's
            # decode, until the prompt is consumed and the first token
            # samples — so a long prompt never stalls in-flight streams
            # for its whole prefill (the head-of-line latency fix)
            self.chunked_prefills += 1
            tok = self._run_prefill_chunk(slot)
        else:
            tok = self._run_prefill_chunk(slot, whole=True)
        if tok is None:
            return None, False
        done = self._done(slot, tok)
        if self.logprobs:
            # (token, logprob) pairs are the emitted unit in logprob mode;
            # the batcher pushes tuples atomically
            return (tok, float(self._lp_pending[slot])), done
        return tok, done

    def _run_prefill_chunk(self, slot: int, whole: bool = False) -> Optional[int]:
        """Consume the next prompt span of the slot's pending prefill
        (one prefill_chunk_tokens chunk, or the whole remainder with
        `whole=True`) through ONE paged-prefill dispatch. Returns the
        first sampled token when this call consumed the prompt's tail,
        else None (still prefilling; intermediate dispatches compute a
        throwaway sample — the B=1 unembed is noise next to the layers).

        The committed span is self._positions[slot]. Mid-prompt chunk
        boundaries need NOT be block-aligned: the prefill window math
        handles a chunk straddling a physical block (the straddled block
        is slot-owned — prefix-hit sharing is whole-block — so the quant
        path's requantize-owned rule keeps the CoW invariant; tests pin
        the straddle edge)."""
        import jax

        t0 = time.monotonic() if self._tel is not None else 0.0
        bt = self.block_tokens
        prompt = self._chunk_state[slot]
        ctx = int(self._positions[slot])
        length = int(prompt.size)
        rem = length - ctx
        take = rem if whole else min(self.prefill_chunk_tokens, rem)
        last = take == rem
        bucket = self._bucket(take)
        padded = np.zeros(bucket, np.int32)
        padded[:take] = prompt[ctx:ctx + take]
        ctx_blocks = self._ctx_bucket_blocks(ctx)
        self.prefill_shapes.add((ctx_blocks, -(-bucket // bt)))
        # intermediate chunks sample a throwaway token — give them a FIXED
        # key so only the completing dispatch consumes the engine's RNG
        # stream: one key per admission regardless of chunking, which is
        # what keeps temperature > 0 tokens invariant to the chunk config
        # (greedy never reads the key at all)
        key = self._next_key() if last else jax.random.PRNGKey(0)
        next_tok, logits, self.pool = self._prefill(
            self.params, self.pool, self._tables[slot],
            padded[None], np.int32(take), np.int32(ctx),
            key, ctx_blocks,
        )
        self._positions[slot] = ctx + take
        self.prefill_tokens += take
        self.prefill_chunks += 1
        if self._tel is not None:
            dur = time.monotonic() - t0
            self._tel.observe_phase("prefill", dur)
            if self._rec is not None:
                self._rec.record(
                    "prefill_chunk", slot=slot, dur=dur,
                    args={"tokens": take, "ctx": ctx, "last": bool(last)},
                )
        if not last:
            return None
        tok = int(next_tok[0])
        if self.logprobs:
            self._lp_pending[slot] = float(
                np.asarray(self._lp_fn(logits, next_tok))[0]
            )
        self._chunk_state[slot] = None
        self._last_tokens[slot] = tok
        self._new_counts[slot] = 1
        hist = self._history[slot]
        if hist is not None:
            hist.append(tok)
        self.tokens_generated += 1
        # make this prompt's full blocks (hit + freshly computed) reusable
        if self.prefix_cache is not None:
            reg = (length - 1) // bt
            if reg:
                self.prefix_cache.register(
                    prompt, [int(b) for b in self._tables[slot, :reg]]
                )
        return tok

    def fork(self, src: int, dst: int) -> None:
        """Share ALL of src's blocks (including the partial tail) with dst:
        zero-copy generation fork. The first divergent write into a shared
        block triggers copy-on-write in step()."""
        if not self._live[src]:
            raise ValueError(f"fork source slot {src} is not live")
        if self._chunk_state[src] is not None:
            raise ValueError(
                f"fork source slot {src} is still prefilling (chunked)"
            )
        if self._live[dst]:
            self._release_blocks(dst)
        self._tables[dst] = self._tables[src].copy()
        self._row_blocks[dst] = self._row_blocks[src]
        for bi in range(int(self._row_blocks[src])):
            b = int(self._tables[src, bi])
            if b:
                self.allocator.incref(b)
        self._live[dst] = True
        self._positions[dst] = self._positions[src]
        self._last_tokens[dst] = self._last_tokens[src]
        self._new_counts[dst] = self._new_counts[src]
        self._max_new[dst] = self._max_new[src]
        self._history[dst] = list(self._history[src] or [])
        self._seq += 1
        self._admit_seq[dst] = self._seq

    def force_token(self, slot: int, token: int) -> None:
        """Teacher-force the next input token for `slot` (replaces the
        pending sampled token — tests and speculative-decode hooks)."""
        if not self._live[slot]:
            raise ValueError(f"slot {slot} is not live")
        if self._chunk_state[slot] is not None:
            raise ValueError(
                f"slot {slot} is still prefilling (chunked) — no pending "
                "sampled token to replace"
            )
        self._last_tokens[slot] = int(token)
        hist = self._history[slot]
        if hist:
            hist[-1] = int(token)

    def step(self, slots: List[int]) -> Dict[int, Tuple[Any, bool]]:
        """One engine step for the live slots in `slots`. Slots the pool
        cannot grow are PREEMPTED (newest first) rather than OOMing; they
        are absent from the result and surface via take_preempted().

        Without speculation each slot's result is (token, done). With
        `speculative_k > 0` a step that verified drafts returns
        ([token, ...], done) — 1..k+1 tokens per slot — and steps where no
        slot drafted fall back to the plain single-token result.

        Slots still streaming a chunked prefill advance by ONE chunk per
        step and report ([], False) until their prompt is consumed (the
        completing chunk reports ([tok], done) with the first sampled
        token); every other slot decodes in the same step — chunk work
        and decode work interleave, so no decode stream ever waits for a
        whole long prompt."""
        surviving = [s for s in sorted(set(slots)) if self._live[s]]
        if not surviving:
            return {}
        out: Dict[int, Tuple[Any, bool]] = {}
        prefilling = [
            s for s in surviving if self._chunk_state[s] is not None
        ]
        for s in prefilling:
            tok = self._run_prefill_chunk(s)
            if tok is None:
                out[s] = ([], False)
            else:
                item = (
                    (tok, float(self._lp_pending[s]))
                    if self.logprobs else tok
                )
                out[s] = ([item], self._done(s, tok))
        decoding = [s for s in surviving if self._chunk_state[s] is None
                    and s not in out]
        if decoding:
            if self.speculative_k:
                drafts = self._propose(decoding)
                if any(drafts.values()):
                    out.update(self._spec_step(decoding, drafts))
                    return out
            out.update(self._plain_step(decoding))
        return out

    def _span_need(self, surviving: List[int], block_span) -> int:
        """Blocks the write spans require right now: unallocated entries
        plus shared blocks that must copy-on-write. Conservative across
        slots (a block shared between two stepping forks counts twice;
        the first CoW un-shares it for the second)."""
        need = 0
        for s in surviving:
            for bi in block_span(s):
                blk = int(self._tables[s, bi])
                if blk == 0 or self.allocator.refcount(blk) > 1:
                    need += 1
        return need

    def _reserve_write_spans(self, surviving: List[int], block_span) -> List[int]:
        """Make every block index in block_span(s) writable for each
        surviving slot — allocated and exclusively owned. ONE reservation
        contract for the plain step (span = the single write block) and
        the speculative step (span = the k+1-token verify window): evict
        cache blocks, preempt newest-first under pressure, then allocate
        + copy-on-write. Returns the surviving list (shrunk by
        preemptions). Note _reclaim cannot change the spans' own need
        (eviction only frees cache-ONLY blocks, refcount 1 — a span
        block is always also held by its slot), so need is computed once
        per pass.

        Newest-first is GLOBAL: slots mid-chunked-prefill are not in
        `surviving` (they allocated at admission and never step here) but
        they ARE preemption candidates — a freshly admitted long prompt
        is the newest work with the least to recompute, and exempting it
        would let one prefill serially evict every older decode stream
        (the exact head-of-line inversion chunking exists to fix). A
        preempted prefilling slot parks its full prompt and readmits like
        any other preemption."""
        prefilling = [
            s for s in range(self.max_batch_size)
            if self._live[s] and self._chunk_state[s] is not None
            and s not in surviving
        ]
        while True:
            need = self._span_need(surviving, block_span)
            self._reclaim(need)
            if need <= self.allocator.num_free:
                break
            victim = max(surviving + prefilling,
                         key=lambda s: self._admit_seq[s])
            self._preempt(victim)
            if victim in prefilling:
                prefilling.remove(victim)
                continue
            surviving.remove(victim)
            if not surviving:
                return surviving

        cow_src: List[int] = []
        cow_dst: List[int] = []
        for s in surviving:
            for bi in block_span(s):
                blk = int(self._tables[s, bi])
                if blk and self.allocator.refcount(blk) == 1:
                    continue  # an earlier CoW this step already un-shared it
                nb = self.allocator.alloc(1)[0]
                if blk:  # shared: copy-on-write before this slot's write
                    cow_src.append(blk)
                    cow_dst.append(nb)
                    self.allocator.decref(blk)
                    self.cow_copies += 1
                self._tables[s, bi] = nb
                self._row_blocks[s] = max(int(self._row_blocks[s]), bi + 1)
        if cow_src:
            self.pool = self._copy_blocks(
                self.pool, np.asarray(cow_src, np.int32),
                np.asarray(cow_dst, np.int32),
            )
        return surviving

    def _plain_step(self, surviving: List[int]) -> Dict[int, Tuple[Any, bool]]:
        bt = self.block_tokens
        t0 = time.monotonic() if self._tel is not None else 0.0

        # resolve this step's block needs (new block at a block boundary,
        # copy-on-write when the write block is shared) under pool pressure
        surviving = self._reserve_write_spans(
            surviving,
            lambda s: (int(self._positions[s]) // bt,),
        )
        if not surviving:
            return {}

        B = self.max_batch_size
        write_phys = np.zeros(B, np.int32)  # inactive rows -> null block
        write_off = np.zeros(B, np.int32)
        for s in surviving:
            pos = int(self._positions[s])
            write_phys[s] = self._tables[s, pos // bt]
            write_off[s] = pos % bt
        next_toks, logits, self.pool = self._decode_step(
            self.params, self.pool, self._tables, self._last_tokens,
            self._positions, write_phys, write_off, self._next_key(),
        )
        toks = np.asarray(next_toks)
        lps = (
            np.asarray(self._lp_fn(logits, next_toks))
            if self.logprobs else None
        )
        out: Dict[int, Tuple[Any, bool]] = {}
        for s in surviving:
            tok = int(toks[s])
            self._positions[s] += 1
            self._last_tokens[s] = tok
            self._new_counts[s] += 1
            hist = self._history[s]
            if hist is not None:
                hist.append(tok)
            item = (tok, float(lps[s])) if lps is not None else tok
            out[s] = (item, self._done(s, tok))
            if (self._rec is not None and self.eos_id is not None
                    and tok == self.eos_id):
                self._rec.record("eos", slot=s)
        self.decode_steps += 1
        self.tokens_generated += len(surviving)
        if self._tel is not None:
            dur = time.monotonic() - t0
            self._tel.observe_phase("decode", dur)
            if self._rec is not None:
                self._rec.record("decode", dur=dur,
                                 args={"slots": tuple(surviving)})
        return out

    # ----------------------------------------------------- speculative path

    def warmup_verify(self) -> int:
        """Compile every speculative verify bucket against the live pool
        (the probe writes touch only the null block, outputs are
        discarded). Call before a timed window or at replica start so a
        drafter's FIRST proposal mid-traffic does not bill a trace +
        compile to a real request. Returns the number of shapes warmed;
        no-op with speculation off or shapes already compiled."""
        if not self.speculative_k:
            return 0
        B = self.max_batch_size
        warmed = 0
        for k_eff in self._k_buckets:
            K1 = k_eff + 1
            if K1 in self.spec_shapes:
                continue
            zeros = np.zeros((B, K1), np.int32)
            _, _, self.pool = self._verify_step(
                self.params, self.pool, self._tables, zeros,
                np.zeros(B, np.int32), np.zeros(B, np.int32),
                zeros, zeros, self._next_key(),
            )
            self.spec_shapes.add(K1)
            warmed += 1
        return warmed

    def _propose(self, surviving: List[int]) -> Dict[int, List[int]]:
        """Ask the drafter for up to k tokens per slot, capped so the
        verify span can neither outrun max_new_tokens (at most
        remaining-1 drafts: the undrafted output is always one token) nor
        write past max_seq_len. Drafter faults and out-of-vocab tokens
        degrade to 'no draft' — a bad drafter may slow a stream down, it
        must never wedge or corrupt it."""
        drafts: Dict[int, List[int]] = {}
        for s in surviving:
            cap = min(
                self.speculative_k,
                int(self._max_new[s] - self._new_counts[s]) - 1,
                self.max_seq_len - 1 - int(self._positions[s]),
            )
            if cap <= 0:
                drafts[s] = []
                continue
            try:
                # the LIVE history list, not a copy — O(seq) boxing per
                # slot per step would erode the latency win speculation
                # exists for; drafters must treat it as read-only
                raw = self.drafter.propose(self._history[s] or (), cap)
            except Exception:
                raw = []
            clean: List[int] = []
            for t in list(raw)[:cap]:
                t = int(t)
                if not 0 <= t < self.cfg.vocab_size:
                    break
                clean.append(t)
            drafts[s] = clean
        return drafts

    def _spec_step(
        self, surviving: List[int], drafts: Dict[int, List[int]]
    ) -> Dict[int, Tuple[List[int], bool]]:
        """Verify each slot's draft in ONE batched forward and commit the
        accepted prefix. Block bookkeeping is the plain step's, widened to
        the k+1-token span: blocks for the whole span are taken up front
        (preempting newest-first under pressure, CoW for shared write
        blocks), and the rejected tail is rolled back afterwards by
        truncating the table — unused blocks go straight back to the
        allocator."""
        bt = self.block_tokens
        t0 = time.monotonic() if self._tel is not None else 0.0

        def _span_blocks(s: int):
            p = int(self._positions[s])
            return range(p // bt, (p + len(drafts.get(s, ()))) // bt + 1)

        # speculation must never cost a preemption that non-speculative
        # decode would not have paid: if the k+1-token spans cannot fit
        # the pool without evicting a generation, drop the drafts and
        # take the plain single-token step (which preempts only when even
        # THAT cannot fit). The feasibility probe is SIDE-EFFECT-FREE —
        # evictable() estimates what reclaim could free without actually
        # flushing prefix-cache blocks for a speculation we then abandon.
        need = self._span_need(surviving, _span_blocks)
        evictable = (
            self.prefix_cache.evictable() if self.prefix_cache else 0
        )
        if need > self.allocator.num_free + evictable:
            return self._plain_step(surviving)
        self._reclaim(need)
        if need > self.allocator.num_free:
            # reclaim under-delivered (evictable() counts blocks only a
            # cascade of leaf evictions could reach): still no preemption
            return self._plain_step(surviving)
        surviving = self._reserve_write_spans(surviving, _span_blocks)
        if not surviving:
            return {}

        kmax = max(len(drafts[s]) for s in surviving)
        k_eff = next(b for b in self._k_buckets if b >= kmax)
        K1 = k_eff + 1
        B = self.max_batch_size
        tokens = np.zeros((B, K1), np.int32)
        draft_len = np.zeros(B, np.int32)
        write_phys = np.zeros((B, K1), np.int32)  # dead/padded -> null block
        write_off = np.zeros((B, K1), np.int32)
        for s in surviving:
            p = int(self._positions[s])
            d = drafts.get(s, [])
            tokens[s, 0] = self._last_tokens[s]
            tokens[s, 1:1 + len(d)] = d
            draft_len[s] = len(d)
            for i in range(len(d) + 1):
                write_phys[s, i] = self._tables[s, (p + i) // bt]
                write_off[s, i] = (p + i) % bt
        out, accepted, self.pool = self._verify_step(
            self.params, self.pool, self._tables, tokens, self._positions,
            draft_len, write_phys, write_off, self._next_key(),
        )
        out = np.asarray(out)
        accepted = np.asarray(accepted)

        results: Dict[int, Tuple[List[int], bool]] = {}
        for s in surviving:
            a = int(accepted[s])
            final: List[int] = []
            done = False
            hist = self._history[s]
            for tok in (int(t) for t in out[s, :a + 1]):
                final.append(tok)
                self._positions[s] += 1
                self._new_counts[s] += 1
                if hist is not None:
                    hist.append(tok)
                if self._done(s, tok):
                    done = True
                    break
            self._last_tokens[s] = final[-1]
            # rollback: truncate the table past the last committed token —
            # span blocks the rejected tail reserved return to the pool
            keep = (int(self._positions[s]) - 1) // bt + 1
            for bi in range(keep, int(self._row_blocks[s])):
                blk = int(self._tables[s, bi])
                if blk:
                    self.allocator.decref(blk)
                    self._tables[s, bi] = 0
            self._row_blocks[s] = min(int(self._row_blocks[s]), keep)
            results[s] = (final, done)
            self.tokens_generated += len(final)
            self.spec_emitted += len(final)
            self.spec_slot_steps += 1
            self.spec_proposed += int(draft_len[s])
            self.spec_accepted += a
            if self._rec is not None:
                if a < int(draft_len[s]):
                    self._rec.record(
                        "rollback", slot=s,
                        args={"rejected": int(draft_len[s]) - a})
                if (self.eos_id is not None and final
                        and final[-1] == self.eos_id):
                    self._rec.record("eos", slot=s)
        self.decode_steps += 1
        self.spec_steps += 1
        self.spec_shapes.add(K1)
        if self._tel is not None:
            dur = time.monotonic() - t0
            self._tel.observe_phase("verify", dur)
            if self._rec is not None:
                self._rec.record(
                    "verify", dur=dur,
                    args={"slots": tuple(surviving),
                          "proposed": int(draft_len[list(surviving)].sum()),
                          "accepted": int(accepted[list(surviving)].sum())},
                )
        return results

    def take_preempted(self) -> List[Tuple[int, Dict[str, Any]]]:
        """(slot, parked_request) pairs preempted since the last call. The
        parked request readmits through the normal admit path (prefill of
        prompt + generated so far = recompute-on-readmit)."""
        out, self._preempted = self._preempted, []
        return out

    def release(self, slot: int) -> None:
        """Free a slot's blocks (idempotent; cache-registered blocks stay
        resident under the cache's own reference until evicted)."""
        if self._live[slot]:
            if self._rec is not None:
                self._rec.record(
                    "retire", slot=slot,
                    args={"tokens": int(self._new_counts[slot])})
            self._release_blocks(slot)
        self._new_counts[slot] = 0

    # --------------------------------------------------- live weight hot-swap

    def set_params(self, params, version: Optional[int] = None,
                   bytes_pulled: int = 0) -> int:
        """Swap the engine's weights between steps (live weight update —
        serve/weight_swap.py routes here via ContinuousBatcher.run_on_loop;
        loop thread only, like admit/step). Returns the new version.

        Swap semantics are RECOMPUTE, not splice: every live slot is
        preempted (full history parked; the batcher readmits it and
        prompt + generated-so-far prefills under the NEW weights) and the
        prefix cache is flushed, so KV computed under the old weights can
        never attend to new-weight queries. That is exactly what makes
        every post-swap token greedy-identical to a fresh engine loaded
        with the new weights — splicing stale KV under new weights would
        emit tokens NEITHER model would produce. In-flight streams stay
        open throughout (recompute-on-readmit, the preemption contract);
        their consumers see added latency, never a drop.

        The transfer signature is re-derived with the new version, so
        cross-replica chain keys minted under the old weights are
        disjoint from the new key space by construction, and the drafter
        is refreshed (refresh(params) hook when it has one, stale state
        cleared) so swap-then-speculate proposes from the new weights."""
        t0 = time.monotonic()
        for s in range(self.max_batch_size):
            if self._live[s]:
                self._preempt(s)
        flushed = (
            self.prefix_cache.flush() if self.prefix_cache is not None else 0
        )
        self.params = params
        self.weight_version = (
            int(version) if version is not None else self.weight_version + 1
        )
        self.transfer_sig = self._compute_transfer_sig()
        self.weight_swaps += 1
        drafter = self.drafter
        if drafter is not None:
            refresh = getattr(drafter, "refresh", None)
            if refresh is not None:
                try:
                    refresh(params)
                except Exception:
                    # drafter faults degrade to 'no draft' (the _propose
                    # contract) — they must never fail the swap
                    pass
        if self._tel is not None:
            gauge = getattr(self._tel, "weight_version", None)
            if gauge is not None:
                gauge.set(self.weight_version)
        if self._rec is not None:
            self._rec.record(
                "weight_swap", dur=time.monotonic() - t0,
                args={"version": self.weight_version,
                      "bytes": int(bytes_pulled),
                      "flushed_blocks": flushed},
            )
        return self.weight_version

    # --------------------------------------------- cross-replica KV transfer

    def transfer_keys(self, tokens, n_blocks: int) -> List[bytes]:
        """Content-addressed keys for the prompt's first `n_blocks` FULL
        blocks. The chain is seeded with `transfer_sig` (model_id + block
        geometry + pool dtype + layer/head shape) and extended per block
        with its int32 token bytes — so two replicas of the same
        deployment compute identical keys for identical prefixes, in any
        process, while engines differing in ANY layout knob compute
        disjoint key spaces. This chain is deliberately separate from the
        in-process PrefixCache key chain (which has no cross-engine
        identity to carry)."""
        prompt = np.asarray(tokens, np.int32)
        bt = self.block_tokens
        if prompt.size < n_blocks * bt:
            raise ValueError(
                f"need {n_blocks * bt} tokens for {n_blocks} blocks, "
                f"got {prompt.size}"
            )
        keys: List[bytes] = []
        key = self.transfer_sig
        for bi in range(int(n_blocks)):
            h = hashlib.sha1()
            h.update(key)
            h.update(np.ascontiguousarray(
                prompt[bi * bt:(bi + 1) * bt], np.int32).tobytes())
            key = h.digest()
            keys.append(key)
        return keys

    def export_prefix(
        self, tokens, max_blocks: Optional[int] = None
    ) -> Optional[Dict[str, Any]]:
        """Export the longest cached chain of full blocks matching the
        prompt prefix as a self-verifying payload: chain keys, the token
        span they cover, and the block contents gathered from the pool
        (k/v, plus k_scale/v_scale on int8 pools). Returns None on a
        cache miss. Runs on the LOOP THREAD (same ownership contract as
        admit/step — the match and the pool gather must see one
        consistent pool state); serving code routes here via
        ContinuousBatcher.run_on_loop."""
        if self.prefix_cache is None:
            return None
        prompt = np.asarray(tokens, np.int32)
        if prompt.ndim != 1:
            return None
        bt = self.block_tokens
        cap = int(prompt.size) // bt
        if max_blocks is not None:
            cap = min(cap, int(max_blocks))
        if cap <= 0:
            return None
        blocks = self.prefix_cache.match_blocks(prompt, cap)
        if not blocks:
            return None
        n = len(blocks)
        idx = np.asarray(blocks, np.int32)
        payload = {
            "sig": self.transfer_sig,
            "keys": self.transfer_keys(prompt, n),
            "tokens": np.ascontiguousarray(prompt[:n * bt], np.int32),
            "block_tokens": bt,
            "kv_cache_dtype": self.kv_cache_dtype,
            "blocks": {
                name: np.asarray(self.pool[name][:, idx])
                for name in self.pool
            },
        }
        self.kv_exports += 1
        self.kv_blocks_exported += n
        if self._rec is not None:
            self._rec.record("kv_export",
                             args={"blocks": n, "tokens": n * bt})
        return payload

    def import_prefix(self, payload: Dict[str, Any], slot: int = -1) -> int:
        """Install an exported prefix into the local pool + PrefixCache.
        Returns the number of tokens newly imported (0 = nothing new:
        already cached locally, or the payload failed verification and
        was dropped — callers treat 0-with-reject as the recompute
        fallback). Verification is strict: the engine signature must
        match, the chain keys must recompute from the shipped tokens, and
        every block leaf must match the pool's slice shape and dtype — a
        payload from a different model, kv dtype, or block geometry can
        never be installed. Imported blocks end up held by the cache at
        refcount 1, exactly like locally-computed chain blocks. Loop
        thread only (admit() applies request-borne payloads itself)."""
        import jax.numpy as jnp

        bt = self.block_tokens
        tokens = None
        n = 0
        ok = (
            isinstance(payload, dict)
            and payload.get("sig") == self.transfer_sig
            and int(payload.get("block_tokens") or 0) == bt
            and payload.get("kv_cache_dtype") == self.kv_cache_dtype
        )
        if ok:
            tokens = np.asarray(payload.get("tokens"), np.int32)
            keys = list(payload.get("keys") or ())
            n = len(keys)
            ok = (
                n > 0 and tokens.ndim == 1 and tokens.size == n * bt
                and self.transfer_keys(tokens, n) == keys
            )
        if ok:
            blocks = payload.get("blocks")
            ok = isinstance(blocks, dict) and set(blocks) == set(self.pool)
            if ok:
                for name, arr in blocks.items():
                    ref = self.pool[name]
                    want = (ref.shape[0], n) + tuple(ref.shape[2:])
                    if (tuple(np.shape(arr)) != want
                            or np.dtype(arr.dtype) != np.dtype(ref.dtype)):
                        ok = False
                        break
        if not ok or self.prefix_cache is None:
            self.kv_import_rejects += 1
            if self._rec is not None:
                self._rec.record("kv_import", slot=slot,
                                 args={"rejected": True})
            return 0
        local = self.prefix_cache.match_blocks(tokens, n)
        m = len(local)
        if m >= n:
            return 0  # whole span already cached locally — nothing to do
        need = n - m
        self._reclaim(need)
        try:
            new_blocks = self.allocator.alloc(need)
        except InsufficientBlocksError:
            # pool pressure, not payload fault — still a recompute
            # fallback from the caller's point of view
            self.kv_import_rejects += 1
            if self._rec is not None:
                self._rec.record("kv_import", slot=slot,
                                 args={"rejected": True, "blocks": need})
            return 0
        idx = np.asarray(new_blocks, np.int32)
        pool = dict(self.pool)
        for name, arr in payload["blocks"].items():
            src = jnp.asarray(np.asarray(arr)[:, m:n])
            pool[name] = pool[name].at[:, idx].set(src)
        self.pool = pool
        # register increfs only the NEW nodes; dropping our allocation
        # reference leaves them cache-held at refcount 1 — identical to a
        # retired locally-computed chain
        self.prefix_cache.register(tokens, local + new_blocks)
        for b in new_blocks:
            self.allocator.decref(b)
        self.kv_imports += 1
        self.kv_blocks_imported += need
        self.kv_tokens_imported += need * bt
        if self._rec is not None:
            self._rec.record(
                "kv_import", slot=slot,
                args={"blocks": need, "reused": m, "tokens": need * bt},
            )
        return need * bt

    def stats(self) -> Dict[str, Any]:
        used = self.allocator.num_usable - self.allocator.num_free
        return {
            # flight recorder (serve/telemetry.py): events currently held
            # in the ring + lifetime total (dropped = total - held)
            "flight_events": len(self._rec) if self._rec is not None else 0,
            "flight_events_total": (
                self._rec.total if self._rec is not None else 0
            ),
            "tokens_generated": self.tokens_generated,
            "prefills": self.prefills,
            "prefill_tokens": self.prefill_tokens,
            # chunked prefill: 0 chunk tokens = whole-prompt admission
            "prefill_chunk_tokens": self.prefill_chunk_tokens,
            "prefill_chunks": self.prefill_chunks,
            "chunked_prefills": self.chunked_prefills,
            "prefilling": sum(
                1 for st in self._chunk_state if st is not None
            ),
            "decode_steps": self.decode_steps,
            "max_batch_size": self.max_batch_size,
            "block_tokens": self.block_tokens,
            "kv_cache_dtype": self.kv_cache_dtype,
            "attention_impl": self.attention_impl,
            "attention_chunk_blocks": self.chunk_blocks,
            "kv_block_bytes": self.kv_block_bytes,
            # true pool HBM: counts the reserved null block too, so this
            # reconciles exactly with a serve_kv_pool_mb budget
            "kv_pool_bytes": self.kv_block_bytes * self.num_blocks,
            "kv_blocks_total": self.allocator.num_usable,
            "kv_blocks_free": self.allocator.num_free,
            "kv_block_utilization": round(
                used / max(1, self.allocator.num_usable), 4
            ),
            "kv_blocks_cached": (
                self.prefix_cache.evictable() if self.prefix_cache else 0
            ),
            "prefix_hits": self.prefix_hits,
            "prefix_tokens_reused": self.prefix_tokens_reused,
            # cross-replica KV transfer (serve/kv_transfer.py): rejects
            # count payloads dropped at verification or under pool
            # pressure — each one is a recompute fallback upstream
            "kv_exports": self.kv_exports,
            "kv_blocks_exported": self.kv_blocks_exported,
            "kv_imports": self.kv_imports,
            "kv_blocks_imported": self.kv_blocks_imported,
            "kv_tokens_imported": self.kv_tokens_imported,
            "kv_import_rejects": self.kv_import_rejects,
            # live weight hot-swap (serve/weight_swap.py)
            "weight_version": self.weight_version,
            "weight_swaps": self.weight_swaps,
            "preemptions": self.preemptions,
            "cow_copies": self.cow_copies,
            # speculative decoding: k=0 means off; rates cover spec steps
            # only (a step where nobody drafted is a plain decode step)
            "spec_k": self.speculative_k,
            "spec_steps": self.spec_steps,
            "spec_slot_steps": self.spec_slot_steps,
            "spec_proposed_tokens": self.spec_proposed,
            "spec_accepted_tokens": self.spec_accepted,
            "spec_emitted_tokens": self.spec_emitted,
            "spec_accept_rate": round(
                self.spec_accepted / max(1, self.spec_proposed), 4
            ),
            # average accepted burst length per slot per verify step
            # (1..k+1) — batch-size-independent, unlike tokens per ENGINE
            # step which would just re-measure occupancy
            "spec_tokens_per_step": round(
                self.spec_emitted / max(1, self.spec_slot_steps), 2
            ),
        }
