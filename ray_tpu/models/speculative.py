"""Draft-token proposers for speculative decoding on the paged engine.

Speculative decoding splits token generation into a cheap PROPOSE and an
exact VERIFY: a drafter guesses the next k tokens from the sequence so
far, and the target model scores all k+1 positions in one batched paged
decode step (`make_paged_decoder`'s `paged_verify_step`). Accepted
tokens commit; the first mismatch rolls the rest back. Greedy output is
token-for-token what non-speculative decode would have produced — the
drafter only changes HOW FAST tokens arrive, never WHICH tokens.

A drafter is anything with

    propose(tokens: Sequence[int], k: int) -> Sequence[int]

where `tokens` is the slot's full history (prompt + generated so far) and
the return is up to k guesses for what comes next (shorter, including
empty, is always legal — the engine pads short proposals and falls back
to the plain single-token step when nobody proposes). Proposals must be
CHEAP relative to a decode step: they run on the batcher's loop thread
between steps. The engine passes its LIVE history sequence (no per-step
copy); drafters must treat `tokens` as read-only.

A drafter MAY also expose

    refresh(params) -> None

which the engine calls on a live weight hot-swap
(`PagedDecodeEngine.set_params`) with the NEW param tree: a
small-draft-model drafter re-derives its model there, a recording
drafter drops continuations minted under the old weights. Greedy output
stays identical either way (verify rejects any stale draft), so refresh
is a throughput lever, not a correctness one; refresh faults are
swallowed by the engine (same degrade-to-no-draft contract as propose).

Built-ins:

  NGramDrafter   self-drafting suffix lookup (prompt-lookup decoding): find
                 the most recent earlier occurrence of the history's last
                 n-gram and propose what followed it. No extra model, no
                 device work — it wins whenever generation revisits spans
                 it has produced or read before (code, quotes, structured
                 output, greedy cycles).
  ReplayDrafter  proposes continuations from recorded sequences whose
                 prefix matches the history. The perfect-draft harness for
                 benchmarks/tests (accept rate 1.0 by construction) and
                 the shape a small-draft-model hook takes: anything that
                 can guess a continuation plugs in the same way.
"""

from __future__ import annotations

from typing import Callable, List, Optional, Sequence

import numpy as np


class NGramDrafter:
    """Suffix-lookup self-drafting: match the last `n` tokens (longest n
    first) against the rest of the history; propose the continuation of
    the most recent match."""

    def __init__(self, max_n: int = 3, min_n: int = 1,
                 max_history: int = 4096):
        if not (1 <= min_n <= max_n):
            raise ValueError(f"need 1 <= min_n <= max_n, got {min_n}..{max_n}")
        self.max_n = int(max_n)
        self.min_n = int(min_n)
        self.max_history = int(max_history)

    def propose(self, tokens: Sequence[int], k: int) -> List[int]:
        # slice BEFORE converting: the history is the engine's live list
        # and can far exceed the lookup window
        arr = np.asarray(tokens[-self.max_history:], np.int64)
        L = arr.size
        for n in range(self.max_n, self.min_n - 1, -1):
            if L <= n:
                continue
            pat = arr[-n:]
            # windows starting at 0..L-n-1: every occurrence EXCEPT the
            # suffix itself (whose continuation is the future we want)
            win = np.lib.stride_tricks.sliding_window_view(arr, n)[:-1]
            hits = np.nonzero((win == pat).all(axis=1))[0]
            if hits.size:
                i = int(hits[-1])
                return arr[i + n:i + n + k].tolist()
        return []

    def refresh(self, params) -> None:
        """Weight hot-swap hook: self-drafting holds no model state — the
        engine re-prefills every live history under the new weights, so
        the lookup source is already consistent. Nothing to do."""


class ReplayDrafter:
    """Propose from recorded sequences: if the history is a proper prefix
    of any recorded sequence, the next k recorded tokens are the draft."""

    def __init__(self, sequences: Sequence[Sequence[int]]):
        self.sequences = [[int(t) for t in s] for s in sequences]

    def propose(self, tokens: Sequence[int], k: int) -> List[int]:
        hist = [int(t) for t in tokens]
        n = len(hist)
        for seq in self.sequences:
            if len(seq) > n and seq[:n] == hist:
                return seq[n:n + k]
        return []

    def refresh(self, params) -> None:
        """Weight hot-swap hook: recorded continuations were sampled from
        the OLD weights — keeping them cannot corrupt output (verify
        rejects mismatches) but would burn a rejected verify span per
        step, so drop them."""
        self.sequences = []


class _CallableDrafter:
    def __init__(self, fn: Callable[[Sequence[int], int], Sequence[int]]):
        self._fn = fn

    def propose(self, tokens: Sequence[int], k: int) -> Sequence[int]:
        return self._fn(tokens, k)


def resolve_drafter(spec) -> Optional[object]:
    """Turn a config value into a drafter: 'ngram' / 'ngram:<max_n>' build
    the built-in, ''/'off'/None disable, and any object with .propose (or
    a bare callable — the small-draft-model hook) passes through."""
    if spec is None:
        return None
    if isinstance(spec, str):
        s = spec.strip().lower()
        if s in ("", "off", "none"):
            return None
        if s == "ngram":
            return NGramDrafter()
        if s.startswith("ngram:"):
            return NGramDrafter(max_n=int(s[len("ngram:"):]))
        raise ValueError(
            f"unknown drafter {spec!r}: expected 'ngram', 'ngram:<max_n>', "
            "'off', or an object with propose(tokens, k)"
        )
    if hasattr(spec, "propose"):
        return spec
    if callable(spec):
        return _CallableDrafter(spec)
    raise ValueError(f"drafter {spec!r} has no propose(tokens, k)")
