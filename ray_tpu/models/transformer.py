"""Decoder-only transformer LM (llama-family), TPU-first.

Design (idiomatic JAX, not a port — the reference has no in-repo LM; its
model-parallel story is external Alpa, release/alpa_tests/):
  - params are a plain dict pytree; every leaf has a logical-axis tuple in a
    parallel `param_specs` tree, mapped to mesh axes by ShardingRules —
    DP/FSDP/TP/EP are sharding-table entries, not code paths.
  - layers are STACKED and scanned (lax.scan over a [L, ...] leading dim):
    one compiled layer body regardless of depth — compile time O(1) in
    layers, and XLA pipelines the scan on TPU.
  - each scan step is jax.checkpoint'ed (rematerialization: trade MXU FLOPs
    for HBM, the standard TPU memory trade).
  - attention impl is selectable: dense (small L), ring (sequence-parallel
    over `sp` via ppermute ring), ulysses (all-to-all head scatter).
  - bf16 compute, f32 params/accumulators.

Decode fast path (serving): `make_decoder` builds prefill + cached
single-token decode — a per-layer KV cache allocated at `max_seq_len`,
written at each sequence's current position and sharded by the same
partition rules as activations, so every generated token pays O(L)
attention reads instead of the O(L^2) full-sequence forward. The decode
step is jit-compiled once (per cache batch size) and reused; see
`ray_tpu/models/decoding.py` for the slot-based engine continuous
batching drives.

Paged variant (serving at scale): `init_paged_kv_cache` + `make_paged_decoder`
swap the per-slot slab for a pool of fixed-size token blocks addressed
through per-slot block tables (gathered inside the jitted step — one
compiled shape regardless of live lengths). Host-side allocation, prefix
reuse and preemption live in `ray_tpu/models/kv_paging.py`.
"""

from __future__ import annotations

import dataclasses
import math
from functools import partial
from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax

from ..ops.attention import (
    NEG_INF,
    _repeat_kv,
    causal_attention,
    causal_attention_bhsd,
)
from ..ops.norm import rms_norm
from ..ops.ring_attention import ring_attention
from ..ops.rope import apply_rope, apply_rope_bhsd, rope_frequencies
from ..ops.ulysses import ulysses_attention
from ..ops.losses import (
    blockwise_softmax_cross_entropy,
    softmax_cross_entropy_with_int_labels,
)
from ..parallel.sharding import ShardingRules, constrain


@dataclasses.dataclass(frozen=True)
class TransformerConfig:
    vocab_size: int = 32000
    d_model: int = 768
    n_layers: int = 12
    n_heads: int = 12
    n_kv_heads: int = 12
    d_head: int = 64
    d_ff: int = 3072
    max_seq_len: int = 2048
    rope_theta: float = 10000.0
    dtype: Any = jnp.bfloat16
    attention: str = "dense"  # dense | flash | ring | ulysses
    remat: bool = True
    # what the per-layer checkpoint saves: "full" recomputes everything
    # (max memory savings), "dots_no_batch" keeps weight-matmul outputs and
    # recomputes only attention + elementwise (the usual best MFU/memory
    # trade), "dots" keeps every dot product, "flash" = dots_no_batch plus
    # the attention-kernel output (backward never re-runs the kernel),
    # "flash_min" = ONLY the named residuals backward actually reads
    # (rope'd q/k, v, attention out+lse, mlp gate/up) — the best measured
    # MFU on the 125M bench
    remat_policy: str = "full"
    # flash attention tile sizes; on v5e big tiles win (grid overhead
    # dominates small blocks — measured 310ms @128 vs 234ms @1024 on the
    # 125M single-chip bench)
    flash_block_q: int = 1024
    flash_block_k: int = 1024
    # True: one lax.scan over stacked layers (O(1) compile in depth; the
    # multi-chip/pp path requires it). False: unrolled python loop —
    # longer compiles but drops the scan's stack dynamic-slice/update
    # traffic (~5% step time at 12 layers on v5e)
    scan_layers: bool = True
    # MoE (expert parallel); n_experts=0 -> dense MLP
    n_experts: int = 0
    top_k: int = 2
    # "dispatch": capacity-based top-k routing (FLOPs scale with top_k) —
    # the real EP path; "dense": every expert computes every token (exact
    # oracle for tests, O(n_experts) FLOPs)
    moe_impl: str = "dispatch"
    moe_capacity_factor: float = 1.25
    tie_embeddings: bool = False
    # "silu_gate": llama-family gated MLP (w_gate/w_up/w_down, silu) —
    # the default everywhere. "gelu": gpt2-family two-matmul MLP
    # (w_up/w_down, tanh-approx gelu, no gate) — what the model hub's
    # gpt2-class checkpoint mapping loads into (models/hub/checkpoint.py);
    # dense MLP only (MoE keeps the gated experts)
    mlp_variant: str = "silu_gate"
    # trailing vocab entries that exist only for sharding alignment (e.g.
    # a checkpoint's 50257-token vocab padded to 50304 so the vocab dim
    # divides a tp mesh): embedding rows are zero, and the samplers mask
    # their logits to -inf so a padded id can never be emitted
    vocab_pad: int = 0
    # pipeline parallelism: >1 splits the layer stack into pp stages
    pp_stages: int = 1
    pp_microbatches: int = 4
    # interleaved-1F1B depth v (parallel/pipeline.py): each pipeline device
    # hosts v of the pp_stages chunks (round-robin: chunk q on device
    # q % (pp_stages/v)), shrinking the bubble toward (pp-1)/(v*n_mb+pp-1).
    # Requires pp_stages % pp_interleave == 0 and pp_microbatches divisible
    # by the per-device stage count pp_stages // pp_interleave.
    pp_interleave: int = 1
    # >0: the training loss never materializes full [tokens, vocab] logits;
    # the unembed matmul + log-softmax run per seq-chunk of this size under
    # jax.checkpoint (ops/losses.py blockwise_softmax_cross_entropy). Frees
    # O(tokens x vocab) residual HBM — worth a batch-size step on 16G chips
    loss_chunk: int = 0

    def __post_init__(self):
        if self.mlp_variant not in ("silu_gate", "gelu"):
            raise ValueError(
                f"mlp_variant must be 'silu_gate' or 'gelu', "
                f"got {self.mlp_variant!r}"
            )
        if self.pp_interleave < 1:
            raise ValueError(
                f"pp_interleave must be >= 1, got {self.pp_interleave}"
            )
        if self.pp_stages % self.pp_interleave:
            raise ValueError(
                f"pp_stages {self.pp_stages} not divisible by "
                f"pp_interleave {self.pp_interleave}"
            )

    def flops_per_token(self) -> float:
        """Approximate training FLOPs/token (fwd+bwd ≈ 6 * params-matmul)."""
        attn = 2 * self.d_model * self.d_head * (self.n_heads + 2 * self.n_kv_heads)
        attn += 2 * self.n_heads * self.d_head * self.d_model
        mlp_mult = self.n_experts if self.n_experts else 1
        n_mats = 2 if (not self.n_experts and self.mlp_variant == "gelu") else 3
        mlp = n_mats * 2 * self.d_model * self.d_ff * (min(self.top_k, mlp_mult) if self.n_experts else 1)
        per_layer = attn + mlp
        # attention scores/values: 2 * 2 * L * d per token (L = seq len, set at call)
        embed = 2 * self.d_model * self.vocab_size
        return 3 * (self.n_layers * per_layer + embed)

    def attention_flops_per_token(self, seq_len: int) -> float:
        return 3 * self.n_layers * (2 * 2 * seq_len * self.n_heads * self.d_head)

    def num_params(self) -> int:
        lp = (
            2 * self.d_model  # norms
            + self.d_model * self.d_head * (self.n_heads + 2 * self.n_kv_heads)
            + self.n_heads * self.d_head * self.d_model
        )
        if self.n_experts:
            lp += self.d_model * self.n_experts  # router
            lp += self.n_experts * 3 * self.d_model * self.d_ff
        else:
            lp += (2 if self.mlp_variant == "gelu" else 3) * self.d_model * self.d_ff
        total = self.n_layers * lp + self.d_model
        total += self.vocab_size * self.d_model * (1 if self.tie_embeddings else 2)
        return total


CONFIGS: Dict[str, TransformerConfig] = {
    "tiny": TransformerConfig(
        vocab_size=256, d_model=64, n_layers=2, n_heads=4, n_kv_heads=2,
        d_head=16, d_ff=128, max_seq_len=128,
    ),
    "tiny_moe": TransformerConfig(
        vocab_size=256, d_model=64, n_layers=2, n_heads=4, n_kv_heads=2,
        d_head=16, d_ff=128, max_seq_len=128, n_experts=4, top_k=2,
    ),
    # GPT-2 small scale (125M) — the single-host integration model
    "gpt2_125m": TransformerConfig(
        vocab_size=50304, d_model=768, n_layers=12, n_heads=12, n_kv_heads=12,
        d_head=64, d_ff=3072, max_seq_len=1024,
    ),
    # ~1.15B params — the single-chip HBM-limit config: fp32 params/adam-v
    # + bf16 momentum fill most of a v5e's 16G; flash attention +
    # flash_qkv remat (mlp gate/up recomputed) + chunked loss keep
    # activations/logits in budget. Measured 0.55 MFU at batch 6 on v5e.
    "gpt_1b": TransformerConfig(
        vocab_size=50304, d_model=2048, n_layers=14, n_heads=16, n_kv_heads=16,
        d_head=128, d_ff=8192, max_seq_len=1024, loss_chunk=256,
    ),
    # Llama-2 7B — the BASELINE.json north-star config
    "llama2_7b": TransformerConfig(
        vocab_size=32000, d_model=4096, n_layers=32, n_heads=32, n_kv_heads=32,
        d_head=128, d_ff=11008, max_seq_len=4096,
    ),
    # Llama-3-8B-style GQA config
    "llama3_8b": TransformerConfig(
        vocab_size=128256, d_model=4096, n_layers=32, n_heads=32, n_kv_heads=8,
        d_head=128, d_ff=14336, max_seq_len=8192, rope_theta=500000.0,
    ),
}


# --------------------------------------------------------------------------
# params
# --------------------------------------------------------------------------


def param_specs(cfg: TransformerConfig) -> Dict[str, Any]:
    """Logical-axis tuples mirroring the param pytree. With pp_stages>1 the
    layer leaves carry a leading ("stage",) dim sharded on the pp axis."""
    layer = {
        "attn_norm": ("layers", "embed"),
        "wq": ("layers", "embed", "heads", "head_dim"),
        "wk": ("layers", "embed", "kv_heads", "head_dim"),
        "wv": ("layers", "embed", "kv_heads", "head_dim"),
        "wo": ("layers", "heads", "head_dim", "embed"),
        "mlp_norm": ("layers", "embed"),
    }
    if cfg.n_experts:
        layer.update(
            router=("layers", "embed", "expert"),
            w_gate=("layers", "expert", "embed", "mlp"),
            w_up=("layers", "expert", "embed", "mlp"),
            w_down=("layers", "expert", "mlp", "embed"),
        )
    elif cfg.mlp_variant == "gelu":
        layer.update(
            w_up=("layers", "embed", "mlp"),
            w_down=("layers", "mlp", "embed"),
        )
    else:
        layer.update(
            w_gate=("layers", "embed", "mlp"),
            w_up=("layers", "embed", "mlp"),
            w_down=("layers", "mlp", "embed"),
        )
    if cfg.pp_stages > 1:
        layer = {k: ("stage",) + v for k, v in layer.items()}
    specs = {
        "embed": ("vocab", "embed"),
        "layers": layer,
        "final_norm": ("embed",),
    }
    if not cfg.tie_embeddings:
        specs["unembed"] = ("embed", "vocab")
    return specs


def init_params(rng: jax.Array, cfg: TransformerConfig) -> Dict[str, Any]:
    L, E, H, KV, D, F = (
        cfg.n_layers, cfg.d_model, cfg.n_heads, cfg.n_kv_heads, cfg.d_head, cfg.d_ff,
    )
    keys = iter(jax.random.split(rng, 16))

    def norm_init(*shape):
        return jnp.ones(shape, jnp.float32)

    def dense_init(key, shape, fan_in):
        return (jax.random.normal(key, shape, jnp.float32) / math.sqrt(fan_in))

    layer: Dict[str, Any] = {
        "attn_norm": norm_init(L, E),
        "wq": dense_init(next(keys), (L, E, H, D), E),
        "wk": dense_init(next(keys), (L, E, KV, D), E),
        "wv": dense_init(next(keys), (L, E, KV, D), E),
        "wo": dense_init(next(keys), (L, H, D, E), H * D),
        "mlp_norm": norm_init(L, E),
    }
    if cfg.n_experts:
        X = cfg.n_experts
        layer.update(
            router=dense_init(next(keys), (L, E, X), E),
            w_gate=dense_init(next(keys), (L, X, E, F), E),
            w_up=dense_init(next(keys), (L, X, E, F), E),
            w_down=dense_init(next(keys), (L, X, F, E), F),
        )
    elif cfg.mlp_variant == "gelu":
        layer.update(
            w_up=dense_init(next(keys), (L, E, F), E),
            w_down=dense_init(next(keys), (L, F, E), F),
        )
    else:
        layer.update(
            w_gate=dense_init(next(keys), (L, E, F), E),
            w_up=dense_init(next(keys), (L, E, F), E),
            w_down=dense_init(next(keys), (L, F, E), F),
        )
    if cfg.pp_stages > 1:
        if L % cfg.pp_stages:
            raise ValueError(f"n_layers {L} not divisible by pp_stages {cfg.pp_stages}")
        lps = L // cfg.pp_stages
        layer = {
            k: v.reshape((cfg.pp_stages, lps) + v.shape[1:]) for k, v in layer.items()
        }
    params = {
        "embed": dense_init(next(keys), (cfg.vocab_size, E), E) * math.sqrt(E) * 0.02,
        "layers": layer,
        "final_norm": norm_init(E),
    }
    if not cfg.tie_embeddings:
        params["unembed"] = dense_init(next(keys), (E, cfg.vocab_size), E)
    return params


# --------------------------------------------------------------------------
# forward
# --------------------------------------------------------------------------


def _moe_dense(h, lp, cfg: TransformerConfig):
    """Dense-dispatch oracle: every expert computes every token; the top-k
    router weights zero out non-selected experts. Exact but O(n_experts)
    FLOPs — kept as the correctness reference for the dispatch path."""
    gate_logits = jnp.einsum("bse,ex->bsx", h, lp["router"].astype(h.dtype))
    probs = jax.nn.softmax(gate_logits.astype(jnp.float32), axis=-1)
    top_vals, _ = lax.top_k(probs, cfg.top_k)
    thresh = top_vals[..., -1:]
    gate = jnp.where(probs >= thresh, probs, 0.0)
    gate = gate / jnp.maximum(gate.sum(-1, keepdims=True), 1e-9)
    g = jnp.einsum("bse,xef->bsxf", h, lp["w_gate"].astype(h.dtype))
    u = jnp.einsum("bse,xef->bsxf", h, lp["w_up"].astype(h.dtype))
    y = jnp.einsum("bsxf,xfe->bsxe", jax.nn.silu(g) * u, lp["w_down"].astype(h.dtype))
    return jnp.einsum("bsxe,bsx->bse", y, gate.astype(h.dtype))


def _moe_dispatch(h, lp, cfg: TransformerConfig, constrain_fn):
    """Capacity-based top-k MoE (GShard/Switch family, TPU-first):

    tokens are sorted by destination expert and scattered into a fixed
    [n_experts, capacity, d_model] buffer; the expert FFNs run as ONE
    batched matmul over that buffer; outputs scatter-add back weighted by
    the (renormalized) router probabilities. FLOPs scale with top_k * N *
    capacity_factor — independent of n_experts. Under an `ep`-sharded mesh
    the sharding constraint on the buffer makes GSPMD insert the token
    all-to-alls (SURVEY §2.4 "mesh expert axis + ragged all-to-all");
    overflow beyond capacity is dropped (standard capacity-factor trade).
    Static shapes throughout: sort + gather/scatter, no ragged compute."""
    B, S, E = h.shape
    N = B * S
    X, k = cfg.n_experts, cfg.top_k
    C = min(N, max(1, math.ceil(k * N / X * cfg.moe_capacity_factor)))

    x = h.reshape(N, E)
    gate_logits = jnp.einsum("ne,ex->nx", x, lp["router"].astype(h.dtype))
    probs = jax.nn.softmax(gate_logits.astype(jnp.float32), axis=-1)
    w, idx = lax.top_k(probs, k)  # [N, k]
    w = w / jnp.maximum(w.sum(-1, keepdims=True), 1e-9)

    flat_e = idx.reshape(-1)                       # [N*k] destination expert
    flat_t = jnp.repeat(jnp.arange(N), k)          # [N*k] source token
    flat_w = w.reshape(-1)
    order = jnp.argsort(flat_e, stable=True)       # group by expert
    se, st, sw = flat_e[order], flat_t[order], flat_w[order]
    # slot within the expert's capacity window
    group_start = jnp.searchsorted(se, jnp.arange(X))
    pos = jnp.arange(N * k) - group_start[se]
    valid = (pos < C).astype(h.dtype)              # overflow -> dropped
    pos_c = jnp.minimum(pos, C - 1)

    buf = jnp.zeros((X, C, E), h.dtype)
    buf = buf.at[se, pos_c].add(x[st] * valid[:, None])
    buf = constrain_fn(buf, "expert", None, "embed")
    g = jnp.einsum("xce,xef->xcf", buf, lp["w_gate"].astype(h.dtype))
    u = jnp.einsum("xce,xef->xcf", buf, lp["w_up"].astype(h.dtype))
    y = jnp.einsum("xcf,xfe->xce", jax.nn.silu(g) * u, lp["w_down"].astype(h.dtype))
    y = constrain_fn(y, "expert", None, "embed")

    contrib = y[se, pos_c] * (sw.astype(h.dtype) * valid)[:, None]  # [N*k, E]
    out = jnp.zeros((N, E), h.dtype).at[st].add(contrib)
    return out.reshape(B, S, E)


_MATMUL_KEYS = ("wq", "wk", "wv", "wo", "w_gate", "w_up", "w_down", "router")


def _cast_matmul_params(cfg: TransformerConfig, params):
    """Cast the stacked matmul weights to compute dtype ONCE — otherwise
    XLA re-converts the f32 masters on every scan iteration and again per
    remat pass (~5% of step time on the 125M bench); norm scales stay f32
    (rms_norm computes in f32 anyway)."""
    layers = dict(params["layers"])
    for key in _MATMUL_KEYS:
        if key in layers:
            layers[key] = layers[key].astype(cfg.dtype)
    return {**params, "layers": layers}


def _mlp(h, lp, cfg: TransformerConfig, constrain_fn):
    if cfg.n_experts:
        if cfg.moe_impl == "dense":
            return _moe_dense(h, lp, cfg)
        return _moe_dispatch(h, lp, cfg, constrain_fn)
    from jax.ad_checkpoint import checkpoint_name

    u = checkpoint_name(
        jnp.einsum("bse,ef->bsf", h, lp["w_up"].astype(h.dtype)), "mlp_up"
    )
    if cfg.mlp_variant == "gelu":
        # gpt2-family two-matmul MLP (tanh-approx gelu, matching gelu_new)
        u = constrain_fn(u, "batch", "seq", "mlp")
        return jnp.einsum(
            "bsf,fe->bse", jax.nn.gelu(u, approximate=True),
            lp["w_down"].astype(h.dtype),
        )
    g = checkpoint_name(
        jnp.einsum("bse,ef->bsf", h, lp["w_gate"].astype(h.dtype)), "mlp_gate"
    )
    g = constrain_fn(g, "batch", "seq", "mlp")
    return jnp.einsum("bsf,fe->bse", jax.nn.silu(g) * u, lp["w_down"].astype(h.dtype))


def make_forward(
    cfg: TransformerConfig,
    rules: Optional[ShardingRules] = None,
    mesh=None,
    _return_backbone: bool = False,
):
    """Build forward(params, tokens) -> logits.

    `rules`+`mesh` enable sharding constraints and (for ring/ulysses
    attention) the shard_map-wrapped sequence-parallel kernels.
    """
    cos, sin = rope_frequencies(cfg.d_head, cfg.max_seq_len, cfg.rope_theta)

    if cfg.attention == "ring":
        inner_attn = partial(ring_attention, axis_name="sp", causal=True)
    elif cfg.attention == "ulysses":
        inner_attn = partial(ulysses_attention, axis_name="sp", causal=True)
    else:
        inner_attn = None

    # dense/flash run head-major ([B,H,S,D], the kernel/MXU-native layout:
    # relayout transposes around attention cost more than attention itself
    # at small d_head); ring/ulysses keep [B,S,H,D] (seq must be a leading
    # non-minor dim for the sp shard_map)
    head_major = inner_attn is None

    def attend(q, k, v):
        if inner_attn is not None and mesh is not None:
            from jax.sharding import PartitionSpec as P

            from ..parallel.sharding import shard_map_compat

            spec = P(None, "sp", None, None)
            return shard_map_compat(
                inner_attn, mesh, (spec, spec, spec), spec, {"sp"}
            )(q, k, v)
        if head_major:
            if cfg.attention == "flash":
                from ..ops.flash_attention import flash_attention

                return flash_attention(
                    q, k, v,
                    block_q=min(cfg.flash_block_q, q.shape[2]),
                    block_k=min(cfg.flash_block_k, k.shape[2]),
                    layout="bhsd",
                )
            return causal_attention_bhsd(q, k, v)
        # ring/ulysses without a mesh: dense correctness oracle
        return causal_attention(q, k, v)

    def _constrain(x, *axes):
        if rules is None or mesh is None:
            return x
        return constrain(x, rules, *axes, mesh=mesh)

    def layer_step(x, lp):
        h = rms_norm(x, lp["attn_norm"])
        if head_major:
            from jax.ad_checkpoint import checkpoint_name

            q = jnp.einsum("bse,ehd->bhsd", h, lp["wq"].astype(h.dtype))
            k = jnp.einsum("bse,ekd->bksd", h, lp["wk"].astype(h.dtype))
            v = jnp.einsum("bse,ekd->bksd", h, lp["wv"].astype(h.dtype))
            # post-rope q/k and v are named so the flash remat policies can
            # save exactly these — backward then reads them instead of
            # re-deriving qkv-matmul + rope per layer (and the "flash_min"
            # policy saves ONLY named residuals: the pre-rope wq/wk outputs
            # dots_no_batch would keep are redundant next to rope_q/k)
            v = checkpoint_name(v, "attn_v")
            q = checkpoint_name(apply_rope_bhsd(q, cos, sin), "rope_q")
            k = checkpoint_name(apply_rope_bhsd(k, cos, sin), "rope_k")
            q = _constrain(q, "batch", "heads", "seq", "head_dim")
            attn = attend(q, k, v)
            x = x + jnp.einsum("bhsd,hde->bse", attn, lp["wo"].astype(h.dtype))
        else:
            q = jnp.einsum("bse,ehd->bshd", h, lp["wq"].astype(h.dtype))
            k = jnp.einsum("bse,ekd->bskd", h, lp["wk"].astype(h.dtype))
            v = jnp.einsum("bse,ekd->bskd", h, lp["wv"].astype(h.dtype))
            q = apply_rope(q, cos, sin)
            k = apply_rope(k, cos, sin)
            q = _constrain(q, "batch", "seq", "heads", "head_dim")
            attn = attend(q, k, v)
            x = x + jnp.einsum("bshd,hde->bse", attn, lp["wo"].astype(h.dtype))
        h2 = rms_norm(x, lp["mlp_norm"])
        x = x + _mlp(h2, lp, cfg, _constrain)
        x = _constrain(x, "batch", "seq", "embed")
        return x, None

    if cfg.remat:
        cp = jax.checkpoint_policies
        policies = {
            "full": None,
            "dots": cp.checkpoint_dots,
            "dots_no_batch": cp.dots_with_no_batch_dims_saveable,
            "flash": cp.save_from_both_policies(
                cp.dots_with_no_batch_dims_saveable,
                cp.save_only_these_names(
                    "flash_out", "flash_lse", "rope_q", "rope_k"
                ),
            ),
            # exactly the residuals backward reads, nothing else: drops the
            # redundant pre-rope wq/wk, wo-out, and mlp-down-out stacks that
            # dots_no_batch would also save (~100MB/layer of scan-stack
            # write+read traffic on the 125M bench)
            "flash_min": cp.save_only_these_names(
                "flash_out", "flash_lse", "rope_q", "rope_k", "attn_v",
                "mlp_gate", "mlp_up",
            ),
            # flash_min minus the mlp gate/up stacks — backward re-derives
            # them (one matmul each from the saved layer input). At
            # d_ff=8192 those two stacks are the LARGEST saved residuals
            # (2 * B*S*d_ff bf16 per layer); trading ~8% more backward
            # flops for that memory is what fits the ~1B HBM-limit config
            "flash_qkv": cp.save_only_these_names(
                "flash_out", "flash_lse", "rope_q", "rope_k", "attn_v",
            ),
        }
        policy = policies[cfg.remat_policy]
        step = jax.checkpoint(layer_step, policy=policy)
    else:
        step = layer_step

    def _apply_layers(params, x):
        if cfg.pp_stages > 1:
            from ..parallel.pipeline import pipeline_apply

            if mesh is None:
                raise ValueError("pp_stages > 1 requires a mesh")

            def stage_fn(stage_layers, xs):
                ys, _ = lax.scan(step, xs, stage_layers)
                return ys

            # stage placement comes from the rule table: "stage" -> "pp"
            # (flat ICI pipeline) or ("dcn", "pp") (multislice pp-outer:
            # stage-groups mapped one per slice, boundary hops over DCN)
            stage_axes = rules.mesh_axes("stage") if rules is not None else None
            batch_axes = rules.mesh_axes("batch") if rules is not None else None
            return pipeline_apply(
                stage_fn,
                params["layers"],
                x,
                mesh=mesh,
                n_microbatches=cfg.pp_microbatches,
                axis_name=stage_axes or "pp",
                batch_axes=batch_axes if batch_axes is not None else ("dp", "fsdp"),
                virtual_stages_per_device=cfg.pp_interleave,
            )
        if not cfg.scan_layers:
            for i in range(cfg.n_layers):
                lp_i = jax.tree.map(lambda a: a[i], params["layers"])
                x, _ = step(x, lp_i)
            return x
        x, _ = lax.scan(step, x, params["layers"])
        return x

    def backbone(params, tokens):
        """Everything up to (and including) the final norm; returns the
        final hidden states plus the compute-dtype unembed matrix so the
        loss can choose how to project them (dense vs blockwise)."""
        x = params["embed"].astype(cfg.dtype)[tokens]
        x = _constrain(x, "batch", "seq", "embed")
        params = _cast_matmul_params(cfg, params)
        x = _apply_layers(params, x)
        x = rms_norm(x, params["final_norm"])
        unembed = params.get("unembed")
        if unembed is None:
            unembed = params["embed"].T
        return x, unembed.astype(cfg.dtype)

    def forward(params, tokens):
        x, unembed = backbone(params, tokens)
        logits = jnp.einsum("bse,ev->bsv", x, unembed)
        logits = _constrain(logits, "batch", "seq", "vocab")
        return logits

    if _return_backbone:
        return forward, backbone, _constrain
    return forward


# --------------------------------------------------------------------------
# autoregressive decode (KV cache)
# --------------------------------------------------------------------------

# cache leaves are [n_layers, batch, max_seq_len, kv_heads, head_dim]; the
# logical axes reuse the activation rules, so the cache shards exactly like
# activations under every existing mesh preset (dp/fsdp shard the slot dim,
# tp shards kv_heads; kv_seq stays unsharded outside sp presets — decode
# scatters at dynamic positions, which sp sharding would turn into
# collectives per token)
KV_CACHE_AXES = ("layers", "batch", "kv_seq", "kv_heads", "head_dim")


def init_kv_cache(
    cfg: TransformerConfig,
    batch_size: int,
    mesh=None,
    rules: Optional[ShardingRules] = None,
    max_seq_len: Optional[int] = None,
):
    """Allocate the per-layer KV cache for `batch_size` decode slots."""
    S = max_seq_len or cfg.max_seq_len
    shape = (cfg.n_layers, batch_size, S, cfg.n_kv_heads, cfg.d_head)
    k = jnp.zeros(shape, cfg.dtype)
    v = jnp.zeros(shape, cfg.dtype)
    if mesh is not None and rules is not None:
        from ..parallel.sharding import logical_sharding

        sh = logical_sharding(mesh, rules, *KV_CACHE_AXES)
        k, v = jax.device_put(k, sh), jax.device_put(v, sh)
    return {"k": k, "v": v}


def _make_sampler(temperature: float, vocab_pad: int = 0):
    """Greedy argmax (temperature 0) or categorical sampling — ONE
    implementation shared by the dense and paged decoders, so their
    token-for-token parity cannot drift. `vocab_pad` masks the trailing
    alignment-only vocab entries (see TransformerConfig.vocab_pad) to
    -inf so a padded id can never win the argmax / be sampled."""

    def _sample(logits, key):
        if vocab_pad:
            V = logits.shape[-1]
            pad = jnp.arange(V) >= V - vocab_pad
            logits = jnp.where(pad, NEG_INF, logits)
        if temperature > 0.0:
            return jax.random.categorical(
                key, logits.astype(jnp.float32) / temperature, axis=-1
            ).astype(jnp.int32)
        return jnp.argmax(logits, axis=-1).astype(jnp.int32)

    return _sample


def _unembed_matrix(cfg: TransformerConfig, params):
    u = params.get("unembed")
    if u is None:
        u = params["embed"].T
    return u.astype(cfg.dtype)


def _cached_attend(q, kc, vc, mask, scale, n_rep):
    """Attention over cache-layout K/V — the single softmax formulation
    both the dense decode step and the paged prefill/decode steps use
    (shared so paged == dense stays bit-identical by construction).

    q [B,Sq,H,D]; kc/vc [B,W,KV,D]; mask [B,Sq,W] (True = attend)."""
    kr = _repeat_kv(kc, n_rep)
    vr = _repeat_kv(vc, n_rep)
    logits = jnp.einsum(
        "bqhd,bkhd->bhqk", q, kr, preferred_element_type=jnp.float32
    ) * scale
    logits = jnp.where(mask[:, None, :, :], logits, NEG_INF)
    probs = jnp.exp(logits - jnp.max(logits, axis=-1, keepdims=True))
    probs = probs / jnp.sum(probs, axis=-1, keepdims=True)
    return jnp.einsum("bhqk,bkhd->bqhd", probs.astype(vr.dtype), vr)


# per-block quantization scales are [n_layers, num_blocks, kv_heads]: the
# block dim shards with the pool's block dim, kv_heads with tp
KV_SCALE_AXES = ("layers", "batch", "kv_heads")


def paged_kv_block_bytes(
    cfg: TransformerConfig, block_tokens: int, dtype=None
) -> int:
    """HBM bytes ONE physical block costs across all layers (K + V + the
    per-block scales when quantized) — the unit the engine's byte-budget
    pool sizing divides by, which is how int8 pools end up with ~2x the
    blocks of a bf16 pool for the same budget."""
    dtype = dtype or cfg.dtype
    itemsize = jnp.dtype(dtype).itemsize
    per = cfg.n_layers * block_tokens * cfg.n_kv_heads * cfg.d_head * itemsize
    total = 2 * per  # k + v
    if dtype == jnp.int8:
        total += 2 * cfg.n_layers * cfg.n_kv_heads * 4  # f32 scales
    return total


def init_paged_kv_cache(
    cfg: TransformerConfig,
    num_blocks: int,
    block_tokens: int,
    mesh=None,
    rules: Optional[ShardingRules] = None,
    dtype=None,
):
    """Allocate the pooled (paged) per-layer KV cache: `num_blocks` physical
    blocks of `block_tokens` tokens each, shared by every decode slot via
    per-slot block tables. The logical axes are the same KV_CACHE_AXES as
    the dense cache — the block dim takes the "batch" axis (dp/fsdp), so
    the pool shards exactly like the dense slot dim under every existing
    mesh preset. Block 0 is reserved as the null block: padded table
    entries and masked-token writes route there (see kv_paging.py).

    `dtype=jnp.int8` stores the pool quantized with per-block, per-kv-head
    f32 scales (`k_scale`/`v_scale` leaves, x ~= q * scale): half the HBM
    per resident token, dequantized at the attention read."""
    dtype = dtype or cfg.dtype
    shape = (cfg.n_layers, num_blocks, block_tokens, cfg.n_kv_heads, cfg.d_head)
    pool = {"k": jnp.zeros(shape, dtype), "v": jnp.zeros(shape, dtype)}
    if dtype == jnp.int8:
        sshape = (cfg.n_layers, num_blocks, cfg.n_kv_heads)
        pool["k_scale"] = jnp.zeros(sshape, jnp.float32)
        pool["v_scale"] = jnp.zeros(sshape, jnp.float32)
    if mesh is not None and rules is not None:
        from ..parallel.sharding import logical_sharding

        sh = logical_sharding(mesh, rules, *KV_CACHE_AXES)
        ssh = logical_sharding(mesh, rules, *KV_SCALE_AXES)
        pool = {
            name: jax.device_put(a, ssh if name.endswith("_scale") else sh)
            for name, a in pool.items()
        }
    return pool


def make_paged_decoder(
    cfg: TransformerConfig,
    rules: Optional[ShardingRules] = None,
    mesh=None,
    temperature: float = 0.0,
    block_tokens: int = 64,
    kv_dtype=None,
    attention_impl: str = "gather",
    fused_impl: str = "auto",
    chunk_blocks: int = 8,
):
    """Build the paged fast path: (paged_prefill, paged_decode_step,
    paged_verify_step, copy_blocks) over a block pool from
    `init_paged_kv_cache`.

    paged_prefill(params, pool, table[Nmax], tokens[1,Sb], length, ctx_len,
                  key, ctx_blocks) -> (next_token[1], logits[1,V], pool)
      B=1 prefill of a prompt SUFFIX whose first `ctx_len` tokens are
      already in the pool — a prefix-cache hit (block multiple), a prior
      prefill CHUNK of the same prompt (any offset; kv_paging's chunked
      admission calls this once per chunk), or 0 for a cold prompt.
      Suffix K/V is scattered into the slot's table blocks — a chunk
      boundary may land mid-block; the straddled block is slot-owned —
      and attention runs over the block window (gathered under "gather",
      walked in place under "fused"), so the committed span is never
      recomputed. `ctx_blocks` is STATIC (bucketed by the caller —
      kv_paging pads block counts to the same bucket boundaries as prompt
      lengths) and keys the compile cache together with the suffix bucket.

    paged_decode_step(params, pool, tables[B,Nmax], tokens[B],
                      positions[B], write_phys[B], write_off[B], key)
        -> (next_tokens[B], logits[B,V], pool)
      One cached decode step for every slot: the new K/V is written at the
      host-resolved (physical block, offset) pair — inactive slots route to
      the null block — and attention gathers each slot's logical sequence
      via its block table. ONE compiled shape per (B, Nmax) regardless of
      live sequence lengths or block-table contents.

    paged_verify_step(params, pool, tables[B,Nmax], tokens[B,K1],
                      positions[B], draft_len[B], write_phys[B,K1],
                      write_off[B,K1], key)
        -> (out_tokens[B,K1], accepted[B], pool)
      Speculative decoding's verify: tokens[:, 0] is each slot's pending
      input token and tokens[:, 1:] its (padded) draft; ONE batched
      forward scores all K1 positions, greedy acceptance is computed
      in-graph (draft i survives iff it matches the model's output at
      position i-1 and every earlier draft survived), and ONLY the
      accepted inputs' K/V commit to the pool — rejected entries route to
      the null block, so there is nothing in the pool to roll back.
      Attention never writes before acceptance: under
      `attention_impl="gather"` the slot's cached window is gathered
      through its table and the K1 in-flight K/V are appended past it
      with a causal tail mask; under `attention_impl="fused"` the cached
      window runs the multi-query fused walk (kv_len = positions keeps
      the unwritten span invisible) and the K1 x K1 in-flight tail folds
      in as a second online-softmax partial via the log-sum-exp merge —
      so long-context speculation keeps the fused win instead of
      re-paying the gather cost.
      Compiled once per (B, K1, Nmax) — the engine
      buckets K1 (kv_paging) so draft-length jitter cannot churn the jit
      cache. Greedy-only: with temperature > 0 the per-position samples
      would not preserve the sampling distribution (the engine refuses to
      enable speculation off greedy).

      fp pools commit with one masked scatter; int8 pools REPLAY the
      single-token RMW sequence (a K1-step in-graph scan of the same
      dequant -> zero-tail -> insert -> requantize write), so the
      committed bytes and scales are bit-identical to non-speculative
      decode having written the accepted tokens one at a time. The only
      int8 divergence is that verify attends the in-flight K/V at full
      precision (the reference attends them post-quantization) — greedy
      tokens can differ only where quantization noise alone would flip
      the argmax.

    copy_blocks(pool, src[n], dst[n]) -> pool
      Copy-on-write: duplicate physical blocks across all layers (refcount
      divergence handled host-side in kv_paging.BlockAllocator).

    `kv_dtype=jnp.int8` runs the pool quantized (per-block per-kv-head f32
    scales): cache writes quantize, attention reads dequantize, and the
    dequantized cache content is authoritative for prefill too — so the
    int8 engine is self-consistent even though it is not bit-identical to
    the fp reference path (which stays exact under the default dtype).

    `attention_impl` picks the attention for EVERY phase — decode (q=1),
    prefill (q=suffix chunk) and speculative verify (q=k+1):
      "gather"  gather each slot's window [B, Nmax*bt] through its block
                table, then dense masked softmax — the exact reference
                path (bit-identical to the dense engine in fp).
      "fused"   ops/paged_attention.py walks the block table and attends
                block-in-place with a q-tile grid axis (Pallas kernel on
                TPU, chunked online softmax under XLA elsewhere;
                `fused_impl` forces one). Composes with KV_CACHE_AXES
                sharding via shard_map: block-sharded pools run per-shard
                with a log-sum-exp merge across the block axes;
                tp-sharded kv_heads need no merge.

    `chunk_blocks` tunes the fused-XLA walk only (blocks folded per
    online-softmax chunk — larger amortizes gather dispatch, smaller caps
    the transient window); the Pallas kernel walks block-by-block.
    """
    if cfg.pp_stages > 1:
        raise NotImplementedError("decode does not support pp_stages > 1")
    bt = int(block_tokens)
    if bt <= 0:
        raise ValueError(f"block_tokens must be positive, got {bt}")
    if attention_impl not in ("gather", "fused"):
        raise ValueError(
            f"attention_impl must be 'gather' or 'fused', got {attention_impl!r}"
        )
    chunk_blocks = int(chunk_blocks)
    if chunk_blocks <= 0:
        raise ValueError(f"chunk_blocks must be positive, got {chunk_blocks}")
    kv_dtype = kv_dtype or cfg.dtype
    quant = kv_dtype == jnp.int8
    cos, sin = rope_frequencies(cfg.d_head, cfg.max_seq_len, cfg.rope_theta)
    scale = cfg.d_head**-0.5
    n_rep = cfg.n_heads // cfg.n_kv_heads

    def _constrain(x, *axes):
        if rules is None or mesh is None:
            return x
        return constrain(x, rules, *axes, mesh=mesh)

    _sample = _make_sampler(temperature, cfg.vocab_pad)

    def _scan_leaves(pool):
        """Pool leaves in the fixed order the layer scans unpack."""
        if quant:
            return (pool["k"], pool["v"], pool["k_scale"], pool["v_scale"])
        return (pool["k"], pool["v"])

    def _pool_dict(leaves):
        names = ("k", "v", "k_scale", "v_scale") if quant else ("k", "v")
        return dict(zip(names, leaves))

    def _dequant(blocks, scales):
        """[..., bt, KV, D] int8 x [..., KV] -> compute dtype."""
        return (
            blocks.astype(jnp.float32) * scales[..., None, :, None]
        ).astype(cfg.dtype)

    def _quantize(win):
        """[..., bt, KV, D] f32 -> (int8 blocks, [..., KV] f32 scales).
        Leading dims are free: the prefill path quantizes [G] blocks, the
        decode RMW [B], the speculative commit [L, B]."""
        amax = jnp.max(jnp.abs(win), axis=(-3, -1))
        s = amax / 127.0
        q8 = jnp.clip(
            jnp.round(win / jnp.maximum(s, 1e-20)[..., None, :, None]),
            -127, 127,
        ).astype(jnp.int8)
        return q8, s

    def _rmw_insert_quant(blk, s0, knew, wo):
        """The int8 token write's shared math — dequantize the write
        block, zero the stale tail, insert ONE token, requantize — over
        arbitrary leading dims: blk [..., B, bt, KV, D], s0 [..., B, KV],
        knew [..., B, KV, D], wo [B]. The single-token decode step and
        the speculative verify commit both call THIS, so the commit's
        replayed write history cannot drift from the per-token reference
        (spec-vs-plain int8 bit-identity of the pool depends on it).
        With an unchanged scale the existing tokens round-trip exactly;
        a scale bump re-rounds them once at the new grain."""
        B = wo.shape[0]
        deq = blk.astype(jnp.float32) * s0[..., None, :, None]
        keep = jnp.arange(bt)[:, None, None] < wo[:, None, None, None]
        deq = jnp.where(keep, deq, 0.0)
        deq = deq.at[..., jnp.arange(B), wo, :, :].set(
            knew.astype(jnp.float32)
        )
        return _quantize(deq)

    # ---- fused attention (ops/paged_attention.py), sharding-aware -------

    def _flat_axes(logical):
        if rules is None or mesh is None:
            return ()
        axes = rules.mesh_axes(logical)
        if axes is None:
            return ()
        if isinstance(axes, str):
            axes = (axes,)
        return tuple(a for a in axes if a in mesh.shape)

    def _fused_attend(qx, kc, vc, ksc, vsc, tables, positions, kv_len=None,
                      partial=False):
        """qx [B, Q, H, D] against the (possibly sharded) per-layer pool.

        One fused formulation for every phase: decode (Q=1), prefill
        (Q=chunk) and speculative verify (Q=k+1) — query i of slot b sits
        at positions[b]+i and `kv_len` caps the live cached window (verify
        passes kv_len=positions so the not-yet-written in-flight span
        stays invisible; see ops/paged_attention.py).

        `partial=True` returns the unnormalized (acc, m, l) online-softmax
        triple — already combined across block-sharded pool shards, so the
        caller can log-sum-exp-merge extra non-pool keys (the verify
        step's in-flight K1 tail) before normalizing."""
        from jax.sharding import PartitionSpec as P

        from ..ops.paged_attention import merge_partials, paged_attention
        from ..parallel.sharding import shard_map_compat

        scales = dict(k_scale=ksc, v_scale=vsc) if quant else {}
        block_axes = _flat_axes("batch")
        kv_axes = _flat_axes("kv_heads")
        q_axes = _flat_axes("heads")
        if kv_len is None:
            kv_len = positions + qx.shape[1]
        if not block_axes and not kv_axes:
            return paged_attention(
                qx, kc, vc, tables, positions, scale=scale,
                impl=fused_impl, chunk_blocks=chunk_blocks, kv_len=kv_len,
                partial_out=partial, **scales,
            )

        def inner(qx, kc, vc, *rest):
            if quant:
                (ksc, vsc), rest = rest[:2], rest[2:]
                sc = dict(k_scale=ksc, v_scale=vsc)
            else:
                sc = {}
            tables, positions, kv_len = rest
            if not block_axes:
                return paged_attention(
                    qx, kc, vc, tables, positions, scale=scale,
                    impl=fused_impl, chunk_blocks=chunk_blocks,
                    kv_len=kv_len, partial_out=partial, **sc,
                )
            # blocks are sharded: remap global table entries to this
            # shard's local ids (others masked dead), attend locally, and
            # log-sum-exp-merge the partial softmax across the block axes
            nloc = kc.shape[0]
            idx = jnp.int32(0)
            for a in block_axes:
                idx = idx * dict(mesh.shape)[a] + lax.axis_index(a)
            lo = idx * nloc
            live = (tables > 0) & (tables >= lo) & (tables < lo + nloc)
            ptab = jnp.where(live, tables - lo, -1).astype(jnp.int32)
            acc, m, l = paged_attention(
                qx, kc, vc, ptab, positions, scale=scale, impl=fused_impl,
                signed_tables=True, partial_out=True,
                chunk_blocks=chunk_blocks, kv_len=kv_len, **sc,
            )
            if partial:
                # fold the shards into ONE globally-valid partial triple
                # (replicated over the block axes): pmax the running max,
                # rescale, psum — the caller still owns normalization
                m_g = lax.pmax(m, block_axes)
                e = jnp.exp(m - m_g)
                num = lax.psum(acc * e[..., None], block_axes)
                den = lax.psum(l * e, block_axes)
                return num, m_g, den
            return merge_partials(
                acc, m, l, axis_names=block_axes, out_dtype=qx.dtype
            )

        bspec = tuple(block_axes) if block_axes else None
        kvspec = tuple(kv_axes) if kv_axes else None
        hspec = tuple(q_axes) if q_axes else None
        qspec = P(None, None, hspec, None)
        in_specs = [qspec, P(bspec, None, kvspec, None), P(bspec, None, kvspec, None)]
        args = [qx, kc, vc]
        if quant:
            in_specs += [P(bspec, kvspec)] * 2
            args += [ksc, vsc]
        in_specs += [P(None, None), P(None), P(None)]
        args += [tables, positions, kv_len]
        manual = set(block_axes) | set(kv_axes) | set(q_axes)
        out_specs = (
            (qspec, P(None, None, hspec), P(None, None, hspec))
            if partial else qspec
        )
        return shard_map_compat(
            inner, mesh, tuple(in_specs), out_specs, manual
        )(*args)

    def _merge_inflight(q, acc_w, m_w, l_w, k_infl, v_infl, fmask):
        """Fold the verify step's K1 in-flight keys (appended past the
        cached window, never yet in the pool) into the fused window
        partial: a tiny dense causal pass produces its own (acc, m, l)
        and the log-sum-exp combine yields the exact softmax over
        window + in-flight — no gathered window ever exists.

        q [B,K1,H,D]; k_infl/v_infl [B,K1,KV,D]; fmask [B,K1,K1]."""
        from ..ops.paged_attention import merge_partials

        kr = _repeat_kv(k_infl, n_rep).astype(jnp.float32)
        vr = _repeat_kv(v_infl, n_rep).astype(jnp.float32)
        s = jnp.einsum(
            "bqhd,bkhd->bhqk", q.astype(jnp.float32), kr,
            preferred_element_type=jnp.float32,
        ) * scale
        mask = fmask[:, None, :, :]  # [B,1,K1,K1]
        s = jnp.where(mask, s, NEG_INF)
        m_f = jnp.max(s, axis=-1)                      # [B,H,K1]
        p = jnp.where(mask, jnp.exp(s - m_f[..., None]), 0.0)
        l_f = jnp.sum(p, axis=-1)                      # [B,H,K1]
        acc_f = jnp.einsum(
            "bhqk,bkhd->bqhd", p, vr, preferred_element_type=jnp.float32
        )
        m_f = m_f.transpose(0, 2, 1)                   # [B,K1,H]
        l_f = l_f.transpose(0, 2, 1)
        return merge_partials(
            jnp.stack([acc_w, acc_f]), jnp.stack([m_w, m_f]),
            jnp.stack([l_w, l_f]), out_dtype=cfg.dtype,
        )

    def _prefill_body(G, params, pool, table, tokens, length, ctx_len, key):
        params = _cast_matmul_params(cfg, params)
        Sb = tokens.shape[1]
        x = params["embed"].astype(cfg.dtype)[tokens]
        x = _constrain(x, "batch", "seq", "embed")
        qpos = ctx_len + jnp.arange(Sb)  # global positions of the suffix
        valid_tok = jnp.arange(Sb) < length
        # padded suffix tokens write into the null block (0), never into a
        # real one; real tokens land at table[pos // bt] offset pos % bt
        w_phys = jnp.where(valid_tok, table[qpos // bt], 0)
        w_off = qpos % bt
        window = table[:G]
        # window position j holds global position j; key j is visible to
        # query at global position p iff j <= p (ctx + causal in one mask)
        kmask = (jnp.arange(G * bt)[None, :] <= qpos[:, None])[None]

        def _write_suffix_quant(kc, ksc, knew):
            """Quantized prefill write: rebuild the window in f32 (dequant
            + suffix insert + stale-tail zeroing), requantize per block,
            scatter the blocks back. Returns the updated pool leaves plus
            the DEQUANTIZED window — attention reads what the cache will
            serve, so int8 prefill and int8 decode agree on every key."""
            raw = kc[window]  # [G, bt, KV, D] int8
            s0 = ksc[window]  # [G, KV]
            win = raw.astype(jnp.float32) * s0[:, None, :, None]
            flat = win.reshape(G * bt, *win.shape[2:])
            # padded suffix tokens scatter out of bounds and are dropped
            # (the fp path routes them to the null block instead)
            wpos = jnp.where(valid_tok, qpos, G * bt)
            flat = flat.at[wpos].set(
                knew.astype(jnp.float32), mode="drop"
            )
            # recycled blocks carry stale values past the live span; they
            # are masked in attention but would poison the block scales
            total = ctx_len + length
            flat = jnp.where(
                jnp.arange(G * bt)[:, None, None] < total, flat, 0.0
            )
            win = flat.reshape(G, bt, *flat.shape[1:])
            q8, s = _quantize(win)
            # shared context blocks (prefix-cache hits, refcount > 1) must
            # never be rewritten — keep their ORIGINAL bytes/scales, so
            # the allocator's copy-on-write invariant holds even if the
            # quantizer stops being a round-trip identity; the slot only
            # owns the suffix blocks it allocated
            owned = jnp.arange(G) >= ctx_len // bt
            q8 = jnp.where(owned[:, None, None, None], q8, raw)
            s = jnp.where(owned[:, None], s, s0)
            kw = _dequant(q8, s).reshape(1, G * bt, *win.shape[2:])
            return kc.at[window].set(q8), ksc.at[window].set(s), kw

        def layer_fn(x, per_layer):
            if quant:
                lp, kc, vc, ksc, vsc = per_layer
            else:
                lp, kc, vc = per_layer
            h = rms_norm(x, lp["attn_norm"])
            q = jnp.einsum("bse,ehd->bshd", h, lp["wq"])
            k = jnp.einsum("bse,ekd->bskd", h, lp["wk"])
            v = jnp.einsum("bse,ekd->bskd", h, lp["wv"])
            q = apply_rope(q, cos, sin, positions=qpos[None])
            k = apply_rope(k, cos, sin, positions=qpos[None])
            q = _constrain(q, "batch", "seq", "heads", "head_dim")
            # write the suffix K/V first — suffix keys are then read back
            # from the pool, so cache content is authoritative either way
            if quant:
                kc, ksc, kw = _write_suffix_quant(kc, ksc, k[0])
                vc, vsc, vw = _write_suffix_quant(vc, vsc, v[0])
            else:
                kc = kc.at[w_phys, w_off].set(k[0].astype(kc.dtype))
                vc = vc.at[w_phys, w_off].set(v[0].astype(vc.dtype))
                kw = vw = None
            if attention_impl == "fused":
                # multi-query fused walk over the window blocks in place:
                # query i sits at ctx_len + i, kv_len caps recycled-block
                # positions past the live span (quant kw/vw are unused —
                # the kernel dequantizes from the pool itself)
                attn = _fused_attend(
                    q, kc, vc, ksc if quant else None,
                    vsc if quant else None, window[None],
                    jnp.reshape(jnp.asarray(ctx_len, jnp.int32), (1,)),
                    kv_len=jnp.reshape(
                        jnp.asarray(ctx_len + length, jnp.int32), (1,)
                    ),
                )
            else:
                if not quant:
                    kw = kc[window].reshape(1, G * bt, *kc.shape[2:])
                    vw = vc[window].reshape(1, G * bt, *vc.shape[2:])
                attn = _cached_attend(q, kw, vw, kmask, scale, n_rep)
            x = x + jnp.einsum("bshd,hde->bse", attn, lp["wo"])
            h2 = rms_norm(x, lp["mlp_norm"])
            x = x + _mlp(h2, lp, cfg, _constrain)
            x = _constrain(x, "batch", "seq", "embed")
            return x, (kc, vc, ksc, vsc) if quant else (kc, vc)

        x, new_leaves = lax.scan(
            layer_fn, x, (params["layers"],) + _scan_leaves(pool)
        )
        x = rms_norm(x, params["final_norm"])
        x_last = x[0, jnp.maximum(length - 1, 0)][None]
        logits = jnp.einsum("be,ev->bv", x_last, _unembed_matrix(cfg, params))
        logits = _constrain(logits, "batch", "vocab")
        return _sample(logits, key), logits, _pool_dict(new_leaves)

    _prefill_jits: Dict[int, Any] = {}

    def paged_prefill(params, pool, table, tokens, length, ctx_len, key,
                      ctx_blocks: int):
        Sb = tokens.shape[1]
        G = min(int(ctx_blocks) + -(-Sb // bt), table.shape[0])
        fn = _prefill_jits.get(G)
        if fn is None:
            fn = jax.jit(partial(_prefill_body, G), donate_argnums=(1,))
            _prefill_jits[G] = fn
        return fn(params, pool, table, tokens, length, ctx_len, key)

    def _decode_body(params, pool, tables, tokens, positions, write_phys,
                     write_off, key):
        params = _cast_matmul_params(cfg, params)
        B, Nmax = tables.shape
        W = Nmax * bt
        x = params["embed"].astype(cfg.dtype)[tokens][:, None, :]  # [B,1,E]
        x = _constrain(x, "batch", "seq", "embed")
        pos2 = positions[:, None]
        kmask = (jnp.arange(W)[None, :] <= pos2)[:, None, :]  # [B,1,W]

        def _write_token_quant(kc, ksc, knew):
            """Quantized decode write: read-modify-write each slot's write
            block (shared math in `_rmw_insert_quant` — recycled blocks
            carry stale values past the live span that would poison the
            scale, hence the zero-tail). knew is [B, KV, D]."""
            q8, s1 = _rmw_insert_quant(
                kc[write_phys], ksc[write_phys], knew, write_off
            )
            return kc.at[write_phys].set(q8), ksc.at[write_phys].set(s1)

        def layer_fn(x, per_layer):
            if quant:
                lp, kc, vc, ksc, vsc = per_layer
            else:
                lp, kc, vc = per_layer
                ksc = vsc = None
            h = rms_norm(x, lp["attn_norm"])
            q = jnp.einsum("bse,ehd->bshd", h, lp["wq"])  # [B,1,H,D]
            k = jnp.einsum("bse,ekd->bskd", h, lp["wk"])  # [B,1,KV,D]
            v = jnp.einsum("bse,ekd->bskd", h, lp["wv"])
            q = apply_rope(q, cos, sin, positions=pos2)
            k = apply_rope(k, cos, sin, positions=pos2)
            if quant:
                kc, ksc = _write_token_quant(kc, ksc, k[:, 0])
                vc, vsc = _write_token_quant(vc, vsc, v[:, 0])
            else:
                kc = kc.at[write_phys, write_off].set(k[:, 0].astype(kc.dtype))
                vc = vc.at[write_phys, write_off].set(v[:, 0].astype(vc.dtype))
            if attention_impl == "fused":
                # block-in-place attention: no [B, W] gather exists. This
                # token's K/V was just written, so the live window is
                # positions + 1 keys deep
                attn = _fused_attend(
                    q, kc, vc, ksc, vsc, tables, positions,
                    kv_len=positions + 1,
                )
            else:
                if quant:
                    kw = _dequant(kc[tables], ksc[tables]).reshape(
                        B, W, *kc.shape[2:]
                    )
                    vw = _dequant(vc[tables], vsc[tables]).reshape(
                        B, W, *vc.shape[2:]
                    )
                else:
                    kw = kc[tables].reshape(B, W, *kc.shape[2:])
                    vw = vc[tables].reshape(B, W, *vc.shape[2:])
                attn = _cached_attend(q, kw, vw, kmask, scale, n_rep)
            x = x + jnp.einsum("bshd,hde->bse", attn, lp["wo"])
            h2 = rms_norm(x, lp["mlp_norm"])
            x = x + _mlp(h2, lp, cfg, _constrain)
            x = _constrain(x, "batch", "seq", "embed")
            return x, (kc, vc, ksc, vsc) if quant else (kc, vc)

        x, new_leaves = lax.scan(
            layer_fn, x, (params["layers"],) + _scan_leaves(pool)
        )
        x = rms_norm(x, params["final_norm"])
        logits = jnp.einsum("be,ev->bv", x[:, 0], _unembed_matrix(cfg, params))
        logits = _constrain(logits, "batch", "vocab")
        return _sample(logits, key), logits, _pool_dict(new_leaves)

    def _rmw_commit_quant(kc, ksc, knew, wp_i, wo_i):
        """[L]-batched twin of the decode step's `_write_token_quant`:
        ONE token into each slot's write block across every layer at
        once. The RMW math itself is `_rmw_insert_quant`, shared with the
        per-token decode write — replaying it per accepted token
        reproduces the single-token write history bit-for-bit. knew is
        [L, B, KV, D]."""
        q8, s1 = _rmw_insert_quant(kc[:, wp_i], ksc[:, wp_i], knew, wo_i)
        return kc.at[:, wp_i].set(q8), ksc.at[:, wp_i].set(s1)

    def _verify_commit(pool, ks, vs, wp, wo):
        """Write the accepted inputs' K/V stacks ([L,B,K1,KV,D]) into the
        pool; rejected/dead entries arrive with wp == 0 (null block)."""
        if not quant:
            return {
                "k": pool["k"].at[:, wp, wo].set(ks.astype(pool["k"].dtype)),
                "v": pool["v"].at[:, wp, wo].set(vs.astype(pool["v"].dtype)),
            }

        def one(carry, xs):
            kc, ksc, vc, vsc = carry
            k_i, v_i, wp_i, wo_i = xs
            kc, ksc = _rmw_commit_quant(kc, ksc, k_i, wp_i, wo_i)
            vc, vsc = _rmw_commit_quant(vc, vsc, v_i, wp_i, wo_i)
            return (kc, ksc, vc, vsc), None

        # token order matters: each RMW zeroes past its own offset, so the
        # scan walks positions ascending — exactly the sequential history
        (kc, ksc, vc, vsc), _ = lax.scan(
            one,
            (pool["k"], pool["k_scale"], pool["v"], pool["v_scale"]),
            (jnp.moveaxis(ks, 2, 0), jnp.moveaxis(vs, 2, 0), wp.T, wo.T),
        )
        return {"k": kc, "v": vc, "k_scale": ksc, "v_scale": vsc}

    def _verify_body(params, pool, tables, tokens, positions, draft_len,
                     write_phys, write_off, key):
        params = _cast_matmul_params(cfg, params)
        B, K1 = tokens.shape
        Nmax = tables.shape[1]
        W = Nmax * bt
        x = params["embed"].astype(cfg.dtype)[tokens]  # [B, K1, E]
        x = _constrain(x, "batch", "seq", "embed")
        qpos = positions[:, None] + jnp.arange(K1)[None, :]  # [B, K1]
        # padded tail positions can run past the rope tables (they are
        # rejected by the draft_len mask; clamp keeps the gather in range)
        rope_pos = jnp.minimum(qpos, cfg.max_seq_len - 1)
        # cached window holds positions 0..p-1 (the pending token's K/V is
        # NOT yet written); everything at or past p in a recycled block is
        # stale. In-flight tokens attend each other causally past the
        # window — appended, never written, so acceptance decides what
        # lands in the pool.
        cmask = jnp.broadcast_to(
            jnp.arange(W)[None, None, :] < positions[:, None, None],
            (B, K1, W),
        )
        fmask = jnp.broadcast_to(
            jnp.tril(jnp.ones((K1, K1), bool))[None], (B, K1, K1)
        )
        mask = jnp.concatenate([cmask, fmask], axis=2)  # [B, K1, W+K1]

        def layer_fn(x, per_layer):
            if quant:
                lp, kc, vc, ksc, vsc = per_layer
            else:
                lp, kc, vc = per_layer
            h = rms_norm(x, lp["attn_norm"])
            q = jnp.einsum("bse,ehd->bshd", h, lp["wq"])
            k = jnp.einsum("bse,ekd->bskd", h, lp["wk"])
            v = jnp.einsum("bse,ekd->bskd", h, lp["wv"])
            q = apply_rope(q, cos, sin, positions=rope_pos)
            k = apply_rope(k, cos, sin, positions=rope_pos)
            q = _constrain(q, "batch", "seq", "heads", "head_dim")
            if attention_impl == "fused":
                # multi-query fused walk over the cached window (kv_len =
                # positions keeps the not-yet-written span invisible and
                # masks recycled-block staleness), then the K1 in-flight
                # keys fold in as a second online-softmax partial — the
                # gather-window concat never materializes
                acc_w, m_w, l_w = _fused_attend(
                    q, kc, vc, ksc if quant else None,
                    vsc if quant else None, tables, positions,
                    kv_len=positions, partial=True,
                )
                attn = _merge_inflight(q, acc_w, m_w, l_w, k, v, fmask)
            else:
                if quant:
                    kw = _dequant(kc[tables], ksc[tables]).reshape(
                        B, W, *kc.shape[2:]
                    )
                    vw = _dequant(vc[tables], vsc[tables]).reshape(
                        B, W, *vc.shape[2:]
                    )
                else:
                    kw = kc[tables].reshape(B, W, *kc.shape[2:])
                    vw = vc[tables].reshape(B, W, *vc.shape[2:])
                kcat = jnp.concatenate([kw, k.astype(kw.dtype)], axis=1)
                vcat = jnp.concatenate([vw, v.astype(vw.dtype)], axis=1)
                attn = _cached_attend(q, kcat, vcat, mask, scale, n_rep)
            x = x + jnp.einsum("bshd,hde->bse", attn, lp["wo"])
            h2 = rms_norm(x, lp["mlp_norm"])
            x = x + _mlp(h2, lp, cfg, _constrain)
            x = _constrain(x, "batch", "seq", "embed")
            return x, (k, v)

        x, (ks, vs) = lax.scan(
            layer_fn, x, (params["layers"],) + _scan_leaves(pool)
        )
        x = rms_norm(x, params["final_norm"])
        logits = jnp.einsum("bse,ev->bsv", x, _unembed_matrix(cfg, params))
        logits = _constrain(logits, "batch", "seq", "vocab")
        out = _sample(logits, key)  # [B, K1]
        # greedy acceptance: draft i survives iff it equals the model's
        # output one position earlier AND every prior draft survived
        match = (tokens[:, 1:] == out[:, :-1]) & (
            jnp.arange(1, K1)[None, :] <= draft_len[:, None]
        )
        accepted = jnp.cumprod(match.astype(jnp.int32), axis=1).sum(
            axis=1
        ).astype(jnp.int32)
        commit = jnp.arange(K1)[None, :] <= accepted[:, None]  # [B, K1]
        wp = jnp.where(commit, write_phys, 0)
        pool = _verify_commit(pool, ks, vs, wp, write_off)
        return out, accepted, pool

    def _copy_body(pool, src, dst):
        # every pool leaf (K/V blocks AND their scales) has the physical
        # block dim at axis 1
        return {
            name: a.at[:, dst].set(a[:, src]) for name, a in pool.items()
        }

    paged_decode_step = jax.jit(_decode_body, donate_argnums=(1,))
    paged_verify_step = jax.jit(_verify_body, donate_argnums=(1,))
    copy_blocks = jax.jit(_copy_body, donate_argnums=(0,))
    return paged_prefill, paged_decode_step, paged_verify_step, copy_blocks


def make_decoder(
    cfg: TransformerConfig,
    rules: Optional[ShardingRules] = None,
    mesh=None,
    temperature: float = 0.0,
):
    """Build the autoregressive fast path: (prefill, write_cache, decode_step).

    prefill(params, tokens[B,Sp], lengths[B], key)
        -> (next_tokens[B], logits[B,V], ks, vs)
      Full forward over the (padded) prompt; logits are read at position
      lengths-1 and ks/vs are the per-layer K/V stacks [L,B,Sp,KV,D] ready
      to be written into a cache. Compiled per (B, Sp) shape — callers pad
      prompts to a small set of buckets.

    write_cache(cache, ks, vs, slot) -> cache
      Scatter a prefill's K/V stack into cache rows [slot, slot+B).

    decode_step(params, cache, tokens[B], positions[B], key)
        -> (next_tokens[B], logits[B,V], cache)
      One cached decode step for every slot: the new K/V is written at each
      slot's own position, attention reads kpos <= position, so slots at
      different sequence lengths decode together in one batch (the
      continuous-batching contract). Jit-compiled once per cache batch
      size, cache donated.

    temperature=0 is greedy argmax; >0 samples categorically with `key`.
    Decode is dense-attention only (the cache read is one [B,S] row per
    slot); ring/ulysses and pp_stages>1 configs must decode with a
    non-sp/pp rules table.
    """
    if cfg.pp_stages > 1:
        raise NotImplementedError("decode does not support pp_stages > 1")
    cos, sin = rope_frequencies(cfg.d_head, cfg.max_seq_len, cfg.rope_theta)
    scale = cfg.d_head**-0.5

    def _constrain(x, *axes):
        if rules is None or mesh is None:
            return x
        return constrain(x, rules, *axes, mesh=mesh)

    _sample = _make_sampler(temperature, cfg.vocab_pad)

    def _prefill(params, tokens, lengths, key):
        params = _cast_matmul_params(cfg, params)
        x = params["embed"].astype(cfg.dtype)[tokens]
        x = _constrain(x, "batch", "seq", "embed")

        def layer_prefill(x, lp):
            h = rms_norm(x, lp["attn_norm"])
            q = jnp.einsum("bse,ehd->bshd", h, lp["wq"])
            k = jnp.einsum("bse,ekd->bskd", h, lp["wk"])
            v = jnp.einsum("bse,ekd->bskd", h, lp["wv"])
            q = apply_rope(q, cos, sin)
            k = apply_rope(k, cos, sin)
            q = _constrain(q, "batch", "seq", "heads", "head_dim")
            attn = causal_attention(q, k, v)
            x = x + jnp.einsum("bshd,hde->bse", attn, lp["wo"])
            h2 = rms_norm(x, lp["mlp_norm"])
            x = x + _mlp(h2, lp, cfg, _constrain)
            x = _constrain(x, "batch", "seq", "embed")
            return x, (k, v)

        x, (ks, vs) = lax.scan(layer_prefill, x, params["layers"])
        x = rms_norm(x, params["final_norm"])
        # logits only at each sequence's last real token (padding beyond
        # lengths-1 produces garbage states that are never read)
        B = tokens.shape[0]
        x_last = x[jnp.arange(B), jnp.maximum(lengths - 1, 0)]
        logits = jnp.einsum("be,ev->bv", x_last, _unembed_matrix(cfg, params))
        logits = _constrain(logits, "batch", "vocab")
        return _sample(logits, key), logits, ks, vs

    def _write_cache(cache, ks, vs, slot):
        k = lax.dynamic_update_slice(cache["k"], ks.astype(cache["k"].dtype),
                                     (0, slot, 0, 0, 0))
        v = lax.dynamic_update_slice(cache["v"], vs.astype(cache["v"].dtype),
                                     (0, slot, 0, 0, 0))
        return {"k": k, "v": v}

    def _decode_step(params, cache, tokens, positions, key):
        params = _cast_matmul_params(cfg, params)
        B = tokens.shape[0]
        S = cache["k"].shape[2]
        n_rep = cfg.n_heads // cfg.n_kv_heads
        x = params["embed"].astype(cfg.dtype)[tokens][:, None, :]  # [B,1,E]
        x = _constrain(x, "batch", "seq", "embed")
        pos2 = positions[:, None]  # [B,1]
        rows = jnp.arange(B)[:, None]
        kvalid = jnp.arange(S)[None, :] <= pos2  # [B,S] incl. this token

        def layer_decode(x, per_layer):
            lp, kc, vc = per_layer
            h = rms_norm(x, lp["attn_norm"])
            q = jnp.einsum("bse,ehd->bshd", h, lp["wq"])  # [B,1,H,D]
            k = jnp.einsum("bse,ekd->bskd", h, lp["wk"])  # [B,1,KV,D]
            v = jnp.einsum("bse,ekd->bskd", h, lp["wv"])
            q = apply_rope(q, cos, sin, positions=pos2)
            k = apply_rope(k, cos, sin, positions=pos2)
            # write this token's K/V at each slot's own position
            kc = kc.at[rows, pos2].set(k.astype(kc.dtype))
            vc = vc.at[rows, pos2].set(v.astype(vc.dtype))
            attn = _cached_attend(q, kc, vc, kvalid[:, None, :], scale, n_rep)
            x = x + jnp.einsum("bshd,hde->bse", attn, lp["wo"])
            h2 = rms_norm(x, lp["mlp_norm"])
            x = x + _mlp(h2, lp, cfg, _constrain)
            x = _constrain(x, "batch", "seq", "embed")
            return x, (kc, vc)

        x, (k_new, v_new) = lax.scan(
            layer_decode, x, (params["layers"], cache["k"], cache["v"])
        )
        x = rms_norm(x, params["final_norm"])
        logits = jnp.einsum("be,ev->bv", x[:, 0], _unembed_matrix(cfg, params))
        logits = _constrain(logits, "batch", "vocab")
        return _sample(logits, key), logits, {"k": k_new, "v": v_new}

    prefill = jax.jit(_prefill)
    write_cache = jax.jit(_write_cache, donate_argnums=(0,))
    decode_step = jax.jit(_decode_step, donate_argnums=(1,))
    return prefill, write_cache, decode_step


def make_loss_fn(cfg: TransformerConfig, rules=None, mesh=None):
    forward, backbone, _constrain = make_forward(
        cfg, rules, mesh, _return_backbone=True
    )

    def loss_fn(params, batch):
        tokens = batch["tokens"]
        labels = tokens[:, 1:]
        mask = batch.get("mask")
        if mask is not None:
            mask = mask[:, 1:].astype(bool)
        if cfg.loss_chunk:
            x, unembed = backbone(params, tokens[:, :-1])
            loss, _ = blockwise_softmax_cross_entropy(
                x, unembed, labels, where=mask, chunk=cfg.loss_chunk,
                constrain_logits=lambda l: _constrain(l, "batch", "seq", "vocab"),
            )
            return loss
        logits = forward(params, tokens[:, :-1])
        loss, _ = softmax_cross_entropy_with_int_labels(logits, labels, where=mask)
        return loss

    return loss_fn
