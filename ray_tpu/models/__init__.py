"""Model zoo: TPU-first pure-functional models (pytree params + jit-able
apply fns, logical-axis sharding annotations)."""

from .transformer import (  # noqa: F401
    TransformerConfig,
    init_params,
    param_specs,
    make_forward,
    make_loss_fn,
    CONFIGS,
)
