"""Model zoo: TPU-first pure-functional models (pytree params + jit-able
apply fns, logical-axis sharding annotations)."""

from .transformer import (  # noqa: F401
    TransformerConfig,
    init_params,
    init_kv_cache,
    init_paged_kv_cache,
    param_specs,
    make_decoder,
    make_paged_decoder,
    make_forward,
    make_loss_fn,
    CONFIGS,
    KV_CACHE_AXES,
)
from .decoding import DecodeEngine  # noqa: F401
from .kv_paging import (  # noqa: F401
    BlockAllocator,
    InsufficientBlocksError,
    PagedDecodeEngine,
    PrefixCache,
)
from .speculative import (  # noqa: F401
    NGramDrafter,
    ReplayDrafter,
    resolve_drafter,
)
from . import hub  # noqa: F401  — real checkpoints + tokenizers (model hub)
