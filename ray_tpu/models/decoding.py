"""Slot-based KV-cache decode engine: the model side of continuous batching.

The engine owns one KV cache of `max_batch_size` slots and exposes the two
operations `ray_tpu.serve.batching.ContinuousBatcher` drives:

  admit(slot, request) -> (token, done)   prefill one request into a free
                                          slot (B=1 prefill, prompt padded
                                          to a length bucket so compiles
                                          are bounded)
  step(slots)          -> {slot: (token, done)}   ONE cached decode step
                                          for every active slot together —
                                          slots at different sequence
                                          lengths share the batch, which is
                                          exactly what makes batched decode
                                          outrun per-request decode

The decode step is jit-compiled once (per cache batch size) and reused for
the engine's lifetime; per-step host work is two [B] int32 transfers and
the sampled-token fetch. Not thread-safe: a single loop thread (the
batcher's) must own admit/step.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np

from .transformer import (
    TransformerConfig,
    init_kv_cache,
    init_params,
    make_decoder,
)


def default_prefill_buckets(max_seq_len: int) -> Tuple[int, ...]:
    """Powers of two up to max_seq_len (always including it): each bucket
    costs one prefill compile, padding within a bucket costs only FLOPs."""
    buckets = []
    b = 16
    while b < max_seq_len:
        buckets.append(b)
        b *= 2
    buckets.append(max_seq_len)
    return tuple(buckets)


class DecodeEngine:
    """KV-cache decode over `max_batch_size` slots (see module docstring)."""

    def __init__(
        self,
        cfg: TransformerConfig,
        params=None,
        *,
        max_batch_size: int = 8,
        rules=None,
        mesh=None,
        eos_id: Optional[int] = None,
        temperature: float = 0.0,
        default_max_new_tokens: int = 64,
        max_seq_len: Optional[int] = None,
        prefill_buckets: Optional[Sequence[int]] = None,
        seed: int = 0,
    ):
        import jax

        self.cfg = cfg
        self.max_batch_size = int(max_batch_size)
        self.eos_id = eos_id
        self.default_max_new_tokens = int(default_max_new_tokens)
        self.max_seq_len = int(max_seq_len or cfg.max_seq_len)
        if self.max_seq_len > cfg.max_seq_len:
            raise ValueError("max_seq_len exceeds the model's rope tables")
        self.params = (
            params if params is not None
            else init_params(jax.random.PRNGKey(seed), cfg)
        )
        self.cache = init_kv_cache(
            cfg, self.max_batch_size, mesh=mesh, rules=rules,
            max_seq_len=self.max_seq_len,
        )
        self._prefill, self._write_cache, self._decode_step = make_decoder(
            cfg, rules=rules, mesh=mesh, temperature=temperature
        )
        self.buckets = tuple(sorted(
            prefill_buckets or default_prefill_buckets(self.max_seq_len)
        ))
        self._key = jax.random.PRNGKey(seed + 1)
        # host-side slot bookkeeping (the decode step consumes these as [B]
        # device transfers each step — trivial next to the matmuls)
        B = self.max_batch_size
        self._positions = np.zeros(B, np.int32)
        self._last_tokens = np.zeros(B, np.int32)
        self._new_counts = np.zeros(B, np.int64)
        self._max_new = np.full(B, self.default_max_new_tokens, np.int64)
        # counters (bench/observability)
        self.tokens_generated = 0
        self.prefills = 0
        self.decode_steps = 0

    def _next_key(self):
        import jax

        self._key, sub = jax.random.split(self._key)
        return sub

    def _bucket(self, length: int) -> int:
        for b in self.buckets:
            if b >= length:
                return b
        raise ValueError(
            f"prompt of {length} tokens exceeds max_seq_len {self.max_seq_len}"
        )

    def _done(self, slot: int, token: int) -> bool:
        if self.eos_id is not None and token == self.eos_id:
            return True
        if self._new_counts[slot] >= self._max_new[slot]:
            return True
        # positions[slot] is the NEXT write position; S-1 is still legal,
        # so only cut once the next write would fall off the cache
        return int(self._positions[slot]) >= self.max_seq_len

    # ----------------------------------------------------------- engine API

    def admit(self, slot: int, request: Dict[str, Any]) -> Tuple[int, bool]:
        """Prefill `request` into `slot`; returns the first generated token.

        request: {"tokens": sequence of int token ids,
                  "max_new_tokens": optional int (default engine-wide)}
        """
        prompt = np.asarray(request["tokens"], np.int32)
        if prompt.ndim != 1 or prompt.size == 0:
            raise ValueError("request['tokens'] must be a non-empty 1-D seq")
        length = int(prompt.size)
        if length >= self.max_seq_len:
            raise ValueError(
                f"prompt of {length} tokens leaves no room to generate "
                f"(max_seq_len {self.max_seq_len})"
            )
        bucket = self._bucket(length)
        padded = np.zeros(bucket, np.int32)
        padded[:length] = prompt
        next_tok, _, ks, vs = self._prefill(
            self.params, padded[None], np.asarray([length], np.int32),
            self._next_key(),
        )
        self.cache = self._write_cache(self.cache, ks, vs, slot)
        tok = int(next_tok[0])
        self._positions[slot] = length
        self._last_tokens[slot] = tok
        self._new_counts[slot] = 1
        mnt = request.get("max_new_tokens")
        # admit always emits one token, so the floor is 1 (an explicit 0
        # must not silently fall back to the engine default)
        self._max_new[slot] = (
            self.default_max_new_tokens if mnt is None else max(1, int(mnt))
        )
        self.prefills += 1
        self.tokens_generated += 1
        return tok, self._done(slot, tok)

    def step(self, slots: List[int]) -> Dict[int, Tuple[int, bool]]:
        """One cached decode step for every slot in `slots` (inactive slots
        ride along as padding — their outputs are ignored)."""
        if not slots:
            return {}
        next_toks, _, self.cache = self._decode_step(
            self.params, self.cache, self._last_tokens, self._positions,
            self._next_key(),
        )
        toks = np.asarray(next_toks)
        out: Dict[int, Tuple[int, bool]] = {}
        for slot in slots:
            tok = int(toks[slot])
            self._positions[slot] += 1
            self._last_tokens[slot] = tok
            self._new_counts[slot] += 1
            out[slot] = (tok, self._done(slot, tok))
        self.decode_steps += 1
        self.tokens_generated += len(slots)
        return out

    def release(self, slot: int) -> None:
        """Free a slot (bookkeeping only — the cache row is overwritten by
        the next admit)."""
        self._new_counts[slot] = 0

    def stats(self) -> Dict[str, Any]:
        return {
            "tokens_generated": self.tokens_generated,
            "prefills": self.prefills,
            "decode_steps": self.decode_steps,
            "max_batch_size": self.max_batch_size,
        }
