"""Cross-language calls: invoke functions registered by C++ executor
processes (cpp/client/ray_tpu_client.hpp `Executor`).

Reference parity: python/ray/cross_language.py (`cpp_function` — the
Python-side handle for calling into the C++ worker API by name). Arguments
and results cross the wire as JSON values; the result arrives as a normal
object, so `ray_tpu.get()` on the returned ref behaves exactly like any
task result (including raising CrossLanguageError on failure).
"""

from __future__ import annotations

from typing import Any, Dict, List

from ._private.ids import ObjectID


class CppFunction:
    """Handle to one named function on one named C++ executor."""

    def __init__(self, executor: str, fn: str):
        self._executor = executor
        self._fn = fn

    def remote(self, *args: Any):
        from ._private.worker import global_worker
        from .object_ref import ObjectRef

        _check_json_args(args)
        oid = ObjectID.from_put(global_worker.job_id).hex()
        global_worker.request(
            {
                "t": "cpp_call",
                "executor": self._executor,
                "fn": self._fn,
                "args": list(args),
                "return_id": oid,
            }
        )
        # the head took the +1 for this ref inside cpp_call
        return ObjectRef(oid, skip_adding_local_ref=True)

    def __repr__(self):
        return f"CppFunction({self._executor}.{self._fn})"


def cpp_function(executor: str, fn: str) -> CppFunction:
    """`cpp_function("calc", "Add").remote(1, 2)` -> ObjectRef."""
    return CppFunction(executor, fn)


def list_cpp_executors() -> Dict[str, List[str]]:
    """Live executors -> the function names each registered."""
    from ._private.worker import global_worker

    return global_worker.request({"t": "list_cpp_executors"})


_JSON_TYPES = (type(None), bool, int, float, str, list, tuple, dict)
_INT64_MIN, _INT64_MAX = -(2**63), 2**63 - 1


def _check_json_args(args) -> None:
    """Reject anything the C++ JSON parser can't round-trip: non-finite
    floats (json.dumps emits bare NaN/Infinity, which kills the executor's
    parser), ints outside int64, and non-string dict keys (json.dumps would
    silently stringify them — data corruption, not an error)."""
    import math

    for a in args:
        if not isinstance(a, _JSON_TYPES):
            raise TypeError(
                f"cross-language args must be JSON-representable, got "
                f"{type(a).__name__}"
            )
        if isinstance(a, bool):
            continue
        if isinstance(a, float) and not math.isfinite(a):
            raise TypeError(f"cross-language float args must be finite, got {a!r}")
        if isinstance(a, int) and not (_INT64_MIN <= a <= _INT64_MAX):
            raise TypeError(f"cross-language int args must fit int64, got {a!r}")
        if isinstance(a, (list, tuple)):
            _check_json_args(a)
        elif isinstance(a, dict):
            for k in a:
                if not isinstance(k, str):
                    raise TypeError(
                        f"cross-language dict keys must be str, got "
                        f"{type(k).__name__}"
                    )
            _check_json_args(a.values())
