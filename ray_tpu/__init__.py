"""ray_tpu: a TPU-native distributed ML framework with Ray-level capabilities.

Public core API parity: python/ray/_private/worker.py — init (:1186),
get (:2506), put (:2621), wait (:2684), remote (:3016), shutdown (:1732),
get_actor (:2805), kill, cancel, cluster_resources, nodes.
"""

from __future__ import annotations

import functools
import inspect
from typing import Any, Dict, List, Optional, Sequence, Union

__version__ = "0.1.0"

from . import exceptions  # noqa: F401
from . import cross_language  # noqa: F401
from .actor import ActorClass, ActorHandle
from .object_ref import ObjectRef, ObjectRefGenerator
from .remote_function import RemoteFunction
from ._private.config import GLOBAL_CONFIG
from ._private.worker import global_worker

__all__ = [
    "init",
    "shutdown",
    "is_initialized",
    "remote",
    "get",
    "put",
    "wait",
    "kill",
    "get_actor",
    "cluster_resources",
    "available_resources",
    "nodes",
    "ObjectRef",
    "ObjectRefGenerator",
    "ActorHandle",
    "get_runtime_context",
    "method",
    "timeline",
]


def init(
    address: Optional[str] = None,
    *,
    num_cpus: Optional[int] = None,
    num_tpus: Optional[int] = None,
    resources: Optional[Dict[str, float]] = None,
    namespace: str = "",
    ignore_reinit_error: bool = False,
    _system_config: Optional[Dict[str, Any]] = None,
    _tracing_startup_hook=None,
    **_kwargs,
):
    """Start (or connect to) a ray_tpu runtime.

    address=None starts a new local runtime; address="auto" (or the
    RAY_TPU_ADDRESS env var, set for submitted jobs) attaches to a running
    head's socket as an additional driver (reference: worker.py:1186
    address resolution)."""
    import os as _os

    global _overrides_before_init
    if global_worker.connected:
        if ignore_reinit_error:
            return _ctx()
        raise RuntimeError("ray_tpu.init() called twice; use ignore_reinit_error=True")
    if _system_config:
        # session-scoped: shutdown() restores — overrides from one session
        # (e.g. a test's aggressive prober) must not leak into the next
        _overrides_before_init = dict(GLOBAL_CONFIG._overrides)
        GLOBAL_CONFIG.apply(_system_config)
    if _tracing_startup_hook is not None:
        # reference: ray.init(_tracing_startup_hook=...) — the hook installs
        # the app's opentelemetry SDK provider, then tracing turns on
        from .util import tracing as _tracing

        _tracing.enable(_tracing_startup_hook)
    address = address or _os.environ.get("RAY_TPU_ADDRESS")
    if address:
        socket_path = _resolve_address(address)
        global_worker.connect_existing(socket_path, namespace=namespace)
        if GLOBAL_CONFIG.log_to_driver:
            global_worker.start_log_forwarding()
    else:
        from ._private.node import Node, default_resources

        node = Node(default_resources(num_cpus, num_tpus, resources))
        global_worker.connect_driver(node, namespace=namespace)
        if GLOBAL_CONFIG.log_to_driver:
            global_worker.start_log_forwarding()
    from ._private import usage as _usage

    _usage.set_session_dir(global_worker.session_dir)
    return _ctx()


def _resolve_address(address: str) -> str:
    import glob as _glob
    import os as _os

    if address != "auto":
        return address  # an explicit head socket path
    # 'auto' prefers the cluster that spawned us (jobs get the exact socket)
    if _os.environ.get("RAY_TPU_ADDRESS"):
        return _os.environ["RAY_TPU_ADDRESS"]
    def mtime(p):
        try:
            return _os.path.getmtime(p)
        except OSError:
            return 0.0

    candidates = sorted(
        _glob.glob(_os.path.join(GLOBAL_CONFIG.session_dir_root, "session_*", "head.sock")),
        key=mtime,
        reverse=True,
    )
    for cand in candidates:  # newest LIVE head (crashed heads leave sockets)
        if _socket_alive(cand):
            return cand
    raise ConnectionError(
        f"address='auto' but no live session under {GLOBAL_CONFIG.session_dir_root}"
    )


def _socket_alive(path: str) -> bool:
    import socket as _socket

    s = _socket.socket(_socket.AF_UNIX, _socket.SOCK_STREAM)
    s.settimeout(0.5)
    try:
        s.connect(path)
        return True
    except OSError:
        return False
    finally:
        s.close()


def _ctx():
    return {
        "session_dir": global_worker.session_dir,
        "node_id": global_worker.node_id,
    }


_overrides_before_init = None


def shutdown():
    global _overrides_before_init
    # close the driver's own connection before stopping the IO loop so its
    # read task is cancelled cleanly (otherwise asyncio warns about a
    # destroyed pending task at loop teardown)
    if global_worker.conn is not None and not global_worker.conn.closed and global_worker.io:
        try:
            global_worker.io.run(global_worker.conn.close(), timeout=2)
        except Exception:
            pass
    node, global_worker.node = global_worker.node, None
    # disconnect first: direct actor channels close while the IO loop is
    # still running (node.stop() tears the loop down)
    global_worker.disconnect()
    if node is not None:
        node.stop()
    # only now drop this session's _system_config overrides: the head's own
    # teardown (final snapshot etc.) must still see them, but they must not
    # leak into the next session
    if _overrides_before_init is not None:
        GLOBAL_CONFIG._overrides = _overrides_before_init
        _overrides_before_init = None


def is_initialized() -> bool:
    return global_worker.connected


def remote(*args, **options):
    """Decorate a function as a remote task or a class as an actor."""

    def decorator(fn_or_cls):
        if inspect.isclass(fn_or_cls):
            return ActorClass(fn_or_cls, **options)
        return RemoteFunction(fn_or_cls, **options)

    if len(args) == 1 and not options and (inspect.isfunction(args[0]) or inspect.isclass(args[0])):
        return decorator(args[0])
    if args:
        raise TypeError("@remote takes keyword options only, e.g. @remote(num_cpus=2)")
    return decorator


def method(num_returns: int = 1):
    """Decorator to annotate actor methods (e.g. multiple returns)."""

    def decorator(m):
        m.__ray_num_returns__ = num_returns
        return m

    return decorator


def get(object_refs, *, timeout: Optional[float] = None):
    from .object_ref import ObjectRefGenerator, StreamDescriptor

    result = global_worker.get(object_refs, timeout=timeout)
    # num_returns="dynamic" parity: the task's single ref resolves to an
    # ObjectRefGenerator over the yielded objects (reference:
    # DynamicObjectRefGenerator via ray.get)
    if isinstance(result, StreamDescriptor) and isinstance(object_refs, ObjectRef):
        return ObjectRefGenerator(object_refs, count=result.count)
    if isinstance(result, list) and any(isinstance(v, StreamDescriptor) for v in result):
        return [
            ObjectRefGenerator(r, count=v.count) if isinstance(v, StreamDescriptor) else v
            for v, r in zip(result, object_refs)
        ]
    return result


def put(value) -> ObjectRef:
    return global_worker.put(value)


def wait(
    object_refs: Sequence[ObjectRef],
    *,
    num_returns: int = 1,
    timeout: Optional[float] = None,
    fetch_local: bool = True,
):
    return global_worker.wait(
        object_refs, num_returns=num_returns, timeout=timeout, fetch_local=fetch_local
    )


def kill(actor: ActorHandle, *, no_restart: bool = True):
    global_worker.request(
        {"t": "kill_actor", "actor_id": actor._actor_id, "no_restart": no_restart}
    )


def cancel(object_ref: ObjectRef, *, force: bool = False, recursive: bool = True) -> bool:
    """Cancel the task that produces `object_ref` (reference:
    python/ray/_private/worker.py cancel). Queued tasks are dropped and
    their refs resolve to TaskCancelledError; running tasks get the
    cancellation raised in the executing thread; force=True kills the
    worker process instead. `recursive` is accepted for API parity —
    child-task trees are not tracked, so it has no effect. Returns True
    when the cancel took effect."""
    return global_worker.cancel_task(object_ref, force=force)


def get_actor(name: str, namespace: Optional[str] = None) -> ActorHandle:
    info = global_worker.request(
        {
            "t": "get_named_actor",
            "name": name,
            "namespace": namespace if namespace is not None else global_worker.namespace,
        }
    )
    meta = info["spec_meta"]
    return ActorHandle(info["actor_id"], meta.get("method_names"), meta.get("cls_name") or "")


def cluster_resources() -> Dict[str, float]:
    return global_worker.request({"t": "cluster_resources"})["total"]


def available_resources() -> Dict[str, float]:
    return global_worker.request({"t": "cluster_resources"})["available"]


def nodes() -> List[dict]:
    return global_worker.request({"t": "nodes"})


def timeline(filename: Optional[str] = None):
    """Chrome-tracing timeline of task execution (reference: ray.timeline,
    python/ray/_private/profiling.py). Returns the event list; writes JSON
    to `filename` if given (load in chrome://tracing or Perfetto)."""
    import json

    events = global_worker.request({"t": "timeline"})
    if filename:
        with open(filename, "w") as f:
            json.dump(events, f)
    return events


class RuntimeContext:
    @property
    def node_id(self):
        return global_worker.node_id

    @property
    def job_id(self):
        return global_worker.job_id

    @property
    def task_id(self):
        return global_worker.current_task_id

    @property
    def actor_id(self):
        return global_worker.current_actor_id

    @property
    def namespace(self):
        return global_worker.namespace

    def get_actor_id(self):
        return global_worker.current_actor_id

    def get_node_id(self):
        return global_worker.node_id


def get_runtime_context() -> RuntimeContext:
    return RuntimeContext()
