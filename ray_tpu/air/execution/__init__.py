"""Generic tracked-actor fleets over pluggable resource managers
(reference: air/execution/_internal/actor_manager.py:23 +
air/execution/resources/)."""

from .actor_manager import ActorManager, TrackedActor  # noqa: F401
from .resources import (  # noqa: F401
    AcquiredResources,
    FixedResourceManager,
    PlacementGroupResourceManager,
    ResourceManager,
    ResourceRequest,
)
