"""ActorManager: the generic "fleet of tracked actors" layer.

Reference parity: air/execution/_internal/actor_manager.py:23
(RayActorManager) + tracked_actor.py/tracked_actor_task.py — the shared
substrate under Tune trials and Train worker groups: request resources via
a pluggable ResourceManager, start actors when grants arrive, route task
results/errors to callbacks, and reclaim resources on stop/failure.

Event delivery is callback-based and runs inside `next()` — the single-
threaded poll loop the controller owns (the reference posts events into the
same kind of loop). No background threads: determinism beats parallel
bookkeeping at control-plane rates.
"""

from __future__ import annotations

import itertools
from typing import Any, Callable, Dict, List, Optional, Tuple

from .resources import AcquiredResources, ResourceManager, ResourceRequest

_counter = itertools.count()


class TrackedActor:
    """Opaque fleet member (reference: tracked_actor.py). States:
    PENDING (waiting for resources) -> STARTING (actor created) ->
    STARTED -> STOPPED | FAILED."""

    PENDING = "PENDING"
    STARTING = "STARTING"
    STARTED = "STARTED"
    STOPPED = "STOPPED"
    FAILED = "FAILED"

    def __init__(self, cls, kwargs, request, on_start, on_stop, on_error):
        self.uid = next(_counter)
        self.cls = cls
        self.kwargs = dict(kwargs or {})
        self.request = request
        self.state = TrackedActor.PENDING
        self.handle = None
        self.acquired: Optional[AcquiredResources] = None
        self.on_start = on_start
        self.on_stop = on_stop
        self.on_error = on_error
        self._inflight: List[Tuple[Any, Optional[Callable], Optional[Callable]]] = []

    def __repr__(self):
        return f"TrackedActor({self.cls.__name__ if self.cls else '?'}#{self.uid}, {self.state})"


class ActorManager:
    def __init__(self, resource_manager: ResourceManager):
        self.resource_manager = resource_manager
        self._pending: List[TrackedActor] = []
        self._live: Dict[int, TrackedActor] = {}

    # ------------------------------------------------------------- fleet API

    def add_actor(
        self,
        cls,
        kwargs: Optional[Dict[str, Any]] = None,
        resource_request: Optional[ResourceRequest] = None,
        *,
        on_start: Optional[Callable[[TrackedActor], None]] = None,
        on_stop: Optional[Callable[[TrackedActor], None]] = None,
        on_error: Optional[Callable[[TrackedActor, Exception], None]] = None,
    ) -> TrackedActor:
        request = resource_request or ResourceRequest([{"CPU": 1.0}])
        ta = TrackedActor(cls, kwargs, request, on_start, on_stop, on_error)
        self.resource_manager.request_resources(request)
        self._pending.append(ta)
        return ta

    def schedule_actor_task(
        self,
        tracked: TrackedActor,
        method: str,
        args: tuple = (),
        kwargs: Optional[Dict[str, Any]] = None,
        *,
        on_result: Optional[Callable[[TrackedActor, Any], None]] = None,
        on_error: Optional[Callable[[TrackedActor, Exception], None]] = None,
    ) -> None:
        if tracked.state not in (TrackedActor.STARTING, TrackedActor.STARTED):
            raise ValueError(f"{tracked} is not live")
        ref = getattr(tracked.handle, method).remote(*args, **(kwargs or {}))
        tracked._inflight.append((ref, on_result, on_error))

    def remove_actor(self, tracked: TrackedActor) -> None:
        """Graceful stop: kills the actor, frees its reservation, fires
        on_stop. Safe on PENDING actors (cancels the resource request).
        Idempotent: a second call — or a call after _fail already settled
        the actor — is a no-op, so a controller reacting to on_error with
        remove_actor never sees on_stop shadow the failure."""
        import ray_tpu

        if tracked.state in (TrackedActor.STOPPED, TrackedActor.FAILED):
            return
        if tracked.state == TrackedActor.PENDING:
            self.resource_manager.cancel_resource_request(tracked.request)
            self._pending.remove(tracked)
            tracked.state = TrackedActor.STOPPED
            return
        if tracked.handle is not None:
            try:
                ray_tpu.kill(tracked.handle)
            except Exception:
                pass
        self._reclaim(tracked, TrackedActor.STOPPED)
        if tracked.on_stop:
            tracked.on_stop(tracked)

    @property
    def num_pending(self) -> int:
        return len(self._pending)

    @property
    def num_live(self) -> int:
        return len(self._live)

    def live_actors(self) -> List[TrackedActor]:
        return list(self._live.values())

    # ------------------------------------------------------------- the loop

    def next(self, timeout: float = 0.1) -> bool:
        """Process ready events: start pending actors whose resources
        arrived, deliver resolved task results, surface failures. Returns
        True if anything happened (the controller's idle heuristic)."""
        import time as _time

        happened = self._start_ready()
        happened = self._poll_tasks() or happened
        happened = self._poll_health() or happened
        if not happened and timeout > 0:
            _time.sleep(min(timeout, 0.05))
        return happened

    def _start_ready(self) -> bool:
        import ray_tpu

        happened = False
        for ta in list(self._pending):
            acq = self.resource_manager.acquire_resources(ta.request)
            if acq is None:
                continue
            opts = acq.annotate_remote_options({"max_concurrency": 2})
            try:
                ta.handle = ray_tpu.remote(ta.cls).options(**opts).remote(**ta.kwargs)
            except Exception as e:
                self.resource_manager.free_resources(acq)
                self._pending.remove(ta)
                ta.state = TrackedActor.FAILED
                if ta.on_error:
                    ta.on_error(ta, e)
                happened = True
                continue
            ta.acquired = acq
            ta.state = TrackedActor.STARTING
            self._pending.remove(ta)
            self._live[ta.uid] = ta
            happened = True
        return happened

    def _poll_tasks(self) -> bool:
        import ray_tpu

        # ONE wait RPC for every in-flight ref across the fleet (not one
        # per ref): a 50-actor fleet polling at 20 Hz must not turn into
        # 1000 head round-trips/s of idle overhead
        all_refs = [ref for ta in self._live.values() for ref, _, _ in ta._inflight]
        if not all_refs:
            return False
        ready_refs, _ = ray_tpu.wait(
            all_refs, num_returns=len(all_refs), timeout=0
        )
        ready_set = set(ready_refs)
        happened = False
        for ta in list(self._live.values()):
            still: List[Tuple[Any, Optional[Callable], Optional[Callable]]] = []
            for ref, on_result, on_error in ta._inflight:
                if ref not in ready_set:
                    still.append((ref, on_result, on_error))
                    continue
                happened = True
                try:
                    result = ray_tpu.get(ref)
                except Exception as e:  # noqa: BLE001
                    if on_error:
                        on_error(ta, e)
                    else:
                        self._fail(ta, e)
                    continue
                # first successful round-trip proves the actor is up
                if ta.state == TrackedActor.STARTING:
                    ta.state = TrackedActor.STARTED
                    if ta.on_start:
                        ta.on_start(ta)
                if on_result:
                    on_result(ta, result)
            ta._inflight = still
        return happened

    _HEALTH_PERIOD_S = 0.5

    def _poll_health(self) -> bool:
        """Catch actors that died with no task in flight (restart storms,
        OOM kills): the head's actor table is the truth. Rate-limited — a
        tight controller loop must not turn idle actors into a per-tick
        actor_state RPC storm on the head."""
        import time as _time

        now = _time.monotonic()
        if now - getattr(self, "_last_health", 0.0) < self._HEALTH_PERIOD_S:
            return False
        self._last_health = now
        happened = False
        for ta in list(self._live.values()):
            if ta._inflight or ta.handle is None:
                continue
            try:
                state = ta.handle._state()
            except Exception:
                continue
            if state == "dead":
                self._fail(ta, RuntimeError("actor died"))
                happened = True
            elif state == "alive" and ta.state == TrackedActor.STARTING:
                ta.state = TrackedActor.STARTED
                if ta.on_start:
                    ta.on_start(ta)
                happened = True
        return happened

    def _fail(self, ta: TrackedActor, err: Exception) -> None:
        # idempotent: two errored in-flight refs resolving in one poll pass
        # must not fire on_error twice or double-free the reservation
        if ta.state in (TrackedActor.FAILED, TrackedActor.STOPPED):
            return
        # the process may still be running (an app-level exception does not
        # kill an actor) — a reservation must never be freed while its
        # holder lives, or the replacement oversubscribes the node
        if ta.handle is not None:
            import ray_tpu

            try:
                ray_tpu.kill(ta.handle)
            except Exception:
                pass
        self._reclaim(ta, TrackedActor.FAILED)
        if ta.on_error:
            ta.on_error(ta, err)

    def _reclaim(self, ta: TrackedActor, state: str) -> None:
        self._live.pop(ta.uid, None)
        ta.state = state
        if ta.acquired is not None:
            self.resource_manager.free_resources(ta.acquired)
            ta.acquired = None

    def shutdown(self) -> None:
        for ta in list(self._live.values()):
            self.remove_actor(ta)
        for ta in list(self._pending):
            self.remove_actor(ta)
        self.resource_manager.clear()
