"""Resource managers: pluggable "who reserves capacity for a fleet" seam.

Reference parity: air/execution/resources/ — ResourceRequest,
FixedResourceManager (:43 fixed.py, counts against a static pool) and
PlacementGroupResourceManager (:46 placement_group.py, one PG per request).
Tune/Train drive fleets of trial/worker actors through this seam so the
reservation strategy (local counting vs cluster-atomic gangs) is swappable.

TPU-first note: a ResourceRequest with multiple bundles + STRICT_SPREAD is
exactly a pod-slice reservation (one bundle per host); acquired resources
annotate actor options with the PG so gang workers land on the reserved
hosts.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Dict, List, Optional


@dataclass(frozen=True)
class ResourceRequest:
    """An acquirable shape: one or more bundles (dicts of resource->amount).

    head_bundle_index semantics match the reference: actors schedule into
    bundle 0 by default; gang workers spread over the rest.
    """

    bundles: tuple
    strategy: str = "PACK"

    def __init__(self, bundles: List[Dict[str, float]], strategy: str = "PACK"):
        object.__setattr__(
            self, "bundles", tuple(tuple(sorted(b.items())) for b in bundles)
        )
        object.__setattr__(self, "strategy", strategy)

    @property
    def bundle_dicts(self) -> List[Dict[str, float]]:
        return [dict(b) for b in self.bundles]

    def total(self) -> Dict[str, float]:
        out: Dict[str, float] = {}
        for b in self.bundle_dicts:
            for k, v in b.items():
                out[k] = out.get(k, 0.0) + v
        return out


class AcquiredResources:
    """A granted request: knows how to annotate actor/task options so the
    consumer actually lands on the reservation."""

    def __init__(self, request: ResourceRequest):
        self.request = request

    def annotate_remote_options(
        self, options: Optional[Dict[str, Any]] = None, bundle_index: int = 0
    ) -> Dict[str, Any]:
        raise NotImplementedError


class ResourceManager:
    """Interface (reference: air/execution/resources/resource_manager.py).

    Flow: request_resources() registers interest; has_ready() polls;
    acquire_resources() converts a ready request into AcquiredResources;
    free_resources() returns them.
    """

    def request_resources(self, request: ResourceRequest) -> None:
        raise NotImplementedError

    def cancel_resource_request(self, request: ResourceRequest) -> None:
        raise NotImplementedError

    def has_resources_ready(self, request: ResourceRequest) -> bool:
        raise NotImplementedError

    def acquire_resources(self, request: ResourceRequest) -> Optional[AcquiredResources]:
        raise NotImplementedError

    def free_resources(self, acquired: AcquiredResources) -> None:
        raise NotImplementedError

    def clear(self) -> None:
        pass


# ---------------------------------------------------------------- fixed


class _FixedAcquired(AcquiredResources):
    def annotate_remote_options(self, options=None, bundle_index: int = 0):
        opts = dict(options or {})
        bundle = self.request.bundle_dicts[bundle_index]
        if "CPU" in bundle:
            opts["num_cpus"] = bundle["CPU"]
        if "TPU" in bundle:
            opts["num_tpus"] = bundle["TPU"]
        extra = {k: v for k, v in bundle.items() if k not in ("CPU", "TPU")}
        if extra:
            opts["resources"] = {**opts.get("resources", {}), **extra}
        return opts


class FixedResourceManager(ResourceManager):
    """Budget-counting manager (reference: fixed.py:43): grants requests
    against a static total without touching the cluster — the consumer's
    own num_cpus/num_tpus options do the real scheduling. Right for tests
    and single-node fleets."""

    def __init__(self, total: Optional[Dict[str, float]] = None):
        if total is None:
            import ray_tpu

            total = dict(ray_tpu.cluster_resources())
        self._total = dict(total)
        self._used: Dict[str, float] = {}
        self._queue: List[ResourceRequest] = []

    def _fits(self, request: ResourceRequest) -> bool:
        for k, v in request.total().items():
            if self._used.get(k, 0.0) + v > self._total.get(k, 0.0) + 1e-9:
                return False
        return True

    def request_resources(self, request: ResourceRequest) -> None:
        self._queue.append(request)

    def cancel_resource_request(self, request: ResourceRequest) -> None:
        try:
            self._queue.remove(request)
        except ValueError:
            pass

    def has_resources_ready(self, request: ResourceRequest) -> bool:
        return request in self._queue and self._fits(request)

    def acquire_resources(self, request: ResourceRequest):
        if not self.has_resources_ready(request):
            return None
        self._queue.remove(request)
        for k, v in request.total().items():
            self._used[k] = self._used.get(k, 0.0) + v
        return _FixedAcquired(request)

    def free_resources(self, acquired: AcquiredResources) -> None:
        for k, v in acquired.request.total().items():
            self._used[k] = max(0.0, self._used.get(k, 0.0) - v)

    @property
    def used(self) -> Dict[str, float]:
        return dict(self._used)


# ---------------------------------------------------------- placement group


class _PGAcquired(AcquiredResources):
    def __init__(self, request: ResourceRequest, pg):
        super().__init__(request)
        self.pg = pg

    def annotate_remote_options(self, options=None, bundle_index: int = 0):
        from ...util.scheduling_strategies import PlacementGroupSchedulingStrategy

        opts = _FixedAcquired(self.request).annotate_remote_options(
            options, bundle_index
        )
        opts["scheduling_strategy"] = PlacementGroupSchedulingStrategy(
            self.pg, placement_group_bundle_index=bundle_index
        )
        return opts


class PlacementGroupResourceManager(ResourceManager):
    """Cluster-atomic manager (reference: placement_group.py:46): one PG per
    request — the grant is all-or-nothing across bundles, which is the gang
    semantic a multi-host TPU fleet needs (SURVEY §7.2 hard part #1)."""

    def __init__(self):
        self._pending: Dict[ResourceRequest, List[Any]] = {}

    def request_resources(self, request: ResourceRequest) -> None:
        from ...util.placement_group import placement_group

        pg = placement_group(request.bundle_dicts, strategy=request.strategy)
        self._pending.setdefault(request, []).append(pg)

    def cancel_resource_request(self, request: ResourceRequest) -> None:
        from ...util.placement_group import remove_placement_group

        pgs = self._pending.get(request)
        if pgs:
            pg = pgs.pop()
            if not pgs:
                del self._pending[request]
            try:
                remove_placement_group(pg)
            except Exception:
                pass

    def has_resources_ready(self, request: ResourceRequest) -> bool:
        for pg in self._pending.get(request, ()):
            if pg.wait(timeout_seconds=0):
                return True
        return False

    def acquire_resources(self, request: ResourceRequest):
        pgs = self._pending.get(request, [])
        for i, pg in enumerate(pgs):
            if pg.wait(timeout_seconds=0):
                pgs.pop(i)
                if not pgs:
                    del self._pending[request]
                return _PGAcquired(request, pg)
        return None

    def free_resources(self, acquired: AcquiredResources) -> None:
        from ...util.placement_group import remove_placement_group

        try:
            remove_placement_group(acquired.pg)  # type: ignore[attr-defined]
        except Exception:
            pass

    def clear(self) -> None:
        from ...util.placement_group import remove_placement_group

        for pgs in self._pending.values():
            for pg in pgs:
                try:
                    remove_placement_group(pg)
                except Exception:
                    pass
        self._pending.clear()
