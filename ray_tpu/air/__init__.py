"""ray_tpu.air: shared execution substrate for the ML libraries.

Reference parity: python/ray/air — here only the execution layer (the AIR
Checkpoint/Predictor surfaces live in train/); see air/execution/.
"""

from .execution import (  # noqa: F401
    ActorManager,
    FixedResourceManager,
    PlacementGroupResourceManager,
    ResourceRequest,
    TrackedActor,
)
