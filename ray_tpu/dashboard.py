"""Dashboard-lite: one HTTP endpoint on the head serving cluster state.

Reference parity: dashboard/head.py + http_server_head.py +
state_aggregator.py — collapsed to a minimal asyncio HTTP server running on
the head's own event loop (no aiohttp, no per-node agents, no React build):
JSON APIs over the same tables the state CLI reads, plus one self-contained
HTML page that polls them. The 25.9k-LoC reference dashboard's essential
surface — what is running where, live — in one file.
"""

from __future__ import annotations

import asyncio
import json
from typing import Optional

_PAGE = """<!doctype html>
<html><head><title>ray_tpu dashboard</title><style>
body{font-family:system-ui,sans-serif;margin:1.2rem;background:#fafafa;color:#222}
h1{font-size:1.2rem;margin:.2rem 0 .6rem} h2{font-size:1rem;margin:1rem 0 .4rem}
table{border-collapse:collapse;width:100%;background:#fff;font-size:.85rem}
th,td{border:1px solid #ddd;padding:.3rem .5rem;text-align:left}
th{background:#f0f0f0} .dead{color:#b00} .alive{color:#080}
#res{font-size:.9rem;margin:.3rem 0}
nav{margin:.4rem 0 .8rem} nav a{margin-right:1rem;text-decoration:none;color:#07c}
nav a.cur{font-weight:bold;color:#000;border-bottom:2px solid #07c}
.tab{display:none} .tab.cur{display:block}
pre.detail{background:#fff;border:1px solid #ddd;padding:.5rem;max-height:22rem;overflow:auto}
pre.log{background:#111;color:#ddd;padding:.5rem;min-height:3rem;max-height:22rem;overflow:auto}
input,select{font-size:.85rem;padding:.15rem .3rem;margin:.2rem .4rem .2rem 0}
button{font-size:.8rem;margin-right:.4rem}
.crumb{font-size:.85rem;margin:.3rem 0;color:#555}
</style></head><body>
<h1>ray_tpu dashboard</h1>
<nav id="nav"></nav>
<div id="err" style="display:none;color:#b00;font-size:.85rem;margin:.2rem 0"></div>
<div id="tab-overview" class="tab">
  <div id="res"></div>
  <h2>Nodes</h2><table id="nodes"></table>
  <div id="spark"></div>
  <h2>Workers</h2><table id="workers"></table>
  <h2>Dataset executions (recent)</h2><table id="datasets"></table>
</div>
<div id="tab-jobs" class="tab">
  <div id="jobdetail" style="display:none">
    <div class="crumb"><a href="#jobs" onclick="closeJob()">jobs</a> /
      <span id="jobid"></span>
      <button onclick="jobAction('stop')">stop</button>
      <button onclick="jobAction('delete')">delete</button></div>
    <pre class="detail" id="jobinfo"></pre>
    <h2>Job log (live tail)</h2><pre class="log" id="joblog"></pre>
  </div>
  <div id="joblist"><h2>Jobs (click a row)</h2><table id="jobs"></table></div>
</div>
<div id="tab-actors" class="tab">
  <input id="actorfilter" placeholder="filter by name/class/state" oninput="tick()">
  <div id="actordetail" style="display:none">
    <div class="crumb"><a href="#actors" onclick="sel.actor=null;render()">actors</a> /
      <span id="actorid"></span></div>
    <pre class="detail" id="actorinfo"></pre>
  </div>
  <h2>Actors (click a row)</h2><table id="actors"></table>
</div>
<div id="tab-tasks" class="tab">
  <input id="taskfilter" placeholder="filter by name/id" oninput="tick()">
  <select id="taskstate" onchange="tick()">
    <option value="">(any state)</option><option>pending</option>
    <option>waiting_deps</option><option>scheduled</option>
    <option>running</option><option>done</option><option>failed</option>
    <option>cancelled</option>
  </select>
  <pre id="taskdetail" class="detail" style="display:none"></pre>
  <h2>Tasks (latest first, click a row)</h2><table id="tasks"></table>
</div>
<div id="tab-logs" class="tab">
  <h2>Worker logs</h2>
  <select id="logsel"><option value="">(choose a worker)</option></select>
  <pre class="log" id="logview"></pre>
</div>
<script>
const TABS = ["overview","jobs","actors","tasks","logs"];
const sel = {job:null, actor:null};
function esc(s){
  return String(s).replace(/[&<>"']/g,
    c=>({"&":"&amp;","<":"&lt;",">":"&gt;",'"':"&quot;","'":"&#39;"}[c]));
}
function curTab(){
  const h = location.hash.replace("#","");
  return TABS.includes(h) ? h : "overview";
}
function render(){
  const cur = curTab();
  document.getElementById("nav").innerHTML = TABS.map(t=>
    `<a href="#${t}" class="${t===cur?"cur":""}">${t}</a>`).join("");
  for(const t of TABS)
    document.getElementById("tab-"+t).className = "tab"+(t===cur?" cur":"");
  document.getElementById("jobdetail").style.display = sel.job?"block":"none";
  document.getElementById("joblist").style.display = sel.job?"none":"block";
  document.getElementById("actordetail").style.display = sel.actor?"block":"none";
  tick();
}
window.onhashchange = render;
function fill(id, rows, cols, onclick){
  const t = document.getElementById(id);
  if(!rows.length){t.innerHTML = "<tr><td>(empty)</td></tr>"; return;}
  let h = "<tr>" + cols.map(c=>`<th>${esc(c)}</th>`).join("") + "</tr>";
  for(const r of rows){
    h += `<tr${onclick?` style="cursor:pointer" data-id="${esc(r[cols[0]])}"`:""}>` + cols.map(c=>{
      let v = r[c]; if(typeof v === "object" && v !== null) v = JSON.stringify(v);
      let cls = (c=="state"||c=="alive"||c=="status") ?
        ((v=="dead"||v==false||v=="FAILED")?"dead":"alive") : "";
      return `<td class="${cls}">${v == null ? "" : esc(v)}</td>`;
    }).join("") + "</tr>";
  }
  t.innerHTML = h;
  if(onclick) for(const tr of t.querySelectorAll("tr[data-id]"))
    tr.onclick = ()=>onclick(tr.dataset.id);
}
function sparkline(pts, color){
  if(!pts.length) return "";
  const w=160,h=28,max=Math.max(...pts,1e-9);
  const path=pts.map((v,i)=>`${i?"L":"M"}${(i/(pts.length-1||1)*w).toFixed(1)},${(h-2-(v/max)*(h-4)).toFixed(1)}`).join(" ");
  return `<svg width="${w}" height="${h}" style="vertical-align:middle"><path d="${path}" fill="none" stroke="${color}" stroke-width="1.5"/></svg>`;
}
// ---- jobs drill-down (REST routes double as the UI backend) ----
function showJob(id){ sel.job = id; render(); }
function closeJob(){ sel.job = null; render(); }
async function jobAction(act){
  if(!sel.job) return;
  if(act==="delete" && !confirm("Delete job "+sel.job+"?")) return;
  const r = await fetch("/api/jobs/"+sel.job+(act==="stop"?"/stop":""),
    {method: act==="stop"?"POST":"DELETE"});
  if(act==="delete" && r.ok) closeJob(); else tick();
}
async function tickJobDetail(){
  if(!sel.job) return;
  document.getElementById("jobid").textContent = sel.job;
  try{
    const [info, logs] = await Promise.all([
      fetch("/api/jobs/"+sel.job).then(r=>r.json()),
      fetch("/api/jobs/"+sel.job+"/logs").then(r=>r.json())]);
    document.getElementById("jobinfo").textContent = JSON.stringify(info, null, 2);
    const v = document.getElementById("joblog");
    const atEnd = v.scrollTop+v.clientHeight >= v.scrollHeight-8;
    v.textContent = logs.logs || "(empty)";
    if(atEnd) v.scrollTop = v.scrollHeight;
  }catch(e){ document.getElementById("jobinfo").textContent = ""+e; }
}
function showActor(id){ sel.actor = id; render(); }
async function showTask(tid){
  const d=document.getElementById("taskdetail");
  try{
    const all=await fetch("/api/tasks").then(r=>r.json());
    const t=all.find(x=>x.task_id===tid);
    d.textContent = t ? JSON.stringify(t, null, 2) : "task gone";
  }catch(e){ d.textContent=""+e; }
  d.style.display="block";
}
async function tickLogs(){
  if(curTab() !== "logs") return;  // don't poll tails the user can't see
  const sel_=document.getElementById("logsel"), view=document.getElementById("logview");
  try{
    const q = sel_.value ? ("?worker_id="+encodeURIComponent(sel_.value)) : "";
    const data = await fetch("/api/logs"+q).then(r=>r.json());
    const cur = new Set([...sel_.options].map(o=>o.value));
    for(const w of data.workers) if(!cur.has(w)){
      const o=document.createElement("option"); o.value=o.textContent=w; sel_.appendChild(o);
    }
    if(sel_.value && data.lines){
      const atEnd = view.scrollTop+view.clientHeight >= view.scrollHeight-8;
      view.textContent = data.lines.join("\\n");
      if(atEnd) view.scrollTop = view.scrollHeight;
    }
  }catch(e){}
}
async function tick(){
  const cur = curTab();
  try{
    if(cur === "overview"){
      const [res, nodes, workers, hist, dstats] = await Promise.all(
        ["cluster","nodes","workers","node_history","data_stats"].map(
          p=>fetch("/api/"+p).then(r=>r.json())));
      document.getElementById("res").textContent =
        Object.entries(res.total).map(([k,v])=>
          `${k}: ${Math.round((res.available[k]??0)*100)/100}/${Math.round(v*100)/100}`).join("   ");
      fill("nodes", nodes, ["node_id","alive","resources","available"]);
      let sh = "";
      for(const [nid, pts] of Object.entries(hist)){
        sh += `<div><code>${esc(nid)}</code> load ` +
          sparkline(pts.map(p=>p.load_1m??0), "#07c") + " mem " +
          sparkline(pts.map(p=>p.mem_frac??0), "#c70") +
          ` ${Math.round((pts.at(-1)?.mem_frac??0)*100)}%</div>`;
      }
      document.getElementById("spark").innerHTML = sh;
      fill("workers", workers, ["worker_id","node_id","state","actor_id","pid"]);
      fill("datasets", dstats.slice(-10).reverse().map(s=>({
        pipeline: s.operators.map(o=>o.name).join(" → "),
        blocks: s.blocks, rows: s.output_rows,
        total_ms: Math.round(s.total_s*1000),
        wait_ms: Math.round(s.iter_wait_s*1000),
        where: s.executed_remotely ? "cluster" : "driver",
      })), ["pipeline","blocks","rows","total_ms","wait_ms","where"]);
    } else if(cur === "jobs"){
      if(sel.job){ await tickJobDetail(); }
      else {
        const jobs = await fetch("/api/jobs").then(r=>r.json());
        fill("jobs", jobs.map(j=>({
          submission_id: j.submission_id, status: j.status,
          entrypoint: j.entrypoint,
          started: j.start_time ? new Date(j.start_time*1000).toLocaleTimeString() : "",
          runtime_s: j.start_time ? Math.round(((j.end_time||Date.now()/1000)-j.start_time)) : "",
        })), ["submission_id","status","entrypoint","started","runtime_s"], showJob);
      }
    } else if(cur === "actors"){
      const actors = await fetch("/api/actors").then(r=>r.json());
      const f = document.getElementById("actorfilter").value.toLowerCase();
      const rows = actors.filter(a => !f ||
        (a.name||"").toLowerCase().includes(f) ||
        (a.class_name||"").toLowerCase().includes(f) ||
        (a.state||"").toLowerCase().includes(f));
      fill("actors", rows,
        ["actor_id","class_name","name","state","worker_id","node_id"], showActor);
      if(sel.actor){
        document.getElementById("actorid").textContent = sel.actor;
        const a = actors.find(x=>x.actor_id===sel.actor);
        document.getElementById("actorinfo").textContent =
          a ? JSON.stringify(a, null, 2) : "actor gone";
      }
    } else if(cur === "tasks"){
      const f = document.getElementById("taskfilter").value.toLowerCase();
      const st = document.getElementById("taskstate").value;
      // full-table fetch ONLY while a filter is active: an idle tasks tab
      // must not make the head serialize its whole history every 2s
      const q = (f || st) ? "?limit=0" : "";
      const tasks = await fetch("/api/tasks"+q).then(r=>r.json());
      const rows = tasks.filter(t =>
        (!f || (t.name||"").toLowerCase().includes(f) ||
               (t.task_id||"").toLowerCase().includes(f)) &&
        (!st || t.state === st));
      fill("tasks", rows.slice(-100).reverse(),
           ["task_id","name","state","node_id","worker_id"], showTask);
    }
    const err = document.getElementById("err");
    err.style.display = "none";
  }catch(e){
    // stale tables must not read as a live-but-idle cluster: surface the
    // failure on EVERY tab
    const err = document.getElementById("err");
    err.textContent = "head unreachable: " + e;
    err.style.display = "block";
  }
}
render(); setInterval(tick, 2000); tickLogs(); setInterval(tickLogs, 1500);
</script></body></html>"""


class Dashboard:
    """Serves the head's state over HTTP, sharing the head's event loop so
    handlers read the tables directly (no RPC hop, no races: the loop
    serializes against the control plane)."""

    def __init__(self, head):
        self.head = head
        self.server: Optional[asyncio.base_events.Server] = None
        self.address: Optional[str] = None

    async def start(self, host: str = "127.0.0.1", port: int = 0) -> Optional[str]:
        try:
            self.server = await asyncio.start_server(self._on_client, host=host, port=port)
        except OSError:
            return None
        from ._private.head import _advertise_host

        bound = self.server.sockets[0].getsockname()
        self.address = f"{_advertise_host(host)}:{bound[1]}"
        return self.address

    async def stop(self):
        if self.server is not None:
            self.server.close()

    # largest accepted request body (working-dir package uploads)
    MAX_BODY = 256 * 1024 * 1024

    async def _on_client(self, reader, writer):
        try:
            request = await asyncio.wait_for(reader.readline(), timeout=10)
            parts = request.decode("latin1").split()
            method = parts[0].upper() if parts else "GET"
            path = parts[1] if len(parts) >= 2 else "/"
            content_length = 0
            while True:  # headers: only Content-Length matters to us
                line = await asyncio.wait_for(reader.readline(), timeout=10)
                if line in (b"\r\n", b"\n", b""):
                    break
                name, _, value = line.decode("latin1").partition(":")
                if name.strip().lower() == "content-length":
                    try:
                        content_length = int(value.strip())
                    except ValueError:
                        content_length = 0
            req_body = b""
            if content_length > self.MAX_BODY:
                # discard the body first — closing with bytes unread sends
                # RST and the client never sees the 413
                remaining = content_length
                while remaining > 0:
                    chunk = await asyncio.wait_for(
                        reader.read(min(remaining, 1 << 20)), timeout=120
                    )
                    if not chunk:
                        break
                    remaining -= len(chunk)
                status, ctype, body = self._json(
                    "413 Payload Too Large",
                    {"error": f"body exceeds {self.MAX_BODY} bytes"},
                )
            elif content_length < 0:
                status, ctype, body = self._json(
                    "400 Bad Request", {"error": "bad Content-Length"}
                )
            else:
                if content_length:
                    req_body = await asyncio.wait_for(
                        reader.readexactly(content_length), timeout=120
                    )
                status, ctype, body = await self._route(path, method, req_body)
            if not writer.is_closing():  # client may have hung up mid-handle
                writer.write(
                    f"HTTP/1.1 {status}\r\nContent-Type: {ctype}\r\n"
                    f"Content-Length: {len(body)}\r\nConnection: close\r\n\r\n".encode()
                    + body
                )
                await writer.drain()
        except Exception:
            pass
        finally:
            try:
                writer.close()
            except Exception:
                pass

    async def _route(self, path: str, method: str = "GET", req_body: bytes = b""):
        if path in ("/", "/index.html"):
            return "200 OK", "text/html; charset=utf-8", _PAGE.encode()
        if not path.startswith("/api/"):
            return "404 Not Found", "text/plain", b"not found"
        bare = path.split("?", 1)[0].rstrip("/")
        if bare in ("/api/jobs", "/api/packages") or path.startswith(
            ("/api/jobs/", "/api/packages/")
        ):
            return await self._route_rest(path, method, req_body)
        kind, _, query = path[len("/api/"):].partition("?")
        if kind == "profile":
            # /api/profile?worker_id=..&kind=cpu|mem|dump&duration=2
            # (reference: the dashboard's py-spy/memray profiling endpoints,
            # dashboard/modules/reporter/profile_manager.py)
            from urllib.parse import parse_qs, unquote

            q = parse_qs(query)
            if not q.get("worker_id"):
                return "400 Bad Request", "text/plain", b"worker_id required"
            try:
                duration = float(q.get("duration", ["2.0"])[0])
            except ValueError:
                return "400 Bad Request", "text/plain", b"bad duration"
            msg = {
                "t": "profile_worker",
                "worker_id": unquote(q["worker_id"][0]),
                "kind": q.get("kind", ["cpu"])[0],
                "duration_s": duration,
            }
            try:
                data = await self.head.handle(None, msg)
            except ValueError as e:  # unknown/dead worker
                return "404 Not Found", "text/plain", str(e).encode()
            except Exception as e:  # timeout / internal failure
                return ("500 Internal Server Error", "text/plain",
                        (repr(e) or "profile failed").encode())
            return "200 OK", "application/json", json.dumps(data).encode()
        if kind == "logs":
            from urllib.parse import parse_qs, unquote

            q = parse_qs(query)
            msg = {"t": "tail_logs"}
            if q.get("worker_id"):
                msg["worker_id"] = unquote(q["worker_id"][0])
            data = await self.head.handle(None, msg)
            return "200 OK", "application/json", json.dumps(data).encode()
        handlers = {
            "nodes": {"t": "nodes"},
            "actors": {"t": "list_actors"},
            "workers": {"t": "list_workers"},
            "tasks": {"t": "list_tasks", "limit": 1000},
            "objects": {"t": "list_objects"},
            # "jobs" is served by the REST router above
            "cluster": {"t": "cluster_resources"},
            "timeline": {"t": "timeline"},
            "metrics": {"t": "get_metrics"},
            # serve engine flight recorders (serve/telemetry.py): the raw
            # per-process event rings replicas push to the head
            "serve_events": {"t": "get_serve_events"},
            "event_stats": {"t": "event_stats"},
            "pgs": {"t": "pg_table"},
            "node_history": {"t": "node_history"},
            "object_stats": {"t": "object_stats"},
            "data_stats": {"t": "data_stats"},
        }
        msg = handlers.get(kind)
        if msg is None:
            return "404 Not Found", "text/plain", b"unknown api"
        msg = dict(msg)
        if kind == "tasks" and query:
            # ?limit=N (0 = all — client-side filters need the full set)
            from urllib.parse import parse_qs

            q = parse_qs(query)
            if q.get("limit"):
                try:
                    msg["limit"] = int(q["limit"][0])
                except ValueError:
                    pass
        data = await self.head.handle(None, msg)
        body = json.dumps(data, default=str).encode()
        return "200 OK", "application/json", body

    # ------------------------------------------------------------------
    # Job REST API (reference: dashboard/modules/job/job_head.py:140,273 —
    # JobHead's curl-able endpoints: submit/list/info/logs/stop/delete +
    # working-dir package upload). Same resource shapes over the head's
    # native job handlers, so curl / CI / a k8s operator can drive the
    # cluster with zero Python.
    # ------------------------------------------------------------------

    @staticmethod
    def _json(status: str, obj) -> tuple:
        return status, "application/json", json.dumps(obj, default=str).encode()

    async def _route_rest(self, path: str, method: str, req_body: bytes):
        try:
            return await self._route_rest_inner(path, method, req_body)
        except ValueError as e:
            msg = str(e)
            status = "404 Not Found" if "no such job" in msg else "400 Bad Request"
            return self._json(status, {"error": msg})
        except Exception as e:
            return self._json("500 Internal Server Error", {"error": repr(e)})

    async def _route_rest_inner(self, path: str, method: str, req_body: bytes):
        import os

        segs = [s for s in path.split("?", 1)[0].split("/") if s]  # api jobs ...
        if segs[1] == "packages":
            # PUT/GET /api/packages/pkg/<name> — zip upload + existence probe
            # (reference: job_head.py PUT /api/packages/{protocol}/{name})
            if len(segs) != 4 or segs[2] != "pkg":
                return self._json("404 Not Found", {"error": "bad package path"})
            name = segs[3]
            if "/" in name or ".." in name or not name:
                return self._json("400 Bad Request", {"error": "bad package name"})
            pkg_dir = os.path.join(self.head.session_dir, "packages")
            pkg_path = os.path.join(pkg_dir, name)
            if method == "PUT":
                loop = asyncio.get_running_loop()

                def _write():
                    import threading

                    os.makedirs(pkg_dir, exist_ok=True)
                    # pid+tid: concurrent PUTs of the same name are safe
                    tmp = f"{pkg_path}.tmp-{os.getpid()}-{threading.get_ident()}"
                    with open(tmp, "wb") as f:
                        f.write(req_body)
                    os.replace(tmp, pkg_path)

                await loop.run_in_executor(None, _write)
                return self._json("200 OK", {"package_uri": f"pkg://{name}"})
            if method == "GET":
                if os.path.exists(pkg_path):
                    return self._json("200 OK", {"package_uri": f"pkg://{name}"})
                return self._json("404 Not Found", {"error": "no such package"})
            return self._json("405 Method Not Allowed", {"error": method})

        # /api/jobs[/<id>[/logs|/stop]]
        if len(segs) == 2:
            if method == "GET":
                jobs = await self.head.handle(None, {"t": "list_jobs"})
                return self._json("200 OK", jobs)
            if method == "POST":
                try:
                    req = json.loads(req_body or b"{}")
                except json.JSONDecodeError:
                    return self._json("400 Bad Request", {"error": "invalid JSON body"})
                if not req.get("entrypoint"):
                    return self._json("400 Bad Request", {"error": "entrypoint required"})
                from .runtime_env import RuntimeEnv

                runtime_env = dict(req.get("runtime_env") or {})
                # pkg:// working_dir resolves against the head's package
                # store at stage time; local-path validation doesn't apply
                pkg_wd = None
                if str(runtime_env.get("working_dir", "")).startswith("pkg://"):
                    pkg_wd = runtime_env.pop("working_dir")
                runtime_env = dict(RuntimeEnv.validate(runtime_env) or {})
                if pkg_wd is not None:
                    runtime_env["working_dir"] = pkg_wd
                sid = await self.head.handle(
                    None,
                    {
                        "t": "submit_job",
                        "entrypoint": req["entrypoint"],
                        "runtime_env": runtime_env,
                        "submission_id": req.get("submission_id"),
                        "metadata": req.get("metadata"),
                    },
                )
                return self._json("200 OK", {"submission_id": sid})
            return self._json("405 Method Not Allowed", {"error": method})
        sid = segs[2]
        if len(segs) == 3:
            if method == "GET":
                info = await self.head.handle(None, {"t": "job_info", "submission_id": sid})
                return self._json("200 OK", info)
            if method == "DELETE":
                await self.head.handle(None, {"t": "delete_job", "submission_id": sid})
                return self._json("200 OK", {"deleted": True})
            return self._json("405 Method Not Allowed", {"error": method})
        if len(segs) == 4 and segs[3] == "logs" and method == "GET":
            logs = await self.head.handle(None, {"t": "job_logs", "submission_id": sid})
            return self._json("200 OK", {"logs": logs})
        if len(segs) == 4 and segs[3] == "stop" and method == "POST":
            stopped = await self.head.handle(None, {"t": "stop_job", "submission_id": sid})
            return self._json("200 OK", {"stopped": bool(stopped)})
        return self._json("404 Not Found", {"error": "unknown jobs api"})


def dashboard_url(session_dir: str) -> Optional[str]:
    """Read the live dashboard address for a session (None if disabled)."""
    import os

    try:
        with open(os.path.join(session_dir, "dashboard_addr")) as f:
            return "http://" + f.read().strip()
    except OSError:
        return None
