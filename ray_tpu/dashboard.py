"""Dashboard-lite: one HTTP endpoint on the head serving cluster state.

Reference parity: dashboard/head.py + http_server_head.py +
state_aggregator.py — collapsed to a minimal asyncio HTTP server running on
the head's own event loop (no aiohttp, no per-node agents, no React build):
JSON APIs over the same tables the state CLI reads, plus one self-contained
HTML page that polls them. The 25.9k-LoC reference dashboard's essential
surface — what is running where, live — in one file.
"""

from __future__ import annotations

import asyncio
import json
from typing import Optional

_PAGE = """<!doctype html>
<html><head><title>ray_tpu dashboard</title><style>
body{font-family:system-ui,sans-serif;margin:1.5rem;background:#fafafa;color:#222}
h1{font-size:1.2rem} h2{font-size:1rem;margin:1.2rem 0 .4rem}
table{border-collapse:collapse;width:100%;background:#fff;font-size:.85rem}
th,td{border:1px solid #ddd;padding:.3rem .5rem;text-align:left}
th{background:#f0f0f0} .dead{color:#b00} .alive{color:#080}
#res{font-size:.9rem;margin:.3rem 0}
</style></head><body>
<h1>ray_tpu dashboard</h1>
<div id="res"></div>
<h2>Nodes</h2><table id="nodes"></table>
<div id="spark"></div>
<h2>Actors</h2><table id="actors"></table>
<h2>Workers</h2><table id="workers"></table>
<h2>Jobs</h2><table id="jobs"></table>
<h2>Dataset executions (recent)</h2><table id="datasets"></table>
<h2>Tasks (last 50 — click a row for its event timeline)</h2>
<pre id="taskdetail" style="display:none;background:#fff;border:1px solid #ddd;padding:.5rem"></pre>
<table id="tasks"></table>
<h2>Worker logs</h2>
<select id="logsel"><option value="">(choose a worker)</option></select>
<pre id="logview" style="background:#111;color:#ddd;padding:.5rem;min-height:3rem;max-height:20rem;overflow:auto"></pre>
<script>
function esc(s){
  return String(s).replace(/[&<>"']/g,
    c=>({"&":"&amp;","<":"&lt;",">":"&gt;",'"':"&quot;","'":"&#39;"}[c]));
}
function fill(id, rows, cols, onclick){
  const t = document.getElementById(id);
  if(!rows.length){t.innerHTML = "<tr><td>(empty)</td></tr>"; return;}
  let h = "<tr>" + cols.map(c=>`<th>${esc(c)}</th>`).join("") + "</tr>";
  for(const r of rows){
    h += `<tr${onclick?` style="cursor:pointer" data-id="${esc(r[cols[0]])}"`:""}>` + cols.map(c=>{
      let v = r[c]; if(typeof v === "object" && v !== null) v = JSON.stringify(v);
      let cls = (c=="state"||c=="alive"||c=="status") ?
        ((v=="dead"||v==false||v=="FAILED")?"dead":"alive") : "";
      return `<td class="${cls}">${v == null ? "" : esc(v)}</td>`;
    }).join("") + "</tr>";
  }
  t.innerHTML = h;
  if(onclick) for(const tr of t.querySelectorAll("tr[data-id]"))
    tr.onclick = ()=>onclick(tr.dataset.id);
}
function sparkline(pts, color){
  if(!pts.length) return "";
  const w=160,h=28,max=Math.max(...pts,1e-9);
  const path=pts.map((v,i)=>`${i?"L":"M"}${(i/(pts.length-1||1)*w).toFixed(1)},${(h-2-(v/max)*(h-4)).toFixed(1)}`).join(" ");
  return `<svg width="${w}" height="${h}" style="vertical-align:middle"><path d="${path}" fill="none" stroke="${color}" stroke-width="1.5"/></svg>`;
}
async function showTask(tid){
  const d=document.getElementById("taskdetail");
  try{
    const all=await fetch("/api/tasks").then(r=>r.json());
    const t=all.find(x=>x.task_id===tid);
    d.textContent = t ? JSON.stringify(t, null, 2) : "task gone";
  }catch(e){ d.textContent=""+e; }
  d.style.display="block";
}
let taskRows=[];
async function tickLogs(){
  const sel=document.getElementById("logsel"), view=document.getElementById("logview");
  try{
    const q = sel.value ? ("?worker_id="+encodeURIComponent(sel.value)) : "";
    const data = await fetch("/api/logs"+q).then(r=>r.json());
    const cur = new Set([...sel.options].map(o=>o.value));
    for(const w of data.workers) if(!cur.has(w)){
      const o=document.createElement("option"); o.value=o.textContent=w; sel.appendChild(o);
    }
    if(sel.value && data.lines){
      const atEnd = view.scrollTop+view.clientHeight >= view.scrollHeight-8;
      view.textContent = data.lines.join("\\n");
      if(atEnd) view.scrollTop = view.scrollHeight;
    }
  }catch(e){}
}
async function tick(){
  try{
    const [res, nodes, actors, workers, jobs, tasks, hist, dstats] = await Promise.all(
      ["cluster","nodes","actors","workers","jobs","tasks","node_history","data_stats"].map(
        p=>fetch("/api/"+p).then(r=>r.json())));
    document.getElementById("res").textContent =
      Object.entries(res.total).map(([k,v])=>
        `${k}: ${Math.round((res.available[k]??0)*100)/100}/${Math.round(v*100)/100}`).join("   ");
    fill("nodes", nodes, ["node_id","alive","resources","available"]);
    let sh = "";
    for(const [nid, pts] of Object.entries(hist)){
      sh += `<div><code>${esc(nid)}</code> load ` +
        sparkline(pts.map(p=>p.load_1m??0), "#07c") + " mem " +
        sparkline(pts.map(p=>p.mem_frac??0), "#c70") +
        ` ${Math.round((pts.at(-1)?.mem_frac??0)*100)}%</div>`;
    }
    document.getElementById("spark").innerHTML = sh;
    fill("actors", actors, ["actor_id","class_name","name","state","worker_id"]);
    fill("workers", workers, ["worker_id","node_id","state","actor_id","pid"]);
    fill("jobs", jobs, ["submission_id","status","entrypoint","log_path"]);
    fill("datasets", dstats.slice(-10).reverse().map(s=>({
      pipeline: s.operators.map(o=>o.name).join(" → "),
      blocks: s.blocks, rows: s.output_rows,
      total_ms: Math.round(s.total_s*1000),
      wait_ms: Math.round(s.iter_wait_s*1000),
      where: s.executed_remotely ? "cluster" : "driver",
    })), ["pipeline","blocks","rows","total_ms","wait_ms","where"]);
    taskRows = tasks;
    fill("tasks", tasks.slice(-50).reverse(),
         ["task_id","name","state","node_id","worker_id"], showTask);
  }catch(e){ document.getElementById("res").textContent = "head unreachable: "+e; }
}
tick(); setInterval(tick, 2000); tickLogs(); setInterval(tickLogs, 1500);
</script></body></html>"""


class Dashboard:
    """Serves the head's state over HTTP, sharing the head's event loop so
    handlers read the tables directly (no RPC hop, no races: the loop
    serializes against the control plane)."""

    def __init__(self, head):
        self.head = head
        self.server: Optional[asyncio.base_events.Server] = None
        self.address: Optional[str] = None

    async def start(self, host: str = "127.0.0.1", port: int = 0) -> Optional[str]:
        try:
            self.server = await asyncio.start_server(self._on_client, host=host, port=port)
        except OSError:
            return None
        from ._private.head import _advertise_host

        bound = self.server.sockets[0].getsockname()
        self.address = f"{_advertise_host(host)}:{bound[1]}"
        return self.address

    async def stop(self):
        if self.server is not None:
            self.server.close()

    # largest accepted request body (working-dir package uploads)
    MAX_BODY = 256 * 1024 * 1024

    async def _on_client(self, reader, writer):
        try:
            request = await asyncio.wait_for(reader.readline(), timeout=10)
            parts = request.decode("latin1").split()
            method = parts[0].upper() if parts else "GET"
            path = parts[1] if len(parts) >= 2 else "/"
            content_length = 0
            while True:  # headers: only Content-Length matters to us
                line = await asyncio.wait_for(reader.readline(), timeout=10)
                if line in (b"\r\n", b"\n", b""):
                    break
                name, _, value = line.decode("latin1").partition(":")
                if name.strip().lower() == "content-length":
                    try:
                        content_length = int(value.strip())
                    except ValueError:
                        content_length = 0
            req_body = b""
            if content_length > self.MAX_BODY:
                # discard the body first — closing with bytes unread sends
                # RST and the client never sees the 413
                remaining = content_length
                while remaining > 0:
                    chunk = await asyncio.wait_for(
                        reader.read(min(remaining, 1 << 20)), timeout=120
                    )
                    if not chunk:
                        break
                    remaining -= len(chunk)
                status, ctype, body = self._json(
                    "413 Payload Too Large",
                    {"error": f"body exceeds {self.MAX_BODY} bytes"},
                )
            elif content_length < 0:
                status, ctype, body = self._json(
                    "400 Bad Request", {"error": "bad Content-Length"}
                )
            else:
                if content_length:
                    req_body = await asyncio.wait_for(
                        reader.readexactly(content_length), timeout=120
                    )
                status, ctype, body = await self._route(path, method, req_body)
            if not writer.is_closing():  # client may have hung up mid-handle
                writer.write(
                    f"HTTP/1.1 {status}\r\nContent-Type: {ctype}\r\n"
                    f"Content-Length: {len(body)}\r\nConnection: close\r\n\r\n".encode()
                    + body
                )
                await writer.drain()
        except Exception:
            pass
        finally:
            try:
                writer.close()
            except Exception:
                pass

    async def _route(self, path: str, method: str = "GET", req_body: bytes = b""):
        if path in ("/", "/index.html"):
            return "200 OK", "text/html; charset=utf-8", _PAGE.encode()
        if not path.startswith("/api/"):
            return "404 Not Found", "text/plain", b"not found"
        bare = path.split("?", 1)[0].rstrip("/")
        if bare in ("/api/jobs", "/api/packages") or path.startswith(
            ("/api/jobs/", "/api/packages/")
        ):
            return await self._route_rest(path, method, req_body)
        kind, _, query = path[len("/api/"):].partition("?")
        if kind == "profile":
            # /api/profile?worker_id=..&kind=cpu|mem|dump&duration=2
            # (reference: the dashboard's py-spy/memray profiling endpoints,
            # dashboard/modules/reporter/profile_manager.py)
            from urllib.parse import parse_qs, unquote

            q = parse_qs(query)
            if not q.get("worker_id"):
                return "400 Bad Request", "text/plain", b"worker_id required"
            try:
                duration = float(q.get("duration", ["2.0"])[0])
            except ValueError:
                return "400 Bad Request", "text/plain", b"bad duration"
            msg = {
                "t": "profile_worker",
                "worker_id": unquote(q["worker_id"][0]),
                "kind": q.get("kind", ["cpu"])[0],
                "duration_s": duration,
            }
            try:
                data = await self.head.handle(None, msg)
            except ValueError as e:  # unknown/dead worker
                return "404 Not Found", "text/plain", str(e).encode()
            except Exception as e:  # timeout / internal failure
                return ("500 Internal Server Error", "text/plain",
                        (repr(e) or "profile failed").encode())
            return "200 OK", "application/json", json.dumps(data).encode()
        if kind == "logs":
            from urllib.parse import parse_qs, unquote

            q = parse_qs(query)
            msg = {"t": "tail_logs"}
            if q.get("worker_id"):
                msg["worker_id"] = unquote(q["worker_id"][0])
            data = await self.head.handle(None, msg)
            return "200 OK", "application/json", json.dumps(data).encode()
        handlers = {
            "nodes": {"t": "nodes"},
            "actors": {"t": "list_actors"},
            "workers": {"t": "list_workers"},
            "tasks": {"t": "list_tasks", "limit": 1000},
            "objects": {"t": "list_objects"},
            # "jobs" is served by the REST router above
            "cluster": {"t": "cluster_resources"},
            "timeline": {"t": "timeline"},
            "metrics": {"t": "get_metrics"},
            "event_stats": {"t": "event_stats"},
            "pgs": {"t": "pg_table"},
            "node_history": {"t": "node_history"},
            "object_stats": {"t": "object_stats"},
            "data_stats": {"t": "data_stats"},
        }
        msg = handlers.get(kind)
        if msg is None:
            return "404 Not Found", "text/plain", b"unknown api"
        data = await self.head.handle(None, dict(msg))
        body = json.dumps(data, default=str).encode()
        return "200 OK", "application/json", body

    # ------------------------------------------------------------------
    # Job REST API (reference: dashboard/modules/job/job_head.py:140,273 —
    # JobHead's curl-able endpoints: submit/list/info/logs/stop/delete +
    # working-dir package upload). Same resource shapes over the head's
    # native job handlers, so curl / CI / a k8s operator can drive the
    # cluster with zero Python.
    # ------------------------------------------------------------------

    @staticmethod
    def _json(status: str, obj) -> tuple:
        return status, "application/json", json.dumps(obj, default=str).encode()

    async def _route_rest(self, path: str, method: str, req_body: bytes):
        try:
            return await self._route_rest_inner(path, method, req_body)
        except ValueError as e:
            msg = str(e)
            status = "404 Not Found" if "no such job" in msg else "400 Bad Request"
            return self._json(status, {"error": msg})
        except Exception as e:
            return self._json("500 Internal Server Error", {"error": repr(e)})

    async def _route_rest_inner(self, path: str, method: str, req_body: bytes):
        import os

        segs = [s for s in path.split("?", 1)[0].split("/") if s]  # api jobs ...
        if segs[1] == "packages":
            # PUT/GET /api/packages/pkg/<name> — zip upload + existence probe
            # (reference: job_head.py PUT /api/packages/{protocol}/{name})
            if len(segs) != 4 or segs[2] != "pkg":
                return self._json("404 Not Found", {"error": "bad package path"})
            name = segs[3]
            if "/" in name or ".." in name or not name:
                return self._json("400 Bad Request", {"error": "bad package name"})
            pkg_dir = os.path.join(self.head.session_dir, "packages")
            pkg_path = os.path.join(pkg_dir, name)
            if method == "PUT":
                loop = asyncio.get_running_loop()

                def _write():
                    import threading

                    os.makedirs(pkg_dir, exist_ok=True)
                    # pid+tid: concurrent PUTs of the same name are safe
                    tmp = f"{pkg_path}.tmp-{os.getpid()}-{threading.get_ident()}"
                    with open(tmp, "wb") as f:
                        f.write(req_body)
                    os.replace(tmp, pkg_path)

                await loop.run_in_executor(None, _write)
                return self._json("200 OK", {"package_uri": f"pkg://{name}"})
            if method == "GET":
                if os.path.exists(pkg_path):
                    return self._json("200 OK", {"package_uri": f"pkg://{name}"})
                return self._json("404 Not Found", {"error": "no such package"})
            return self._json("405 Method Not Allowed", {"error": method})

        # /api/jobs[/<id>[/logs|/stop]]
        if len(segs) == 2:
            if method == "GET":
                jobs = await self.head.handle(None, {"t": "list_jobs"})
                return self._json("200 OK", jobs)
            if method == "POST":
                try:
                    req = json.loads(req_body or b"{}")
                except json.JSONDecodeError:
                    return self._json("400 Bad Request", {"error": "invalid JSON body"})
                if not req.get("entrypoint"):
                    return self._json("400 Bad Request", {"error": "entrypoint required"})
                from .runtime_env import RuntimeEnv

                runtime_env = dict(req.get("runtime_env") or {})
                # pkg:// working_dir resolves against the head's package
                # store at stage time; local-path validation doesn't apply
                pkg_wd = None
                if str(runtime_env.get("working_dir", "")).startswith("pkg://"):
                    pkg_wd = runtime_env.pop("working_dir")
                runtime_env = dict(RuntimeEnv.validate(runtime_env) or {})
                if pkg_wd is not None:
                    runtime_env["working_dir"] = pkg_wd
                sid = await self.head.handle(
                    None,
                    {
                        "t": "submit_job",
                        "entrypoint": req["entrypoint"],
                        "runtime_env": runtime_env,
                        "submission_id": req.get("submission_id"),
                        "metadata": req.get("metadata"),
                    },
                )
                return self._json("200 OK", {"submission_id": sid})
            return self._json("405 Method Not Allowed", {"error": method})
        sid = segs[2]
        if len(segs) == 3:
            if method == "GET":
                info = await self.head.handle(None, {"t": "job_info", "submission_id": sid})
                return self._json("200 OK", info)
            if method == "DELETE":
                await self.head.handle(None, {"t": "delete_job", "submission_id": sid})
                return self._json("200 OK", {"deleted": True})
            return self._json("405 Method Not Allowed", {"error": method})
        if len(segs) == 4 and segs[3] == "logs" and method == "GET":
            logs = await self.head.handle(None, {"t": "job_logs", "submission_id": sid})
            return self._json("200 OK", {"logs": logs})
        if len(segs) == 4 and segs[3] == "stop" and method == "POST":
            stopped = await self.head.handle(None, {"t": "stop_job", "submission_id": sid})
            return self._json("200 OK", {"stopped": bool(stopped)})
        return self._json("404 Not Found", {"error": "unknown jobs api"})


def dashboard_url(session_dir: str) -> Optional[str]:
    """Read the live dashboard address for a session (None if disabled)."""
    import os

    try:
        with open(os.path.join(session_dir, "dashboard_addr")) as f:
            return "http://" + f.read().strip()
    except OSError:
        return None
