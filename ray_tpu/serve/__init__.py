"""ray_tpu.serve: online model serving.

Reference parity: python/ray/serve — @serve.deployment (api.py:241),
serve.run (api.py:413), deployment composition via bind (deployment.py:261),
controller reconciliation (controller.py:79), replica autoscaling
(autoscaling_policy.py), @serve.batch (batching.py), HTTP proxy
(http_proxy.py:320).
"""

from __future__ import annotations

import time
from typing import Any, Dict, Optional, Union

from .batching import (  # noqa: F401
    ContinuousBatcher,
    GenerationStream,
    batch,
)
from .deployment import Application, AutoscalingConfig, Deployment, DeploymentConfig
from .handle import (  # noqa: F401
    CONTROLLER_NAME,
    DeploymentHandle,
    DeploymentResponse,
    DeploymentUnavailableError,
)
from .drivers import http_adapters  # noqa: F401
from .http_proxy import (  # noqa: F401
    Request,
    Response,
    StreamingResponse,
    sse_stream,
)
from . import telemetry  # noqa: F401  (serve.telemetry.dump_timeline etc.)
from .ingress import HTTPException, Router, ingress  # noqa: F401
from .multiplex import get_multiplexed_model_id, multiplexed  # noqa: F401
from .openai_api import OpenAICompletions, openai_app  # noqa: F401
from .replica import ReplicaDrainingError, ReplicaStreamHandle  # noqa: F401
from .kv_transfer import (  # noqa: F401
    KVGenerationServer,
    KVTransferError,
    KVTransferManager,
    deploy_disaggregated,
    deploy_generation,
    prefix_hint,
)
from .weight_swap import (  # noqa: F401
    WeightPublisher,
    WeightSubscriber,
    WeightSwapError,
)

_PROXY_NAME = "SERVE_HTTP_PROXY"

# DAGDriver is itself a Deployment so `serve.DAGDriver.bind({...})` reads
# exactly like the reference (serve/drivers.py:30). Each bind() mints a
# UNIQUELY-NAMED deployment: the controller keys deployments globally by
# name, so a shared "DAGDriver" name would make two apps' drivers clobber
# each other on redeploy/delete.
from .drivers import _DAGDriverImpl as _DAGDriverImpl  # noqa: E402


class _DAGDriverFactory(Deployment):
    def bind(self, *args, **kwargs) -> Application:
        import copy
        import uuid

        # uuid, not a counter: driver processes sharing one detached
        # controller must never mint colliding deployment names
        fresh = Deployment(
            self.func_or_class,
            f"{self.name}_{uuid.uuid4().hex[:8]}",
            copy.deepcopy(self.config),  # carry the FULL options config
        )
        return fresh.bind(*args, **kwargs)


DAGDriver = _DAGDriverFactory(
    _DAGDriverImpl, "DAGDriver", DeploymentConfig(num_replicas=1)
)


def deployment(
    _func_or_class=None,
    *,
    name: Optional[str] = None,
    num_replicas: int = 1,
    max_ongoing_requests: int = 100,
    autoscaling_config: Optional[Union[dict, AutoscalingConfig]] = None,
    ray_actor_options: Optional[Dict[str, Any]] = None,
    graceful_shutdown_timeout_s: float = 10.0,
    graceful_shutdown_wait_loop_s: float = 0.1,
):
    """@serve.deployment decorator.

    graceful_shutdown_timeout_s / graceful_shutdown_wait_loop_s configure
    the drain lifecycle: replicas leaving the set (redeploy, downscale,
    delete, shutdown) stop taking new requests and get up to the timeout to
    finish in-flight ones before being reaped (see serve/README.md)."""

    def wrap(func_or_class):
        ac = autoscaling_config
        if isinstance(ac, dict):
            ac = AutoscalingConfig(**ac)
        cfg = DeploymentConfig(
            num_replicas=num_replicas,
            max_ongoing_requests=max_ongoing_requests,
            autoscaling_config=ac,
            ray_actor_options=ray_actor_options or {},
            graceful_shutdown_timeout_s=graceful_shutdown_timeout_s,
            graceful_shutdown_wait_loop_s=graceful_shutdown_wait_loop_s,
        )
        return Deployment(func_or_class, name or func_or_class.__name__, cfg)

    if _func_or_class is not None:
        return wrap(_func_or_class)
    return wrap


def _get_or_create_controller():
    import ray_tpu

    try:
        return ray_tpu.get_actor(CONTROLLER_NAME)
    except Exception:
        pass
    from .controller import ServeController

    Ctl = ray_tpu.remote(ServeController)
    h = Ctl.options(name=CONTROLLER_NAME, lifetime="detached", max_concurrency=16).remote()
    ray_tpu.get(h.ready.remote())
    return h


def run(
    app: Application,
    *,
    name: str = "default",
    route_prefix: Optional[str] = None,
    pass_request: bool = False,
    _blocking: bool = True,
) -> DeploymentHandle:
    """Deploy an application graph; returns a handle to its ingress.

    pass_request=True hands the ingress deployment a http_proxy.Request
    (method/path/query/headers/body) instead of just the parsed body."""
    import ray_tpu

    if not isinstance(app, Application):
        raise TypeError("serve.run takes the result of deployment.bind(...)")
    controller = _get_or_create_controller()

    ordered = app._walk({})  # dependencies first, ingress last
    specs = []
    for dep_name, node in ordered.items():
        def to_handle(a):
            if isinstance(a, Application):
                return DeploymentHandle(a.deployment.name)
            if isinstance(a, dict):
                return {k: to_handle(v) for k, v in a.items()}
            if isinstance(a, (list, tuple)):
                return type(a)(to_handle(v) for v in a)
            return a

        specs.append(
            {
                "name": dep_name,
                "func_or_class": node.deployment.func_or_class,
                "init_args": tuple(to_handle(a) for a in node.args),
                "init_kwargs": {k: to_handle(v) for k, v in node.kwargs.items()},
                "config": node.deployment.config,
            }
        )
    ingress_name = app.deployment.name
    ray_tpu.get(controller.deploy_application.remote(name, specs, ingress_name))

    # @serve.ingress deployments always receive the raw Request
    if getattr(app.deployment.func_or_class, "_serve_ingress", False):
        pass_request = True
    if route_prefix is not None:
        proxy = start_http_proxy()
        ray_tpu.get(proxy.set_route.remote(route_prefix, ingress_name, pass_request))
        # record on the controller too: per-node fleet proxies (if/when
        # started) pick the route up from there
        ray_tpu.get(
            controller.set_route.remote(route_prefix, ingress_name, pass_request)
        )
    return DeploymentHandle(ingress_name)


def start_http_proxy(host: str = "127.0.0.1", port: int = 0):
    """Idempotently start the HTTP proxy actor; returns its handle."""
    import ray_tpu

    try:
        return ray_tpu.get_actor(_PROXY_NAME)
    except Exception:
        pass
    from .http_proxy import HTTPProxyActor

    Proxy = ray_tpu.remote(HTTPProxyActor)
    h = Proxy.options(name=_PROXY_NAME, lifetime="detached", max_concurrency=32).remote(
        host, port
    )
    ray_tpu.get(h.ready.remote())
    return h


def start_proxies(port: int = 0) -> Dict[str, str]:
    """Start the per-node HTTP proxy fleet: one proxy actor pinned to every
    alive node, all sharing the controller's routing table; new nodes get a
    proxy on the controller's next reconcile tick, dead nodes' proxies are
    dropped (reference: serve/_private/http_state.py one-proxy-per-node).
    Returns {node_id: "host:port"}. port=0 picks a free port per node; a
    fixed port gives the uniform ingress endpoint a load balancer expects."""
    import ray_tpu

    controller = _get_or_create_controller()
    return ray_tpu.get(controller.start_proxies.remote(port))


def proxy_addresses() -> Dict[str, str]:
    """Live per-node proxy endpoints ({} until start_proxies)."""
    import ray_tpu

    controller = _get_or_create_controller()
    return ray_tpu.get(controller.proxy_addresses.remote())


def proxy_address() -> Optional[str]:
    import ray_tpu

    try:
        h = ray_tpu.get_actor(_PROXY_NAME)
    except Exception:
        return None
    info = ray_tpu.get(h.ready.remote())
    return f"{info['host']}:{info['port']}"


def status() -> Dict[str, dict]:
    import ray_tpu

    controller = _get_or_create_controller()
    return ray_tpu.get(controller.list_deployments.remote())


def delete(name: str = "default"):
    import ray_tpu

    controller = _get_or_create_controller()
    return ray_tpu.get(controller.delete_application.remote(name))


def shutdown():
    import ray_tpu

    from .handle import _reset_breakers
    from .long_poll import stop_watchers

    stop_watchers()
    # circuit-breaker state is per (process, deployment): a breaker tripped
    # by this session's teardown must not fail-fast a later session's
    # same-named deployment
    _reset_breakers()
    try:
        controller = ray_tpu.get_actor(CONTROLLER_NAME)
    except Exception:
        return
    try:
        ray_tpu.get(controller.graceful_shutdown.remote(), timeout=10)
    except Exception:
        pass
    try:
        ray_tpu.kill(controller)
    except Exception:
        pass
    try:
        proxy = ray_tpu.get_actor(_PROXY_NAME)
        ray_tpu.get(proxy.stop.remote(), timeout=5)
        ray_tpu.kill(proxy)
    except Exception:
        pass

from .._private.usage import record_library_usage as _rlu  # noqa: E402

_rlu("serve")
