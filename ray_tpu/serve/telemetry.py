"""Serving telemetry plane: request-lifecycle metrics + engine flight
recorder with Chrome-trace export.

Reference parity: Ray's per-node metrics agent -> Prometheus pipeline
(python/ray/_private/metrics_agent.py), the Serve request metrics
(serve/_private/metrics_utils.py: serve_request_latency/ttft/queue-wait
families), and `ray timeline` (python/ray/_private/profiling.py) — here
extended down to the DECODE ENGINE: a bounded, lock-cheap ring buffer of
step-level events (admit, prefill_chunk, decode, verify, rollback,
preempt, readmit, retire, eos) with monotonic timestamps and slot ids,
dumpable as Chrome trace-event JSON.

Three layers, all behind the `serve_telemetry` flag:

  ServeTelemetry   per-process singleton bundling the metric handles
                   (util/metrics.py Counters/Gauges/Histograms, tagged by
                   deployment/replica[/phase/outcome]) and the flight
                   recorder. Engines/batchers take it as `telemetry=`;
                   `False` disables per-instance (zero per-token work),
                   `None` resolves the process singleton per the flag.
  FlightRecorder   deque(maxlen) ring of (ts, name, slot, dur, args)
                   tuples — appends are GIL-atomic, no lock on the hot
                   path; `snapshot()` converts to wall-clock dicts so
                   recorders from many processes merge on one axis.
  dump_timeline()  flush every live replica's recorder to the head
                   (controller fan-out), pull the merged store, convert
                   to Chrome trace events (`ph`/`ts`/`pid`/`tid`), write
                   a chrome://tracing-loadable JSON file. The CLI twin is
                   `python -m ray_tpu.scripts timeline` (which also
                   merges the head's task timeline into the same file).

The recorder is ALSO force-pushed by the paths that precede a post-mortem:
replica drain, batcher close, engine-step faults, and the data-plane
orphaned-request watchdog (protocol.Connection.request) — so the head
holds the last `serve_telemetry_recorder_events` events of a wedged
process even when nobody got to call dump_timeline() in time.
"""

from __future__ import annotations

import json
import os
import threading
import time
from collections import deque
from typing import Any, Dict, List, Optional

from ray_tpu.util.metrics import Counter, Gauge, Histogram

# finer-than-default low end: TTFT/inter-token on a warm decode path sit
# in the 1-50ms band; the default boundaries would dump them into 3 buckets
LATENCY_BOUNDARIES = [
    0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5,
    1.0, 2.5, 5.0, 10.0, 30.0, 60.0,
]


class FlightRecorder:
    """Bounded ring of step-level engine events.

    record() is the hot path: one uncontended lock, one tuple build, one
    deque append. Oldest events fall off the end — the recorder is a
    crash/hang post-mortem window, not a complete log. `dur` is seconds
    and dates the event's START at now-dur, so spans nest correctly in
    the trace viewer."""

    def __init__(self, capacity: int = 4096):
        self.capacity = int(capacity)
        self._buf: "deque" = deque(maxlen=self.capacity)
        self.total = 0
        self._seq_lock = threading.Lock()
        # monotonic->wall anchor: events are stamped monotonic (immune to
        # clock steps) and converted once at snapshot so recorders from
        # different processes merge on one wall-clock axis
        self._wall_offset = time.time() - time.monotonic()

    def record(self, name: str, slot: int = -1, dur: float = 0.0,
               args: Optional[Dict[str, Any]] = None) -> None:
        # total doubles as the event's sequence number, which the delta
        # push + head merge key on — minting and appending happen under
        # one (uncontended, ~100ns) lock so two racing recorders (batcher
        # loop + a watchdog thread) can neither duplicate a seq nor
        # append out of order, either of which would silently drop an
        # event from the head's merge
        with self._seq_lock:
            self.total += 1
            self._buf.append(
                (time.monotonic() - dur, name, slot, dur, args, self.total))

    def __len__(self) -> int:
        return len(self._buf)

    @property
    def dropped(self) -> int:
        return max(0, self.total - len(self._buf))

    def clear(self) -> None:
        self._buf.clear()

    def snapshot(self) -> List[Dict[str, Any]]:
        """Wall-clock event dicts, oldest first (safe from any thread:
        list(deque) is atomic)."""
        off = self._wall_offset
        return [
            {"ts": t + off, "name": n, "slot": s, "dur": d, "seq": q,
             **({"args": a} if a else {})}
            for t, n, s, d, a, q in list(self._buf)
        ]


class ServeTelemetry:
    """Metric handles + flight recorder for one process. Handles are
    registry-backed (util/metrics.py), so two instances with the same
    metric names share values; `set_context` stamps deployment/replica
    default tags on everything at replica construction."""

    def __init__(self, recorder_capacity: Optional[int] = None):
        from ray_tpu._private.config import GLOBAL_CONFIG as cfg

        cap = (int(cfg.serve_telemetry_recorder_events)
               if recorder_capacity is None else int(recorder_capacity))
        self.recorder = FlightRecorder(cap) if cap > 0 else None
        base = ("deployment", "replica")
        self.ttft = Histogram(
            "serve_ttft_s", "time to first generated token",
            boundaries=LATENCY_BOUNDARIES, tag_keys=base)
        self.inter_token = Histogram(
            "serve_inter_token_latency_s",
            "gap between consecutive streamed tokens",
            boundaries=LATENCY_BOUNDARIES, tag_keys=base)
        self.queue_wait = Histogram(
            "serve_queue_wait_s",
            "submit->engine-admission wait (readmissions measure from "
            "their re-enqueue)",
            boundaries=LATENCY_BOUNDARIES, tag_keys=base)
        self.request_latency = Histogram(
            "serve_request_latency_s", "submit->finish generation latency",
            boundaries=LATENCY_BOUNDARIES, tag_keys=base)
        self.engine_step = Histogram(
            "serve_engine_step_s", "engine dispatch latency by phase",
            boundaries=LATENCY_BOUNDARIES, tag_keys=base + ("phase",))
        self.requests = Counter(
            "serve_requests_total", "finished generations by outcome",
            tag_keys=base + ("outcome",))
        self.preemptions = Counter(
            "serve_preemptions_total",
            "generations evicted under KV-pool pressure", tag_keys=base)
        self.tokens = Counter(
            "serve_tokens_total", "tokens streamed to consumers",
            tag_keys=base)
        self.kv_util = Gauge(
            "serve_kv_pool_utilization",
            "live fraction of the paged KV block pool", tag_keys=base)
        self.occupancy = Gauge(
            "serve_batch_occupancy",
            "slots active in the last engine step", tag_keys=base)
        self.spec_accept = Gauge(
            "serve_spec_accept_rate",
            "speculative drafts accepted / proposed (cumulative)",
            tag_keys=base)
        # cluster-wide KV plane (serve/kv_transfer.py): cross-replica
        # prefix traffic, by direction ("export" = bytes packed for a
        # peer, "import" = bytes pulled and installed locally)
        self.kv_transfer_bytes = Counter(
            "serve_kv_transfer_bytes_total",
            "cross-replica KV block bytes by direction",
            tag_keys=base + ("direction",))
        self.kv_transfer_hits = Counter(
            "serve_kv_transfer_hits_total",
            "remote prefix pulls that installed blocks locally",
            tag_keys=base)
        self.prefix_remote_hit_rate = Gauge(
            "serve_prefix_remote_hit_rate",
            "remote pulls installed / remote pulls attempted (cumulative)",
            tag_keys=base)
        # live weight plane (serve/weight_swap.py): the version the
        # engine is CURRENTLY serving — advances mid-stream on a hot swap
        self.weight_version = Gauge(
            "serve_weight_version",
            "learner weight version the replica's engine is serving",
            tag_keys=base)
        self._all = [
            self.ttft, self.inter_token, self.queue_wait,
            self.request_latency, self.engine_step, self.requests,
            self.preemptions, self.tokens, self.kv_util, self.occupancy,
            self.spec_accept, self.kv_transfer_bytes, self.kv_transfer_hits,
            self.prefix_remote_hit_rate, self.weight_version,
        ]
        self._last_push = 0.0
        self._last_push_total = -1  # recorder.total at the last push
        self._rebuild_phase_keys()

    def _rebuild_phase_keys(self) -> None:
        # precomputed observe keys for the per-step phase histogram: the
        # engine hot loop must not pay a dict merge + sort per dispatch
        self._phase_keys = {
            p: self.engine_step.tags_key({"phase": p})
            for p in ("prefill", "decode", "verify")
        }

    def observe_phase(self, phase: str, dur: float) -> None:
        self.engine_step.observe_key(dur, self._phase_keys[phase])

    def set_context(self, deployment: str = "", replica: str = "") -> None:
        tags = {}
        if deployment:
            tags["deployment"] = deployment
        if replica:
            tags["replica"] = replica
        for m in self._all:
            m.set_default_tags(tags)
        self._rebuild_phase_keys()

    # -------------------------------------------------- cross-process push

    def flush_events(self, force: bool = False) -> None:
        """Throttled DELTA push of the flight-recorder ring to the head
        (the metrics-push channel's sibling: `push_serve_events`). Must
        never break the workload. Only events past the last pushed seq go
        on the wire — a busy replica must not re-serialize its whole
        4096-event ring every interval, and an idle one (no new events)
        pushes nothing; the head appends by seq (`_h_push_serve_events`),
        so already-delivered events survive there past the local ring."""
        if self.recorder is None or not len(self.recorder):
            return
        from ray_tpu._private.config import GLOBAL_CONFIG as cfg

        now = time.monotonic()
        if not force:
            if now - self._last_push < float(cfg.serve_telemetry_push_s):
                return
            if self.recorder.total == self._last_push_total:
                return
        self._last_push = now
        try:
            from ray_tpu._private.worker import global_worker

            if global_worker.connected:
                snap = self.recorder.snapshot()
                if self._last_push_total > 0:
                    snap = [e for e in snap
                            if e["seq"] > self._last_push_total]
                if not snap:
                    return
                node = getattr(global_worker, "node_id", None) or "node"
                global_worker.send({
                    "t": "push_serve_events",
                    "proc": f"{node}:pid-{os.getpid()}",
                    "events": snap,
                    "dropped": self.recorder.dropped,
                })
                self._last_push_total = snap[-1]["seq"]
        except Exception:
            pass


_TEL: Optional[ServeTelemetry] = None
_TEL_FLAG_OFF = False  # singleton was force-built while the flag was off
_TEL_LOCK = threading.Lock()


def get_telemetry(force: bool = False) -> Optional[ServeTelemetry]:
    """The process singleton; None when `serve_telemetry` is off (pass
    force=True to build one regardless — benches that compare on vs off).
    A force-built singleton under a disabled flag stays invisible to
    non-forced callers: one bench row must not re-enable telemetry for
    every later telemetry=None engine in the same process."""
    global _TEL, _TEL_FLAG_OFF
    if _TEL is None:
        from ray_tpu._private.config import GLOBAL_CONFIG as cfg

        enabled = bool(cfg.serve_telemetry)
        if not force and not enabled:
            return None
        with _TEL_LOCK:
            if _TEL is None:
                _TEL = ServeTelemetry()
                _TEL_FLAG_OFF = not enabled
    if _TEL_FLAG_OFF and not force:
        return None
    return _TEL


def resolve(telemetry) -> Optional[ServeTelemetry]:
    """The engine/batcher `telemetry=` contract: None -> process singleton
    per the flag, False -> off for this instance, anything else passes
    through (tests inject their own)."""
    if telemetry is None:
        return get_telemetry()
    if telemetry is False:
        return None
    return telemetry


def set_context(deployment: str = "", replica: str = "") -> None:
    tel = get_telemetry()
    if tel is not None:
        tel.set_context(deployment, replica)


def flush_events(force: bool = False) -> None:
    tel = _TEL
    if tel is not None:
        tel.flush_events(force=force)


def record_orphaned_request(mtype: str, rid: int, tag: str = "") -> None:
    """Data-plane watchdog hook (protocol.Connection.request): a request
    with no reply past the warn deadline lands in BOTH planes — the
    `data_plane_orphaned_requests_total` counter (scrapable at /metrics)
    and a flight-recorder instant next to whatever the engine was doing —
    then force-flushes so the head holds the evidence at hang time."""
    try:
        from ray_tpu.util import metrics

        metrics.data_plane_orphaned_counter().inc(
            tags={"kind": tag or str(mtype)})
        tel = get_telemetry()
        if tel is not None and tel.recorder is not None:
            tel.recorder.record(
                "orphaned_request",
                args={"mtype": str(mtype), "rid": int(rid), "tag": tag},
            )
            tel.flush_events(force=True)
        metrics.flush()
    except Exception:
        pass  # telemetry must never break the data plane


def record_request_recovered(mtype: str, rid: int, attempts: int) -> None:
    """The self-healing counterpart of record_orphaned_request: a
    retransmitted plane request got its reply. Lands in
    `data_plane_requests_recovered_total` and as a `request_recovered`
    flight-recorder instant, so recovery is as visible in the timeline as
    loss was."""
    try:
        from ray_tpu.util import metrics

        metrics.data_plane_recovered_counter().inc(tags={"kind": str(mtype)})
        tel = get_telemetry()
        if tel is not None and tel.recorder is not None:
            tel.recorder.record(
                "request_recovered",
                args={"mtype": str(mtype), "rid": int(rid),
                      "attempts": int(attempts)},
            )
            tel.flush_events(force=True)
        metrics.flush()
    except Exception:
        pass  # telemetry must never break the data plane


# --------------------------------------------------------------------------
# Chrome trace export
# --------------------------------------------------------------------------


def to_chrome_trace(snapshots: Dict[str, List[Dict[str, Any]]]) -> List[dict]:
    """Convert per-process flight-recorder snapshots into Chrome
    trace-event JSON (the `ray timeline` format): pid = process, tid =
    engine slot, `X` complete events for spans (dur > 0), `i` instants
    otherwise. Batch-wide events carrying args["slots"] expand to one
    event per slot so each slot's lane shows its own decode/verify work;
    slot-LESS events (slot -1, e.g. orphaned_request) render on a
    dedicated "process-wide" lane (tid -1) so a post-mortem reader never
    misattributes them to slot 0's request."""
    out: List[dict] = []
    for pid, (proc, events) in enumerate(sorted(snapshots.items()), start=1):
        out.append({
            "name": "process_name", "ph": "M", "pid": pid, "tid": 0,
            "args": {"name": proc},
        })
        proc_lane_named = False
        for ev in events:
            args = dict(ev.get("args") or {})
            slots = args.pop("slots", None)
            slot = int(ev.get("slot", -1))
            if slots:
                tids = [int(s) for s in slots]
            elif slot >= 0:
                tids = [slot]
            else:
                tids = [-1]
                if not proc_lane_named:
                    proc_lane_named = True
                    out.append({
                        "name": "thread_name", "ph": "M", "pid": pid,
                        "tid": -1, "args": {"name": "process-wide"},
                    })
            ts_us = float(ev["ts"]) * 1e6
            dur_s = float(ev.get("dur", 0.0))
            for tid in tids:
                e = {
                    "name": ev["name"], "cat": "serve", "pid": pid,
                    "tid": tid, "ts": ts_us, "args": args,
                }
                if dur_s > 0:
                    e["ph"] = "X"
                    e["dur"] = dur_s * 1e6
                else:
                    e["ph"] = "i"
                    e["s"] = "t"
                out.append(e)
    return out


def dump_timeline(path: Optional[str] = None) -> List[dict]:
    """Dump the cluster-wide engine flight recorder as Chrome trace
    events (`ray timeline` parity for the serving plane). Asks every live
    serve replica to push its recorder to the head first (controller
    fan-out), then merges the head's store with this process's own
    recorder. Writes chrome://tracing-loadable JSON when `path` is given;
    returns the event list either way."""
    try:
        import ray_tpu
        from .handle import CONTROLLER_NAME

        controller = ray_tpu.get_actor(CONTROLLER_NAME)
        ray_tpu.get(controller.flush_telemetry.remote(), timeout=15)
    except Exception:
        pass  # no controller (engine driven in-process): local-only dump
    flush_events(force=True)
    snapshots: Dict[str, List[Dict[str, Any]]] = {}
    try:
        from ray_tpu._private.worker import global_worker

        if global_worker.connected:
            store = global_worker.request({"t": "get_serve_events"})
            snapshots = {
                proc: entry.get("events", [])
                for proc, entry in (store or {}).items()
            }
    except Exception:
        pass
    if not snapshots:
        tel = _TEL
        if tel is not None and tel.recorder is not None:
            snapshots = {f"local:pid-{os.getpid()}": tel.recorder.snapshot()}
    trace = to_chrome_trace(snapshots)
    if path:
        with open(path, "w") as f:
            json.dump(trace, f)
    return trace
