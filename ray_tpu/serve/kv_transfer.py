"""Cluster-wide KV plane: cross-replica prefix transfer + disaggregation.

ROADMAP item 3's serving half: each replica's `PrefixCache` turns a
repeated prompt prefix into an admission-time block reuse — but only for
prompts that land on THAT replica. This module makes the hit rate
cluster-wide by shipping cached KV blocks between replicas over the bulk
object plane, and layers two fleet capabilities on the same transfer
path:

  payload plumbing     `pack_payload`/`unpack_payload` flatten an engine
                       export (kv_paging.PagedDecodeEngine.export_prefix:
                       content-addressed chain keys + k/v block contents,
                       int8 scales included) into ONE contiguous uint8
                       buffer + a small meta dict. The buffer rides
                       `ray_tpu.put`/`get` — the PR 12 bulk plane:
                       recv-into-slab on the consumer, striping for
                       multi-MB spans, relay fallback on stream fault,
                       zero-copy shm attach on the same host. A CRC +
                       length check rejects anything truncated or
                       corrupted mid-flight.
  KVTransferManager    per-replica glue: serves peers' export requests
                       (engine reads routed through the batcher loop
                       thread — the pool's owner), pulls remote prefixes
                       before admission, verifies, and accounts every
                       outcome. ANY failure — peer gone, payload
                       truncated, signature mismatch, local pool
                       pressure — degrades to local recompute and bumps
                       `kv_transfer_fallbacks_total`; a transfer can cost
                       latency, never correctness.
  prefix hints         `prefix_hint` hashes the prompt's leading
                       `serve_prefix_hint_tokens` tokens — the routing
                       currency shared by proxy, handle, controller and
                       replicas (see handle._pick_replica / the
                       controller's prefix digest).
  KVGenerationServer   a deployment-ready paged generation server with
                       the whole plane wired in, and the building block
                       of `deploy_disaggregated`: prefill-tagged replicas
                       run chunked prefill to completion and hand the
                       committed blocks to decode replicas over the
                       transfer path; decode resumes token-for-token
                       identically (greedy parity vs a monolithic
                       replica — the tail past the last FULL block is
                       recomputed locally, so the first sampled token is
                       derived from the same hidden state either way).

Flag matrix: `serve_kv_transfer` (the transfer path itself),
`serve_prefix_affinity` (hint-based routing), `serve_disaggregate`
(deploy_disaggregated's default) — see serve/README.md for the fallback
matrix.
"""

from __future__ import annotations

import hashlib
import threading
import zlib
from collections import OrderedDict
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

try:  # np.dtype("bfloat16") resolves only once ml_dtypes registered it
    import ml_dtypes  # noqa: F401
except ImportError:  # pragma: no cover - ml_dtypes ships with jax
    pass


class KVTransferError(RuntimeError):
    """A transfer payload failed the wire-integrity check (short read,
    truncation, corruption). Callers fall back to local recompute."""


# ----------------------------------------------------------- prefix hints


def prefix_hint(tokens, hint_tokens: Optional[int] = None) -> str:
    """Stable short hash over the prompt's leading tokens — the routing
    currency of prefix affinity. Proxy, handle and replicas must agree on
    the window, so it comes from config (`serve_prefix_hint_tokens`), not
    engine geometry; prompts shorter than the window hash what they have
    (their hint simply never matches a longer prompt's)."""
    from ray_tpu._private.config import GLOBAL_CONFIG as cfg

    n = int(cfg.serve_prefix_hint_tokens if hint_tokens is None
            else hint_tokens)
    arr = np.asarray(tokens, np.int32)
    if arr.ndim != 1 or n <= 0:
        return ""
    take = min(int(arr.size), n)
    if take <= 0:
        return ""
    h = hashlib.sha1(b"ray_tpu.prefix_hint.v1")
    h.update(np.ascontiguousarray(arr[:take], np.int32).tobytes())
    return h.hexdigest()[:16]


def request_hint(args, kwargs) -> str:
    """Best-effort prefix hint for a handle/proxy call: looks for a token
    sequence under the conventional request keys (`tokens`, or an int
    `prompt` list in an OpenAI-shaped body). Returns "" when the call
    shape is not a generation request — routing then falls through to
    plain power-of-two-choices."""
    candidates: List[Any] = []
    if isinstance(kwargs, dict):
        candidates.append(kwargs)
    for a in args or ():
        if isinstance(a, dict):
            candidates.append(a)
    for body in candidates:
        for key in ("tokens", "prompt"):
            toks = body.get(key)
            if (isinstance(toks, (list, tuple)) and toks
                    and all(isinstance(t, (int, np.integer)) for t in toks)):
                try:
                    return prefix_hint(toks)
                except Exception:
                    return ""
    return ""


# ------------------------------------------------------- payload plumbing


def pack_payload(payload: Dict[str, Any]) -> Tuple[Dict[str, Any], np.ndarray]:
    """Flatten an engine export into (meta, one contiguous uint8 buffer).
    The buffer is what rides the bulk plane; meta is a small dict carried
    in the actor reply (sig, chain keys, token span, leaf layout, length
    + CRC for wire integrity)."""
    parts: List[np.ndarray] = []
    leaves: List[Dict[str, Any]] = []
    off = 0
    for name in sorted(payload["blocks"]):
        arr = np.ascontiguousarray(payload["blocks"][name])
        raw = arr.view(np.uint8).reshape(-1)
        leaves.append({
            "name": name,
            "dtype": str(arr.dtype),
            "shape": tuple(int(d) for d in arr.shape),
            "offset": off,
            "nbytes": int(raw.size),
        })
        parts.append(raw)
        off += int(raw.size)
    buf = np.concatenate(parts) if parts else np.zeros(0, np.uint8)
    meta = {
        "sig": payload["sig"],
        "keys": list(payload["keys"]),
        "tokens": np.ascontiguousarray(payload["tokens"], np.int32),
        "block_tokens": int(payload["block_tokens"]),
        "kv_cache_dtype": payload["kv_cache_dtype"],
        "leaves": leaves,
        "total_bytes": int(buf.size),
        "crc": zlib.crc32(buf),
    }
    return meta, buf


def unpack_payload(meta: Dict[str, Any], buf) -> Dict[str, Any]:
    """Rebuild the engine-import payload from (meta, buffer). Raises
    KVTransferError when the buffer does not match meta's length/CRC —
    a transfer that died or was corrupted mid-flight must be detected
    HERE, before any byte could reach a pool."""
    if isinstance(buf, (bytes, bytearray, memoryview)):
        buf = np.frombuffer(buf, np.uint8)
    buf = np.asarray(buf)
    if buf.dtype != np.uint8:
        buf = buf.view(np.uint8)
    buf = buf.reshape(-1)
    if int(buf.size) != int(meta.get("total_bytes", -1)):
        raise KVTransferError(
            f"KV transfer payload length mismatch: got {buf.size} bytes, "
            f"expected {meta.get('total_bytes')}"
        )
    if zlib.crc32(np.ascontiguousarray(buf)) != meta.get("crc"):
        raise KVTransferError("KV transfer payload failed its CRC check")
    blocks: Dict[str, np.ndarray] = {}
    for leaf in meta["leaves"]:
        raw = buf[leaf["offset"]:leaf["offset"] + leaf["nbytes"]]
        blocks[leaf["name"]] = np.ascontiguousarray(raw).view(
            np.dtype(leaf["dtype"])
        ).reshape(leaf["shape"])
    return {
        "sig": meta["sig"],
        "keys": list(meta["keys"]),
        "tokens": np.asarray(meta["tokens"], np.int32),
        "block_tokens": int(meta["block_tokens"]),
        "kv_cache_dtype": meta["kv_cache_dtype"],
        "blocks": blocks,
    }


# ------------------------------------------------------- transfer manager


class KVTransferManager:
    """Per-replica glue between the engine's export/import primitives and
    the fleet: serves peers' export requests, pulls remote prefixes
    before admission, advertises this replica's cached chains (the prefix
    digest affinity routing feeds on), and accounts every byte/outcome.

    Replica.stats discovers instances by the `_serve_kv_transfer` marker
    (the same duck-typed scan as `_serve_drainable`)."""

    _serve_kv_transfer = True

    def __init__(self, batcher, engine=None, *, enabled: Optional[bool] = None,
                 deployment: str = "", digest_size: Optional[int] = None,
                 telemetry=None):
        from ray_tpu._private.config import GLOBAL_CONFIG as cfg
        from ray_tpu.util import metrics as _metrics

        from .telemetry import resolve as _tel_resolve

        self.batcher = batcher
        self.engine = engine if engine is not None else batcher.engine
        self.enabled = bool(
            cfg.serve_kv_transfer if enabled is None else enabled
        )
        self.deployment = deployment
        self.min_blocks = max(1, int(cfg.serve_kv_transfer_min_blocks))
        self._tel = _tel_resolve(telemetry)
        self._fallbacks = _metrics.kv_transfer_fallbacks_counter()
        self._lock = threading.Lock()
        # hint -> cached chain depth (full blocks); bounded LRU — the
        # slice of this replica's PrefixCache the controller aggregates
        self._digest: "OrderedDict[str, int]" = OrderedDict()
        self._digest_size = int(
            cfg.serve_prefix_digest_size if digest_size is None
            else digest_size
        )
        self.pulls = 0          # remote pulls attempted
        self.pull_hits = 0      # pulls that yielded a verified payload
        self.fallbacks = 0      # pulls abandoned for local recompute
        self.exports_served = 0
        self.bytes_in = 0
        self.bytes_out = 0

    # -- export side (peer-facing; runs on replica request threads) ------

    def export_serve(self, tokens) -> Optional[Tuple[Dict[str, Any], Any]]:
        """Serve a peer's export request: (meta, bulk-plane ref to the
        packed buffer), or None on a local cache miss. The engine read
        runs on the batcher loop thread — the pool's single owner — so
        the chain match and the block gather see one consistent pool."""
        if not self.enabled:
            return None
        import ray_tpu
        from ray_tpu._private import faults

        toks = np.asarray(tokens, np.int32)
        payload = self.batcher.run_on_loop(
            lambda: self.engine.export_prefix(toks)
        )
        if payload is None:
            return None
        meta, buf = pack_payload(payload)
        if faults.ACTIVE and faults.kv_transfer_action() == "drop":
            # chaos: the transfer dies mid-flight — ship a truncated
            # buffer so the importer's length/CRC check fires (the
            # fallback path the chaos suite pins)
            buf = np.ascontiguousarray(buf[:max(1, buf.size // 2)])
        ref = ray_tpu.put(buf)
        self.exports_served += 1
        self.bytes_out += int(buf.size)
        if self._tel is not None:
            self._tel.kv_transfer_bytes.inc(
                int(buf.size), tags={"direction": "export"})
        return meta, ref

    # -- import side (before admission) ----------------------------------

    def try_import(self, tokens, peers=()) -> Optional[Dict[str, Any]]:
        """Pull this prompt's prefix from a peer replica. Returns a
        verified engine payload to ride the request (`kv_import=...`), or
        None — already cached locally, no peer has it, or the transfer
        failed (fallback counted). Peers are actor handles tried in
        order; the first verified payload wins."""
        if not self.enabled or not peers:
            return None
        arr = np.asarray(tokens, np.int32)
        bt = self.engine.block_tokens
        # same cap as admission's lookup: at least one real token must
        # remain to prefill, so a full final block is never worth pulling
        want = (int(arr.size) - 1) // bt
        if want < self.min_blocks:
            return None
        cache = self.engine.prefix_cache
        if cache is None:
            return None
        # match_blocks off-thread: dict lookups against the trie (no LRU
        # touch, no iteration) — same read-safety class as stats()
        if len(cache.match_blocks(arr, want)) >= want:
            return None  # the whole span is already local
        self.pulls += 1
        payload = self._pull(arr, peers)
        if payload is None:
            self._note_fallback()
            return None
        self.pull_hits += 1
        if self._tel is not None:
            self._tel.kv_transfer_hits.inc()
            self._update_hit_rate()
        return payload

    def _pull(self, arr: np.ndarray, peers) -> Optional[Dict[str, Any]]:
        import ray_tpu

        toks_list = [int(t) for t in arr]
        for peer in peers:
            try:
                res = ray_tpu.get(
                    peer.handle_request.remote("kv_export", (toks_list,), {}),
                    timeout=30,
                )
                if res is None:
                    continue
                meta, ref = res
                buf = ray_tpu.get(ref, timeout=30)
                payload = unpack_payload(meta, buf)
                # the peer must have answered for OUR prompt: its token
                # span has to be a prefix of ours, or the payload would
                # pollute the local cache with an unrelated chain
                span = payload["tokens"]
                if (span.size > arr.size
                        or not np.array_equal(span, arr[:span.size])):
                    continue
                self.bytes_in += int(np.asarray(buf).size)
                if self._tel is not None:
                    self._tel.kv_transfer_bytes.inc(
                        int(np.asarray(buf).size),
                        tags={"direction": "import"})
                return payload
            except Exception:
                continue
        return None

    def _note_fallback(self) -> None:
        self.fallbacks += 1
        self._fallbacks.inc()
        self._update_hit_rate()

    def _update_hit_rate(self) -> None:
        if self._tel is not None:
            self._tel.prefix_remote_hit_rate.set(
                self.pull_hits / max(1, self.pulls))

    # -- digest (affinity advertisement) ---------------------------------

    def note_prompt(self, tokens) -> None:
        """Advertise this replica's cached chain depth for the prompt's
        hint. Called after a generation completes (the chain is
        registered by then); the controller harvests digest() from
        Replica.stats and publishes the per-deployment aggregate."""
        cache = self.engine.prefix_cache
        if cache is None:
            return
        hint = prefix_hint(tokens)
        if not hint:
            return
        arr = np.asarray(tokens, np.int32)
        depth = len(cache.match_blocks(
            arr, int(arr.size) // self.engine.block_tokens))
        with self._lock:
            self._digest[hint] = depth
            self._digest.move_to_end(hint)
            while len(self._digest) > self._digest_size:
                self._digest.popitem(last=False)

    def digest(self) -> Dict[str, int]:
        with self._lock:
            return dict(self._digest)

    # -- observability ----------------------------------------------------

    def stats(self) -> Dict[str, Any]:
        return {
            "kv_transfer_enabled": self.enabled,
            "kv_transfer_pulls": self.pulls,
            "kv_transfer_hits": self.pull_hits,
            "kv_transfer_fallbacks": self.fallbacks,
            "kv_transfer_exports_served": self.exports_served,
            "kv_transfer_bytes_in": self.bytes_in,
            "kv_transfer_bytes_out": self.bytes_out,
            "prefix_remote_hit_rate": round(
                self.pull_hits / max(1, self.pulls), 4),
        }


# --------------------------------------------------- generation deployment


class KVGenerationServer:
    """Deployment-ready paged generation server with the cluster-wide KV
    plane wired in. Builds a PagedDecodeEngine (weights re-derived from
    `weights_seed`, so every replica holds identical parameters) + a
    ContinuousBatcher + a KVTransferManager, and exposes:

      generate(tokens, max_new_tokens)  greedy generation; pulls the
          prompt's prefix from a peer (monolithic role) or from the
          prefill pool (decode role) before admission — any transfer
          failure falls back to local prefill
      kv_export(tokens)                 the peer-facing export endpoint
      prefill(tokens)                   prefill role: run chunked prefill
          to completion (one sampled token) and export the committed
          chain for a decode replica
      engine_stats()                    the batcher/engine stats dict

    Roles: "monolithic" (default — peer pulls within one deployment),
    "prefill" / "decode" (the two pools of deploy_disaggregated)."""

    def __init__(self, cfg, *, weights_seed: int = 0,
                 engine_kwargs: Optional[Dict[str, Any]] = None,
                 deployment: str = "", role: str = "monolithic",
                 prefill=None, transfer: Optional[bool] = None):
        import jax

        from ray_tpu.models.kv_paging import PagedDecodeEngine
        from ray_tpu.models.transformer import init_params

        from .batching import ContinuousBatcher

        if role not in ("monolithic", "prefill", "decode"):
            raise ValueError(f"unknown role {role!r}")
        self.role = role
        self.deployment = deployment
        params = init_params(jax.random.PRNGKey(int(weights_seed)), cfg)
        kw = dict(engine_kwargs or {})
        self.engine = PagedDecodeEngine(cfg, params, **kw)
        self.batcher = ContinuousBatcher(self.engine)
        self.kv = KVTransferManager(
            self.batcher, deployment=deployment, enabled=transfer
        )
        self._prefill_handle = prefill

    # -- peer discovery ---------------------------------------------------

    def _peers(self) -> List[Any]:
        """Sibling replica actor handles, self excluded. Empty outside a
        serve deployment (bare construction in tests/benches)."""
        if not self.deployment:
            return []
        try:
            import ray_tpu

            from .handle import CONTROLLER_NAME

            me = ray_tpu.get_runtime_context().get_actor_id()
            ctl = ray_tpu.get_actor(CONTROLLER_NAME)
            reps = ray_tpu.get(
                ctl.get_replicas.remote(self.deployment), timeout=5
            )
            return [r for r in reps
                    if getattr(r, "_actor_id", None) != me]
        except Exception:
            return []

    # -- serving surface --------------------------------------------------

    def kv_export(self, tokens):
        return self.kv.export_serve(tokens)

    def prefill(self, tokens):
        """Prefill-pool endpoint: run the prompt's prefill to completion
        (chunked per the engine's prefill_chunk_tokens; exactly one
        sampled token, discarded) and export the committed chain. Returns
        (meta, bulk-plane ref) or None when nothing exportable."""
        toks = [int(t) for t in tokens]
        stream = self.batcher.submit(tokens=toks, max_new_tokens=1)
        for _ in stream:
            pass
        self.kv.note_prompt(toks)
        return self.kv.export_serve(toks)

    def _pull_from_prefill(self, toks: List[int]) -> Optional[Dict[str, Any]]:
        """Decode-pool import: the prefill handle runs the prefill and
        hands back the committed blocks over the transfer path."""
        import ray_tpu

        self.kv.pulls += 1
        try:
            res = self._prefill_handle.prefill.remote(toks).result(
                timeout_s=120
            )
            if res is None:
                raise KVTransferError("prefill pool exported nothing")
            meta, ref = res
            buf = ray_tpu.get(ref, timeout=30)
            payload = unpack_payload(meta, buf)
            span = payload["tokens"]
            arr = np.asarray(toks, np.int32)
            if (span.size > arr.size
                    or not np.array_equal(span, arr[:span.size])):
                raise KVTransferError("prefill pool answered for another prompt")
        except Exception:
            self.kv._note_fallback()
            return None
        self.kv.pull_hits += 1
        self.kv.bytes_in += int(np.asarray(buf).size)
        if self.kv._tel is not None:
            self.kv._tel.kv_transfer_hits.inc()
            self.kv._tel.kv_transfer_bytes.inc(
                int(np.asarray(buf).size), tags={"direction": "import"})
            self.kv._update_hit_rate()
        return payload

    def generate(self, tokens, max_new_tokens: int = 16) -> Dict[str, Any]:
        toks = [int(t) for t in tokens]
        payload = None
        if self.role == "decode" and self._prefill_handle is not None:
            payload = self._pull_from_prefill(toks)
        elif self.role != "prefill" and self.kv.enabled:
            payload = self.kv.try_import(toks, self._peers())
        kw: Dict[str, Any] = {}
        if payload is not None:
            kw["kv_import"] = payload
        stream = self.batcher.submit(
            tokens=toks, max_new_tokens=int(max_new_tokens), **kw
        )
        out = [int(t) for t in stream]
        self.kv.note_prompt(toks)
        return {"tokens": out}

    def __call__(self, body) -> Dict[str, Any]:
        req = body if isinstance(body, dict) else {}
        return self.generate(
            req.get("tokens") or (), int(req.get("max_new_tokens") or 16)
        )

    def engine_stats(self) -> Dict[str, Any]:
        return self.batcher.stats()

    def transfer_stats(self) -> Dict[str, Any]:
        return self.kv.stats()


# ------------------------------------------------ disaggregated deployment


def deploy_generation(
    name: str,
    cfg,
    *,
    num_replicas: int = 1,
    disaggregate: Optional[bool] = None,
    weights_seed: int = 0,
    engine_kwargs: Optional[Dict[str, Any]] = None,
    route_prefix: Optional[str] = None,
    **disagg_kwargs,
):
    """Deploy a KVGenerationServer fleet. Topology comes from
    `disaggregate` (default: the `serve_disaggregate` flag): off — one
    monolithic pool of `num_replicas` peers sharing prefixes over the
    transfer path; on — deploy_disaggregated's prefill/decode split with
    `num_replicas` decode replicas. Returns the serving handle."""
    from ray_tpu._private.config import GLOBAL_CONFIG as gcfg

    if disaggregate is None:
        disaggregate = bool(gcfg.serve_disaggregate)
    if disaggregate:
        return deploy_disaggregated(
            name, cfg, weights_seed=weights_seed,
            engine_kwargs=engine_kwargs, decode_replicas=num_replicas,
            route_prefix=route_prefix, **disagg_kwargs,
        )
    from ray_tpu.serve import deployment as serve_deployment
    from ray_tpu.serve import run as serve_run

    Dep = serve_deployment(
        name=name, num_replicas=int(num_replicas)
    )(KVGenerationServer)
    app = Dep.bind(
        cfg, weights_seed=weights_seed,
        engine_kwargs=dict(engine_kwargs or {}), deployment=name,
    )
    # route_prefix=None -> handle-only (no HTTP proxy spun up)
    return serve_run(app, name=name, route_prefix=route_prefix)


def deploy_disaggregated(
    name: str,
    cfg,
    *,
    weights_seed: int = 0,
    engine_kwargs: Optional[Dict[str, Any]] = None,
    prefill_replicas: int = 1,
    decode_replicas: int = 1,
    prefill_autoscaling=None,
    decode_autoscaling=None,
    autoscale: Optional[bool] = None,
    route_prefix: Optional[str] = None,
):
    """Deploy the disaggregated prefill/decode topology: a prefill pool
    (`<name>-prefill`) running chunked prefill to completion and a decode
    pool (`<name>`, the ingress) resuming each stream from the handed-off
    blocks — token-for-token identical to a monolithic replica (greedy).

    With `autoscale` (default: the `serve_disaggregate` flag being on
    does NOT autoscale by itself — pass autoscale=True or explicit
    configs), the two pools scale on the EXISTING autoscaling signals,
    each on the one that binds it: block saturation for prefill (long
    prompts exhaust the pool first) and batch occupancy for decode
    (slots saturate first). Returns the decode pool's handle."""
    # serve.deployment here means the decorator in serve/__init__ (which
    # wins the name over the .deployment submodule), not the submodule
    from ray_tpu.serve import deployment as serve_deployment
    from ray_tpu.serve import run as serve_run

    from .deployment import AutoscalingConfig

    if autoscale:
        if prefill_autoscaling is None:
            prefill_autoscaling = AutoscalingConfig(
                min_replicas=1,
                max_replicas=max(1, int(prefill_replicas)),
                target_kv_utilization=0.85,
            )
        if decode_autoscaling is None:
            decode_autoscaling = AutoscalingConfig(
                min_replicas=1,
                max_replicas=max(1, int(decode_replicas)),
                target_batch_occupancy=0.8,
            )
    prefill_name = f"{name}-prefill"
    ek = dict(engine_kwargs or {})
    Prefill = serve_deployment(
        name=prefill_name,
        num_replicas=1 if prefill_autoscaling else int(prefill_replicas),
        autoscaling_config=prefill_autoscaling,
    )(KVGenerationServer)
    Decode = serve_deployment(
        name=name,
        num_replicas=1 if decode_autoscaling else int(decode_replicas),
        autoscaling_config=decode_autoscaling,
    )(KVGenerationServer)
    prefill_app = Prefill.bind(
        cfg, weights_seed=weights_seed, engine_kwargs=ek,
        deployment=prefill_name, role="prefill",
    )
    decode_app = Decode.bind(
        cfg, weights_seed=weights_seed, engine_kwargs=ek,
        deployment=name, role="decode", prefill=prefill_app,
    )
    # route_prefix=None -> handle-only (no HTTP proxy spun up)
    return serve_run(decode_app, name=name, route_prefix=route_prefix)
