"""HTTP ingress: asyncio HTTP/1.1 server over longest-prefix routes ->
ingress DeploymentHandles.

Reference parity: serve/_private/http_proxy.py:320 (HTTPProxy /
HTTPProxyActor, uvicorn+starlette). Rebuilt on an asyncio server (VERDICT
r2 item 8 — the previous stdlib ThreadingHTTPServer held one THREAD per
in-flight request, so 100 slow streaming consumers pinned 100 threads):
  - persistent connections (HTTP/1.1 keep-alive): one coroutine per
    connection loops over requests
  - replica calls run on a BOUNDED thread pool (they block on the handle),
    but response STREAMING happens on the event loop with backpressure
    (`await writer.drain()`) — slow clients hold a coroutine, not a thread
  - longest-prefix route match (an app at "/app" serves "/app/anything");
    the matched remainder + query string ride along for handlers that want
    them (pass_request=True deployments receive a Request object)
  - JSON bodies parse to Python values; other content types pass through as
    raw bytes
  - responses: bytes -> application/octet-stream, str -> text/plain,
    StreamingResponse -> chunked transfer, anything else -> {"result": ...}
    JSON (the v1 wire shape, kept stable)
  - per-proxy configurable request timeout -> 504 on expiry
"""

from __future__ import annotations

import asyncio
import json
import threading
from concurrent.futures import ThreadPoolExecutor
from dataclasses import dataclass
from typing import Any, Dict, Iterable, Optional
from urllib.parse import parse_qs, urlsplit

_MAX_HEADER_BYTES = 64 * 1024
# Replica-call threads; streaming holds none. KNOWN LIMIT: the pool bounds
# concurrent REPLICA CALLS, so >pool-size slow calls queue (and their
# wait_for clocks include queue time) — overload degrades to 504s, which is
# deliberate backpressure where the old thread-per-request server grew
# unboundedly instead.
_CALL_POOL_SIZE = 16


@dataclass
class Request:
    """What a deployment sees when it asks for the raw request."""

    method: str
    path: str            # full request path
    route: str           # matched route prefix
    subpath: str         # path remainder after the route
    query: Dict[str, Any]
    headers: Dict[str, str]
    body: Any            # parsed JSON or raw bytes


@dataclass
class StreamingResponse:
    """Chunked-transfer response: iterable of str/bytes chunks.

    The iterable is materialized at construction (generators included) so
    the response pickles across the replica->proxy actor boundary — actor
    results are single messages; the streaming happens proxy->client."""

    chunks: Iterable[Any]
    content_type: str = "text/plain; charset=utf-8"

    def __post_init__(self):
        self.chunks = list(self.chunks)


@dataclass
class Response:
    """Explicit-status response from a handler (ingress handlers use it for
    201/4xx etc.). body follows the normal result contract: str -> text,
    bytes -> octet-stream, anything else -> JSON."""

    status: int
    body: Any = None
    content_type: Optional[str] = None


@dataclass
class _Route:
    prefix: str
    handle: Any
    pass_request: bool = False


def _parse_body(raw: bytes, ctype: str):
    ctype = (ctype or "").split(";")[0].strip()
    if not raw:
        return None
    if ctype in ("application/json", "", "text/json"):
        try:
            return json.loads(raw)
        except json.JSONDecodeError:
            pass
    if ctype.startswith("text/"):
        return raw.decode(errors="replace")
    return raw  # binary passthrough


class HTTPProxyActor:
    def __init__(
        self,
        host: str = "127.0.0.1",
        port: int = 8000,
        request_timeout_s: float = 60.0,
    ):
        self.host = host
        self.port = port
        self.request_timeout_s = request_timeout_s
        self.routes: Dict[str, _Route] = {}
        # replica calls block a pool thread; the loop never blocks
        self._pool = ThreadPoolExecutor(
            max_workers=_CALL_POOL_SIZE, thread_name_prefix="ingress-call"
        )
        self._loop = asyncio.new_event_loop()
        started = threading.Event()

        def _run():
            asyncio.set_event_loop(self._loop)
            self._server = self._loop.run_until_complete(
                asyncio.start_server(self._on_client, host=host, port=port)
            )
            self.port = self._server.sockets[0].getsockname()[1]
            started.set()
            self._loop.run_forever()

        self._thread = threading.Thread(target=_run, daemon=True)
        self._thread.start()
        if not started.wait(10):
            raise RuntimeError("ingress server failed to start")

    # ---------------------------------------------------------- http plane

    def _match(self, path: str) -> Optional[_Route]:
        """Longest-prefix routing (reference: route_prefix semantics)."""
        best = None
        for prefix, route in self.routes.items():
            if path == prefix or path.startswith(
                prefix if prefix.endswith("/") else prefix + "/"
            ) or prefix == "/":
                if best is None or len(prefix) > len(best.prefix):
                    best = route
        return best

    async def _on_client(self, reader: asyncio.StreamReader,
                         writer: asyncio.StreamWriter):
        """One coroutine per connection; loops over keep-alive requests."""
        try:
            while True:
                try:
                    head = await reader.readuntil(b"\r\n\r\n")
                except (asyncio.IncompleteReadError, ConnectionError):
                    return
                except asyncio.LimitOverrunError:
                    await self._reply(writer, 431, "application/json",
                                      b'{"error": "headers too large"}')
                    return
                if len(head) > _MAX_HEADER_BYTES:
                    await self._reply(writer, 431, "application/json",
                                      b'{"error": "headers too large"}')
                    return
                lines = head.decode("latin1").split("\r\n")
                try:
                    method, target, version = lines[0].split(" ", 2)
                except ValueError:
                    await self._reply(writer, 400, "application/json",
                                      b'{"error": "bad request line"}')
                    return
                headers = {}
                for ln in lines[1:]:
                    if not ln:
                        continue
                    k, _, v = ln.partition(":")
                    headers[k.strip().lower()] = v.strip()
                if "chunked" in headers.get("transfer-encoding", "").lower():
                    await self._reply(writer, 411, "application/json",
                                      b'{"error": "chunked request bodies '
                                      b'not supported; send Content-Length"}')
                    return
                n = int(headers.get("content-length", 0) or 0)
                raw = await reader.readexactly(n) if n else b""
                keep_alive = (
                    headers.get("connection", "").lower() != "close"
                    and version.upper() != "HTTP/1.0"
                )
                await self._dispatch(writer, method, target, headers, raw)
                if not keep_alive:
                    return
        except (ConnectionError, asyncio.CancelledError):
            pass
        finally:
            try:
                writer.close()
            except Exception:
                pass

    async def _reply(self, writer, status: int, ctype: str, payload: bytes):
        reason = {200: "OK", 400: "Bad Request", 404: "Not Found",
                  411: "Length Required", 431: "Headers Too Large",
                  500: "Internal Server Error",
                  504: "Gateway Timeout"}.get(status, "")
        writer.write(
            f"HTTP/1.1 {status} {reason}\r\n"
            f"Content-Type: {ctype}\r\n"
            f"Content-Length: {len(payload)}\r\n\r\n".encode("latin1")
        )
        writer.write(payload)
        await writer.drain()

    async def _reply_chunked(self, writer, resp: StreamingResponse):
        writer.write(
            f"HTTP/1.1 200 OK\r\nContent-Type: {resp.content_type}\r\n"
            "Transfer-Encoding: chunked\r\n\r\n".encode("latin1")
        )
        for chunk in resp.chunks:
            data = chunk.encode() if isinstance(chunk, str) else bytes(chunk)
            if not data:
                continue
            writer.write(f"{len(data):x}\r\n".encode() + data + b"\r\n")
            # backpressure: a slow client parks THIS coroutine only
            await writer.drain()
        writer.write(b"0\r\n\r\n")
        await writer.drain()

    def _call_route(self, route: _Route, args: tuple):
        """Blocking replica call; runs on the bounded pool."""
        return route.handle.remote(*args).result(
            timeout_s=self.request_timeout_s
        )

    async def _dispatch(self, writer, method: str, target: str,
                        headers: Dict[str, str], raw: bytes):
        parts = urlsplit(target)
        path = parts.path.rstrip("/") or "/"
        route = self._match(path)
        if route is None:
            await self._reply(writer, 404, "application/json",
                              b'{"error": "no app at this route"}')
            return
        body = _parse_body(raw, headers.get("content-type", "")) if method not in (
            "GET", "DELETE") else None
        if route.pass_request:
            arg = Request(
                method=method,
                path=parts.path,
                route=route.prefix,
                subpath=path[len(route.prefix):].lstrip("/"),
                query={k: v[0] if len(v) == 1 else v
                       for k, v in parse_qs(parts.query).items()},
                headers=headers,
                body=body,
            )
            args = (arg,)
        else:
            args = () if body is None else (body,)
        try:
            result = await asyncio.wait_for(
                self._loop.run_in_executor(self._pool, self._call_route,
                                           route, args),
                timeout=self.request_timeout_s + 5.0,
            )
        except asyncio.TimeoutError:
            await self._reply(writer, 504, "application/json",
                              b'{"error": "request timed out"}')
            return
        except Exception as e:  # noqa: BLE001
            await self._reply(writer, 500, "application/json",
                              json.dumps({"error": repr(e)}).encode())
            return
        status = 200
        bare = isinstance(result, Response)  # Response bodies serialize bare
        ctype_override = None
        if bare:
            status = result.status
            ctype_override = result.content_type
            result = result.body
        try:
            if ctype_override is not None:
                data = (
                    result.encode() if isinstance(result, str)
                    else bytes(result) if isinstance(result, (bytes, bytearray, memoryview))
                    else json.dumps(result).encode()
                )
                await self._reply(writer, status, ctype_override, data)
                return
            if isinstance(result, StreamingResponse):
                await self._reply_chunked(writer, result)
                return
            if isinstance(result, (bytes, bytearray, memoryview)):
                await self._reply(writer, status, "application/octet-stream",
                                  bytes(result))
                return
            if isinstance(result, str):
                await self._reply(writer, status, "text/plain; charset=utf-8",
                                  result.encode())
                return
            # Response bodies serialize bare; plain results keep the stable
            # v1 {"result": ...} wire shape
            payload = json.dumps(result if bare else {"result": result}).encode()
        except ConnectionError:
            raise
        except Exception as e:  # a non-JSON-able result must 500, not drop
            await self._reply(writer, 500, "application/json",
                              json.dumps({"error": repr(e)}).encode())
            return
        await self._reply(writer, status, "application/json", payload)

    # ---------------------------------------------------------- actor API

    def ready(self):
        host = self.host
        if host in ("0.0.0.0", ""):
            # advertise a ROUTABLE address, not the wildcard bind (fleet
            # proxies feed proxy_addresses() -> load balancers off-box)
            from .._private.head import _advertise_host

            host = _advertise_host(host)
        return {"host": host, "port": self.port}

    def set_route(
        self, route_prefix: str, deployment_name: str, pass_request: bool = False
    ):
        from .handle import DeploymentHandle

        prefix = route_prefix.rstrip("/") or "/"
        self.routes[prefix] = _Route(
            prefix=prefix,
            handle=DeploymentHandle(deployment_name),
            pass_request=pass_request,
        )
        return True

    def remove_route(self, route_prefix: str):
        self.routes.pop(route_prefix.rstrip("/") or "/", None)
        return True

    def set_request_timeout(self, timeout_s: float):
        self.request_timeout_s = float(timeout_s)
        return True

    def stop(self):
        def _stop():
            try:
                self._server.close()
            except Exception:
                pass
            self._loop.stop()

        try:
            self._loop.call_soon_threadsafe(_stop)
        except RuntimeError:
            pass
        return True
