"""Minimal HTTP ingress: JSON POST/GET -> ingress DeploymentHandle.

Reference parity: serve/_private/http_proxy.py:320 (HTTPProxy / HTTPProxyActor).
The reference rides uvicorn+starlette; here a stdlib ThreadingHTTPServer is
enough — TPU model serving is throughput-bound on the replicas, not the
ingress parser.
"""

from __future__ import annotations

import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Dict


class HTTPProxyActor:
    def __init__(self, host: str = "127.0.0.1", port: int = 8000):
        self.host = host
        self.port = port
        self.routes: Dict[str, object] = {}  # route_prefix -> DeploymentHandle
        proxy = self

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, *a):  # quiet
                pass

            def _dispatch(self, body):
                route = self.path.rstrip("/") or "/"
                handle = proxy.routes.get(route)
                if handle is None:
                    self.send_response(404)
                    self.end_headers()
                    self.wfile.write(b'{"error": "no app at this route"}')
                    return
                try:
                    args = () if body is None else (body,)
                    result = handle.remote(*args).result(timeout_s=60)
                    payload = json.dumps({"result": result}).encode()
                    self.send_response(200)
                except Exception as e:  # noqa: BLE001
                    payload = json.dumps({"error": repr(e)}).encode()
                    self.send_response(500)
                self.send_header("Content-Type", "application/json")
                self.end_headers()
                self.wfile.write(payload)

            def do_GET(self):
                self._dispatch(None)

            def do_POST(self):
                n = int(self.headers.get("Content-Length", 0))
                raw = self.rfile.read(n) if n else b""
                try:
                    body = json.loads(raw) if raw else None
                except json.JSONDecodeError:
                    body = raw.decode()
                self._dispatch(body)

        self._server = ThreadingHTTPServer((host, port), Handler)
        self.port = self._server.server_address[1]
        self._thread = threading.Thread(target=self._server.serve_forever, daemon=True)
        self._thread.start()

    def ready(self):
        return {"host": self.host, "port": self.port}

    def set_route(self, route_prefix: str, deployment_name: str):
        from .handle import DeploymentHandle

        self.routes[route_prefix.rstrip("/") or "/"] = DeploymentHandle(deployment_name)
        return True

    def remove_route(self, route_prefix: str):
        self.routes.pop(route_prefix.rstrip("/") or "/", None)
        return True

    def stop(self):
        self._server.shutdown()
        return True
