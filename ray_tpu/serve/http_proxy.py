"""HTTP ingress: content-type-aware request/response handling over
longest-prefix routes -> ingress DeploymentHandles.

Reference parity: serve/_private/http_proxy.py:320 (HTTPProxy /
HTTPProxyActor, uvicorn+starlette). Rebuilt on a stdlib ThreadingHTTPServer
(one thread per in-flight request; TPU model serving is throughput-bound on
the replicas, not the ingress parser) with the reference's routing and body
semantics:
  - longest-prefix route match (an app at "/app" serves "/app/anything");
    the matched remainder + query string ride along for handlers that want
    them (pass_request=True deployments receive a Request object)
  - JSON bodies parse to Python values; other content types pass through as
    raw bytes
  - responses: bytes -> application/octet-stream, str -> text/plain,
    StreamingResponse -> chunked transfer, anything else -> {"result": ...}
    JSON (the v1 wire shape, kept stable)
  - per-proxy configurable request timeout (was a fixed 60s)
"""

from __future__ import annotations

import json
import threading
from dataclasses import dataclass, field
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any, Dict, Iterable, Optional
from urllib.parse import parse_qs, urlsplit


@dataclass
class Request:
    """What a deployment sees when it asks for the raw request."""

    method: str
    path: str            # full request path
    route: str           # matched route prefix
    subpath: str         # path remainder after the route
    query: Dict[str, Any]
    headers: Dict[str, str]
    body: Any            # parsed JSON or raw bytes


@dataclass
class StreamingResponse:
    """Chunked-transfer response: iterable of str/bytes chunks.

    The iterable is materialized at construction (generators included) so
    the response pickles across the replica->proxy actor boundary — actor
    results are single messages; the streaming happens proxy->client."""

    chunks: Iterable[Any]
    content_type: str = "text/plain; charset=utf-8"

    def __post_init__(self):
        self.chunks = list(self.chunks)


@dataclass
class _Route:
    prefix: str
    handle: Any
    pass_request: bool = False


class HTTPProxyActor:
    def __init__(
        self,
        host: str = "127.0.0.1",
        port: int = 8000,
        request_timeout_s: float = 60.0,
    ):
        self.host = host
        self.port = port
        self.request_timeout_s = request_timeout_s
        self.routes: Dict[str, _Route] = {}
        proxy = self

        class Handler(BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.1"

            def log_message(self, *a):  # quiet
                pass

            def _match(self, path: str) -> Optional[_Route]:
                """Longest-prefix routing (reference: route_prefix semantics)."""
                best = None
                for prefix, route in proxy.routes.items():
                    if path == prefix or path.startswith(
                        prefix if prefix.endswith("/") else prefix + "/"
                    ) or prefix == "/":
                        if best is None or len(prefix) > len(best.prefix):
                            best = route
                return best

            def _reply(self, status: int, ctype: str, payload: bytes):
                self.send_response(status)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(payload)))
                self.end_headers()
                self.wfile.write(payload)

            def _reply_chunked(self, resp: StreamingResponse):
                self.send_response(200)
                self.send_header("Content-Type", resp.content_type)
                self.send_header("Transfer-Encoding", "chunked")
                self.end_headers()
                for chunk in resp.chunks:
                    data = chunk.encode() if isinstance(chunk, str) else bytes(chunk)
                    if not data:
                        continue
                    self.wfile.write(f"{len(data):x}\r\n".encode() + data + b"\r\n")
                self.wfile.write(b"0\r\n\r\n")

            def _dispatch(self, body):
                parts = urlsplit(self.path)
                path = parts.path.rstrip("/") or "/"
                route = self._match(path)
                if route is None:
                    self._reply(404, "application/json",
                                b'{"error": "no app at this route"}')
                    return
                if route.pass_request:
                    arg = Request(
                        method=self.command,
                        path=parts.path,
                        route=route.prefix,
                        subpath=path[len(route.prefix):].lstrip("/"),
                        query={k: v[0] if len(v) == 1 else v
                               for k, v in parse_qs(parts.query).items()},
                        headers={k.lower(): v for k, v in self.headers.items()},
                        body=body,
                    )
                    args = (arg,)
                else:
                    args = () if body is None else (body,)
                try:
                    result = route.handle.remote(*args).result(
                        timeout_s=proxy.request_timeout_s
                    )
                    if isinstance(result, StreamingResponse):
                        self._reply_chunked(result)
                        return
                    if isinstance(result, (bytes, bytearray, memoryview)):
                        self._reply(200, "application/octet-stream", bytes(result))
                        return
                    if isinstance(result, str):
                        self._reply(200, "text/plain; charset=utf-8", result.encode())
                        return
                    # serialization stays inside the try: a non-JSON-able
                    # result must 500, not drop the connection
                    payload = json.dumps({"result": result}).encode()
                except Exception as e:  # noqa: BLE001
                    self._reply(500, "application/json",
                                json.dumps({"error": repr(e)}).encode())
                    return
                self._reply(200, "application/json", payload)

            def do_GET(self):
                self._dispatch(None)

            def do_DELETE(self):
                self._dispatch(None)

            def _read_body(self):
                n = int(self.headers.get("Content-Length", 0))
                raw = self.rfile.read(n) if n else b""
                ctype = (self.headers.get("Content-Type") or "").split(";")[0].strip()
                if not raw:
                    return None
                if ctype in ("application/json", "", "text/json"):
                    try:
                        return json.loads(raw)
                    except json.JSONDecodeError:
                        pass
                if ctype.startswith("text/"):
                    return raw.decode(errors="replace")
                return raw  # binary passthrough

            def do_POST(self):
                self._dispatch(self._read_body())

            def do_PUT(self):
                self._dispatch(self._read_body())

        self._server = ThreadingHTTPServer((host, port), Handler)
        self.port = self._server.server_address[1]
        self._thread = threading.Thread(target=self._server.serve_forever, daemon=True)
        self._thread.start()

    def ready(self):
        return {"host": self.host, "port": self.port}

    def set_route(
        self, route_prefix: str, deployment_name: str, pass_request: bool = False
    ):
        from .handle import DeploymentHandle

        prefix = route_prefix.rstrip("/") or "/"
        self.routes[prefix] = _Route(
            prefix=prefix,
            handle=DeploymentHandle(deployment_name),
            pass_request=pass_request,
        )
        return True

    def remove_route(self, route_prefix: str):
        self.routes.pop(route_prefix.rstrip("/") or "/", None)
        return True

    def set_request_timeout(self, timeout_s: float):
        self.request_timeout_s = float(timeout_s)
        return True

    def stop(self):
        self._server.shutdown()
        return True
