"""HTTP ingress: asyncio HTTP/1.1 server over longest-prefix routes ->
ingress DeploymentHandles.

Reference parity: serve/_private/http_proxy.py:320 (HTTPProxy /
HTTPProxyActor, uvicorn+starlette). Rebuilt on an asyncio server (VERDICT
r2 item 8 — the previous stdlib ThreadingHTTPServer held one THREAD per
in-flight request, so 100 slow streaming consumers pinned 100 threads):
  - persistent connections (HTTP/1.1 keep-alive): one coroutine per
    connection loops over requests, bounded by a connection cap (excess
    connections get 503 + Retry-After)
  - request-lifecycle deadlines (slow-loris defense): the request head must
    arrive within `keep_alive_timeout_s` (covers idle keep-alive waits AND
    header trickle), the body within `read_timeout_s`; expiry sends 408 and
    reaps the connection — well-behaved neighbors are untouched because a
    slow client only ever parks its own coroutine
  - hard size limits: head > max_header_bytes -> 431, body >
    max_body_bytes -> 413 (both content-length and chunked)
  - chunked request bodies are decoded (uvicorn parity); chunked responses
    unchanged
  - replica calls run on a BOUNDED thread pool (they block on the handle),
    with 503 + Retry-After backpressure once the queued-call cap is hit or
    the deployment is unavailable (draining, no replicas, circuit breaker
    open); response STREAMING happens on the event loop with backpressure
    (`await writer.drain()`)
  - longest-prefix route match (an app at "/app" serves "/app/anything");
    the matched remainder + query string ride along for handlers that want
    them (pass_request=True deployments receive a Request object)
  - JSON bodies parse to Python values; other content types pass through as
    raw bytes
  - responses: bytes -> application/octet-stream, str -> text/plain,
    StreamingResponse -> chunked transfer, anything else -> {"result": ...}
    JSON (the v1 wire shape, kept stable)
  - per-proxy configurable request timeout -> 504 on expiry

All limits/deadlines default from the `serve_http_*` config flags
(_private/config.py, RAY_TPU_* env-overridable) and can be set per proxy via
constructor kwargs or the set_limits() actor method.
"""

from __future__ import annotations

import asyncio
import json
import threading
from concurrent.futures import ThreadPoolExecutor
from dataclasses import dataclass
from typing import Any, Dict, Iterable, Optional
from urllib.parse import parse_qs, urlsplit

# Replica-call threads; streaming holds none. KNOWN LIMIT: the pool bounds
# concurrent REPLICA CALLS, so >pool-size slow calls queue (and their
# wait_for clocks include queue time) — overload degrades to 503s/504s,
# which is deliberate backpressure where the old thread-per-request server
# grew unboundedly instead.
_CALL_POOL_SIZE = 16

_REASONS = {
    200: "OK", 400: "Bad Request", 404: "Not Found",
    408: "Request Timeout", 411: "Length Required",
    413: "Payload Too Large", 431: "Request Header Fields Too Large",
    500: "Internal Server Error", 503: "Service Unavailable",
    504: "Gateway Timeout",
}


@dataclass
class Request:
    """What a deployment sees when it asks for the raw request."""

    method: str
    path: str            # full request path
    route: str           # matched route prefix
    subpath: str         # path remainder after the route
    query: Dict[str, Any]
    headers: Dict[str, str]
    body: Any            # parsed JSON or raw bytes


@dataclass
class StreamingResponse:
    """Chunked-transfer response: iterable of str/bytes chunks.

    buffered=True (default): the iterable is materialized at construction
    (generators included) so the response pickles across the replica->proxy
    actor boundary — actor results are single messages; the streaming
    happens proxy->client.

    buffered=False: the chunks are still being PRODUCED (e.g. a
    ContinuousBatcher generation). The replica registers the live stream
    and hands the proxy a ReplicaStreamHandle; the proxy pulls chunks with
    stream_next() and forwards each to the client as it arrives — true
    incremental delivery, one chunked frame per chunk."""

    chunks: Iterable[Any]
    content_type: str = "text/plain; charset=utf-8"
    buffered: bool = True

    def __post_init__(self):
        if self.buffered:
            self.chunks = list(self.chunks)


def _sse_encode(item) -> str:
    """Default SSE payload encoding: strings pass through, everything else
    is JSON — str() of a dict/list would emit python repr (single quotes),
    which standard SSE consumers (OpenAI clients included) cannot parse."""
    return item if isinstance(item, str) else json.dumps(item)


class _SSEStream:
    """Format a pull-style token stream (GenerationStream) as server-sent
    events while PRESERVING its long-poll next_batch surface, so replica
    stream_next pulls stay batched and timeout-bounded. The terminal event
    is `data: [DONE]` — preceded by `event: cut` when the generation was
    truncated at a drain deadline."""

    def __init__(self, inner, encode=_sse_encode):
        self._inner = inner
        self._encode = encode

    def next_batch(self, max_items: int, wait_s: float):
        items, done = self._inner.next_batch(max_items, wait_s)
        out = [f"data: {self._encode(i)}\n\n" for i in items]
        if done:
            if getattr(self._inner, "cut", False):
                out.append("event: cut\ndata: [DONE]\n\n")
            else:
                out.append("data: [DONE]\n\n")
        return out, done

    def cancel(self):
        cancel = getattr(self._inner, "cancel", None)
        if cancel is not None:
            cancel()


def sse_stream(stream, encode=_sse_encode) -> StreamingResponse:
    """Wrap a token stream as a non-buffered text/event-stream response:
    every token becomes its own SSE `data:` event delivered per-token over
    chunked transfer — `data: <payload>\\n\\n` frames ending with the
    `data: [DONE]\\n\\n` sentinel (the OpenAI wire shape; dict/list items
    are JSON-encoded by default). `stream` is ideally pull-style (has
    next_batch, e.g. ContinuousBatcher.submit()'s GenerationStream); plain
    iterables work but pull one chunk per stream_next round-trip."""
    if hasattr(stream, "next_batch"):
        chunks: Any = _SSEStream(stream, encode)
    else:
        def _gen():
            for item in stream:
                yield f"data: {encode(item)}\n\n"
            yield "data: [DONE]\n\n"

        chunks = _gen()
    return StreamingResponse(
        chunks, content_type="text/event-stream", buffered=False
    )


@dataclass
class Response:
    """Explicit-status response from a handler (ingress handlers use it for
    201/4xx etc.). body follows the normal result contract: str -> text,
    bytes -> octet-stream, anything else -> JSON."""

    status: int
    body: Any = None
    content_type: Optional[str] = None


@dataclass
class _Route:
    prefix: str
    handle: Any
    pass_request: bool = False


class _HttpReject(Exception):
    """Internal: abort request processing with this status; the connection
    closes after the reply (its stream state is unknown/hostile)."""

    def __init__(self, status: int, message: str,
                 retry_after_s: Optional[float] = None):
        super().__init__(message)
        self.status = status
        self.message = message
        self.retry_after_s = retry_after_s


def _parse_body(raw: bytes, ctype: str):
    ctype = (ctype or "").split(";")[0].strip()
    if not raw:
        return None
    if ctype in ("application/json", "", "text/json"):
        try:
            return json.loads(raw)
        except json.JSONDecodeError:
            pass
    if ctype.startswith("text/"):
        return raw.decode(errors="replace")
    return raw  # binary passthrough


class HTTPProxyActor:
    def __init__(
        self,
        host: str = "127.0.0.1",
        port: int = 8000,
        request_timeout_s: float = 60.0,
        keep_alive_timeout_s: Optional[float] = None,
        read_timeout_s: Optional[float] = None,
        max_header_bytes: Optional[int] = None,
        max_body_bytes: Optional[int] = None,
        max_connections: Optional[int] = None,
        max_queued_calls: Optional[int] = None,
        retry_after_s: Optional[float] = None,
    ):
        from ray_tpu._private.config import GLOBAL_CONFIG as cfg

        def _knob(value, flag):
            return cfg.get(flag) if value is None else value

        self.host = host
        self.port = port
        self.request_timeout_s = request_timeout_s
        self.keep_alive_timeout_s = float(
            _knob(keep_alive_timeout_s, "serve_http_keep_alive_timeout_s"))
        self.read_timeout_s = float(
            _knob(read_timeout_s, "serve_http_read_timeout_s"))
        self.max_header_bytes = int(
            _knob(max_header_bytes, "serve_http_max_header_bytes"))
        self.max_body_bytes = int(
            _knob(max_body_bytes, "serve_http_max_body_bytes"))
        self.max_connections = int(
            _knob(max_connections, "serve_http_max_connections"))
        self.max_queued_calls = int(
            _knob(max_queued_calls, "serve_http_max_queued_calls"))
        self.retry_after_s = float(
            _knob(retry_after_s, "serve_http_retry_after_s"))
        self.routes: Dict[str, _Route] = {}
        self._nconn = 0
        self._ncalls = 0  # replica calls submitted but not yet finished
        # replica calls block a pool thread; the loop never blocks
        self._pool = ThreadPoolExecutor(
            max_workers=_CALL_POOL_SIZE, thread_name_prefix="ingress-call"
        )
        # /metrics gets its OWN single thread: a saturated call pool (the
        # incident) must not make the proxy unobservable — scrapes never
        # compete with replica calls, and the export's bounded head
        # round-trip bounds this thread
        self._scrape_pool = ThreadPoolExecutor(
            max_workers=1, thread_name_prefix="metrics-scrape"
        )
        self._loop = asyncio.new_event_loop()
        started = threading.Event()

        # stream limit gates readuntil/readline and is FIXED at server
        # construction; keep it above the header cap so the explicit 431
        # check fires first (set_limits clamps later raises against it)
        self._stream_limit = max(2 * self.max_header_bytes, 256 * 1024)

        def _run():
            asyncio.set_event_loop(self._loop)
            self._server = self._loop.run_until_complete(
                asyncio.start_server(
                    self._on_client, host=host, port=port,
                    limit=self._stream_limit,
                )
            )
            self.port = self._server.sockets[0].getsockname()[1]
            started.set()
            self._loop.run_forever()

        self._thread = threading.Thread(target=_run, daemon=True)
        self._thread.start()
        if not started.wait(10):
            raise RuntimeError("ingress server failed to start")

    # ---------------------------------------------------------- http plane

    def _match(self, path: str) -> Optional[_Route]:
        """Longest-prefix routing (reference: route_prefix semantics)."""
        best = None
        for prefix, route in self.routes.items():
            if path == prefix or path.startswith(
                prefix if prefix.endswith("/") else prefix + "/"
            ) or prefix == "/":
                if best is None or len(prefix) > len(best.prefix):
                    best = route
        return best

    async def _read_body(self, reader, headers: Dict[str, str]) -> bytes:
        """Request body under the read deadline and size cap. Raises
        _HttpReject (408 slow body / 413 oversized / 400 malformed)."""
        deadline = self._loop.time() + self.read_timeout_s

        async def _timed(coro):
            remaining = deadline - self._loop.time()
            if remaining <= 0:
                raise _HttpReject(408, "request body read timed out")
            try:
                return await asyncio.wait_for(coro, timeout=remaining)
            except asyncio.TimeoutError:
                raise _HttpReject(408, "request body read timed out")

        if "chunked" in headers.get("transfer-encoding", "").lower():
            # chunked request decoding (uvicorn/h11 parity): size-line,
            # data+CRLF, ... , 0-size line, optional trailers, blank line
            raw = bytearray()
            while True:
                line = await _timed(reader.readline())
                try:
                    size = int(line.split(b";")[0].strip() or b"0", 16)
                except ValueError:
                    raise _HttpReject(400, "malformed chunk size")
                if size == 0:
                    while True:  # drain trailers up to the blank line
                        tl = await _timed(reader.readline())
                        if tl in (b"\r\n", b"\n", b""):
                            break
                    return bytes(raw)
                if len(raw) + size > self.max_body_bytes:
                    raise _HttpReject(413, "request body too large")
                chunk = await _timed(reader.readexactly(size + 2))
                if chunk[-2:] != b"\r\n":
                    raise _HttpReject(400, "malformed chunk terminator")
                raw += chunk[:-2]
        try:
            n = int(headers.get("content-length", 0) or 0)
        except ValueError:
            raise _HttpReject(400, "malformed content-length")
        if n > self.max_body_bytes:
            raise _HttpReject(413, "request body too large")
        return await _timed(reader.readexactly(n)) if n else b""

    async def _on_client(self, reader: asyncio.StreamReader,
                         writer: asyncio.StreamWriter):
        """One coroutine per connection; loops over keep-alive requests.
        Every read is under a deadline, so hostile clients (slow-loris,
        half-open sockets) cost one bounded coroutine, never a thread."""
        if self._nconn >= self.max_connections:
            try:
                await self._reply(
                    writer, 503, "application/json",
                    b'{"error": "connection limit reached"}',
                    extra_headers=self._retry_after(), close=True,
                )
            except Exception:
                pass
            finally:
                try:
                    writer.close()
                except Exception:
                    pass
            return
        self._nconn += 1
        try:
            while True:
                try:
                    head = await asyncio.wait_for(
                        reader.readuntil(b"\r\n\r\n"),
                        timeout=self.keep_alive_timeout_s,
                    )
                except asyncio.TimeoutError:
                    # idle keep-alive OR trickling headers (slow-loris):
                    # 408 best-effort, then reap the connection
                    try:
                        await self._reply(writer, 408, "application/json",
                                          b'{"error": "request timed out"}',
                                          close=True)
                    except Exception:
                        pass
                    return
                except (asyncio.IncompleteReadError, ConnectionError):
                    return
                except asyncio.LimitOverrunError:
                    await self._reply(writer, 431, "application/json",
                                      b'{"error": "headers too large"}',
                                      close=True)
                    return
                if len(head) > self.max_header_bytes:
                    await self._reply(writer, 431, "application/json",
                                      b'{"error": "headers too large"}',
                                      close=True)
                    return
                lines = head.decode("latin1").split("\r\n")
                try:
                    method, target, version = lines[0].split(" ", 2)
                except ValueError:
                    await self._reply(writer, 400, "application/json",
                                      b'{"error": "bad request line"}',
                                      close=True)
                    return
                headers = {}
                for ln in lines[1:]:
                    if not ln:
                        continue
                    k, _, v = ln.partition(":")
                    headers[k.strip().lower()] = v.strip()
                try:
                    raw = await self._read_body(reader, headers)
                except _HttpReject as rej:
                    await self._reply(
                        writer, rej.status, "application/json",
                        json.dumps({"error": rej.message}).encode(),
                        extra_headers=self._retry_after(rej.retry_after_s),
                        close=True,
                    )
                    return
                except (asyncio.IncompleteReadError, ConnectionError):
                    return  # client hung up mid-body: nothing to answer
                except ValueError:
                    # stream-limit overrun inside a chunked body (readline
                    # raises ValueError on LimitOverrunError)
                    await self._reply(writer, 400, "application/json",
                                      b'{"error": "malformed request body"}',
                                      close=True)
                    return
                keep_alive = (
                    headers.get("connection", "").lower() != "close"
                    and version.upper() != "HTTP/1.0"
                )
                await self._dispatch(writer, method, target, headers, raw)
                if not keep_alive:
                    return
        except (ConnectionError, asyncio.CancelledError):
            pass
        finally:
            self._nconn -= 1
            try:
                writer.close()
            except Exception:
                pass

    def _retry_after(self, retry_after_s: Optional[float] = None):
        secs = self.retry_after_s if retry_after_s is None else retry_after_s
        return {"Retry-After": str(max(1, int(round(secs))))}

    async def _reply(self, writer, status: int, ctype: str, payload: bytes,
                     extra_headers: Optional[Dict[str, str]] = None,
                     close: bool = False):
        reason = _REASONS.get(status, "")
        lines = [
            f"HTTP/1.1 {status} {reason}",
            f"Content-Type: {ctype}",
            f"Content-Length: {len(payload)}",
        ]
        if status in (503,) and extra_headers is None:
            extra_headers = self._retry_after()
        for k, v in (extra_headers or {}).items():
            lines.append(f"{k}: {v}")
        if close:
            lines.append("Connection: close")
        writer.write(("\r\n".join(lines) + "\r\n\r\n").encode("latin1"))
        writer.write(payload)
        await writer.drain()

    async def _reply_chunked(self, writer, resp: StreamingResponse):
        writer.write(
            f"HTTP/1.1 200 OK\r\nContent-Type: {resp.content_type}\r\n"
            "Transfer-Encoding: chunked\r\n\r\n".encode("latin1")
        )
        for chunk in resp.chunks:
            data = chunk.encode() if isinstance(chunk, str) else bytes(chunk)
            if not data:
                continue
            writer.write(f"{len(data):x}\r\n".encode() + data + b"\r\n")
            # backpressure: a slow client parks THIS coroutine only
            await writer.drain()
        writer.write(b"0\r\n\r\n")
        await writer.drain()

    def _call_route(self, route: _Route, args: tuple):
        """Blocking replica call; runs on the bounded pool. Returns the
        DeploymentResponse too: a streaming result must be pulled from the
        exact replica that holds the live stream (replica affinity)."""
        resp = route.handle.remote(*args)
        return resp, resp.result(timeout_s=self.request_timeout_s)

    async def _pool_call(self, fn, timeout: float):
        """Submit a blocking callable to the call pool with the shared
        occupancy accounting: _ncalls mirrors POOL-THREAD occupancy, so
        the slot is released only by the future's done callback — never
        by the timeout path (a timed-out call's thread keeps blocking,
        and the saturation cap must keep counting it). The shield means
        wait_for abandons the WAIT on timeout, not the thread. One
        helper, so the invariant cannot drift between dispatch sites."""
        self._ncalls += 1
        fut = self._loop.run_in_executor(self._pool, fn)

        def _done(f):
            self._ncalls -= 1
            if not f.cancelled():
                f.exception()  # retrieved: a post-timeout error must not warn

        fut.add_done_callback(_done)
        return await asyncio.wait_for(asyncio.shield(fut), timeout=timeout)

    def _export_metrics(self) -> bytes:
        """Cluster-wide Prometheus text (runs on the call pool: the merge
        pulls every process's snapshot from the head over the worker
        socket). The head round-trip is BOUNDED — a wedged head must cost
        one failed scrape, never a permanently parked pool thread."""
        from ray_tpu.util.metrics import export_prometheus

        return export_prometheus(timeout=20.0).encode()

    async def _dispatch(self, writer, method: str, target: str,
                        headers: Dict[str, str], raw: bytes):
        from .handle import DeploymentUnavailableError
        from .replica import ReplicaDrainingError

        parts = urlsplit(target)
        path = parts.path.rstrip("/") or "/"
        if method == "GET" and path == "/metrics":
            # Prometheus scrape endpoint (reference: the per-node metrics
            # agent's exposition port). Reserved ahead of route matching —
            # an app mounted at "/" cannot shadow the scrape — and served
            # off a DEDICATED thread, outside the call pool and its
            # saturation gate: the scrape must keep answering during the
            # very incidents (pool saturation, SSE floods) the metrics
            # exist to explain. Bounded by the export's own head timeout.
            fut = self._loop.run_in_executor(
                self._scrape_pool, self._export_metrics)
            try:
                payload = await asyncio.wait_for(fut, timeout=30.0)
            except Exception as e:  # noqa: BLE001
                await self._reply(writer, 500, "application/json",
                                  json.dumps({"error": repr(e)}).encode())
                return
            await self._reply(
                writer, 200,
                "text/plain; version=0.0.4; charset=utf-8", payload,
            )
            return
        route = self._match(path)
        if route is None:
            await self._reply(writer, 404, "application/json",
                              b'{"error": "no app at this route"}')
            return
        body = _parse_body(raw, headers.get("content-type", "")) if method not in (
            "GET", "DELETE") else None
        if route.pass_request:
            arg = Request(
                method=method,
                path=parts.path,
                route=route.prefix,
                subpath=path[len(route.prefix):].lstrip("/"),
                query={k: v[0] if len(v) == 1 else v
                       for k, v in parse_qs(parts.query).items()},
                headers=headers,
                body=body,
            )
            args = (arg,)
        else:
            args = () if body is None else (body,)
        if self._ncalls >= self.max_queued_calls:
            # saturation backpressure AHEAD of the pool: queueing more work
            # would only grow tail latency past the 504 deadline anyway
            await self._reply(
                writer, 503, "application/json",
                b'{"error": "proxy saturated"}',
                extra_headers=self._retry_after(),
            )
            return
        try:
            dresp, result = await self._pool_call(
                lambda: self._call_route(route, args),
                self.request_timeout_s + 5.0,
            )
        except asyncio.TimeoutError:
            await self._reply(writer, 504, "application/json",
                              b'{"error": "request timed out"}')
            return
        except DeploymentUnavailableError as e:
            # draining / no replicas / circuit breaker open: transient by
            # construction — tell the client when to come back
            await self._reply(
                writer, 503, "application/json",
                json.dumps({"error": str(e)}).encode(),
                extra_headers=self._retry_after(
                    getattr(e, "retry_after_s", None)),
            )
            return
        except ReplicaDrainingError as e:
            # handle retries exhausted against a still-draining set
            await self._reply(
                writer, 503, "application/json",
                json.dumps({"error": str(e)}).encode(),
                extra_headers=self._retry_after(),
            )
            return
        except Exception as e:  # noqa: BLE001
            await self._reply(writer, 500, "application/json",
                              json.dumps({"error": repr(e)}).encode())
            return
        from .replica import ReplicaStreamHandle

        if isinstance(result, ReplicaStreamHandle):
            await self._stream_replica_pull(writer, route, args, dresp, result)
            return
        await self._write_result(writer, result)

    async def _write_result(self, writer, result):
        status = 200
        bare = isinstance(result, Response)  # Response bodies serialize bare
        ctype_override = None
        if bare:
            status = result.status
            ctype_override = result.content_type
            result = result.body
        try:
            if ctype_override is not None:
                data = (
                    result.encode() if isinstance(result, str)
                    else bytes(result) if isinstance(result, (bytes, bytearray, memoryview))
                    else json.dumps(result).encode()
                )
                await self._reply(writer, status, ctype_override, data)
                return
            if isinstance(result, StreamingResponse):
                await self._reply_chunked(writer, result)
                return
            if isinstance(result, (bytes, bytearray, memoryview)):
                await self._reply(writer, status, "application/octet-stream",
                                  bytes(result))
                return
            if isinstance(result, str):
                await self._reply(writer, status, "text/plain; charset=utf-8",
                                  result.encode())
                return
            # Response bodies serialize bare; plain results keep the stable
            # v1 {"result": ...} wire shape
            payload = json.dumps(result if bare else {"result": result}).encode()
        except ConnectionError:
            raise
        except Exception as e:  # a non-JSON-able result must 500, not drop
            await self._reply(writer, 500, "application/json",
                              json.dumps({"error": repr(e)}).encode())
            return
        await self._reply(writer, status, "application/json", payload)

    # ------------------------------------------------------ live streaming

    def _stream_cancel(self, replica, stream_id: int) -> None:
        """Fire-and-forget: tell the replica its consumer went away so the
        batcher can retire the slot instead of generating into the void."""
        try:
            replica.stream_cancel.remote(stream_id)
        except Exception:
            pass

    async def _stream_replica_pull(self, writer, route: _Route, args: tuple,
                                   dresp, sh) -> None:
        """Forward a live replica stream: pull chunk batches with
        stream_next (long-poll on the replica) and write each chunk as its
        own chunked frame with backpressure.

        The response head is written only after the FIRST successful pull:
        a generation that was never admitted (its submit raced a drain —
        stream_next raises ReplicaDrainingError) is re-dispatched ONCE
        against the refreshed replica set, or answered 503 — never a dead
        200. Once streaming has started, errors can only end the
        connection (chunked truncation); the replica-side drain cut keeps
        that path bounded."""
        from ray_tpu._private.config import GLOBAL_CONFIG as cfg
        from ray_tpu.exceptions import (
            ActorDiedError,
            ActorUnavailableError,
            GetTimeoutError,
            WorkerCrashedError,
        )

        from .handle import DeploymentUnavailableError
        from .replica import ReplicaDrainingError, ReplicaStreamHandle

        max_chunks = int(cfg.serve_stream_pull_max_chunks)
        pull_wait = float(cfg.serve_stream_pull_wait_s)
        replica = getattr(dresp, "replica", None)
        head_written = False
        retried = False
        idle_deadline = self._loop.time() + self.request_timeout_s

        def _pull(rep, sid):
            import ray_tpu

            return ray_tpu.get(
                rep.stream_next.remote(sid, max_chunks, pull_wait),
                timeout=self.request_timeout_s,
            )

        while True:
            if replica is None:
                if not head_written:
                    await self._reply(
                        writer, 500, "application/json",
                        b'{"error": "stream lost its serving replica"}')
                return
            try:
                rep, sid = replica, sh.stream_id
                chunks, done = await self._pool_call(
                    lambda: _pull(rep, sid), self.request_timeout_s + 5.0
                )
            except (asyncio.TimeoutError, GetTimeoutError):
                # GetTimeoutError is the common spelling (the blocking
                # ray_tpu.get inside _pull times out first); the asyncio
                # guard only fires if the pool thread itself wedges
                if not head_written:
                    await self._reply(writer, 504, "application/json",
                                      b'{"error": "stream pull timed out"}')
                self._stream_cancel(replica, sh.stream_id)
                writer.close()
                return
            except (ReplicaDrainingError, ActorDiedError,
                    ActorUnavailableError, WorkerCrashedError) as e:
                # the generation was never admitted (drain raced the call)
                # or the replica died before the first token
                if head_written:
                    writer.close()  # mid-stream: truncate, client retries
                    return
                if retried:
                    await self._reply(
                        writer, 503, "application/json",
                        json.dumps({"error": str(e)}).encode(),
                        extra_headers=self._retry_after())
                    return
                retried = True
                try:
                    # same occupancy accounting as every other pool
                    # submission: the retry call can block a pool thread
                    # for up to request_timeout_s and must be visible to
                    # the saturation gate
                    dresp, result = await self._pool_call(
                        lambda: self._call_route(route, args),
                        self.request_timeout_s + 5.0,
                    )
                except asyncio.TimeoutError:
                    await self._reply(writer, 504, "application/json",
                                      b'{"error": "request timed out"}')
                    return
                except (DeploymentUnavailableError, ReplicaDrainingError) as e2:
                    await self._reply(
                        writer, 503, "application/json",
                        json.dumps({"error": str(e2)}).encode(),
                        extra_headers=self._retry_after(
                            getattr(e2, "retry_after_s", None)))
                    return
                except Exception as e2:  # noqa: BLE001
                    await self._reply(writer, 500, "application/json",
                                      json.dumps({"error": repr(e2)}).encode())
                    return
                if not isinstance(result, ReplicaStreamHandle):
                    await self._write_result(writer, result)
                    return
                replica = getattr(dresp, "replica", None)
                sh = result
                idle_deadline = self._loop.time() + self.request_timeout_s
                continue
            except Exception as e:  # noqa: BLE001 — producer raised
                if not head_written:
                    await self._reply(writer, 500, "application/json",
                                      json.dumps({"error": repr(e)}).encode())
                else:
                    writer.close()
                return
            try:
                if not head_written:
                    writer.write(
                        f"HTTP/1.1 200 OK\r\nContent-Type: {sh.content_type}"
                        "\r\nTransfer-Encoding: chunked\r\n\r\n".encode("latin1")
                    )
                    head_written = True
                for chunk in chunks:
                    data = (chunk.encode() if isinstance(chunk, str)
                            else bytes(chunk))
                    if not data:
                        continue
                    writer.write(f"{len(data):x}\r\n".encode() + data + b"\r\n")
                # backpressure: a slow client parks THIS coroutine only
                await writer.drain()
                if done:
                    writer.write(b"0\r\n\r\n")
                    await writer.drain()
                    return
            except (ConnectionError, asyncio.CancelledError):
                self._stream_cancel(replica, sh.stream_id)
                raise
            now = self._loop.time()
            if chunks:
                idle_deadline = now + self.request_timeout_s
            elif now >= idle_deadline:
                self._stream_cancel(replica, sh.stream_id)
                if not head_written:
                    # nothing sent yet (e.g. parked behind a full batch
                    # past the deadline): a proper 504, not a dead socket
                    await self._reply(writer, 504, "application/json",
                                      b'{"error": "stream timed out"}')
                    return
                # mid-stream there is no status code left — cut the
                # connection (chunked truncation tells the client)
                writer.close()
                return

    # ---------------------------------------------------------- actor API

    def ready(self):
        host = self.host
        if host in ("0.0.0.0", ""):
            # advertise a ROUTABLE address, not the wildcard bind (fleet
            # proxies feed proxy_addresses() -> load balancers off-box)
            from .._private.head import _advertise_host

            host = _advertise_host(host)
        return {"host": host, "port": self.port}

    def set_route(
        self, route_prefix: str, deployment_name: str, pass_request: bool = False
    ):
        from .handle import DeploymentHandle

        prefix = route_prefix.rstrip("/") or "/"
        self.routes[prefix] = _Route(
            prefix=prefix,
            handle=DeploymentHandle(deployment_name),
            pass_request=pass_request,
        )
        return True

    def remove_route(self, route_prefix: str):
        self.routes.pop(route_prefix.rstrip("/") or "/", None)
        return True

    def set_request_timeout(self, timeout_s: float):
        self.request_timeout_s = float(timeout_s)
        return True

    def set_limits(self, **limits):
        """Tune the hardening knobs on a live proxy (tests, operators).
        Accepts any of: keep_alive_timeout_s, read_timeout_s,
        max_header_bytes, max_body_bytes, max_connections,
        max_queued_calls, retry_after_s, request_timeout_s."""
        allowed = {
            "keep_alive_timeout_s": float, "read_timeout_s": float,
            "max_header_bytes": int, "max_body_bytes": int,
            "max_connections": int, "max_queued_calls": int,
            "retry_after_s": float, "request_timeout_s": float,
        }
        for k, v in limits.items():
            if k not in allowed:
                raise ValueError(f"unknown proxy limit {k!r}")
            v = allowed[k](v)
            if k == "max_header_bytes":
                # the asyncio stream limit is fixed at construction:
                # readuntil would LimitOverrunError below a larger cap, so
                # clamp instead of silently advertising headroom that the
                # transport can't deliver (raising it for real needs a new
                # proxy constructed with the bigger cap)
                v = min(v, self._stream_limit // 2)
            setattr(self, k, v)
        return True

    def limits(self) -> Dict[str, Any]:
        return {
            "keep_alive_timeout_s": self.keep_alive_timeout_s,
            "read_timeout_s": self.read_timeout_s,
            "max_header_bytes": self.max_header_bytes,
            "max_body_bytes": self.max_body_bytes,
            "max_connections": self.max_connections,
            "max_queued_calls": self.max_queued_calls,
            "retry_after_s": self.retry_after_s,
            "request_timeout_s": self.request_timeout_s,
        }

    def stop(self):
        def _stop():
            try:
                self._server.close()
            except Exception:
                pass
            self._loop.stop()

        try:
            self._loop.call_soon_threadsafe(_stop)
        except RuntimeError:
            pass
        return True
