"""Deployment declaration and application graphs.

Reference parity: serve/deployment.py:97 (Deployment, bind :261),
serve/api.py:241 (@serve.deployment decorator), serve/config.py
(DeploymentConfig / AutoscalingConfig).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional


@dataclass
class AutoscalingConfig:
    min_replicas: int = 1
    max_replicas: int = 8
    target_ongoing_requests: float = 2.0
    upscale_delay_s: float = 0.5
    downscale_delay_s: float = 2.0
    # decode-aware signal: generation-bound deployments (ContinuousBatcher
    # replicas) scale on SLOT SATURATION, not just queued calls — a batcher
    # running all slots full is at capacity even when nothing queues yet.
    # Desired replicas also satisfies: load_fraction <= target_batch_occupancy,
    # where load_fraction = (active + queued generations) / total slots.
    target_batch_occupancy: float = 0.8
    # paged-KV third signal: replicas over a PagedDecodeEngine scale up
    # when aggregate block-pool utilization exceeds this — long-prompt
    # traffic exhausts blocks (preemption/recompute churn) while slots and
    # queue depth still look healthy
    target_kv_utilization: float = 0.85


@dataclass
class DeploymentConfig:
    num_replicas: int = 1
    max_ongoing_requests: int = 100
    autoscaling_config: Optional[AutoscalingConfig] = None
    ray_actor_options: Dict[str, Any] = field(default_factory=dict)
    health_check_period_s: float = 2.0
    # graceful drain (reference: serve/config.py DeploymentConfig
    # graceful_shutdown_* knobs): a replica slated for removal — redeploy,
    # downscale, delete, shutdown — stops accepting new requests, gets up
    # to `graceful_shutdown_timeout_s` to finish in-flight ones (polled
    # every `graceful_shutdown_wait_loop_s`), and only then is killed
    graceful_shutdown_timeout_s: float = 10.0
    graceful_shutdown_wait_loop_s: float = 0.1


class Deployment:
    def __init__(self, func_or_class, name: str, config: DeploymentConfig):
        self.func_or_class = func_or_class
        self.name = name
        self.config = config

    def options(self, **kwargs) -> "Deployment":
        import copy

        cfg = copy.deepcopy(self.config)
        name = kwargs.pop("name", self.name)
        if "autoscaling_config" in kwargs:
            ac = kwargs.pop("autoscaling_config")
            cfg.autoscaling_config = (
                AutoscalingConfig(**ac) if isinstance(ac, dict) else ac
            )
        for k, v in kwargs.items():
            if hasattr(cfg, k):
                setattr(cfg, k, v)
            else:
                raise ValueError(f"unknown deployment option {k!r}")
        # type(self), not Deployment: subclasses with special bind()
        # semantics (the DAGDriver unique-name factory) must survive options
        return type(self)(self.func_or_class, name, cfg)

    def bind(self, *args, **kwargs) -> "Application":
        return Application(self, args, kwargs)

    def __repr__(self):
        return f"Deployment({self.name})"


class Application:
    """A bound deployment node; bound args may contain other Applications
    (composition — reference: serve DAG from Deployment.bind)."""

    def __init__(self, deployment: Deployment, args, kwargs):
        self.deployment = deployment
        self.args = args
        self.kwargs = kwargs

    def _walk(self, seen: Dict[str, "Application"]):
        """Collect all Applications in the graph, ingress last. Bound args
        may nest Applications inside dicts/lists/tuples (DAGDriver's
        route->dag map is the canonical case)."""
        def visit(a):
            if isinstance(a, Application):
                a._walk(seen)
            elif isinstance(a, dict):
                for v in a.values():
                    visit(v)
            elif isinstance(a, (list, tuple)):
                for v in a:
                    visit(v)

        for a in list(self.args) + list(self.kwargs.values()):
            visit(a)
        if self.deployment.name in seen and seen[self.deployment.name] is not self:
            raise ValueError(
                f"two different deployments named {self.deployment.name!r} in one app"
            )
        seen[self.deployment.name] = self
        return seen
