"""OpenAI-compatible completions API over the paged serving stack.

`OpenAICompletions` is a serve deployment that loads a model-hub bundle
(models/hub: safetensors checkpoint + byte-level BPE tokenizer) into a
`PagedDecodeEngine` + `ContinuousBatcher` and speaks the OpenAI HTTP
surface, so standard client libraries and load generators drive the
fleet unmodified:

    POST {route}/completions     text completions, stream and non-stream
    GET  {route}/models          the one-model list

Request shape (the OpenAI `/v1/completions` contract, greedy decoding):
    prompt       str | [str, ...] | [token_id, ...]
    max_tokens   int (default 16)
    stream       bool — SSE chunks `data: {json}\n\n`, terminated by the
                 `data: [DONE]\n\n` sentinel (Content-Type:
                 text/event-stream); non-stream returns one JSON body
    stop         str | [str, ...] (<= 4): generation cut BEFORE the first
                 match; streaming holds back any text that could still
                 become a stop match, so no post-stop text ever escapes
    echo         bool — prepend the prompt text to the output
    temperature  accepted and IGNORED (the serving engine is greedy;
                 OpenAI clients default to 1.0, rejecting it would break
                 every stock client). n > 1, logprobs, best_of are
                 rejected with an OpenAI-shaped error.

finish_reason: "stop" (eos token or stop sequence) or "length"
(max_tokens, context-window cut, drain cut). The eos token itself is
never surfaced as text. Token ids flow through
`IncrementalDetokenizer`, so a multi-byte character split across tokens
streams as ONE complete character (never mojibake), and the drafter
behind `serve_speculative_k` now proposes over real token ids.

Deploy with:

    from ray_tpu import serve
    from ray_tpu.serve.openai_api import openai_app
    serve.run(openai_app(model_path), name="llm", route_prefix="/v1")

`model_path` defaults from the `serve_model_path` config flag; the
advertised model id from `serve_model_id` (else the checkpoint dir name).
"""

from __future__ import annotations

import json
import time
import uuid
from typing import Any, Dict, List, Optional, Tuple

from .batching import ContinuousBatcher
from .http_proxy import Request, Response, StreamingResponse


class _OpenAIError(Exception):
    def __init__(self, status: int, message: str,
                 err_type: str = "invalid_request_error"):
        super().__init__(message)
        self.status = status
        self.message = message
        self.err_type = err_type

    def response(self) -> Response:
        return Response(
            status=self.status,
            body={"error": {"message": self.message, "type": self.err_type,
                            "param": None, "code": None}},
        )


class _StopBuffer:
    """Hold back any text tail that could still grow into a stop match, so
    a streaming response never emits characters past a stop sequence that
    only completes in a later token."""

    def __init__(self, stops: List[str]):
        self._stops = stops
        self._buf = ""
        self.matched = False

    def push(self, text: str) -> str:
        if self.matched or not self._stops:
            return "" if self.matched else text
        self._buf += text
        cut = None
        for s in self._stops:
            i = self._buf.find(s)
            if i != -1 and (cut is None or i < cut):
                cut = i
        if cut is not None:
            self.matched = True
            out, self._buf = self._buf[:cut], ""
            return out
        # longest suffix that is a proper prefix of some stop string stays
        hold = 0
        for s in self._stops:
            for j in range(min(len(s) - 1, len(self._buf)), 0, -1):
                if self._buf.endswith(s[:j]):
                    hold = max(hold, j)
                    break
        if hold:
            out, self._buf = self._buf[:-hold], self._buf[-hold:]
            return out
        out, self._buf = self._buf, ""
        return out

    def flush(self) -> str:
        """End of stream: whatever was held back was never a stop."""
        if self.matched:
            return ""
        out, self._buf = self._buf, ""
        return out


def _chunk_frame(cid: str, created: int, model: str, text: str,
                 finish_reason: Optional[str],
                 extra: Optional[Dict[str, Any]] = None) -> str:
    frame = {
        "id": cid,
        "object": "text_completion",
        "created": created,
        "model": model,
        "choices": [{"text": text, "index": 0, "logprobs": None,
                     "finish_reason": finish_reason}],
    }
    if extra:
        frame.update(extra)
    return "data: " + json.dumps(frame, ensure_ascii=False) + "\n\n"


def _timing_block(stream) -> Optional[Dict[str, float]]:
    """TTFT + total latency off the GenerationStream's lifecycle
    timestamps (kept even with the metrics plane off) — the per-response
    twin of the serve_ttft_s histogram, so one request's latency is
    auditable without a scrape. Extension field, absent from the OpenAI
    schema; stock clients ignore unknown keys."""
    t_first = getattr(stream, "t_first", None)
    t_submit = getattr(stream, "t_submit", None)
    if t_first is None or t_submit is None:
        return None
    return {
        "ttft_ms": round((t_first - t_submit) * 1000, 2),
        "latency_ms": round((time.monotonic() - t_submit) * 1000, 2),
    }


class _CompletionSSE:
    """Adapt a GenerationStream of token ids into OpenAI SSE frames while
    PRESERVING the batched long-poll pull surface (next_batch), so the
    replica->proxy stream_next path stays timeout-bounded and batched.

    Detokenization is incremental (incomplete UTF-8 tails held back) and
    stop sequences are enforced here — once a stop matches, the inner
    generation is cancelled and the stream ends with finish_reason
    "stop" and the [DONE] sentinel."""

    def __init__(self, stream, tokenizer, eos_id: Optional[int],
                 model_id: str, cid: str, created: int,
                 stops: List[str], echo_text: str = "",
                 n_prompt: int = 0):
        self._stream = stream
        self._detok = tokenizer.detokenizer()
        self._eos_id = eos_id
        self._model = model_id
        self._cid = cid
        self._created = created
        self._stop = _StopBuffer(stops)
        self._echo_text = echo_text
        self._done_sent = False
        self._n_prompt = n_prompt
        self._n_completion = 0

    def _frame(self, text: str, finish: Optional[str] = None,
               extra: Optional[Dict[str, Any]] = None) -> str:
        return _chunk_frame(self._cid, self._created, self._model, text,
                            finish, extra)

    def next_batch(self, max_items: int, wait_s: float) -> Tuple[List[str], bool]:
        if self._done_sent:
            return [], True
        # stream faults PROPAGATE: a never-admitted request's
        # ReplicaDrainingError must reach the proxy before the response
        # head so it re-dispatches to a live replica ("never a dead
        # 200"), and a mid-stream engine fault must truncate the chunked
        # response, not fabricate a clean [DONE]
        items, done = self._stream.next_batch(max_items, wait_s)
        out: List[str] = []
        if self._echo_text:
            out.append(self._frame(self._echo_text))
            self._echo_text = ""
        finish: Optional[str] = None
        emit = ""
        # per-token stop matching: counting must STOP at the token that
        # completes a stop match (a burst pull — e.g. a speculative
        # accept — may deliver tokens past it), or the streamed usage
        # would diverge from the non-stream path's count for the same
        # request
        for tok in items:
            if self._eos_id is not None and tok == self._eos_id:
                finish = "stop"
                break
            self._n_completion += 1
            emit += self._stop.push(self._detok.push(tok))
            if self._stop.matched:
                finish = "stop"
                break
        if emit:
            out.append(self._frame(emit))
        if finish == "stop" and not done:
            # eos/stop decided the end before the engine did (stop match,
            # or eos arrived mid-burst): stop pulling and free the slot —
            # a SUCCESSFUL completion, so metrics must not count it as a
            # client abort
            self._cancel_inner(completed=True)
            done = True
        if done:
            tail = "" if self._stop.matched else (
                self._stop.push(self._detok.flush()) + self._stop.flush()
            )
            if finish is None:
                finish = ("stop" if self._stop.matched else "length")
            # the finishing frame carries usage + timing (telemetry in the
            # response itself): prompt/completion token accounting and the
            # stream's measured TTFT/total latency
            extra: Dict[str, Any] = {"usage": {
                "prompt_tokens": self._n_prompt,
                "completion_tokens": self._n_completion,
                "total_tokens": self._n_prompt + self._n_completion,
            }}
            timing = _timing_block(self._stream)
            if timing is not None:
                extra["timing"] = timing
            out.append(self._frame(tail, finish, extra))
            out.append("data: [DONE]\n\n")
            self._done_sent = True
        return out, done

    def cancel(self) -> None:
        self._cancel_inner()

    def _cancel_inner(self, completed: bool = False) -> None:
        cancel = getattr(self._stream, "cancel", None)
        if cancel is None:
            return
        try:
            cancel(completed=completed)
        except TypeError:  # plain iterables' cancel() takes no kwargs
            cancel()


class OpenAICompletions:
    """The deployment callable behind `/v1`: loads the hub bundle in the
    replica process, owns engine + batcher, routes OpenAI requests."""

    _serve_ingress = True  # serve.run hands us the raw http_proxy.Request

    def __init__(
        self,
        model_path: Optional[str] = None,
        model_id: Optional[str] = None,
        engine_kwargs: Optional[Dict[str, Any]] = None,
        batcher_kwargs: Optional[Dict[str, Any]] = None,
        mesh=None,
        rules=None,
    ):
        from ray_tpu._private.config import GLOBAL_CONFIG as cfg
        from ray_tpu.models.hub import load_model
        from ray_tpu.models.kv_paging import PagedDecodeEngine

        model_path = model_path or str(cfg.serve_model_path)
        if not model_path:
            raise ValueError(
                "OpenAICompletions needs a checkpoint directory: pass "
                "model_path or set the serve_model_path config flag"
            )
        # mesh + rules flow into BOTH the loader (per-leaf sharded
        # device_put + vocab padding — the host never replicates the full
        # model) and the engine (sharded KV pool). jax meshes do not
        # pickle across the deployment boundary: pass them when
        # constructing in-process, or build them inside a subclass's
        # __init__ for fleet deployments.
        self.bundle = load_model(
            model_path, mesh=mesh, rules=rules,
            model_id=model_id or str(cfg.serve_model_id) or None,
        )
        engine_kwargs = dict(engine_kwargs or {})
        engine_kwargs.setdefault("mesh", mesh)
        engine_kwargs.setdefault("rules", rules)
        self.engine = PagedDecodeEngine(
            self.bundle.cfg, self.bundle.params,
            eos_id=self.bundle.eos_id,
            **engine_kwargs,
        )
        self.batcher = ContinuousBatcher(self.engine, **(batcher_kwargs or {}))
        self.created = int(time.time())

    # ------------------------------------------------------------- routing

    def __call__(self, request: Request):
        try:
            sub = (request.subpath or "").strip("/")
            if request.method == "GET" and sub in ("models", "v1/models"):
                return self._models()
            if request.method == "POST" and sub in (
                "completions", "v1/completions"
            ):
                return self._completions(request.body)
            raise _OpenAIError(
                404, f"no route for {request.method} {request.path!r}",
                "not_found_error",
            )
        except _OpenAIError as e:
            return e.response()

    def _models(self):
        # explicit Response: plain dict results get the {"result": ...} v1
        # wrapper, but OpenAI clients need the bare object
        return Response(200, {
            "object": "list",
            "data": [{
                "id": self.bundle.model_id,
                "object": "model",
                "created": self.created,
                "owned_by": "ray_tpu",
            }],
        })

    # --------------------------------------------------------- completions

    def _encode_prompt(self, prompt) -> List[List[int]]:
        tok = self.bundle.tokenizer
        if isinstance(prompt, str):
            return [tok.encode(prompt)]
        if isinstance(prompt, list) and prompt:
            # bool is an int subclass: JSON true/false must not pass as ids
            if all(isinstance(p, int) and not isinstance(p, bool)
                   for p in prompt):
                # bound by the REAL vocab: cfg.vocab_size includes
                # alignment-only padded entries (cfg.vocab_pad) whose
                # embeddings are zero rows, not tokens
                real_vocab = (self.bundle.cfg.vocab_size
                              - self.bundle.cfg.vocab_pad)
                bad = [p for p in prompt if not 0 <= p < real_vocab]
                if bad:
                    raise _OpenAIError(
                        400, f"prompt token ids out of vocab: {bad[:4]}")
                return [list(prompt)]
            if all(isinstance(p, str) for p in prompt):
                return [tok.encode(p) for p in prompt]
        raise _OpenAIError(
            400, "prompt must be a string, a list of strings, or a list "
            "of token ids")

    def _completions(self, body):
        if not isinstance(body, dict):
            raise _OpenAIError(400, "request body must be a JSON object")
        try:
            n, best_of = int(body.get("n", 1)), int(body.get("best_of", 1))
        except (TypeError, ValueError):
            raise _OpenAIError(400, "n and best_of must be integers")
        if n != 1:
            raise _OpenAIError(400, "n > 1 is not supported")
        if body.get("logprobs") not in (None, 0):
            raise _OpenAIError(400, "logprobs are not supported")
        if best_of != 1:
            raise _OpenAIError(400, "best_of > 1 is not supported")
        if "prompt" not in body:
            raise _OpenAIError(400, "missing required field: prompt")
        prompts = self._encode_prompt(body["prompt"])
        try:
            max_tokens = int(body.get("max_tokens", 16))
        except (TypeError, ValueError):
            raise _OpenAIError(400, "max_tokens must be an integer")
        if max_tokens < 1:
            raise _OpenAIError(400, "max_tokens must be >= 1")
        stop = body.get("stop") or []
        if isinstance(stop, str):
            stop = [stop]
        if not isinstance(stop, list) or len(stop) > 4 or not all(
            isinstance(s, str) and s for s in stop
        ):
            raise _OpenAIError(
                400, "stop must be a non-empty string or up to 4 of them")
        echo = bool(body.get("echo", False))
        stream = bool(body.get("stream", False))
        max_ctx = self.engine.max_seq_len
        for ids in prompts:
            if not ids:
                raise _OpenAIError(400, "prompt encoded to zero tokens")
            if len(ids) >= max_ctx:
                raise _OpenAIError(
                    400,
                    f"prompt of {len(ids)} tokens exceeds the context "
                    f"window of {max_ctx}",
                    "context_length_exceeded",
                )
        cid = "cmpl-" + uuid.uuid4().hex[:24]
        created = int(time.time())
        model_id = self.bundle.model_id
        if stream:
            if len(prompts) != 1:
                raise _OpenAIError(
                    400, "stream=true supports a single prompt")
            return self._stream_one(prompts[0], max_tokens, stop, echo,
                                    cid, created, model_id)
        return self._complete(prompts, max_tokens, stop, echo, cid,
                              created, model_id)

    def _submit(self, ids: List[int], max_tokens: int):
        # submit() only ENQUEUES — engine.admit's validation runs later on
        # the batcher loop thread and surfaces through the stream. The one
        # admit-time hard failure a request can cause by itself (worst-case
        # KV span larger than the whole pool) is checked HERE so the
        # client gets an OpenAI-shaped 400, not a mid-generation fault.
        worst_fn = getattr(self.engine, "worst_case_blocks", None)
        if worst_fn is not None:
            worst = worst_fn(len(ids), max_tokens)
            usable = self.engine.allocator.num_usable
            if worst > usable:
                raise _OpenAIError(
                    400,
                    f"prompt + max_tokens spans {worst} KV blocks; this "
                    f"deployment's pool holds {usable}",
                )
        return self.batcher.submit(tokens=ids, max_new_tokens=max_tokens)

    def _stream_one(self, ids, max_tokens, stop, echo, cid, created,
                    model_id):
        echo_text = self.bundle.tokenizer.decode(ids) if echo else ""
        sse = _CompletionSSE(
            self._submit(ids, max_tokens), self.bundle.tokenizer,
            self.bundle.eos_id, model_id, cid, created, stop, echo_text,
            n_prompt=len(ids),
        )
        return StreamingResponse(
            sse, content_type="text/event-stream", buffered=False
        )

    def _complete(self, prompts, max_tokens, stop, echo, cid, created,
                  model_id):
        streams = [self._submit(ids, max_tokens) for ids in prompts]
        try:
            return self._collect(prompts, streams, stop, echo, cid,
                                 created, model_id)
        except ValueError as e:
            # an engine-side validation fault surfacing through a stream
            # (bad request by construction) answers as an OpenAI 400
            raise _OpenAIError(400, str(e))
        finally:
            # a fault on one stream must not orphan its siblings: an
            # unconsumed generation would keep its slot + KV blocks
            # decoding to max_tokens with no reader
            for s in streams:
                if not s.finished:
                    s.cancel()

    def _collect(self, prompts, streams, stop, echo, cid, created,
                 model_id):
        tok = self.bundle.tokenizer
        eos = self.bundle.eos_id
        choices = []
        n_completion = 0
        for i, (ids, stream) in enumerate(zip(prompts, streams)):
            # incremental stop enforcement, same as the streaming path: a
            # stop match CANCELS the generation so the decode slot and its
            # KV blocks free at the match, not after max_tokens more steps
            detok = tok.detokenizer()
            sb = _StopBuffer(stop)
            finish = "length"
            text = ""
            n_toks = 0
            for t in stream:
                if eos is not None and t == eos:
                    finish = "stop"
                    break
                n_toks += 1
                text += sb.push(detok.push(t))
                if sb.matched:
                    finish = "stop"
                    stream.cancel(completed=True)
                    break
            if not sb.matched:
                text += sb.push(detok.flush()) + sb.flush()
            n_completion += n_toks
            if echo:
                text = tok.decode(ids) + text
            choices.append({
                "text": text,
                "index": i,
                "logprobs": None,
                "finish_reason": finish,
            })
        n_prompt = sum(len(p) for p in prompts)
        body = {
            "id": cid,
            "object": "text_completion",
            "created": created,
            "model": model_id,
            "choices": choices,
            "usage": {
                "prompt_tokens": n_prompt,
                "completion_tokens": n_completion,
                "total_tokens": n_prompt + n_completion,
            },
        }
        # measured per-request latency next to usage (extension field;
        # multi-prompt requests report the first stream's TTFT — the
        # moment the response started producing)
        timing = _timing_block(streams[0]) if streams else None
        if timing is not None:
            body["timing"] = timing
        return Response(200, body)

    # ------------------------------------------------------------- serving

    def stats(self) -> Dict[str, Any]:
        out = self.batcher.stats()
        out["model_id"] = self.bundle.model_id
        out["params_source"] = self.bundle.params_source
        return out

    def check_health(self) -> bool:
        if not self.batcher._thread.is_alive():
            raise RuntimeError("continuous batcher loop thread died")
        return True


def openai_app(
    model_path: Optional[str] = None,
    model_id: Optional[str] = None,
    *,
    deployment_name: Optional[str] = None,
    num_replicas: int = 1,
    engine_kwargs: Optional[Dict[str, Any]] = None,
    batcher_kwargs: Optional[Dict[str, Any]] = None,
    **deployment_kwargs,
):
    """Bind OpenAICompletions as a serve Application:

        serve.run(openai_app("/path/to/ckpt"), name="llm",
                  route_prefix="/v1")

    Each call mints a UNIQUELY-NAMED deployment by default (the
    controller keys deployments globally by name — the same trap the
    DAGDriver factory solves): two models deployed at two routes must
    not silently redeploy each other's replicas. Pass `deployment_name`
    to pin a stable name (single-model fleets, targeted redeploys).
    """
    from . import deployment

    name = deployment_name or f"OpenAICompletions_{uuid.uuid4().hex[:8]}"
    dep = deployment(
        OpenAICompletions, name=name,
        num_replicas=num_replicas, **deployment_kwargs,
    )
    return dep.bind(
        model_path, model_id,
        engine_kwargs=engine_kwargs, batcher_kwargs=batcher_kwargs,
    )
