"""Request batching inside a replica.

Two batching models live here:

  @serve.batch — request-level coalescing (reference parity:
  serve/batching.py _BatchQueue: collect up to max_batch_size requests or
  batch_wait_timeout_s, call the wrapped fn once with the list, scatter
  results). Implemented with a flusher thread because replica methods
  execute on a thread pool (see _private/worker_main.py).

  ContinuousBatcher — TOKEN-level batching for autoregressive generation
  (the Orca/vLLM iteration-level scheduling shape): one loop thread owns an
  engine with `max_batch_size` decode slots, admits queued requests into
  the RUNNING batch between decode steps and retires finished sequences at
  token granularity — no stop-the-world between generations. Emitted
  tokens stream to per-request GenerationStreams (the replica exposes them
  to the proxy via stream_next pulls; see serve/README.md).

Both compose with graceful draining: `drain(deadline_s)` stops admissions,
bounces queued-but-unadmitted work with ReplicaDrainingError (the handle
retries it transparently on a live replica) and lets in-flight work finish
— a running generation keeps decoding until done or the drain deadline, at
which point it is CUT (its stream ends, marked `cut`), never orphaned.
"""

from __future__ import annotations

import functools
import itertools
import queue
import threading
import time
from collections import deque
from concurrent.futures import Future
from typing import Any, Callable, Dict, List, Optional, Tuple


class _BatchQueue:
    _serve_drainable = True

    def __init__(self, fn: Callable, max_batch_size: int, timeout_s: float):
        self.fn = fn
        self.max_batch_size = max_batch_size
        self.timeout_s = timeout_s
        self.q: "queue.Queue" = queue.Queue()
        self._draining = False
        self._thread = threading.Thread(target=self._flush_loop, daemon=True)
        self._thread.start()

    def submit(self, self_arg, item) -> Future:
        fut: Future = Future()
        if self._draining:
            fut.set_exception(self._drain_error())
            return fut
        self.q.put((self_arg, item, fut))
        if self._draining:
            # raced drain(): make sure nothing lingers in the queue
            self._bounce_queued()
        return fut

    @staticmethod
    def _drain_error():
        from .replica import ReplicaDrainingError

        return ReplicaDrainingError()

    def _bounce_queued(self):
        while True:
            try:
                *_, fut = self.q.get_nowait()
            except queue.Empty:
                return
            if not fut.done():
                fut.set_exception(self._drain_error())

    def drain(self, deadline_s: Optional[float] = None) -> None:
        """Stop batching: queued-but-unadmitted items fail with
        ReplicaDrainingError (no user code ran — the handle re-routes them
        to a live replica); the batch currently executing completes."""
        self._draining = True
        self._bounce_queued()

    def _flush_loop(self):
        while True:
            first = self.q.get()
            batch = [first]
            deadline = self.timeout_s
            t0 = time.monotonic()
            while len(batch) < self.max_batch_size and not self._draining:
                remaining = deadline - (time.monotonic() - t0)
                if remaining <= 0:
                    break
                try:
                    batch.append(self.q.get(timeout=remaining))
                except queue.Empty:
                    break
            if self._draining:
                # collected but user code never ran: bounce for retry
                for *_, f in batch:
                    if not f.done():
                        f.set_exception(self._drain_error())
                continue
            self_arg = batch[0][0]
            items = [b[1] for b in batch]
            futs = [b[2] for b in batch]
            try:
                if self_arg is None:
                    results = self.fn(items)
                else:
                    results = self.fn(self_arg, items)
                if len(results) != len(items):
                    raise ValueError(
                        f"@serve.batch fn returned {len(results)} results for "
                        f"{len(items)} inputs"
                    )
                for f, r in zip(futs, results):
                    f.set_result(r)
            except Exception as e:  # noqa: BLE001
                for f in futs:
                    f.set_exception(e)


def batch(_fn=None, *, max_batch_size: int = 8, batch_wait_timeout_s: float = 0.01):
    """Decorate a method taking List[T] -> List[R]; callers pass single T."""

    def decorator(fn):
        bq_attr = f"__batch_queue_{fn.__name__}"

        @functools.wraps(fn)
        def wrapper(*args):
            if len(args) == 2:  # bound method: (self, item)
                self_arg, item = args
                holder = self_arg
            else:  # plain function: (item,)
                (item,) = args
                self_arg, holder = None, wrapper
            bq = getattr(holder, bq_attr, None)
            if bq is None:
                bq = _BatchQueue(fn, max_batch_size, batch_wait_timeout_s)
                setattr(holder, bq_attr, bq)
            return bq.submit(self_arg, item).result()

        return wrapper

    if _fn is not None:
        return decorator(_fn)
    return decorator


# --------------------------------------------------------------------------
# continuous batching (token-granularity admission/retirement)
# --------------------------------------------------------------------------


class GenerationStream:
    """Per-request token stream: the batcher pushes, one consumer pulls.

    Iterable in-process; `next_batch` is the long-poll pull the replica's
    stream_next uses (block up to wait_s for the first item, then drain
    whatever else is ready)."""

    _END = object()

    def __init__(self, request_id: int, request: Dict[str, Any]):
        self.request_id = request_id
        self.request = request
        self.cut = False        # drain deadline truncated this generation
        self.cancelled = False  # consumer went away
        # cancel(completed=True): an API layer ended the generation as a
        # SUCCESS (stop-sequence match, eos decided mid-burst) — the slot
        # frees like any cancel, but metrics count the request as "ok",
        # not as a client abort
        self.cancel_completed = False
        self.preempted = False  # evicted under KV pressure, parked to resume
        self._q: "queue.Queue" = queue.Queue()
        self._finished = threading.Event()
        self._error: Optional[BaseException] = None
        self._drained = False   # END consumed; only the error (if any) left
        # lifecycle timestamps (monotonic): kept unconditionally (they're
        # one clock read per token) so API layers can report TTFT even
        # with the metrics plane off; _tel is set by the owning batcher
        self.t_submit = time.monotonic()
        self.t_enqueue = self.t_submit  # re-stamped on preemption re-parks
        self.t_first: Optional[float] = None
        self._t_last = self.t_submit
        self.n_tokens = 0
        self._tel = None
        # finalize-once guard is a real lock: close() (caller thread) and
        # the batcher loop can race _finish on the same stream, and a
        # check-then-set would double-count request metrics
        self._finalized = False
        self._final_lock = threading.Lock()

    # -- producer side (batcher loop thread)

    def _push(self, token) -> None:
        now = time.monotonic()
        tel = self._tel
        if tel is not None:
            if self.n_tokens == 0:
                tel.ttft.observe(now - self.t_submit)
            else:
                tel.inter_token.observe(now - self._t_last)
        if self.n_tokens == 0:
            self.t_first = now
        self._t_last = now
        self.n_tokens += 1
        self._q.put(token)

    def _outcome(self) -> str:
        if self._error is not None:
            from .replica import ReplicaDrainingError

            return ("draining" if isinstance(self._error, ReplicaDrainingError)
                    else "error")
        if self.cut:
            return "cut"
        if self.cancelled and not self.cancel_completed:
            return "cancelled"
        return "ok"

    def _finish(self, error: Optional[BaseException] = None,
                cut: bool = False) -> None:
        # FIRST finish wins the terminal state — close()/drain racing the
        # loop thread's own _finish must neither clear a recorded engine
        # fault (self._error = None would turn it into a silent clean
        # cut) nor double-count the request's metrics. State is published
        # INSIDE the lock and losers return before touching the queue, so
        # a loser's END can never release the consumer ahead of the
        # winner's error write.
        with self._final_lock:
            if self._finalized:
                return
            self._finalized = True
            self._error = error
            self.cut = cut or self.cut
        tel = self._tel
        if tel is not None:
            tel.request_latency.observe(time.monotonic() - self.t_submit)
            tel.requests.inc(tags={"outcome": self._outcome()})
            if self.n_tokens:
                # counted at retirement, not per token: one Counter.inc
                # per request keeps the per-token hot path to exactly
                # one histogram observe
                tel.tokens.inc(self.n_tokens)
        self._finished.set()
        self._q.put(self._END)

    # -- consumer side

    def cancel(self, completed: bool = False) -> None:
        """Consumer gone (or, with completed=True, the API layer closed a
        SUCCESSFUL generation early — stop match): the batcher retires
        the slot at the next step."""
        if completed:
            self.cancel_completed = True
        self.cancelled = True

    @property
    def finished(self) -> bool:
        return self._finished.is_set()

    def next_batch(self, max_items: int = 64,
                   wait_s: float = 0.25) -> Tuple[List[Any], bool]:
        """Pull up to max_items; returns (items, done). Blocks up to wait_s
        for the first item; raises the stream's error (e.g.
        ReplicaDrainingError for a never-admitted request, an engine fault
        mid-generation) once all produced items have been delivered — a
        faulted stream must never end looking like a clean completion, so
        when tokens and the END marker land in one pull the items go out
        with done=False and the NEXT pull raises."""
        if self._drained:
            if self._error is not None:
                raise self._error
            return [], True
        items: List[Any] = []
        try:
            first = self._q.get(timeout=max(0.0, wait_s))
        except queue.Empty:
            return items, False
        ended = first is self._END
        if not ended:
            items.append(first)
            while len(items) < max_items:
                try:
                    nxt = self._q.get_nowait()
                except queue.Empty:
                    break
                if nxt is self._END:
                    ended = True
                    break
                items.append(nxt)
        if ended:
            self._drained = True
            if self._error is not None:
                if items:
                    return items, False  # error surfaces on the next pull
                raise self._error
        return items, ended

    def __iter__(self):
        while True:
            items, done = self.next_batch(max_items=64, wait_s=5.0)
            yield from items
            if done:
                return


class ContinuousBatcher:
    """Token-granularity continuous batching over a slot-based engine.

    engine contract (see ray_tpu.models.decoding.DecodeEngine):
      admit(slot, request) -> (token, done)
      step(slots)          -> {slot: (token, done)}
      release(slot)          optional

    A step result may also carry a token LIST per slot (speculative
    decoding: PagedDecodeEngine with speculative_k > 0 emits 1..k+1
    accepted tokens per verify step). Every token is pushed to the
    stream individually, so SSE consumers see the whole accepted burst
    and deadlines/drain/preemption still cut at token granularity.

    Chunked prefill (PagedDecodeEngine with prefill_chunk_tokens > 0)
    stretches the contract the other way: admit() may return
    (None, False) — nothing is pushed — and subsequent steps return
    ([], False) for that slot while its prompt streams in chunk-per-step,
    INTERLEAVED with everyone else's decode in the same engine step. The
    first sampled token arrives through step() once the prompt is
    consumed. The batcher needs no scheduling changes for this: the
    engine owns the chunk/decode interleave; empty token lists simply
    push nothing.

    One loop thread owns the engine. Requests submitted while the batch is
    full wait in a queue and are admitted the moment a slot retires —
    mid-generation of everyone else (that is the whole point). The
    per-step occupancy log (`occupancy_log()`) records which requests
    shared each engine step; tests use it to prove interleaving.

    Paging-aware engines (ray_tpu.models.kv_paging.PagedDecodeEngine) are
    driven through two optional duck-typed hooks:

      can_admit(request) -> bool   block-budget admission: a request whose
        worst-case KV-block need exceeds the pool's current headroom waits
        at the head of the line (order preserved) instead of thrashing —
        unless NOTHING is running, in which case it is admitted
        best-effort so a lone oversized request still gets a clear error
        rather than queueing forever.
      take_preempted() -> [(slot, parked_request)]   generations the
        engine evicted under pool exhaustion: their stream stays OPEN and
        the parked request (prompt + tokens generated so far) re-enters at
        the head of the admission line — on readmit the engine recomputes
        the cache and the stream resumes exactly where it stopped, so the
        consumer (an SSE socket, an iter_stream caller) never notices
        beyond latency.
    """

    _serve_drainable = True

    def __init__(
        self,
        engine,
        max_batch_size: Optional[int] = None,
        batch_wait_timeout_s: Optional[float] = None,
        telemetry=None,
    ):
        from ray_tpu._private.config import GLOBAL_CONFIG as cfg
        from .telemetry import resolve as _tel_resolve

        # request-lifecycle metrics + flight recorder (serve/telemetry.py):
        # None = process singleton per the serve_telemetry flag, False =
        # off for this batcher (zero per-token work)
        self._tel = _tel_resolve(telemetry)
        self._rec = self._tel.recorder if self._tel is not None else None
        self.engine = engine
        engine_cap = getattr(engine, "max_batch_size", None)
        self.max_batch_size = int(
            max_batch_size
            or engine_cap
            or cfg.serve_generation_max_batch_size
        )
        if engine_cap is not None and self.max_batch_size > engine_cap:
            raise ValueError(
                f"max_batch_size {self.max_batch_size} exceeds the engine's "
                f"{engine_cap} slots"
            )
        self.batch_wait_timeout_s = float(
            cfg.serve_generation_batch_wait_timeout_s
            if batch_wait_timeout_s is None else batch_wait_timeout_s
        )
        self._pending: "queue.Queue[GenerationStream]" = queue.Queue()
        # head-of-line parking: preempted generations awaiting readmission
        # and requests the engine's block budget cannot cover yet — checked
        # before the pending queue so admission order is preserved
        self._holdback: "deque" = deque()
        # items popped from holdback/pending but not yet admitted ("in
        # hand"): counted as ongoing so a drain poll sampling mid-gather
        # never sees a momentarily-empty replica and reaps an open stream
        self._in_hand = 0
        # memoized verdict for the parked head-of-line request: pool
        # headroom only changes on retire/preempt/admit, so the per-step
        # can_admit recheck (prompt hashing + cache scan) is skipped until
        # one of those happens
        self._admission_verdict: Optional[Tuple[int, bool]] = None
        self._admission_dirty = True
        self._free = list(range(self.max_batch_size))
        self._active: Dict[int, GenerationStream] = {}
        self._ids = itertools.count()
        self._lock = threading.Lock()
        self._draining = False
        self._drain_deadline: Optional[float] = None
        self._shutdown = False
        self._steps = 0
        # bounded: observability for tests/operators, not a flight recorder
        self._occupancy: "deque" = deque(maxlen=65536)
        # cross-thread calls serviced by the loop thread (run_on_loop):
        # (fn, result box, done event) triples, drained every iteration
        self._loop_calls: "deque" = deque()
        self._thread = threading.Thread(
            target=self._loop, daemon=True, name="continuous-batcher"
        )
        self._thread.start()

    # ------------------------------------------------------------ public API

    def submit(self, **request) -> GenerationStream:
        """Queue a generation request; returns its token stream. Raises
        ReplicaDrainingError while draining (nothing ran — retryable)."""
        from .replica import ReplicaDrainingError

        with self._lock:
            if self._draining or self._shutdown:
                raise ReplicaDrainingError()
            stream = GenerationStream(next(self._ids), request)
            stream._tel = self._tel
            self._pending.put(stream)
        return stream

    def drain(self, deadline_s: Optional[float] = None) -> None:
        """Stop admissions; bounce queued-but-unadmitted requests for
        handle-side retry; let running generations finish until
        `deadline_s` from now, then cut them."""
        with self._lock:
            self._draining = True
            # explicit None check: deadline_s=0 means cut NOW, not never
            self._drain_deadline = (
                None if deadline_s is None else time.monotonic() + deadline_s
            )
        self._bounce_pending()
        if self._tel is not None:
            # drain precedes a reap: persist the post-mortem window now
            self._tel.flush_events(force=True)

    def close(self) -> None:
        """Terminal stop: bounce queued requests AND cut active streams so
        no consumer is left blocking on a loop thread that exited."""
        self._shutdown = True
        self._bounce_pending()
        self._cut_parked()
        with self._lock:
            active = list(self._active.values())
            self._active.clear()
        for stream in active:
            stream._finish(cut=True)
        if self._tel is not None:
            self._tel.flush_events(force=True)

    def occupancy_log(self) -> List[Tuple[int, int, Tuple[int, ...]]]:
        """[(step, n_active, request_ids active that step), ...]"""
        return list(self._occupancy)

    def run_on_loop(self, fn, timeout_s: float = 10.0):
        """Run `fn()` on the batcher's loop thread and return its result.

        The loop thread owns the engine (admit/step/release are not
        thread-safe), so anything that must see one consistent engine
        state — cross-replica prefix exports reading the pool, ad-hoc
        engine surgery in tests — goes through here instead of touching
        the engine from a request thread. Calls are drained at the top of
        every loop iteration (the idle loop wakes at least every ~50ms).
        Raises TimeoutError when the loop cannot service the call in
        `timeout_s` and RuntimeError after close()."""
        if threading.current_thread() is self._thread:
            return fn()
        if self._shutdown:
            raise RuntimeError("batcher is closed")
        box: Dict[str, Any] = {}
        done = threading.Event()
        self._loop_calls.append((fn, box, done))
        if not done.wait(timeout_s):
            raise TimeoutError(
                f"batcher loop did not service the call in {timeout_s}s"
            )
        if "error" in box:
            raise box["error"]
        return box.get("result")

    def stats(self) -> Dict[str, Any]:
        with self._lock:
            out = {
                "active": len(self._active),
                "free_slots": len(self._free),
                "queued": self._pending.qsize() + len(self._holdback),
                "steps": self._steps,
                "draining": self._draining,
                "max_batch_size": self.max_batch_size,
            }
        # free-block headroom from paging-aware engines: the autoscaler's
        # third scale signal and the admission gate's observability
        get_stats = getattr(self.engine, "stats", None)
        if get_stats is not None:
            try:
                es = get_stats()
            except Exception:
                es = None
            if isinstance(es, dict):
                for k in ("flight_events", "flight_events_total",
                          "kv_blocks_total", "kv_blocks_free",
                          "kv_blocks_cached", "preemptions", "prefix_hits",
                          "kv_block_bytes", "kv_pool_bytes",
                          "kv_cache_dtype", "attention_impl",
                          "prefill_chunk_tokens", "prefill_chunks",
                          "chunked_prefills", "prefilling",
                          "prefill_tokens", "prefix_tokens_reused",
                          "kv_exports", "kv_blocks_exported",
                          "kv_imports", "kv_blocks_imported",
                          "kv_tokens_imported", "kv_import_rejects",
                          "spec_k", "spec_steps", "spec_slot_steps",
                          "spec_proposed_tokens", "spec_accepted_tokens",
                          "spec_emitted_tokens", "spec_accept_rate",
                          "spec_tokens_per_step",
                          "weight_version", "weight_swaps"):
                    if k in es:
                        out[k] = es[k]
        return out

    def num_ongoing(self) -> int:
        with self._lock:
            return (len(self._active) + self._pending.qsize()
                    + len(self._holdback) + self._in_hand)

    # -------------------------------------------------------------- internals

    def _bounce_pending(self) -> None:
        """Fail queued-but-unadmitted requests with the retryable drain
        error. Preempted holdback streams already emitted tokens through
        THIS replica, so they cannot be re-routed — they stay parked for
        readmission until the drain deadline cuts them."""
        from .replica import ReplicaDrainingError

        keep = []
        with self._lock:
            while self._holdback:
                item = self._holdback.popleft()
                if item[0].preempted:
                    keep.append(item)
                else:
                    item[0]._finish(error=ReplicaDrainingError())
            self._holdback.extend(keep)
        while True:
            try:
                stream = self._pending.get_nowait()
            except queue.Empty:
                return
            stream._finish(error=ReplicaDrainingError())

    def _cut_parked(self) -> None:
        """Terminal: cut preempted streams still parked (drain deadline or
        close — they can never resume here)."""
        with self._lock:
            parked = list(self._holdback)
            self._holdback.clear()
        for stream, _ in parked:
            stream._finish(cut=True)

    def _admissible(self, stream: GenerationStream,
                    request: Dict[str, Any]) -> bool:
        can = getattr(self.engine, "can_admit", None)
        if can is None:
            return True
        # the verdict for the parked head item is stable until a retire /
        # preemption / admission changes the pool — skip the recheck
        # (prompt hashing + cache scan) on the per-step hot path until then
        rid = stream.request_id
        if (not self._admission_dirty
                and self._admission_verdict is not None
                and self._admission_verdict[0] == rid):
            return self._admission_verdict[1]
        try:
            verdict = bool(can(request))
        except Exception:
            return True  # a broken budget check must not wedge admission
        self._admission_verdict = (rid, verdict)
        self._admission_dirty = False
        return verdict

    def _admit_one(self, stream: GenerationStream,
                   request: Optional[Dict[str, Any]] = None) -> bool:
        """Admit into a free slot; returns False when the request was
        PARKED for lack of KV blocks (the caller must stop gathering this
        round or it would spin on the same head-of-line item)."""
        if request is None:
            request = stream.request
        if stream.cancelled or stream.finished:
            if not stream.finished:
                stream._finish()
            return True
        with self._lock:
            slot = self._free.pop()
            self._active[slot] = stream
        # queue wait ends where ADMISSION STARTS: admit() runs the prefill
        # (possibly a whole long prompt), which must not read as queue time
        t_admit = time.monotonic()
        try:
            tok, done = self.engine.admit(slot, request)
        except Exception as e:  # noqa: BLE001 — bad request must not kill the loop
            import sys

            kvmod = sys.modules.get("ray_tpu.models.kv_paging")
            if kvmod is not None and isinstance(
                    e, kvmod.InsufficientBlocksError):
                # pool can't cover the prompt right now: park for retry —
                # blocks free as running generations retire (a prompt that
                # can NEVER fit raises ValueError instead and fails here)
                with self._lock:
                    self._active.pop(slot, None)
                    self._free.append(slot)
                    self._holdback.appendleft((stream, request))
                return False
            stream._finish(error=e)
            self._retire(slot)
            return True
        if self._tel is not None:
            self._tel.queue_wait.observe(t_admit - stream.t_enqueue)
            if self._rec is not None:
                # rid<->slot correlation for the timeline: the engine's own
                # "admit" event knows the slot but not the request id
                self._rec.record(
                    "readmit" if stream.preempted else "request",
                    slot=slot, args={"rid": stream.request_id})
        # a chunked-prefill admission (PagedDecodeEngine with
        # prefill_chunk_tokens) returns no token yet — the prompt streams
        # in chunk-per-step and the first sampled token arrives via step()
        if tok is not None:
            stream._push(tok)
        if done:
            stream._finish()
            self._retire(slot)
        return True

    def _retire(self, slot: int) -> None:
        with self._lock:
            self._active.pop(slot, None)
            self._free.append(slot)
            self._admission_dirty = True  # freed blocks: recheck parked head
        release = getattr(self.engine, "release", None)
        if release is not None:
            release(slot)

    def _gather(self, first_timeout: float) -> None:
        """Admit queued work into free slots: holdback (preempted /
        budget-parked, order preserved) first, then the pending queue —
        blocking up to first_timeout for the first pending item (idle
        parking / coalescing), then taking whatever else is ready."""
        block = first_timeout
        while self._free and not self._shutdown:
            with self._lock:
                item = self._holdback.popleft() if self._holdback else None
                if item is not None:
                    self._in_hand += 1
            if item is None:
                try:
                    stream = self._pending.get(timeout=block)
                except queue.Empty:
                    return
                # counted the instant the pop returns (before the lengthy
                # admissibility check) so a drain poll never sees the
                # stream in neither queue nor batch; counting BEFORE the
                # blocking get would instead report a phantom ongoing
                # request on every idle batcher
                with self._lock:
                    self._in_hand += 1
                item = (stream, stream.request)
            try:
                block = 0.0
                stream, request = item
                if not self._admissible(stream, request):
                    with self._lock:
                        busy = bool(self._active)
                        if busy:
                            # head-of-line wait: blocks free as the running
                            # batch retires; admitting past budget would
                            # only force preemption churn
                            self._holdback.appendleft(item)
                    if busy:
                        return
                    # nothing running to free blocks: admit best-effort so
                    # the request either squeezes in (cache eviction) or
                    # fails with the engine's real error instead of
                    # parking forever
                if not self._admit_one(stream, request):
                    return
                with self._lock:
                    self._admission_dirty = True  # pool changed: recheck
            finally:
                with self._lock:
                    self._in_hand -= 1

    def _absorb_preempted(self) -> None:
        """Park engine-evicted generations (stream stays open) at the head
        of the admission line for recompute-on-readmit."""
        take = getattr(self.engine, "take_preempted", None)
        if take is None:
            return
        try:
            evicted = take() or ()
        except Exception:
            return
        for slot, parked in reversed(list(evicted)):
            with self._lock:
                stream = self._active.pop(slot, None)
                if slot not in self._free:
                    self._free.append(slot)
            if stream is None:
                continue
            if stream.cancelled:
                stream._finish()
                continue
            stream.preempted = True
            # queue wait for the READMISSION measures from this re-park,
            # not the original submit (that span is request latency's job)
            stream.t_enqueue = time.monotonic()
            if self._tel is not None:
                self._tel.preemptions.inc()
            with self._lock:
                self._holdback.appendleft((stream, parked))
                self._admission_dirty = True  # blocks freed by the eviction

    def _run_loop_calls(self) -> None:
        while self._loop_calls:
            try:
                fn, box, done = self._loop_calls.popleft()
            except IndexError:
                return
            try:
                box["result"] = fn()
            except Exception as e:  # noqa: BLE001 — caller re-raises
                box["error"] = e
            done.set()

    def _loop(self) -> None:
        while not self._shutdown:
            self._run_loop_calls()
            if not self._active:
                if self._draining:
                    self._bounce_pending()
                    # preempted generations parked in holdback are
                    # in-flight work: keep readmitting them until done or
                    # the drain deadline cuts them
                    with self._lock:
                        has_parked = bool(self._holdback)
                    if has_parked:
                        self._gather(first_timeout=0.0)
                    if (self._draining and self._drain_deadline is not None
                            and time.monotonic() >= self._drain_deadline):
                        self._cut_parked()
                    if not self._active:
                        time.sleep(0.01)
                        continue
                # idle: park on the queue; once the first request lands,
                # hold the batch open for the coalescing window so
                # near-simultaneous requests share the first step
                self._gather(first_timeout=0.05)
                if self._active and self.batch_wait_timeout_s > 0:
                    deadline = time.monotonic() + self.batch_wait_timeout_s
                    while (len(self._free) > 0
                           and time.monotonic() < deadline):
                        self._gather(
                            first_timeout=max(0.0, deadline - time.monotonic())
                        )
                        if not self._free:
                            break
                if not self._active:
                    continue
            else:
                # running batch: admit whatever is queued, no waiting
                self._gather(first_timeout=0.0)

            with self._lock:
                slots = sorted(self._active)
                ids = tuple(self._active[s].request_id for s in slots)
            if not slots:
                continue
            try:
                results = self.engine.step(slots)
            except Exception as e:  # noqa: BLE001 — engine fault fails the batch
                if self._tel is not None:
                    if self._rec is not None:
                        self._rec.record(
                            "engine_fault",
                            args={"error": repr(e)[:200],
                                  "slots": tuple(slots)})
                    # a faulting engine is exactly when the post-mortem
                    # window matters: get it off this process NOW
                    self._tel.flush_events(force=True)
                # discard any preemptions staged before the fault: their
                # streams are errored with everyone else's below, and a
                # stale parked entry must never hijack the slot's NEXT
                # stream on a later successful step
                take = getattr(self.engine, "take_preempted", None)
                if take is not None:
                    try:
                        take()
                    except Exception:
                        pass
                for slot in slots:
                    stream = self._active.get(slot)
                    if stream is not None:
                        stream._finish(error=e)
                    self._retire(slot)
                continue
            # slots the engine preempted mid-step are absent from results:
            # park their streams (still open) for recompute-on-readmit
            self._absorb_preempted()
            self._steps += 1
            self._occupancy.append((self._steps, len(slots), ids))
            if self._tel is not None and self._steps % 8 == 1:
                # cheap occupancy/pool gauges (attribute reads, no
                # engine.stats() call — that walks the prefix-cache trie),
                # refreshed every 8th step: gauge freshness at sub-step
                # granularity buys nothing, the hot loop's budget does
                self._tel.occupancy.set(len(slots))
                alloc = getattr(self.engine, "allocator", None)
                if alloc is not None:
                    self._tel.kv_util.set(
                        (alloc.num_usable - alloc.num_free)
                        / max(1, alloc.num_usable))
                if getattr(self.engine, "speculative_k", 0):
                    self._tel.spec_accept.set(
                        self.engine.spec_accepted
                        / max(1, self.engine.spec_proposed))
                self._tel.flush_events()
            for slot, (tok, done) in results.items():
                stream = self._active.get(slot)
                if stream is None:
                    continue
                if stream.cancelled:
                    stream._finish()
                    self._retire(slot)
                    continue
                # multi-token retirement: a speculative verify step may
                # emit a burst of accepted tokens — push each one so the
                # stream (and its SSE consumer) sees them all in order.
                # Only LISTS fan out: a tuple is one atomic item — the
                # (token, logprob) pair a logprobs=True engine emits
                for t in (tok if isinstance(tok, list) else (tok,)):
                    stream._push(t)
                if done:
                    stream._finish()
                    self._retire(slot)
            # drain deadline: cut whatever is still running or parked
            if (self._draining and self._drain_deadline is not None
                    and time.monotonic() >= self._drain_deadline):
                with self._lock:
                    leftover = dict(self._active)
                for slot, stream in leftover.items():
                    stream._finish(cut=True)
                    self._retire(slot)
                self._cut_parked()
        # loop exit (close()): fail parked cross-thread calls, or their
        # callers would block until their timeout
        while self._loop_calls:
            try:
                _, box, done = self._loop_calls.popleft()
            except IndexError:
                break
            box["error"] = RuntimeError("batcher loop exited")
            done.set()
