"""@serve.batch: transparent request batching inside a replica.

Reference parity: serve/batching.py (_BatchQueue: collect up to
max_batch_size requests or batch_wait_timeout_s, call the wrapped fn once
with the list, scatter results). Implemented with a flusher thread because
replica methods execute on a thread pool (see _private/worker_main.py).
"""

from __future__ import annotations

import functools
import queue
import threading
from concurrent.futures import Future
from typing import Any, Callable, List


class _BatchQueue:
    def __init__(self, fn: Callable, max_batch_size: int, timeout_s: float):
        self.fn = fn
        self.max_batch_size = max_batch_size
        self.timeout_s = timeout_s
        self.q: "queue.Queue" = queue.Queue()
        self._thread = threading.Thread(target=self._flush_loop, daemon=True)
        self._thread.start()

    def submit(self, self_arg, item) -> Future:
        fut: Future = Future()
        self.q.put((self_arg, item, fut))
        return fut

    def _flush_loop(self):
        while True:
            first = self.q.get()
            batch = [first]
            deadline = self.timeout_s
            import time

            t0 = time.monotonic()
            while len(batch) < self.max_batch_size:
                remaining = deadline - (time.monotonic() - t0)
                if remaining <= 0:
                    break
                try:
                    batch.append(self.q.get(timeout=remaining))
                except queue.Empty:
                    break
            self_arg = batch[0][0]
            items = [b[1] for b in batch]
            futs = [b[2] for b in batch]
            try:
                if self_arg is None:
                    results = self.fn(items)
                else:
                    results = self.fn(self_arg, items)
                if len(results) != len(items):
                    raise ValueError(
                        f"@serve.batch fn returned {len(results)} results for "
                        f"{len(items)} inputs"
                    )
                for f, r in zip(futs, results):
                    f.set_result(r)
            except Exception as e:  # noqa: BLE001
                for f in futs:
                    f.set_exception(e)


def batch(_fn=None, *, max_batch_size: int = 8, batch_wait_timeout_s: float = 0.01):
    """Decorate a method taking List[T] -> List[R]; callers pass single T."""

    def decorator(fn):
        bq_attr = f"__batch_queue_{fn.__name__}"

        @functools.wraps(fn)
        def wrapper(*args):
            if len(args) == 2:  # bound method: (self, item)
                self_arg, item = args
                holder = self_arg
            else:  # plain function: (item,)
                (item,) = args
                self_arg, holder = None, wrapper
            bq = getattr(holder, bq_attr, None)
            if bq is None:
                bq = _BatchQueue(fn, max_batch_size, batch_wait_timeout_s)
                setattr(holder, bq_attr, bq)
            return bq.submit(self_arg, item).result()

        return wrapper

    if _fn is not None:
        return decorator(_fn)
    return decorator
