"""Model multiplexing: many models per replica with LRU loading.

Reference parity: serve/_private/multiplex.py (_ModelMultiplexWrapper) and
the public @serve.multiplexed / serve.get_multiplexed_model_id() API — one
deployment serves MANY fine-tuned models; each replica lazily loads the
models it is asked for and LRU-evicts beyond max_num_models_per_replica.
On TPU serving this is the standard shape for LoRA fleets: one base-model
replica per host, adapters multiplexed on top.

Routing: handles keep model->replica affinity (a model already loaded on a
replica keeps receiving that model's traffic) with power-of-two-choices as
the fallback for unseen models — a handle-side simplification of the
reference's router, which learns replica model sets from replica pushes.
"""

from __future__ import annotations

import asyncio
import contextvars
import inspect
import threading
import weakref
from collections import OrderedDict
from typing import Any, Callable

_current_model_id: contextvars.ContextVar[str] = contextvars.ContextVar(
    "ray_tpu_serve_multiplexed_model_id", default=""
)


def get_multiplexed_model_id() -> str:
    """Inside a request: the model id the caller routed with
    (handle.options(multiplexed_model_id=...)); "" when not set."""
    return _current_model_id.get()


def _set_model_id(model_id: str):
    _current_model_id.set(model_id or "")


class _ModelCache:
    """Per-replica LRU of loaded models; loads are deduplicated so two
    concurrent requests for the same cold model trigger one load."""

    def __init__(self, max_models: int):
        self.max_models = max_models
        self._models: "OrderedDict[str, Any]" = OrderedDict()
        self._lock = threading.Lock()
        self._loading: dict = {}  # model_id -> threading.Event

    def loaded_ids(self):
        with self._lock:
            return list(self._models)

    def get(self, model_id: str, load: Callable[[], Any]):
        while True:
            with self._lock:
                if model_id in self._models:
                    self._models.move_to_end(model_id)
                    return self._models[model_id]
                ev = self._loading.get(model_id)
                if ev is None:
                    self._loading[model_id] = threading.Event()
                    break
            ev.wait()  # another thread is loading this model; then re-check
        try:
            model = load()
            if inspect.iscoroutine(model):
                model = asyncio.run(model)
            with self._lock:
                self._models[model_id] = model
                while len(self._models) > self.max_models:
                    self._models.popitem(last=False)  # LRU evict; GC tears down
            return model
        finally:
            with self._lock:
                ev = self._loading.pop(model_id, None)
            if ev is not None:
                ev.set()


def multiplexed(max_num_models_per_replica: int = 3):
    """Decorator for the model-loading method of a deployment:

        @serve.deployment
        class LoRAServer:
            @serve.multiplexed(max_num_models_per_replica=4)
            def get_model(self, model_id: str):
                return load_adapter(model_id)

            def __call__(self, prompt):
                model = self.get_model(serve.get_multiplexed_model_id())
                return model(prompt)

    The wrapped loader takes the model id and returns the loaded model,
    cached per replica with LRU eviction (async loaders supported).
    """

    def decorate(fn):
        params = list(inspect.signature(fn).parameters)
        takes_self = bool(params) and params[0] == "self"

        if takes_self:

            def wrapper(self, model_id: str):
                # per-INSTANCE cache: two instances in one process must not
                # cross-serve models built against each other's state
                caches = self.__dict__.setdefault("_ray_tpu_mux_caches", {})
                cache = caches.get(id(wrapper))
                if cache is None:
                    cache = caches[id(wrapper)] = _ModelCache(
                        wrapper._multiplex_max_models
                    )
                return cache.get(model_id, lambda: fn(self, model_id))

        else:

            def wrapper(model_id: str):
                return cache_of(wrapper).get(model_id, lambda: fn(model_id))

        # only picklable config rides on the function — the cache itself
        # (locks, loaded models) is built lazily PER PROCESS via cache_of,
        # so deployment classes carrying this method still cloudpickle
        wrapper._multiplex_max_models = max_num_models_per_replica
        wrapper._multiplex_takes_self = takes_self
        return wrapper

    return decorate


_caches = weakref.WeakKeyDictionary()
_caches_lock = threading.Lock()


def cache_of(wrapper) -> _ModelCache:
    """The per-process model cache behind a FUNCTION-style @multiplexed
    wrapper. Method-style wrappers keep per-INSTANCE caches (on the
    instance itself) — inspect those via instance._ray_tpu_mux_caches."""
    if getattr(wrapper, "_multiplex_takes_self", False):
        raise TypeError(
            "cache_of() works on function-style @multiplexed wrappers; "
            "method-style caches are per instance "
            "(instance._ray_tpu_mux_caches)"
        )
    with _caches_lock:
        cache = _caches.get(wrapper)
        if cache is None:
            cache = _caches[wrapper] = _ModelCache(
                getattr(wrapper, "_multiplex_max_models", 3)
            )
        return cache
