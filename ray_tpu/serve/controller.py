"""ServeController: the reconciliation control plane, as a named actor.

Reference parity: serve/controller.py:79 (ServeController detached actor),
deployment_state.py:2073 (DeploymentStateManager reconciling target vs live
replicas), autoscaling decision loop (_private/autoscaling_policy.py:69-141),
and the graceful-drain sequencing of deployment_state.py's
stop_replicas(graceful_shutdown) path: replicas leaving the set (redeploy,
downscale, delete, shutdown) are DRAINED — new traffic routed away first,
in-flight requests given a deadline to finish — and only then reaped.
"""

from __future__ import annotations

import threading
import time
from collections import OrderedDict
from typing import Any, Dict, List, Optional

from .autoscaling import calculate_desired_num_replicas
from .deployment import AutoscalingConfig, DeploymentConfig
from .replica import Replica


class _DeploymentState:
    def __init__(self, name: str, func_or_class, init_args, init_kwargs, config):
        self.name = name
        self.func_or_class = func_or_class
        self.init_args = init_args
        self.init_kwargs = init_kwargs
        self.config: DeploymentConfig = config
        self.replicas: List[Any] = []  # ActorHandles
        self.draining = False  # whole deployment slated for removal
        # prefix-affinity digest: hint -> (replica actor_id, cached chain
        # depth in blocks). Bounded LRU, harvested from replica stats on
        # the heartbeat and published over serve:prefix:<name>.
        self.prefix_digest: "OrderedDict[str, tuple]" = OrderedDict()
        self.target: int = (
            config.autoscaling_config.min_replicas
            if config.autoscaling_config
            else config.num_replicas
        )
        self.last_scale_ts = 0.0


class ServeController:
    def __init__(self):
        self._deployments: Dict[str, _DeploymentState] = {}
        self._apps: Dict[str, Dict[str, Any]] = {}
        self._lock = threading.Lock()
        self._stop = threading.Event()
        # per-node proxy fleet (reference: _private/http_state.py
        # HTTPProxyStateManager — one proxy actor per alive node, shared
        # routing table). Disabled until start_proxies().
        self._proxy_fleet = False
        self._proxy_port = 0
        self._proxies: Dict[str, Any] = {}  # node_id -> handle
        self._proxy_addrs: Dict[str, str] = {}
        self._routes: Dict[str, tuple] = {}  # prefix -> (deployment, pass_req)
        self._drainers: List[threading.Thread] = []
        self._loop_thread = threading.Thread(target=self._reconcile_loop, daemon=True)
        self._loop_thread.start()

    def ready(self):
        return True

    # ------------------------------------------------------- proxy fleet

    def set_route(self, route_prefix: str, deployment_name: str,
                  pass_request: bool = False):
        """Record a route and push it to every fleet proxy. Routes set
        before start_proxies() apply when the fleet comes up."""
        prefix = route_prefix.rstrip("/") or "/"
        with self._lock:
            self._routes[prefix] = (deployment_name, pass_request)
            proxies = list(self._proxies.values())
        self._broadcast(
            [h.set_route.remote(prefix, deployment_name, pass_request)
             for h in proxies]
        )
        return True

    def remove_route(self, route_prefix: str):
        prefix = route_prefix.rstrip("/") or "/"
        with self._lock:
            self._routes.pop(prefix, None)
            proxies = list(self._proxies.values())
        self._broadcast([h.remove_route.remote(prefix) for h in proxies])
        return True

    @staticmethod
    def _broadcast(refs):
        """Push to all proxies with ONE shared deadline — a wedged member
        costs one bounded wait, never N serial timeouts on serve.run's
        critical path (the reconcile loop replaces stragglers)."""
        import ray_tpu

        if refs:
            ray_tpu.wait(refs, num_returns=len(refs), timeout=10)

    def start_proxies(self, port: int = 0) -> Dict[str, str]:
        """Enable the per-node fleet; returns {node_id: host:port}."""
        self._proxy_fleet = True
        self._proxy_port = port
        self._ensure_proxies()
        return self.proxy_addresses()

    def proxy_addresses(self) -> Dict[str, str]:
        with self._lock:
            return dict(self._proxy_addrs)

    def _spawn_proxy(self, node_id: str):
        import ray_tpu
        from ray_tpu.util.scheduling_strategies import NodeAffinitySchedulingStrategy

        from .http_proxy import HTTPProxyActor

        Proxy = ray_tpu.remote(HTTPProxyActor)
        h = Proxy.options(
            name=f"SERVE_PROXY:{node_id}",
            lifetime="detached",
            max_concurrency=32,
            scheduling_strategy=NodeAffinitySchedulingStrategy(
                node_id=node_id, soft=False
            ),
        ).remote("0.0.0.0", self._proxy_port)
        info = ray_tpu.get(h.ready.remote(), timeout=30)
        with self._lock:
            routes = dict(self._routes)
        for prefix, (dep, pr) in routes.items():
            ray_tpu.get(h.set_route.remote(prefix, dep, pr), timeout=10)
        with self._lock:
            if not self._proxy_fleet:
                # shutdown raced this spawn: don't leak a detached proxy
                # that would block the name for every future fleet
                abort = True
            else:
                abort = False
                self._proxies[node_id] = h
                self._proxy_addrs[node_id] = f"{info['host']}:{info['port']}"
        if abort:
            try:
                ray_tpu.kill(h)
            except Exception:
                pass

    def _ensure_proxies(self):
        """One healthy proxy per alive node: spawn on new nodes, drop on
        dead ones, replace unresponsive ones (reference: http_state.py
        reconciliation)."""
        if not self._proxy_fleet:
            return
        import ray_tpu

        alive = {n["node_id"] for n in ray_tpu.nodes() if n.get("alive")}
        with self._lock:
            current = dict(self._proxies)
        for node_id in set(current) - alive:
            try:
                ray_tpu.kill(current[node_id])
            except Exception:
                pass
            with self._lock:
                self._proxies.pop(node_id, None)
                self._proxy_addrs.pop(node_id, None)
            current.pop(node_id)
        # health: ping every proxy CONCURRENTLY with one shared deadline, so
        # wedged members cost one bounded wait, not a serial stall each
        if current:
            nodes_order = list(current)
            refs = [current[n].ready.remote() for n in nodes_order]
            ready, _ = ray_tpu.wait(refs, num_returns=len(refs), timeout=10)
            ready_ids = {r.id for r in ready}
            for node_id, ref in zip(nodes_order, refs):
                healthy = ref.id in ready_ids
                if healthy:
                    try:
                        ray_tpu.get(ref)
                    except Exception:
                        healthy = False
                if not healthy:
                    # KILL before respawn: the detached name must free up,
                    # and a wedged-but-listening proxy must not keep
                    # serving stale routes
                    try:
                        ray_tpu.kill(current[node_id])
                    except Exception:
                        pass
                    with self._lock:
                        self._proxies.pop(node_id, None)
                        self._proxy_addrs.pop(node_id, None)
        with self._lock:
            have = set(self._proxies)
        for node_id in alive - have:
            try:
                self._spawn_proxy(node_id)
            except Exception:
                pass  # node may have just died; next tick retries

    # ---------------------------------------------------------- deploy API

    def deploy_application(self, app_name: str, specs: List[dict], ingress: str):
        """specs: [{name, func_or_class, init_args, init_kwargs, config}],
        dependencies first (so handles in init args resolve to live replicas)."""
        with self._lock:
            prev = self._apps.get(app_name, {}).get("deployments", [])
            new_names = [s["name"] for s in specs]
            self._apps[app_name] = {"deployments": new_names, "ingress": ingress}
            # reap deployments the redeploy dropped (e.g. a fresh uniquely-
            # named DAGDriver per bind) — otherwise their replicas leak
            # until full shutdown
            orphaned = [
                n for n in prev
                if n not in new_names
                and not any(
                    n in a["deployments"]
                    for an, a in self._apps.items() if an != app_name
                )
            ]
        for n in orphaned:
            self._retire_deployment(n)
        for s in specs:
            with self._lock:
                state = self._deployments.get(s["name"])
                old: List[Any] = []
                if state is None:
                    state = _DeploymentState(
                        s["name"], s["func_or_class"], s["init_args"], s["init_kwargs"], s["config"]
                    )
                    self._deployments[s["name"]] = state
                else:  # redeploy: replace code/config, then swap replicas
                    state.func_or_class = s["func_or_class"]
                    state.init_args = s["init_args"]
                    state.init_kwargs = s["init_kwargs"]
                    state.config = s["config"]
                    state.draining = False
                    ac = state.config.autoscaling_config
                    state.target = ac.min_replicas if ac else state.config.num_replicas
                    # the OLD replica set keeps serving until the new one is
                    # ready — get_replicas()/the push channel never expose an
                    # empty set mid-redeploy
                    old = state.replicas
            if old:
                import ray_tpu

                new = []
                try:
                    new = [
                        self._spawn_replica(state)
                        for _ in range(state.target)
                    ]
                    ray_tpu.get([r.ready.remote() for r in new])
                except Exception:
                    # failed redeploy must not leak half-built replicas
                    # (each pins num_cpus) — reap them and keep the OLD set
                    # serving; the caller sees the deploy error
                    self._kill_replicas(new)
                    raise
                state.replicas = new
                self._publish_replicas(state)
                # drain -> reap: old replicas finish their in-flight
                # requests (up to the deadline) before being killed
                self._drain_then_stop(old, state.config)
            else:
                self._reconcile(state)
        return True

    def get_replicas(self, deployment_name: str):
        state = self._deployments.get(deployment_name)
        if state is None:
            raise ValueError(f"no deployment named {deployment_name!r}")
        return list(state.replicas)

    def get_ingress(self, app_name: str) -> str:
        return self._apps[app_name]["ingress"]

    def flush_telemetry(self) -> int:
        """Fan-out: every live replica force-pushes its flight recorder +
        metrics to the head (serve.telemetry.dump_timeline's first step).
        One shared deadline — a wedged replica costs one bounded wait.
        Returns the number of replicas reached."""
        import ray_tpu

        with self._lock:
            replicas = [
                r for s in self._deployments.values() for r in s.replicas
            ]
        refs = []
        for r in replicas:
            try:
                refs.append(r.flush_telemetry.remote())
            except Exception:
                pass
        if not refs:
            return 0
        ready, _ = ray_tpu.wait(refs, num_returns=len(refs), timeout=10)
        return len(ready)

    def list_deployments(self) -> Dict[str, dict]:
        return {
            name: {
                "target": s.target,
                "live": len(s.replicas),
                "draining": s.draining,
                "autoscaling": s.config.autoscaling_config is not None,
            }
            for name, s in self._deployments.items()
        }

    def _retire_deployment(self, name: str, wait: bool = False):
        """Drain a whole deployment out of existence: broadcast the drain
        state (handles fail fast with DeploymentUnavailableError -> proxies
        emit 503), then drain -> reap the replicas."""
        state = self._deployments.pop(name, None)
        if state is None:
            return
        state.draining = True
        victims = state.replicas
        state.replicas = []
        self._publish_replicas(state)
        self._drain_then_stop(victims, state.config, wait=wait)

    def delete_application(self, app_name: str):
        app = self._apps.pop(app_name, None)
        if not app:
            return False
        for name in app["deployments"]:
            self._retire_deployment(name)
        return True

    def graceful_shutdown(self):
        self._stop.set()
        for name in list(self._deployments):
            # wait=True: the controller actor dies right after this call
            # returns, so background drainers would be killed mid-drain
            self._retire_deployment(name, wait=True)
        self._deployments.clear()
        self._apps.clear()
        import ray_tpu

        with self._lock:
            self._proxy_fleet = False  # in-flight spawns self-abort
            self._routes.clear()
            proxies = list(self._proxies.values())
            self._proxies.clear()
            self._proxy_addrs.clear()
        for h in proxies:
            try:
                ray_tpu.get(h.stop.remote(), timeout=5)
                ray_tpu.kill(h)
            except Exception:
                pass
        return True

    # ------------------------------------------------------- reconciliation

    def _kill_replicas(self, replicas):
        import ray_tpu

        for r in replicas:
            try:
                ray_tpu.kill(r)
            except Exception:
                pass

    def _drain_then_stop(self, replicas, config: DeploymentConfig,
                         wait: bool = False):
        """Drain -> reap: close each victim's request gate, then kill it as
        soon as it reports idle — or at the drain deadline, whichever comes
        first. The caller must already have published a replica set that
        excludes the victims (no new traffic routes to them)."""
        if not replicas:
            return
        import ray_tpu

        drain_s = float(getattr(config, "graceful_shutdown_timeout_s", 10.0))
        poll_s = max(
            0.02, float(getattr(config, "graceful_shutdown_wait_loop_s", 0.1))
        )
        # 1) close the gates (best-effort, one shared deadline: a dead
        # victim must neither stall nor abort the others' drain)
        refs = []
        for r in replicas:
            try:
                # the deadline rides along so replica-side batchers
                # (@serve.batch queues, ContinuousBatchers) can bounce
                # queued work for retry and cut running generations in time
                refs.append(r.prepare_to_drain.remote(drain_s))
            except Exception:
                pass  # already dead: the drain worker reaps it
        try:
            if refs:
                ray_tpu.wait(refs, num_returns=len(refs), timeout=5)
        except Exception:
            pass

        def _drain_worker():
            from ray_tpu.exceptions import GetTimeoutError

            deadline = time.time() + drain_s
            pending = list(replicas)
            while pending and time.time() < deadline:
                still = []
                for r in pending:
                    try:
                        busy = ray_tpu.get(r.num_ongoing.remote(), timeout=2) > 0
                    except GetTimeoutError:
                        busy = True  # all actor slots occupied -> in flight
                    except Exception:
                        busy = False  # already dead: just reap
                    if busy:
                        still.append(r)
                    else:
                        self._kill_replicas([r])
                pending = still
                if pending:
                    time.sleep(poll_s)
            # deadline: force-reap stragglers (bounded drain, never hung)
            self._kill_replicas(pending)

        t = threading.Thread(target=_drain_worker, daemon=True,
                             name="serve-drain")
        t.start()
        with self._lock:
            self._drainers = [d for d in self._drainers if d.is_alive()]
            self._drainers.append(t)
        if wait:
            t.join(timeout=drain_s + 10)

    def _spawn_replica(self, state: _DeploymentState):
        import ray_tpu

        opts = dict(state.config.ray_actor_options)
        opts.setdefault("num_cpus", 1)
        ReplicaCls = ray_tpu.remote(Replica)
        return ReplicaCls.options(max_concurrency=8, **opts).remote(
            state.name, state.func_or_class, state.init_args, state.init_kwargs
        )

    def _reconcile(self, state: _DeploymentState):
        import ray_tpu

        while len(state.replicas) < state.target:
            state.replicas.append(self._spawn_replica(state))
        if len(state.replicas) > state.target:
            victims = state.replicas[state.target :]
            state.replicas = state.replicas[: state.target]
            # publish the shrunken set FIRST so no new request routes to a
            # victim, then drain -> reap in the background (downscale must
            # not drop in-flight requests)
            self._publish_replicas(state)
            self._drain_then_stop(victims, state.config)
        # block until new replicas constructed
        ray_tpu.get([r.ready.remote() for r in state.replicas])
        self._publish_replicas(state)

    def _publish_replicas(self, state: _DeploymentState):
        """Push the live replica set + drain state to handles/proxies over
        the long-poll channel (reference: long_poll.py:68 — controller-side
        broadcast)."""
        from .long_poll import replica_channel
        from ..util import pubsub

        try:
            pubsub.publish(
                replica_channel(state.name),
                {"replicas": list(state.replicas), "draining": state.draining},
            )
        except Exception:
            pass  # handles fall back to their polling refresh

    def _autoscale(self, state: _DeploymentState):
        import ray_tpu

        ac: AutoscalingConfig = state.config.autoscaling_config
        try:
            stats = ray_tpu.get(
                [r.stats.remote() for r in state.replicas], timeout=5
            )
        except Exception:
            return
        total_ongoing = sum(s["ongoing"] for s in stats)
        # decode-aware signal: generation slots + their load, when replicas
        # host ContinuousBatchers (0 otherwise -> pure queue-depth policy)
        batch_slots = sum(s.get("batch_slots", 0) for s in stats)
        batch_load = sum(
            s.get("batch_active", 0) + s.get("batch_queued", 0) for s in stats
        )
        # paged-KV signal: block-pool saturation (0 total -> signal off)
        kv_total = sum(s.get("kv_blocks_total", 0) for s in stats)
        kv_free = sum(s.get("kv_blocks_free", 0) for s in stats)
        desired = calculate_desired_num_replicas(
            ac, total_ongoing, len(state.replicas),
            batch_slots=batch_slots, batch_load=batch_load,
            kv_blocks_total=kv_total, kv_blocks_free=kv_free,
        )
        now = time.time()
        delay = ac.upscale_delay_s if desired > state.target else ac.downscale_delay_s
        if desired != state.target and now - state.last_scale_ts >= delay:
            state.target = desired
            state.last_scale_ts = now
            self._reconcile(state)

    def _harvest_prefix_digest(self, state: _DeploymentState):
        """Fold every replica's advertised prefix digest (hint -> cached
        chain depth, from KVTransferManager via Replica.stats) into one
        bounded per-deployment LRU and publish it on serve:prefix:<name>.
        Longest advertised chain wins a hint; entries from replicas that
        left the set are dropped — the digest only ever names routable
        replicas. Runs on the ~5s heartbeat, gated on
        serve_prefix_affinity (one stats fan-out per beat)."""
        import ray_tpu

        from ray_tpu._private.config import GLOBAL_CONFIG as cfg

        from ..util import pubsub
        from .long_poll import prefix_channel

        replicas = list(state.replicas)
        if not replicas:
            return
        try:
            stats = ray_tpu.get(
                [r.stats.remote() for r in replicas], timeout=5
            )
        except Exception:
            return
        merged = state.prefix_digest
        live = {getattr(r, "_actor_id", None) for r in replicas}
        for r, s in zip(replicas, stats):
            aid = getattr(r, "_actor_id", None)
            for hint, depth in (s.get("prefix_digest") or {}).items():
                cur = merged.get(hint)
                if cur is None or cur[0] not in live or int(depth) >= cur[1]:
                    merged[hint] = (aid, int(depth))
                    merged.move_to_end(hint)
        for hint in [h for h, (aid, _) in merged.items() if aid not in live]:
            del merged[hint]
        cap = max(1, int(cfg.serve_prefix_digest_size))
        while len(merged) > cap:
            merged.popitem(last=False)
        try:
            pubsub.publish(
                prefix_channel(state.name),
                {"digest": {h: [a, d] for h, (a, d) in merged.items()}},
            )
        except Exception:
            pass  # handles just keep their last snapshot

    def get_prefix_digest(self, deployment_name: str) -> Dict[str, tuple]:
        """Pull-path mirror of the serve:prefix push (tests/debugging)."""
        state = self._deployments.get(deployment_name)
        return dict(state.prefix_digest) if state is not None else {}

    def _health_check(self, state: _DeploymentState):
        import ray_tpu

        alive = []
        dead = 0
        for r in state.replicas:
            try:
                ray_tpu.get(r.check_health.remote(), timeout=10)
                alive.append(r)
            except Exception:
                dead += 1
        if dead:
            state.replicas = alive
            self._reconcile(state)  # replace dead replicas

    def _reconcile_loop(self):
        last_heartbeat = 0.0
        while not self._stop.is_set():
            time.sleep(0.25)
            # heartbeat republish: watchers gauge push-pipeline health by
            # data recency, so a periodic re-publish both self-heals a
            # dropped publish and keeps healthy() honest (long_poll.py)
            heartbeat = time.time() - last_heartbeat >= 5.0
            if heartbeat:
                last_heartbeat = time.time()
            for state in list(self._deployments.values()):
                try:
                    if state.config.autoscaling_config is not None:
                        self._autoscale(state)
                    self._health_check(state)
                    if heartbeat:
                        self._publish_replicas(state)
                        from ray_tpu._private.config import (
                            GLOBAL_CONFIG as _cfg,
                        )

                        if _cfg.serve_prefix_affinity:
                            self._harvest_prefix_digest(state)
                except Exception:
                    pass
            if heartbeat:
                try:
                    self._ensure_proxies()
                except Exception:
                    pass
