"""DAGDriver: HTTP front door for deployment GRAPHS, with http adapters.

Reference parity: serve/drivers.py:30 (DAGDriver — a driver deployment
routing HTTP into bound DAGs, one route prefix per dag) +
serve/http_adapters.py (functions shaping the raw request into the model's
input). Compose with Deployment.bind graphs:

    @serve.deployment
    def preprocess(x): ...
    @serve.deployment
    class Model:
        def __call__(self, x): ...

    graph = Model.bind(preprocess.bind())
    serve.run(
        serve.DAGDriver.bind({"/classify": graph, "/echo": other},
                             http_adapter=serve.http_adapters.json_request),
        route_prefix="/",
    )

The driver also answers python-side calls: handle.predict.remote(x[,
route]) hits the dag directly, skipping HTTP.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, Optional, Union

from .http_proxy import Request, Response


class http_adapters:
    """Request -> model-input shapers (reference: serve/http_adapters.py).
    Any callable(Request) -> Any works; these are the stock ones."""

    @staticmethod
    def json_request(request: Request) -> Any:
        """The parsed JSON (or raw) body — the default adapter."""
        return request.body

    @staticmethod
    def query_params(request: Request) -> Dict[str, Any]:
        return dict(request.query)

    @staticmethod
    def raw_request(request: Request) -> Request:
        return request


class _DAGDriverImpl:
    """The deployment body behind serve.DAGDriver."""

    # serve.run flips pass_request for this class (raw Request in)
    _serve_ingress = True

    def __init__(
        self,
        dags: Union[Any, Dict[str, Any]],
        http_adapter: Optional[Callable[[Request], Any]] = None,
    ):
        if not isinstance(dags, dict):
            dags = {"/": dags}
        # longest prefix first, "/" normalized
        self._routes = {
            ("/" + k.strip("/")).rstrip("/") or "/": v for k, v in dags.items()
        }
        self._order = sorted(self._routes, key=len, reverse=True)
        self._adapter = http_adapter or http_adapters.json_request

    def _match(self, subpath: str):
        path = "/" + subpath.strip("/")
        for prefix in self._order:
            if path == prefix or prefix == "/" or path.startswith(prefix + "/"):
                return self._routes[prefix]
        return None

    def __call__(self, request: Request):
        handle = self._match(request.subpath)
        if handle is None:
            return Response(404, {"detail": f"no dag at {request.subpath!r}"})
        return handle.remote(self._adapter(request)).result()

    def predict(self, value: Any, route: str = "/"):
        """Python-side entry: run a dag directly (reference:
        DAGDriver.predict)."""
        handle = self._routes.get(("/" + route.strip("/")).rstrip("/") or "/")
        if handle is None:
            raise ValueError(f"no dag bound at route {route!r}")
        return handle.remote(value).result()
