"""Live weight hot-swap: the learner->replica weight plane.

Reference parity: Serve's in-place deployment updates + the weight-sync
half of RLlib's new-stack Learner (learner pushes versioned weights,
samplers adopt them without a restart) — rebuilt TPU-native on two planes
this repo already has:

  publish   `WeightPublisher.publish(params)` flattens the param tree to
            leaves, ships every leaf as BULK-PLANE objects (`ray_tpu.put`;
            leaves larger than serve_weight_chunk_mb split into chunks so
            pulls stripe across senders and one giant embedding can never
            serialize the swap), and pushes a version MANIFEST — leaf
            paths, shapes, dtypes, sha1 digests, content-addressed
            per-version keys, object refs — over the head's pubsub
            channel `serve:weights:<deployment>` (long_poll.py
            weights_channel). The manifest is tiny; the weights ride the
            zero-copy slab senders like any other large object.

  subscribe `WeightSubscriber` long-polls the channel (same daemon-thread
            shape as long_poll.ReplicaWatcher), pulls the leaves, verifies
            EVERY leaf (length + sha1 — a truncated or corrupt pull fails
            verification, the swap aborts whole, and the replica keeps
            serving its previous version intact: never a half-swapped
            tree; counted in `weight_swap_fallbacks_total`), re-places
            each leaf by the REPLICA'S OWN partition rules (device_put
            onto the current leaf's sharding — a dp=8 learner can feed a
            tp=4 replica), and swaps between engine steps via
            `ContinuousBatcher.run_on_loop(engine.set_params)`.

Swap semantics (PagedDecodeEngine.set_params): live slots are preempted
and readmitted so their continuations recompute under the new weights —
in-flight streams survive (no drop, added latency only) and every
post-swap token is greedy-identical to a fresh engine loaded with the new
weights. The prefix cache flushes and the transfer signature re-derives
with the new version, so KV minted under old weights — local or
cross-replica — can never serve new-weight traffic (stale chain keys are
disjoint by construction, not merely checked).

Fault injection: the `weight_swap_drop:<nth|rand:p>` directive
(_private/faults.py) truncates the selected pull before verification —
the chaos suite proves the old version keeps serving.
"""

from __future__ import annotations

import hashlib
import threading
import time
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from .._private.config import GLOBAL_CONFIG
from .._private import faults
from ..util import pubsub
from ..util.metrics import weight_swap_fallbacks_counter
from .long_poll import weights_channel

# wire-format identity: bumped only on incompatible manifest changes
WEIGHT_WIRE_SIG = "ray_tpu.weight_swap.v1"


class WeightSwapError(RuntimeError):
    """A pulled version failed verification (truncated/corrupt leaf,
    manifest mismatch). The subscriber catches it: the OLD version keeps
    serving and the failure counts as a fallback, never a half-swap."""


def _np_dtype(name: str) -> np.dtype:
    try:
        return np.dtype(name)
    except TypeError:
        # bfloat16 & friends live in ml_dtypes (a jax dependency); their
        # names register with numpy on import
        import ml_dtypes

        return np.dtype(getattr(ml_dtypes, name))


def _flatten_with_paths(params) -> Tuple[List[str], List[Any], Any]:
    """Stable (path, leaf) flattening. Paths are the cross-process leaf
    identity: the subscriber rebuilds against ITS OWN tree structure by
    path match, so no treedef ever rides the wire."""
    import jax

    flat, treedef = jax.tree_util.tree_flatten_with_path(params)
    paths = [jax.tree_util.keystr(p) for p, _ in flat]
    return paths, [leaf for _, leaf in flat], treedef


def content_key(deployment: str, version: int, path: str) -> str:
    """Content-addressed per-version leaf key: two publishers of the same
    deployment mint identical keys for identical (version, leaf), and keys
    from different versions/deployments are disjoint by construction —
    the weight-plane analogue of the KV plane's transfer_keys chain."""
    h = hashlib.sha1()
    h.update(f"{WEIGHT_WIRE_SIG}|{deployment}|v{int(version)}|{path}".encode())
    return h.hexdigest()


class WeightPublisher:
    """Learner-side half: ships a param tree as versioned bulk-plane
    objects + a pubsub manifest. One publisher per deployment per learner
    process; publish() is cheap relative to a train step (host gather +
    N object puts).

    The publisher RETAINS the refs of the last two published versions:
    pubsub is snapshot-semantics (late subscribers see only the latest
    manifest) but a replica may still be mid-pull on version N when N+1
    publishes — dropping N's refs under it would turn a healthy swap into
    a fallback."""

    def __init__(
        self,
        deployment: str,
        *,
        chunk_bytes: Optional[int] = None,
        model_id: str = "",
    ):
        self.deployment = str(deployment)
        self.model_id = str(model_id)
        if chunk_bytes is None:
            chunk_bytes = int(GLOBAL_CONFIG.serve_weight_chunk_mb) * (1 << 20)
        self.chunk_bytes = int(chunk_bytes)
        self.version = 0
        self.published_bytes = 0
        self._retained: List[Tuple[int, List[Any]]] = []

    def publish(self, params, version: Optional[int] = None) -> int:
        """Ship `params` as the next version; returns the version number.
        Blocks until every leaf is in the object store (so the manifest
        never references objects that do not exist yet)."""
        import ray_tpu

        version = int(version) if version is not None else self.version + 1
        if version <= self.version:
            raise ValueError(
                f"version must advance: have {self.version}, got {version}"
            )
        paths, leaves, _ = _flatten_with_paths(params)
        entries: List[Dict[str, Any]] = []
        refs_live: List[Any] = []
        total = 0
        cb = self.chunk_bytes
        for path, leaf in zip(paths, leaves):
            arr = np.ascontiguousarray(np.asarray(leaf))
            buf = arr.tobytes()
            n = len(buf)
            if cb > 0 and n > cb:
                chunks = [buf[i:i + cb] for i in range(0, n, cb)]
            else:
                chunks = [buf]
            refs = [ray_tpu.put(c) for c in chunks]
            refs_live.extend(refs)
            entries.append({
                "path": path,
                "key": content_key(self.deployment, version, path),
                "shape": tuple(int(d) for d in arr.shape),
                "dtype": str(arr.dtype),
                "nbytes": n,
                "sha1": hashlib.sha1(buf).hexdigest(),
                "refs": refs,
            })
            total += n
        manifest = {
            "sig": WEIGHT_WIRE_SIG,
            "deployment": self.deployment,
            "model_id": self.model_id,
            "version": version,
            "total_bytes": total,
            "entries": entries,
        }
        pubsub.publish(weights_channel(self.deployment), manifest)
        self.version = version
        self.published_bytes += total
        self._retained.append((version, refs_live))
        while len(self._retained) > 2:
            self._retained.pop(0)
        return version


def pull_manifest(manifest: Dict[str, Any]) -> Tuple[Dict[str, np.ndarray], int]:
    """Pull + verify every leaf of a published version. Returns
    ({path: host array}, bytes pulled). Raises WeightSwapError on ANY
    verification failure — all-or-nothing is the whole contract.

    The `weight_swap_drop` fault directive hooks here: a selected pull
    truncates one leaf's bytes before verification, which is
    indistinguishable from a mid-flight sender death — exactly the
    failure the abort-whole path exists for."""
    import ray_tpu

    if not isinstance(manifest, dict) or manifest.get("sig") != WEIGHT_WIRE_SIG:
        raise WeightSwapError(f"bad manifest sig: {manifest!r:.80}")
    drop = faults.weight_swap_action() if faults.ACTIVE else None
    out: Dict[str, np.ndarray] = {}
    total = 0
    for i, entry in enumerate(manifest["entries"]):
        bufs = ray_tpu.get(list(entry["refs"]))
        data = b"".join(bufs)
        if drop == "drop" and i == 0:
            data = data[: len(data) // 2]
        if len(data) != int(entry["nbytes"]):
            raise WeightSwapError(
                f"leaf {entry['path']} truncated: {len(data)} of "
                f"{entry['nbytes']} bytes"
            )
        if hashlib.sha1(data).hexdigest() != entry["sha1"]:
            raise WeightSwapError(f"leaf {entry['path']} digest mismatch")
        arr = np.frombuffer(data, _np_dtype(entry["dtype"]))
        out[entry["path"]] = arr.reshape(entry["shape"])
        total += len(data)
    return out, total


class WeightSubscriber:
    """Replica-side half: adopt published versions into one engine.

    With `batcher` given, the swap executes on the batcher's loop thread
    (run_on_loop) BETWEEN engine steps — the only thread allowed to touch
    admit/step state. Without one (bare-engine rollout workers), the
    caller owns the engine's threading and apply() swaps directly.

    `start()` (or auto_start=True with the serve_weight_swap flag on)
    spawns a daemon watcher thread long-polling the weights channel —
    the long_poll.ReplicaWatcher shape, one per subscriber because each
    adopts into its own engine."""

    def __init__(
        self,
        engine,
        deployment: str,
        *,
        batcher=None,
        auto_start: bool = False,
        poll_timeout_s: Optional[float] = None,
        swap_timeout_s: float = 60.0,
    ):
        self.engine = engine
        self.batcher = batcher
        self.deployment = str(deployment)
        self.channel = weights_channel(self.deployment)
        self.version = int(getattr(engine, "weight_version", 0))
        self.swaps = 0
        self.fallbacks = 0
        self.bytes_pulled = 0
        self._fallback_counter = weight_swap_fallbacks_counter()
        self._poll_timeout = float(
            GLOBAL_CONFIG.serve_weight_poll_timeout_s
            if poll_timeout_s is None else poll_timeout_s
        )
        self._swap_timeout = float(swap_timeout_s)
        self._seq = 0
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._lock = threading.Lock()
        tel = getattr(engine, "_tel", None)
        self._rec = tel.recorder if tel is not None else None
        if auto_start and bool(GLOBAL_CONFIG.serve_weight_swap):
            self.start()

    # ------------------------------------------------------------ lifecycle

    def start(self) -> "WeightSubscriber":
        if self._thread is None or not self._thread.is_alive():
            self._stop.clear()
            self._thread = threading.Thread(
                target=self._run, daemon=True,
                name=f"weight-swap:{self.channel}",
            )
            self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()

    def _run(self) -> None:
        while not self._stop.is_set():
            try:
                item = pubsub.poll(
                    self.channel, self._seq, timeout=self._poll_timeout
                )
            except Exception:
                # head unreachable / shutting down: back off, re-arm
                self._stop.wait(1.0)
                continue
            if item is None:
                continue
            self._seq, manifest = item
            try:
                self.apply(manifest)
            except Exception:
                # apply() already accounted the fallback; a bug in the
                # swap path must not kill the watcher
                pass

    def poll_once(self, timeout: float = 0.0) -> bool:
        """One synchronous poll+apply (tests, manual adoption). Returns
        True when a NEW version was adopted."""
        item = pubsub.poll(self.channel, self._seq, timeout=timeout)
        if item is None:
            return False
        self._seq, manifest = item
        return self.apply(manifest)

    # ------------------------------------------------------------- adoption

    def _rebuild(self, by_path: Dict[str, np.ndarray]):
        """Reassemble the pulled leaves into THIS engine's tree structure
        and placement: path-match against the current params, device_put
        each leaf onto the current leaf's sharding (= the replica's own
        partition rules — the learner's layout never leaks in)."""
        import jax
        import jax.numpy as jnp

        flat, treedef = jax.tree_util.tree_flatten_with_path(
            self.engine.params
        )
        have = {jax.tree_util.keystr(p) for p, _ in flat}
        want = set(by_path)
        if have != want:
            missing = sorted(want ^ have)[:4]
            raise WeightSwapError(
                f"param tree mismatch (paths differ, e.g. {missing})"
            )
        new_leaves = []
        for p, cur in flat:
            arr = by_path[jax.tree_util.keystr(p)]
            if tuple(arr.shape) != tuple(np.shape(cur)):
                raise WeightSwapError(
                    f"leaf {jax.tree_util.keystr(p)} shape "
                    f"{tuple(arr.shape)} != engine's {tuple(np.shape(cur))}"
                )
            sharding = getattr(cur, "sharding", None)
            if sharding is not None:
                new_leaves.append(jax.device_put(arr, sharding))
            else:
                new_leaves.append(jnp.asarray(arr))
        return jax.tree_util.tree_unflatten(treedef, new_leaves)

    def apply(self, manifest: Dict[str, Any]) -> bool:
        """Adopt one published version; returns True on a swap, False
        when the manifest is stale or the pull failed verification (the
        fallback: OLD version keeps serving, counted)."""
        with self._lock:
            version = int(manifest.get("version", 0) or 0)
            if version <= self.version:
                return False
            t0 = time.monotonic()
            try:
                by_path, nbytes = pull_manifest(manifest)
                tree = self._rebuild(by_path)
            except Exception as e:  # noqa: BLE001 — any failure = fallback
                self.fallbacks += 1
                self._fallback_counter.inc()
                if self._rec is not None:
                    self._rec.record(
                        "weight_swap_fallback",
                        dur=time.monotonic() - t0,
                        args={"version": version, "error": repr(e)[:160]},
                    )
                return False

            def _swap():
                return self.engine.set_params(
                    tree, version=version, bytes_pulled=nbytes
                )

            if self.batcher is not None:
                self.batcher.run_on_loop(_swap, timeout_s=self._swap_timeout)
            else:
                _swap()
            self.version = version
            self.swaps += 1
            self.bytes_pulled += nbytes
            if self._rec is not None:
                self._rec.record(
                    "weight_pull", dur=time.monotonic() - t0,
                    args={"version": version, "bytes": nbytes},
                )
            return True

    def stats(self) -> Dict[str, Any]:
        return {
            "weight_version": self.version,
            "weight_swaps": self.swaps,
            "weight_swap_fallbacks": self.fallbacks,
            "weight_bytes_pulled": self.bytes_pulled,
        }
