"""Queue-depth + batch-saturation autoscaling policy.

Reference parity: serve/_private/autoscaling_policy.py:9
(calculate_desired_num_replicas: desired = ongoing / target_per_replica,
clamped to [min, max]).

Decode-aware extension (ROADMAP serving remainder): replicas hosting a
`serve.ContinuousBatcher` report generation-slot occupancy next to queue
depth (Replica.stats "batch_*" keys). A generation-bound deployment whose
slots are saturated is at capacity even while its request queue is still
shallow — per-token streaming means ongoing-request counts understate load
until latency has already degraded. The desired replica count is the max of
the queue-depth target and the slot-occupancy target.

Paged-KV extension: replicas over a PagedDecodeEngine additionally report
block-pool headroom ("kv_blocks_total"/"kv_blocks_free"). Block saturation
is a THIRD scale signal, independent of the other two: long-prompt traffic
can exhaust the pool (forcing preemption/recompute churn) while slots sit
free and the queue stays shallow. Desired replicas is the max of all three
targets.
"""

from __future__ import annotations

import math

from .deployment import AutoscalingConfig


def calculate_desired_num_replicas(
    config: AutoscalingConfig,
    total_ongoing_requests: float,
    current_replicas: int,
    *,
    batch_slots: float = 0.0,
    batch_load: float = 0.0,
    kv_blocks_total: float = 0.0,
    kv_blocks_free: float = 0.0,
) -> int:
    """batch_slots: total generation slots across the deployment's current
    replicas; batch_load: active + queued generations against those slots.
    kv_blocks_total/kv_blocks_free: aggregate paged-KV pool size and
    headroom across the replicas. All default to 0 (no batcher / no paged
    engine -> the corresponding signal is off)."""
    if current_replicas == 0:
        return config.min_replicas
    desired = math.ceil(total_ongoing_requests / max(config.target_ongoing_requests, 1e-9))
    if batch_slots > 0:
        # scale so the per-replica slot load lands at target occupancy:
        # slots_per_replica stays constant, so desired_batch satisfies
        # batch_load / (desired_batch * slots_per_replica) <= target
        slots_per_replica = batch_slots / current_replicas
        target = max(config.target_batch_occupancy, 1e-9)
        desired_batch = math.ceil(batch_load / (slots_per_replica * target))
        desired = max(desired, desired_batch)
    if kv_blocks_total > 0:
        # same shape for block saturation: blocks_per_replica is a
        # replica-count invariant, so desired_kv spreads the in-use blocks
        # until per-replica utilization lands at target_kv_utilization
        blocks_per_replica = kv_blocks_total / current_replicas
        kv_used = max(0.0, kv_blocks_total - kv_blocks_free)
        target = max(config.target_kv_utilization, 1e-9)
        desired_kv = math.ceil(kv_used / (blocks_per_replica * target))
        desired = max(desired, desired_kv)
    return max(config.min_replicas, min(config.max_replicas, desired))
