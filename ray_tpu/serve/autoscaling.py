"""Queue-depth autoscaling policy.

Reference parity: serve/_private/autoscaling_policy.py:9
(calculate_desired_num_replicas: desired = ongoing / target_per_replica,
clamped to [min, max]).
"""

from __future__ import annotations

import math

from .deployment import AutoscalingConfig


def calculate_desired_num_replicas(
    config: AutoscalingConfig, total_ongoing_requests: float, current_replicas: int
) -> int:
    if current_replicas == 0:
        return config.min_replicas
    desired = math.ceil(total_ongoing_requests / max(config.target_ongoing_requests, 1e-9))
    return max(config.min_replicas, min(config.max_replicas, desired))
