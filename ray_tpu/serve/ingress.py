"""@serve.ingress: route decorators on a deployment class.

Reference parity: serve/api.py:169 — `@serve.ingress(fastapi_app)` mounts a
FastAPI app on a deployment so one deployment serves many routes with path
parameters, per-method handlers, and typed responses. This deployment ships
a dependency-free equivalent: `serve.Router()` plays the FastAPI app's
role (method decorators + path templates), and `@serve.ingress(router)`
installs a dispatching __call__ on the deployment class.

    router = serve.Router()

    @serve.deployment
    @serve.ingress(router)
    class Api:
        @router.get("/items/{item_id}")
        def get_item(self, item_id: str):
            return {"id": item_id}

        @router.post("/items")
        def create(self, body):
            return Response(201, body)

    serve.run(Api.bind(), route_prefix="/api")

Handler parameter binding (by name, FastAPI-style):
- a path-template name ({item_id}) binds the captured segment, cast via
  the parameter's int/float annotation when present
- `request` binds the full http_proxy.Request
- `body` binds the parsed request body
- any other name binds the query parameter of that name (cast via
  annotation), or its default when absent
Return values follow the proxy contract (str/bytes/JSON/Streaming), plus
`Response(status, body)` for explicit status codes; raise
`HTTPException(status, detail)` for error responses.
"""

from __future__ import annotations

import inspect
from typing import Any, Callable, Dict, List, Optional, Tuple

from .http_proxy import Request, Response

_METHODS = ("get", "post", "put", "delete", "patch", "head", "options")


class HTTPException(Exception):
    """Raise inside an ingress handler to return a specific status
    (reference: fastapi.HTTPException, honored by serve ingress)."""

    def __init__(self, status_code: int, detail: Any = None):
        super().__init__(detail)
        self.status_code = int(status_code)
        self.detail = detail


class _IngressRoute:
    __slots__ = ("method", "parts", "fn", "pattern")

    def __init__(self, method: str, pattern: str, fn: Callable):
        self.method = method.upper()
        self.pattern = pattern
        self.parts = [p for p in pattern.strip("/").split("/") if p]
        self.fn = fn

    def match(self, segments: List[str]) -> Optional[Dict[str, str]]:
        if len(segments) != len(self.parts):
            return None
        params: Dict[str, str] = {}
        for pat, seg in zip(self.parts, segments):
            if pat.startswith("{") and pat.endswith("}"):
                params[pat[1:-1]] = seg
            elif pat != seg:
                return None
        return params


class Router:
    """Collects (method, path template) -> handler while the deployment
    class body executes (the FastAPI-app stand-in)."""

    def __init__(self):
        self.routes: List[_IngressRoute] = []

    def _register(self, method: str, pattern: str):
        def deco(fn):
            self.routes.append(_IngressRoute(method, pattern, fn))
            return fn

        return deco

    def match(self, method: str, subpath: str) -> Optional[Tuple[Callable, Dict[str, str]]]:
        segments = [s for s in subpath.strip("/").split("/") if s]
        method_matched = False
        for route in self.routes:
            params = route.match(segments)
            if params is None:
                continue
            if route.method != method.upper():
                method_matched = True
                continue
            return route.fn, params
        if method_matched:
            raise HTTPException(405, "method not allowed")
        return None


for _m in _METHODS:
    setattr(
        Router,
        _m,
        (lambda m: lambda self, pattern: self._register(m, pattern))(_m),
    )


def _cast(value: str, annotation) -> Any:
    if annotation in (int, float):
        try:
            return annotation(value)
        except ValueError:
            raise HTTPException(422, f"invalid {annotation.__name__}: {value!r}")
    return value


def _bind_args(fn: Callable, request: Request, path_params: Dict[str, str]) -> dict:
    kwargs: Dict[str, Any] = {}
    sig = inspect.signature(fn)
    for name, param in list(sig.parameters.items())[1:]:  # skip self
        if name == "request":
            kwargs[name] = request
        elif name == "body":
            kwargs[name] = request.body
        elif name in path_params:
            kwargs[name] = _cast(path_params[name], param.annotation)
        elif name in request.query:
            value = request.query[name]
            if isinstance(value, list):
                # repeated query param (?x=1&x=2): scalar handlers get the
                # LAST value (FastAPI semantics); a list annotation gets all
                if param.annotation is list:
                    kwargs[name] = value
                    continue
                value = value[-1]
            kwargs[name] = _cast(str(value), param.annotation)
        elif param.default is not inspect.Parameter.empty:
            kwargs[name] = param.default
        else:
            raise HTTPException(422, f"missing required parameter {name!r}")
    return kwargs


def ingress(router: Router):
    """Class decorator installing a router-dispatching __call__. The
    deployment automatically receives raw Requests (serve.run detects
    `_serve_ingress` and sets pass_request)."""
    if not isinstance(router, Router):
        raise TypeError("serve.ingress takes a serve.Router()")

    def deco(cls):
        if not inspect.isclass(cls):
            raise TypeError("@serve.ingress decorates a deployment CLASS")

        def __call__(self, request: Request):
            try:
                matched = router.match(request.method, request.subpath)
                if matched is None:
                    raise HTTPException(404, "no matching route")
                fn, path_params = matched
                return fn(self, **_bind_args(fn, request, path_params))
            except HTTPException as e:
                body = {"detail": e.detail} if e.detail is not None else {}
                return Response(e.status_code, body)

        cls.__call__ = __call__
        cls._serve_ingress = True
        cls._serve_router = router
        return cls

    return deco
