"""DeploymentHandle: the Python-native ingress to a deployment.

Reference parity: serve/handle.py (DeploymentHandle/DeploymentResponse) with
the router's power-of-two-choices replica selection (serve/_private/router.py:370)
done handle-side over locally-tracked in-flight counts.

Robustness layer (request-lifecycle hardening):
  - replica-death / replica-draining retries re-route with CAPPED
    EXPONENTIAL BACKOFF + JITTER instead of hot-looping against a replica
    set the controller is still rebuilding
  - a per-deployment CIRCUIT BREAKER trips after consecutive failures and
    fails calls fast with DeploymentUnavailableError (the HTTP proxy maps
    it to 503 + Retry-After) while the controller restarts replicas; a
    half-open probe closes it again once a call succeeds
"""

from __future__ import annotations

import random
import threading
import time
from collections import deque
from typing import Any, Optional

CONTROLLER_NAME = "SERVE_CONTROLLER"


class DeploymentUnavailableError(RuntimeError):
    """The deployment cannot take requests right now (no live replicas,
    draining for removal, or its circuit breaker is open). Transient by
    design: callers should retry after `retry_after_s`; the HTTP proxy
    translates it to 503 + Retry-After."""

    def __init__(self, deployment_name: str, reason: str,
                 retry_after_s: float = 1.0):
        super().__init__(
            f"deployment {deployment_name!r} unavailable: {reason}"
        )
        self.deployment_name = deployment_name
        self.reason = reason
        self.retry_after_s = retry_after_s


class _CircuitBreaker:
    """Per-deployment failure gate (reference intent: the router's backoff
    on UNAVAILABLE replicas; shape follows the classic closed -> open ->
    half-open machine). Thread-safe: proxy pool threads share one breaker
    per deployment."""

    def __init__(self, failure_threshold: int, reset_s: float):
        self.failure_threshold = max(1, int(failure_threshold))
        self.reset_s = float(reset_s)
        self._lock = threading.Lock()
        self._consecutive = 0
        self._opened_at: Optional[float] = None
        self._probing_since: Optional[float] = None

    def allow(self) -> bool:
        """True if a call may proceed (closed, or half-open probe slot)."""
        with self._lock:
            if self._opened_at is None:
                return True
            now = time.time()
            if now - self._opened_at < self.reset_s:
                return False
            # half-open: one probe at a time — but a probe slot EXPIRES
            # after reset_s so a caller that never reports back (fire-and-
            # forget .remote() with no .result()) can't brick the breaker
            if (self._probing_since is None
                    or now - self._probing_since >= self.reset_s):
                self._probing_since = now
                return True
            return False

    def record_success(self) -> None:
        with self._lock:
            self._consecutive = 0
            self._opened_at = None
            self._probing_since = None

    def record_failure(self) -> None:
        with self._lock:
            self._consecutive += 1
            if self._probing_since is not None:
                # failed probe re-opens a fresh window
                self._opened_at = time.time()
                self._probing_since = None
            elif (self._opened_at is None
                  and self._consecutive >= self.failure_threshold):
                self._opened_at = time.time()

    def release_probe(self) -> None:
        """Give back a probe slot without judging the deployment either way
        (e.g. the probe call timed out caller-side): the next allow() may
        probe again immediately."""
        with self._lock:
            if self._probing_since is not None:
                self._probing_since = None
                if self._opened_at is not None:
                    # make the next probe eligible now, not reset_s from now
                    self._opened_at = time.time() - self.reset_s

    @property
    def is_open(self) -> bool:
        with self._lock:
            return self._opened_at is not None

    def seconds_until_probe(self) -> float:
        with self._lock:
            if self._opened_at is None:
                return 0.0
            return max(0.0, self.reset_s - (time.time() - self._opened_at))


_breakers: dict = {}
_breakers_lock = threading.Lock()


def get_breaker(deployment_name: str) -> _CircuitBreaker:
    """One breaker per (process, deployment) — handles are minted freely
    (attribute access, options(), unpickling), so breaker state must not
    live on the handle itself."""
    with _breakers_lock:
        b = _breakers.get(deployment_name)
        if b is None:
            from ray_tpu._private.config import GLOBAL_CONFIG as cfg

            b = _breakers[deployment_name] = _CircuitBreaker(
                cfg.serve_breaker_failure_threshold, cfg.serve_breaker_reset_s
            )
        return b


def _reset_breakers() -> None:
    """Test/shutdown hook: forget breaker state between serve sessions."""
    with _breakers_lock:
        _breakers.clear()


def _backoff_s(attempt: int) -> float:
    """Capped exponential backoff with full jitter (attempt is 0-based)."""
    from ray_tpu._private.config import GLOBAL_CONFIG as cfg

    cap = min(
        float(cfg.serve_handle_backoff_max_s),
        float(cfg.serve_handle_backoff_base_s) * (2 ** attempt),
    )
    return random.uniform(cap / 2, cap)


def _retryable_errors() -> tuple:
    from ray_tpu.exceptions import (
        ActorDiedError,
        ActorUnavailableError,
        WorkerCrashedError,
    )

    from .replica import ReplicaDrainingError

    return (ActorDiedError, ActorUnavailableError, WorkerCrashedError,
            ReplicaDrainingError)


class DeploymentResponse:
    def __init__(self, ref, handle=None, call=None):
        self._ref = ref
        self._handle = handle
        self._call = call  # (args, kwargs) for replica-death retry
        self.retries = 0   # re-route attempts this response consumed
        # the replica actor that served this call: streaming results
        # (ReplicaStreamHandle) must be pulled from the replica that holds
        # the live stream, not re-routed
        self.replica = None

    def result(self, timeout_s: Optional[float] = None):
        import ray_tpu

        from ray_tpu.exceptions import GetTimeoutError, PlaneRequestTimeout

        breaker = (
            get_breaker(self._handle.deployment_name)
            if self._handle is not None else None
        )
        # timeout_s bounds the WHOLE logical call — backoff sleeps and
        # every retry's get() draw down one shared deadline, so a caller
        # asking for 5s never blocks (attempts+1) x 5s
        deadline = (
            None if timeout_s is None else time.monotonic() + timeout_s
        )

        def _remaining():
            return (
                None if deadline is None else deadline - time.monotonic()
            )

        try:
            out = ray_tpu.get(self._ref, timeout=timeout_s)
            if breaker is not None:
                breaker.record_success()
            return out
        except GetTimeoutError:
            # no verdict on the deployment — give any probe slot back so
            # the breaker can't wedge half-open
            if breaker is not None:
                breaker.release_probe()
            raise
        except PlaneRequestTimeout:
            # a plane blip, NOT a replica verdict: the data plane lost the
            # request/reply pair (black-holed link, wedged head handler) —
            # the replica may well have computed the answer. Retry the SAME
            # replica once (idempotent re-execution / head-side rid dedup
            # make the duplicate safe), then fall into the re-route path.
            # Never feeds the breaker: an unresponsive plane says nothing
            # about the deployment's health.
            if breaker is not None:
                breaker.release_probe()
            if (self._handle is None or self._call is None
                    or self.replica is None):
                raise
            args, kwargs = self._call
            try:
                self.retries += 1
                retry = self.replica.handle_request.remote(
                    self._handle.method_name, args, kwargs,
                    model_id=self._handle.multiplexed_model_id,
                )
                out = ray_tpu.get(retry, timeout=_remaining())
                if breaker is not None:
                    breaker.record_success()
                return out
            except (PlaneRequestTimeout,) + _retryable_errors() as e:
                # same replica unreachable twice (or genuinely dead): now
                # re-route like a replica failure
                return self._reroute(e, breaker, _remaining)
        except _retryable_errors() as first_exc:
            # the chosen replica died mid-call or was draining (e.g. torn
            # down by a redeploy that raced this request): re-route
            # immediately — death is a verdict, unlike a plane blip above
            if self._handle is None or self._call is None:
                raise
            return self._reroute(first_exc, breaker, _remaining)
        except Exception:
            # the replica answered with a user-code error: the deployment
            # is SERVING — close/feed the breaker as a success so an open
            # breaker's probe that reaches user code recovers the circuit
            if breaker is not None:
                breaker.record_success()
            raise

    def _reroute(self, first_exc, breaker, _remaining):
        """Re-route the logical call against a refreshed replica set with
        spaced, bounded attempts (reference: the router retries system
        failures transparently, serve/_private/router.py — plus backoff so
        a crash-looping deployment isn't hammered). The breaker samples the
        LOGICAL call once at the end — a transient drain race retried to
        success must not march the breaker toward open, and a final failure
        that is merely a plane timeout releases the probe instead of
        recording a failure (plane blips never trip the circuit)."""
        import ray_tpu

        from ray_tpu._private.config import GLOBAL_CONFIG as cfg
        from ray_tpu.exceptions import GetTimeoutError, PlaneRequestTimeout

        args, kwargs = self._call
        attempts = max(0, int(cfg.serve_handle_retry_attempts))
        last_exc = first_exc
        for attempt in range(attempts):
            left = _remaining()
            if left is not None and left <= 0:
                break
            pause = _backoff_s(attempt)
            time.sleep(pause if left is None else min(pause, left))
            self.retries += 1
            try:
                self._handle._refresh(force=True)
                retry = self._handle.remote(*args, **kwargs)
                out = ray_tpu.get(retry.ref, timeout=_remaining())
                self.replica = retry.replica
                breaker.record_success()
                return out
            except GetTimeoutError:
                breaker.release_probe()
                raise
            except (PlaneRequestTimeout,) + _retryable_errors() as e:
                last_exc = e
            except DeploymentUnavailableError:
                # breaker opened (or replicas gone) while we retried:
                # fail fast — the proxy turns this into 503
                raise
        if isinstance(last_exc, PlaneRequestTimeout):
            breaker.release_probe()
        else:
            breaker.record_failure()
        raise last_exc

    @property
    def ref(self):
        return self._ref

    def iter_stream(self, timeout_s: Optional[float] = None,
                    pull_max_chunks: Optional[int] = None,
                    pull_wait_s: Optional[float] = None):
        """Iterate a streaming result without going through HTTP: resolves
        the call to its ReplicaStreamHandle, then pulls chunks from the
        serving replica as they are produced. Raises TypeError if the
        deployment returned a non-streaming result."""
        import ray_tpu

        from ray_tpu._private.config import GLOBAL_CONFIG as cfg

        from .replica import ReplicaStreamHandle

        sh = self.result(timeout_s=timeout_s)
        if not isinstance(sh, ReplicaStreamHandle):
            raise TypeError(
                f"deployment returned {type(sh).__name__}, not a stream — "
                "iter_stream needs a non-buffered StreamingResponse"
            )
        n = int(cfg.serve_stream_pull_max_chunks
                if pull_max_chunks is None else pull_max_chunks)
        wait = float(cfg.serve_stream_pull_wait_s
                     if pull_wait_s is None else pull_wait_s)
        done = False
        try:
            # timeout_s bounds PROGRESS, not just one pull: a request
            # parked behind a full batch yields empty pulls forever — the
            # idle deadline turns that into GetTimeoutError like any other
            # stalled call
            idle_deadline = (
                None if timeout_s is None
                else time.monotonic() + float(timeout_s)
            )
            while True:
                chunks, done = ray_tpu.get(
                    self.replica.stream_next.remote(sh.stream_id, n, wait),
                    timeout=timeout_s,
                )
                yield from chunks
                if done:
                    return
                if chunks:
                    idle_deadline = (
                        None if timeout_s is None
                        else time.monotonic() + float(timeout_s)
                    )
                elif (idle_deadline is not None
                      and time.monotonic() >= idle_deadline):
                    from ray_tpu.exceptions import GetTimeoutError

                    raise GetTimeoutError(
                        f"stream produced nothing for {timeout_s}s"
                    )
        finally:
            if not done:
                # consumer stopped early (break / GC): free the replica's
                # decode slot instead of generating into the void
                try:
                    self.replica.stream_cancel.remote(sh.stream_id)
                except Exception:
                    pass


class DeploymentHandle:
    def __init__(
        self,
        deployment_name: str,
        method_name: str = "__call__",
        multiplexed_model_id: str = "",
    ):
        self.deployment_name = deployment_name
        self.method_name = method_name
        self.multiplexed_model_id = multiplexed_model_id
        self._replicas = []
        self._refreshed = 0.0
        self._inflight: deque = deque()  # (replica_index, ref)
        self._counts: dict = {}
        self._seen_version = -1  # last adopted ReplicaWatcher.version
        self._deployment_draining = False
        # model affinity: id -> replica actor_id last used (keeps a loaded
        # model's traffic on the replica that holds it — serve/multiplex.py)
        self._model_affinity: dict = {}

    # -- pickling: drop live state; reconnect lazily on the other side
    def __reduce__(self):
        return (
            DeploymentHandle,
            (self.deployment_name, self.method_name, self.multiplexed_model_id),
        )

    def options(
        self,
        *,
        method_name: Optional[str] = None,
        multiplexed_model_id: Optional[str] = None,
    ) -> "DeploymentHandle":
        h = DeploymentHandle(
            self.deployment_name,
            method_name or self.method_name,
            multiplexed_model_id
            if multiplexed_model_id is not None
            else self.multiplexed_model_id,
        )
        h._model_affinity = self._model_affinity  # shared map across options()
        return h

    def __getattr__(self, name: str):
        if name.startswith("_"):
            raise AttributeError(name)
        # method handles keep the multiplexed model id and SHARE the
        # affinity map — h.options(multiplexed_model_id=...).generate must
        # route/identify exactly like h itself
        h = DeploymentHandle(self.deployment_name, name, self.multiplexed_model_id)
        h._model_affinity = self._model_affinity
        return h

    # ------------------------------------------------------------- routing

    def _controller(self):
        import ray_tpu

        return ray_tpu.get_actor(CONTROLLER_NAME)

    def _adopt(self, replicas):
        self._replicas = list(replicas)
        self._refreshed = time.time()
        self._counts = {i: self._counts.get(i, 0) for i in range(len(self._replicas))}

    def _refresh(self, force: bool = False):
        """Adopt the shared long-poll watcher's replica snapshot when it has
        a newer one (reference: handle-side LongPollClient updating the
        router, serve/_private/long_poll.py:68); only fall back to pulling
        from the controller when the push pipeline isn't delivering."""
        from .long_poll import get_watcher

        watcher = get_watcher(self.deployment_name)
        if watcher.version != self._seen_version and watcher.replicas is not None:
            self._seen_version = watcher.version
            self._deployment_draining = watcher.draining
            self._adopt(watcher.replicas)
            # a just-landed push is at least as fresh as a pull started
            # after it — even on the force (error-retry) path
            return
        if watcher.replicas is not None:
            self._deployment_draining = watcher.draining
        # push healthy -> the long TTL is safe; push broken/unproven -> the
        # 1s pull keeps routing at most one interval stale
        ttl = 30.0 if watcher.healthy() else 1.0
        if not force and time.time() - self._refreshed < ttl and self._replicas:
            return
        import ray_tpu

        try:
            self._adopt(
                ray_tpu.get(
                    self._controller().get_replicas.remote(self.deployment_name)
                )
            )
        except ValueError:
            # the controller no longer knows this deployment (retired, or
            # this pull raced its removal broadcast): treat as drained-to-
            # nothing so callers get DeploymentUnavailableError, never a
            # raw controller error
            self._deployment_draining = True
            self._adopt([])

    def _prune(self):
        import ray_tpu

        still = deque()
        while self._inflight:
            idx, ref = self._inflight.popleft()
            ready, _ = ray_tpu.wait([ref], timeout=0)
            if ready:
                self._counts[idx] = max(0, self._counts.get(idx, 1) - 1)
            else:
                still.append((idx, ref))
        self._inflight = still

    def _prefix_idx(self, hint: str) -> Optional[int]:
        """Index of the replica the prefix digest advertises for `hint`,
        or None (no digest entry, or that replica left the set)."""
        from .long_poll import get_prefix_watcher

        entry = get_prefix_watcher(self.deployment_name).digest.get(hint)
        if not entry:
            return None
        aid = entry[0]
        for i, r in enumerate(self._replicas):
            if getattr(r, "_actor_id", None) == aid:
                return i
        return None

    def _pick_replica(self, hint: str = "") -> int:
        n = len(self._replicas)
        if n == 1:
            return 0
        model_id = self.multiplexed_model_id
        if model_id:
            # affinity first: keep a loaded model's traffic on its replica
            want = self._model_affinity.get(model_id)
            for i, r in enumerate(self._replicas):
                if getattr(r, "_actor_id", None) == want:
                    return i
        a, b = random.sample(range(n), 2)
        pick = a if self._counts.get(a, 0) <= self._counts.get(b, 0) else b
        if hint:
            # prefix affinity: ties break toward the replica advertising
            # the longest cached chain for this prompt's hint — but only
            # while its queue stays within max_skew of the two-choices
            # floor. Load wins when depths diverge: a hot prefix cannot
            # pin a replica (the hint is a bounded-weight tie-break, not
            # a hard route).
            idx = self._prefix_idx(hint)
            if idx is not None:
                from ray_tpu._private.config import GLOBAL_CONFIG as cfg

                floor = min(self._counts.get(a, 0), self._counts.get(b, 0))
                skew = int(cfg.serve_prefix_affinity_max_skew)
                if self._counts.get(idx, 0) <= floor + skew:
                    return idx
        return pick

    def remote(self, *args, **kwargs) -> DeploymentResponse:
        from ray_tpu._private.config import GLOBAL_CONFIG as cfg

        breaker = get_breaker(self.deployment_name)
        if not breaker.allow():
            # fail FAST while the controller restarts replicas — no routing,
            # no remote call, no hot loop
            raise DeploymentUnavailableError(
                self.deployment_name, "circuit breaker open",
                retry_after_s=max(
                    breaker.seconds_until_probe(), cfg.serve_http_retry_after_s
                ),
            )
        self._refresh()
        self._prune()
        hint = ""
        if cfg.serve_prefix_affinity:
            # one hint per call, shared by both attempts: proxy traffic
            # arrives as a body dict in args[0], handle traffic as
            # tokens= kwargs — request_hint covers both shapes
            from .kv_transfer import request_hint

            hint = request_hint(args, kwargs)
        for attempt in range(2):
            # re-checked every attempt: a force-refresh after a failed
            # submit may have adopted an empty/draining set. Failing here is
            # a breaker FAILURE (not just a fast error): it re-opens the
            # window cleanly when this call held the half-open probe slot,
            # so the slot can never leak.
            if self._deployment_draining:
                breaker.record_failure()
                raise DeploymentUnavailableError(
                    self.deployment_name, "deployment is draining",
                    retry_after_s=cfg.serve_http_retry_after_s,
                )
            if not self._replicas:
                breaker.record_failure()
                raise DeploymentUnavailableError(
                    self.deployment_name, "no live replicas",
                    retry_after_s=cfg.serve_http_retry_after_s,
                )
            idx = self._pick_replica(hint)
            try:
                ref = self._replicas[idx].handle_request.remote(
                    self.method_name, args, kwargs,
                    model_id=self.multiplexed_model_id,
                )
                break
            except Exception:
                if attempt == 1:
                    raise
                self._refresh(force=True)  # replica set changed under us
        if self.multiplexed_model_id:
            self._model_affinity[self.multiplexed_model_id] = getattr(
                self._replicas[idx], "_actor_id", None
            )
        self._counts[idx] = self._counts.get(idx, 0) + 1
        self._inflight.append((idx, ref))
        resp = DeploymentResponse(ref, handle=self, call=(args, kwargs))
        resp.replica = self._replicas[idx]
        return resp
