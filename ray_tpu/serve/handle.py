"""DeploymentHandle: the Python-native ingress to a deployment.

Reference parity: serve/handle.py (DeploymentHandle/DeploymentResponse) with
the router's power-of-two-choices replica selection (serve/_private/router.py:370)
done handle-side over locally-tracked in-flight counts.
"""

from __future__ import annotations

import random
import time
from collections import deque
from typing import Any, Optional

CONTROLLER_NAME = "SERVE_CONTROLLER"


class DeploymentResponse:
    def __init__(self, ref, handle=None, call=None):
        self._ref = ref
        self._handle = handle
        self._call = call  # (args, kwargs) for replica-death retry

    def result(self, timeout_s: Optional[float] = None):
        import ray_tpu
        from ray_tpu.exceptions import ActorDiedError, WorkerCrashedError

        try:
            return ray_tpu.get(self._ref, timeout=timeout_s)
        except (ActorDiedError, WorkerCrashedError):
            # the chosen replica died mid-call (e.g. torn down by a
            # redeploy that raced this request): re-route once against a
            # refreshed replica set (reference: the router retries system
            # failures transparently, serve/_private/router.py)
            if self._handle is None or self._call is None:
                raise
            self._handle._refresh(force=True)
            args, kwargs = self._call
            retry = self._handle.remote(*args, **kwargs)
            return ray_tpu.get(retry.ref, timeout=timeout_s)

    @property
    def ref(self):
        return self._ref


class DeploymentHandle:
    def __init__(
        self,
        deployment_name: str,
        method_name: str = "__call__",
        multiplexed_model_id: str = "",
    ):
        self.deployment_name = deployment_name
        self.method_name = method_name
        self.multiplexed_model_id = multiplexed_model_id
        self._replicas = []
        self._refreshed = 0.0
        self._inflight: deque = deque()  # (replica_index, ref)
        self._counts: dict = {}
        self._seen_version = -1  # last adopted ReplicaWatcher.version
        # model affinity: id -> replica actor_id last used (keeps a loaded
        # model's traffic on the replica that holds it — serve/multiplex.py)
        self._model_affinity: dict = {}

    # -- pickling: drop live state; reconnect lazily on the other side
    def __reduce__(self):
        return (
            DeploymentHandle,
            (self.deployment_name, self.method_name, self.multiplexed_model_id),
        )

    def options(
        self,
        *,
        method_name: Optional[str] = None,
        multiplexed_model_id: Optional[str] = None,
    ) -> "DeploymentHandle":
        h = DeploymentHandle(
            self.deployment_name,
            method_name or self.method_name,
            multiplexed_model_id
            if multiplexed_model_id is not None
            else self.multiplexed_model_id,
        )
        h._model_affinity = self._model_affinity  # shared map across options()
        return h

    def __getattr__(self, name: str):
        if name.startswith("_"):
            raise AttributeError(name)
        # method handles keep the multiplexed model id and SHARE the
        # affinity map — h.options(multiplexed_model_id=...).generate must
        # route/identify exactly like h itself
        h = DeploymentHandle(self.deployment_name, name, self.multiplexed_model_id)
        h._model_affinity = self._model_affinity
        return h

    # ------------------------------------------------------------- routing

    def _controller(self):
        import ray_tpu

        return ray_tpu.get_actor(CONTROLLER_NAME)

    def _adopt(self, replicas):
        self._replicas = list(replicas)
        self._refreshed = time.time()
        self._counts = {i: self._counts.get(i, 0) for i in range(len(self._replicas))}

    def _refresh(self, force: bool = False):
        """Adopt the shared long-poll watcher's replica snapshot when it has
        a newer one (reference: handle-side LongPollClient updating the
        router, serve/_private/long_poll.py:68); only fall back to pulling
        from the controller when the push pipeline isn't delivering."""
        from .long_poll import get_watcher

        watcher = get_watcher(self.deployment_name)
        if watcher.version != self._seen_version and watcher.replicas is not None:
            self._seen_version = watcher.version
            self._adopt(watcher.replicas)
            # a just-landed push is at least as fresh as a pull started
            # after it — even on the force (error-retry) path
            return
        # push healthy -> the long TTL is safe; push broken/unproven -> the
        # 1s pull keeps routing at most one interval stale
        ttl = 30.0 if watcher.healthy() else 1.0
        if not force and time.time() - self._refreshed < ttl and self._replicas:
            return
        import ray_tpu

        self._adopt(
            ray_tpu.get(self._controller().get_replicas.remote(self.deployment_name))
        )

    def _prune(self):
        import ray_tpu

        still = deque()
        while self._inflight:
            idx, ref = self._inflight.popleft()
            ready, _ = ray_tpu.wait([ref], timeout=0)
            if ready:
                self._counts[idx] = max(0, self._counts.get(idx, 1) - 1)
            else:
                still.append((idx, ref))
        self._inflight = still

    def _pick_replica(self) -> int:
        n = len(self._replicas)
        if n == 1:
            return 0
        model_id = self.multiplexed_model_id
        if model_id:
            # affinity first: keep a loaded model's traffic on its replica
            want = self._model_affinity.get(model_id)
            for i, r in enumerate(self._replicas):
                if getattr(r, "_actor_id", None) == want:
                    return i
        a, b = random.sample(range(n), 2)
        return a if self._counts.get(a, 0) <= self._counts.get(b, 0) else b

    def remote(self, *args, **kwargs) -> DeploymentResponse:
        self._refresh()
        self._prune()
        if not self._replicas:
            raise RuntimeError(f"deployment {self.deployment_name!r} has no replicas")
        for attempt in range(2):
            idx = self._pick_replica()
            try:
                ref = self._replicas[idx].handle_request.remote(
                    self.method_name, args, kwargs,
                    model_id=self.multiplexed_model_id,
                )
                break
            except Exception:
                if attempt == 1:
                    raise
                self._refresh(force=True)  # replica set changed under us
        if self.multiplexed_model_id:
            self._model_affinity[self.multiplexed_model_id] = getattr(
                self._replicas[idx], "_actor_id", None
            )
        self._counts[idx] = self._counts.get(idx, 0) + 1
        self._inflight.append((idx, ref))
        return DeploymentResponse(ref, handle=self, call=(args, kwargs))
