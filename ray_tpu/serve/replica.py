"""Replica actor: hosts one copy of a deployment's callable.

Reference parity: serve/_private/replica.py:382 (RayServeReplica — wraps the
user callable, tracks ongoing requests for autoscaling stats).
"""

from __future__ import annotations

import inspect
import threading
import time
from typing import Any, Dict


class Replica:
    def __init__(self, deployment_name: str, func_or_class, init_args, init_kwargs):
        self.deployment_name = deployment_name
        self._ongoing = 0
        self._total = 0
        self._lock = threading.Lock()
        if inspect.isclass(func_or_class):
            self.callable = func_or_class(*init_args, **init_kwargs)
            self.is_function = False
        else:
            self.callable = func_or_class
            self.is_function = True

    def ready(self):
        return True

    def handle_request(self, method_name: str, args, kwargs, model_id: str = ""):
        with self._lock:
            self._ongoing += 1
            self._total += 1
        if model_id:
            from .multiplex import _set_model_id

            _set_model_id(model_id)
        try:
            if self.is_function:
                return self.callable(*args, **kwargs)
            if method_name == "__call__":
                fn = self.callable
            else:
                fn = getattr(self.callable, method_name)
            return fn(*args, **kwargs)
        finally:
            if model_id:
                from .multiplex import _set_model_id

                _set_model_id("")
            with self._lock:
                self._ongoing -= 1

    def stats(self) -> Dict[str, Any]:
        return {"ongoing": self._ongoing, "total": self._total, "ts": time.time()}

    def check_health(self) -> bool:
        user_check = getattr(self.callable, "check_health", None)
        if user_check is not None and not self.is_function:
            user_check()
        return True
